"""Structural pattern matching for plan rewrite rules.

Reference analog: ``presto-matching`` (Pattern.java / Match.java — the
tiny library the iterative optimizer's rules declare their shapes
with).  A pattern matches a plan node by type, optional predicates,
and optional source sub-patterns; ``match`` returns a Match carrying
captured nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Capture:
    """A named slot filled by a sub-pattern match."""

    name: str


@dataclasses.dataclass
class Match:
    node: Any
    captures: Dict[str, Any]

    def get(self, capture: Capture):
        return self.captures[capture.name]


class Pattern:
    """node-type pattern with predicates and source sub-patterns."""

    def __init__(self, node_type=None):
        self.node_type = node_type
        self.predicates: List[Callable[[Any], bool]] = []
        self.source_patterns: Optional[List["Pattern"]] = None
        self.capture_as: Optional[Capture] = None

    @classmethod
    def type_of(cls, node_type) -> "Pattern":
        return cls(node_type)

    @classmethod
    def any(cls) -> "Pattern":
        return cls(None)

    def where(self, pred: Callable[[Any], bool]) -> "Pattern":
        self.predicates.append(pred)
        return self

    def with_sources(self, *patterns: "Pattern") -> "Pattern":
        self.source_patterns = list(patterns)
        return self

    def captured_as(self, capture: Capture) -> "Pattern":
        self.capture_as = capture
        return self

    def match(self, node) -> Optional[Match]:
        caps: Dict[str, Any] = {}
        if self._match_into(node, caps):
            return Match(node, caps)
        return None

    def _match_into(self, node, caps: Dict[str, Any]) -> bool:
        if self.node_type is not None and not isinstance(node, self.node_type):
            return False
        for p in self.predicates:
            if not p(node):
                return False
        if self.source_patterns is not None:
            sources = node.sources
            if len(sources) != len(self.source_patterns):
                return False
            for sp, s in zip(self.source_patterns, sources):
                if not sp._match_into(s, caps):
                    return False
        if self.capture_as is not None:
            caps[self.capture_as.name] = node
        return True
