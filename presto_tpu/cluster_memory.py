"""Cluster memory management: pool polling + low-memory killer.

Reference analog: ``memory/ClusterMemoryManager.java:88`` — the
coordinator polls every worker's memory pools (``RemoteNodeMemory``),
and when the cluster is out of memory picks a victim query via the
pluggable ``LowMemoryKiller`` (default
``TotalReservationLowMemoryKiller``: the query with the largest total
reservation).  Here the pools are HBM ``MemoryPool``s; workers expose
reservation in ``/v1/info`` and the coordinator kills through the
normal cancel path.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence

_log = logging.getLogger("presto_tpu.cluster_memory")


def total_reservation_low_memory_killer(
    by_query: Dict[str, int]
) -> Optional[str]:
    """Pick the query holding the most reserved bytes
    (TotalReservationLowMemoryKiller.java)."""
    if not by_query:
        return None
    return max(by_query.items(), key=lambda kv: kv[1])[0]


def query_reservations(pool) -> Dict[str, int]:
    """Aggregate a pool's tagged reservations by query id (tags are
    '{query_id}/{what}#{seq}' — memory.py QueryMemoryContext)."""
    out: Dict[str, int] = {}
    for tag, nbytes in pool.tags().items():
        qid = tag.split("/", 1)[0]
        out[qid] = out.get(qid, 0) + nbytes
    return out


class ClusterMemoryManager:
    """Polls local + remote pools; kills the biggest query when the
    cluster exceeds its memory threshold."""

    def __init__(
        self,
        local_pool,
        kill_query: Callable[[str], None],
        worker_uris: Sequence[str] = (),
        threshold: float = 0.95,
        poll_interval: float = 1.0,
        killer: Callable[[Dict[str, int]], Optional[str]] = total_reservation_low_memory_killer,
        events=None,
    ):
        self.local_pool = local_pool
        self.kill_query = kill_query
        self.worker_uris = list(worker_uris)
        self.threshold = threshold
        self.poll_interval = poll_interval
        self.killer = killer
        # EventListenerManager (or None): each kill emits a
        # MemoryKillEvent so the query log records the DECISION —
        # pool pressure and bytes freed — not just the victim's
        # eventual failure line
        self.events = events
        self.kills: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # availability-transition logging for the worker polls: one
        # line per state change, never one per poll cycle
        from presto_tpu.net import PollHealth

        self._poll_health = PollHealth("worker memory", _log)

    # -- polling ------------------------------------------------------------
    def cluster_usage(self) -> Dict[str, int]:
        """(reserved, limit) across local + remote pools
        (RemoteNodeMemory poll). Workers are polled concurrently so one
        hung socket cannot stretch the decision cycle past ~2s."""
        from presto_tpu.net import poll_each, request_json

        reserved = self.local_pool.reserved if self.local_pool else 0
        limit = self.local_pool.limit if self.local_pool else 0
        # failures are classified/counted by request_json and
        # transition-logged by the health tracker; EXCLUDING a dead
        # worker from the usage sum is the correct degradation — its
        # liveness is the failure detector's job
        infos = poll_each(
            self.worker_uris,
            lambda uri: request_json(f"{uri}/v1/info", timeout=2.0,
                                     site="cluster.memory_poll_errors"),
            health=self._poll_health)
        for info in infos.values():
            mem = info.get("memory") or {}
            reserved += int(mem.get("reserved", 0))
            limit += int(mem.get("limit", 0))
        return {"reserved": reserved, "limit": limit}

    def check_once(self) -> Optional[str]:
        """One poll cycle; returns the killed query id, if any. A kill
        frees the victim's reservations immediately (pool.kill_query)
        so the next cycle escalates to the next-biggest query instead
        of re-selecting a dead one.

        Kill authority is LOCAL: the decision threshold uses the local
        pool only, so remote worker pressure (whose queries this
        coordinator cannot attribute) never kills innocent local
        queries. The freeing itself is cooperative — the victim's
        thread unwinds at its next reservation, so a short overcommit
        window exists while it finishes its current kernel (the
        reference's revoke protocol has the same property).
        cluster_usage() remains the fleet-wide view for /v1/cluster."""
        if self.local_pool is None:
            return None
        reserved, limit = self.local_pool.reserved, self.local_pool.limit
        if limit <= 0 or reserved < self.threshold * limit:
            return None
        candidates = {q: b for q, b in query_reservations(self.local_pool).items()
                      if q not in self.kills}
        victim = self.killer(candidates)
        if victim is None:
            return None
        self.kills.append(victim)
        freed = self.local_pool.kill_query(victim)  # immediate relief
        self.kill_query(victim)
        from presto_tpu.obs import METRICS

        METRICS.counter("memory.query_killed").inc()
        if self.events is not None:
            # telemetry AFTER both kill actions, and guarded: a raising
            # user listener must not leave the victim half-killed
            try:
                import time

                from presto_tpu.events import MemoryKillEvent

                self.events.memory_killed(MemoryKillEvent(
                    query_id=victim, freed_bytes=freed,
                    reserved_bytes=reserved, limit_bytes=limit,
                    kill_time=time.time()))
            except Exception:
                pass
        return victim

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.poll_interval):
                try:
                    self.check_once()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="cluster-memory")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # reap the poll loop (sanitizer thread-lifecycle): a stop()
        # that abandons it lets one more kill cycle race the teardown
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.poll_interval + 1.0)
