"""Columnar device batches: Block and Page.

Reference analog: ``presto-spi/.../spi/Page.java:34`` (array of Blocks +
positionCount) and ``spi/block/Block.java:23``.  The reference's Blocks
are heap byte slices with per-position object access; here a Block is a
dense device array plus a validity bitmap so every operator is a
whole-array XLA computation.

TPU-first representational choices:

* **Static capacity.** XLA wants static shapes.  A Page's arrays all
  have length ``capacity`` (padded); the live rows are flagged by a
  boolean ``row_mask`` (the analog of Presto's SelectedPositions,
  operator/project/SelectedPositions.java, but kept as a mask instead of
  a position list so filters are free and nothing ever recompiles).
  Compaction happens only at exchange boundaries or host output.

* **Two masks.** ``Block.valid`` is SQL NULL-ness per value;
  ``Page.row_mask`` is row liveness after filters.  Operators must
  ignore rows where ``row_mask`` is False.

* **Dictionary blocks.** VARCHAR columns store int32 codes; the code ->
  string mapping is a host-side :class:`Dictionary` (reference:
  spi/block/DictionaryBlock.java).  String predicates evaluate once on
  the dictionary host-side, becoming a device boolean LUT gather.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.types import Type


class Dictionary:
    """Host-side immutable string dictionary for a VARCHAR column.

    Codes are indexes into ``values``.  Identity-hashed so it can ride
    in jit-static fields without content comparison.
    """

    __slots__ = ("values", "_index")

    def __init__(self, values: Sequence[str]):
        self.values = list(values)
        self._index: Optional[Dict[str, int]] = None

    def code_of(self, s: str) -> int:
        if self._index is None:
            self._index = {v: i for i, v in enumerate(self.values)}
        return self._index.get(s, -1)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        arr = np.asarray(self.values, dtype=object)
        out = np.empty(codes.shape, dtype=object)
        in_range = (codes >= 0) & (codes < len(self.values))
        out[in_range] = arr[codes[in_range]]
        out[~in_range] = None
        return out

    def lut(self, predicate) -> np.ndarray:
        """Evaluate a python str->bool predicate over all unique values,
        returning a bool LUT indexable by code (device-gatherable)."""
        return np.asarray([bool(predicate(v)) for v in self.values], dtype=np.bool_)

    def __len__(self) -> int:
        return len(self.values)

    def __repr__(self) -> str:
        return f"Dictionary({len(self.values)} values)"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Block:
    """One column: dense device array + validity bitmap.

    ``data`` and ``valid`` have shape ``(capacity,)``.  ``type`` and
    ``dictionary`` are static (not traced).
    """

    data: jax.Array
    valid: jax.Array
    type: Type
    dictionary: Optional[Dictionary] = None

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.valid), (self.type, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid = children
        type_, dictionary = aux
        return cls(data=data, valid=valid, type=type_, dictionary=dictionary)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_numpy(
        cls,
        values: np.ndarray,
        type_: Type,
        valid: Optional[np.ndarray] = None,
        dictionary: Optional[Dictionary] = None,
        capacity: Optional[int] = None,
    ) -> "Block":
        n = len(values)
        cap = capacity if capacity is not None else n
        if type_.is_long_decimal and (
            not isinstance(values, np.ndarray) or values.ndim == 1
        ):
            # python ints (possibly > 2^63) -> base-10^18 (or, for
            # decimal(37..38), base-10^9) limbs
            from presto_tpu.ops.decimal128 import encode_py

            data = encode_py(list(values), cap,
                             limbs=type_.value_shape[0])
        elif type_.is_raw_string and not isinstance(values, np.ndarray):
            from presto_tpu.ops.rawstring import encode_strings

            width = type_.value_shape[0]
            data = np.zeros((cap, width), dtype=np.uint8)
            data[:n] = encode_strings(list(values), width)
        elif type_.is_array and (
            not isinstance(values, np.ndarray) or values.ndim == 1
        ):
            from presto_tpu.ops.container import encode_arrays

            data = encode_arrays(list(values), type_, cap)
        elif type_.is_map and (
            not isinstance(values, np.ndarray) or values.ndim == 1
        ):
            from presto_tpu.ops.container import encode_maps

            data = encode_maps(list(values), type_, cap)
        else:
            data = np.zeros((cap,) + type_.value_shape, dtype=type_.np_dtype)
            data[:n] = values
        v = np.zeros(cap, dtype=np.bool_)
        v[:n] = True if valid is None else valid
        return cls(jnp.asarray(data), jnp.asarray(v), type_, dictionary)

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def __repr__(self) -> str:
        return f"Block({self.type}, capacity={self.capacity})"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Page:
    """A batch of rows: tuple of Blocks + row liveness mask.

    Reference: spi/Page.java.  ``positionCount`` becomes the dynamic
    ``num_rows()`` (popcount of row_mask); shapes stay static.
    """

    blocks: Tuple[Block, ...]
    row_mask: jax.Array  # bool (capacity,)

    def tree_flatten(self):
        return (self.blocks, self.row_mask), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        blocks, row_mask = children
        return cls(blocks=tuple(blocks), row_mask=row_mask)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_arrays(
        cls,
        columns: Sequence[np.ndarray],
        types: Sequence[Type],
        valids: Optional[Sequence[Optional[np.ndarray]]] = None,
        dictionaries: Optional[Sequence[Optional[Dictionary]]] = None,
        capacity: Optional[int] = None,
    ) -> "Page":
        n = len(columns[0]) if columns else 0
        cap = capacity if capacity is not None else max(n, 1)
        blocks = []
        for i, (col, t) in enumerate(zip(columns, types)):
            v = valids[i] if valids is not None else None
            d = dictionaries[i] if dictionaries is not None else None
            blocks.append(Block.from_numpy(col, t, valid=v, dictionary=d, capacity=cap))
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = True
        return cls(tuple(blocks), jnp.asarray(mask))

    @classmethod
    def empty(cls, types: Sequence[Type], capacity: int) -> "Page":
        blocks = tuple(
            Block(
                jnp.zeros((capacity,) + t.value_shape, dtype=t.np_dtype),
                jnp.zeros(capacity, dtype=jnp.bool_),
                t,
            )
            for t in types
        )
        return cls(blocks, jnp.zeros(capacity, dtype=jnp.bool_))

    # -- properties --------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.row_mask.shape[0]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def types(self) -> Tuple[Type, ...]:
        return tuple(b.type for b in self.blocks)

    def num_rows(self) -> jax.Array:
        return jnp.sum(self.row_mask.astype(jnp.int32))

    # -- host materialization ---------------------------------------------
    def device_get(self) -> "Page":
        """One batched device->host transfer of the whole page.  The
        axon TPU tunnel charges a full round trip (~70ms) per
        *separate* host read, so serial ``np.asarray`` per block is
        k+1 round trips; ``jax.device_get`` of the pytree batches them.
        The returned Page holds numpy arrays (valid pytree leaves —
        they re-upload transparently if handed back to device code)."""
        datas, valids, mask = jax.device_get((
            tuple(b.data for b in self.blocks),
            tuple(b.valid for b in self.blocks),
            self.row_mask,
        ))
        return Page(
            tuple(
                Block(d, v, b.type, b.dictionary)
                for d, v, b in zip(datas, valids, self.blocks)
            ),
            mask,
        )

    def to_pylist(self, decode_strings: bool = True) -> List[tuple]:
        """Compact live rows to host python tuples (None for NULLs).
        Test/CLI/REST output path — not on the hot loop."""
        if isinstance(self.row_mask, jax.Array):
            return self.device_get().to_pylist(decode_strings)
        mask = np.asarray(self.row_mask)
        rows_idx = np.nonzero(mask)[0]
        cols = []
        for b in self.blocks:
            data = np.asarray(b.data)[rows_idx]
            valid = np.asarray(b.valid)[rows_idx]
            if b.type.is_string and b.dictionary is not None and decode_strings:
                vals = b.dictionary.decode(data)
            elif b.type.is_raw_string and decode_strings:
                from presto_tpu.ops.rawstring import decode_strings as _dec

                vals = np.asarray(_dec(data), dtype=object)
            elif b.type.is_array:
                from presto_tpu.ops.container import decode_arrays

                vals = np.empty(len(data), dtype=object)
                vals[:] = decode_arrays(data, b.type, b.dictionary)
            elif b.type.is_map:
                from presto_tpu.ops.container import decode_maps

                vals = np.empty(len(data), dtype=object)
                vals[:] = decode_maps(data, b.type, b.dictionary)
            elif b.type.name == "row":
                from presto_tpu.ops.container import decode_rows

                vals = np.empty(len(data), dtype=object)
                vals[:] = decode_rows(data, b.type)
            elif b.type.is_long_decimal:
                import decimal

                from presto_tpu.ops.decimal128 import decode_py

                vals = np.empty(len(data), dtype=object)
                with decimal.localcontext() as ctx:
                    ctx.prec = 50  # scaleb must not round 38-digit values
                    vals[:] = [decimal.Decimal(v).scaleb(-(b.type.scale or 0))
                               for v in decode_py(data)]
            elif b.type.is_decimal:
                # exact scaled-int values surface as decimal.Decimal —
                # floats would silently round p>15 results (the
                # reference returns java BigDecimal)
                import decimal

                sc = b.type.scale or 0
                vals = np.empty(len(data), dtype=object)
                vals[:] = [decimal.Decimal(int(v)).scaleb(-sc) for v in data]
            else:
                vals = data
            col = [None if not v else _to_py(vals[i], b.type) for i, v in enumerate(valid)]
            cols.append(col)
        return [tuple(c[i] for c in cols) for i in range(len(rows_idx))]

    def compact_host(self) -> "Page":
        """Host-side compaction: gather live rows to a prefix.  Pulls
        the page in ONE batched transfer and stays numpy — consumers
        that need device arrays re-upload on first use."""
        p = self.device_get() if isinstance(self.row_mask, jax.Array) else self
        mask = np.asarray(p.row_mask)
        idx = np.nonzero(mask)[0]
        n = len(idx)
        blocks = []
        for b in p.blocks:
            data = np.asarray(b.data)[idx]
            valid = np.asarray(b.valid)[idx]
            if n == 0:
                data = np.zeros((1,) + data.shape[1:], dtype=data.dtype)
                valid = np.zeros(1, dtype=np.bool_)
            blocks.append(Block(data, valid, b.type, b.dictionary))
        mask_out = np.zeros(max(n, 1), dtype=np.bool_)
        mask_out[:n] = True
        return Page(tuple(blocks), mask_out)

    def __repr__(self) -> str:
        return f"Page({self.num_blocks} blocks, capacity={self.capacity})"


def _to_py(v, t: Type):
    if t.name == "decimal":
        return v  # already decimal.Decimal (exact)
    if t.name == "double":
        return float(v)
    if t.name == "boolean":
        return bool(v)
    if t.name == "timestamp":
        import datetime

        return datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(v))
    if t.name == "interval day to second":
        import datetime

        return datetime.timedelta(microseconds=int(v))
    if t.name == "interval year to month":
        return int(v)  # months (the reference renders 'Y-M')
    if t.is_string:
        return v  # already decoded (str) or raw code
    if isinstance(v, (np.integer,)):
        return int(v)
    return v


def concat_pages_host(pages: Sequence[Page]) -> Page:
    """Host-side concatenation of compacted pages (result assembly)."""
    pages = [p.compact_host() for p in pages]
    pages = [p for p in pages if int(np.asarray(p.row_mask).sum()) > 0] or pages[:1]
    ntypes = pages[0].types
    cols, valids, dicts = [], [], []
    for i, t in enumerate(ntypes):
        datas, vs = [], []
        for p in pages:
            n = int(np.asarray(p.row_mask).sum())
            datas.append(np.asarray(p.blocks[i].data)[:n])
            vs.append(np.asarray(p.blocks[i].valid)[:n])
        cols.append(np.concatenate(datas) if datas else np.zeros(0, t.np_dtype))
        valids.append(np.concatenate(vs) if vs else np.zeros(0, np.bool_))
        dicts.append(pages[0].blocks[i].dictionary)
    return Page.from_arrays(cols, ntypes, valids=valids, dictionaries=dicts)
