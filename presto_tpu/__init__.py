"""presto_tpu: a TPU-native distributed SQL query engine.

A from-scratch reimplementation of the capabilities of Presto
(reference: haozhun/presto @ 0.208-SNAPSHOT) designed idiomatically for
TPUs: columnar Pages are device-resident ``jnp.ndarray`` batches,
Presto's runtime-JIT'd JVM bytecode kernels become XLA-compiled JAX
functions, and the HTTP pull-shuffle becomes ``jax.lax.all_to_all``
over the ICI mesh.

Layer map (mirrors reference layers; see SURVEY.md §1):
  L0 data representation  -> presto_tpu.page, presto_tpu.types
  L2 operators            -> presto_tpu.ops
  L2b expression JIT      -> presto_tpu.expr
  L3/L4 driver/task exec  -> presto_tpu.exec
  L5 exchange             -> presto_tpu.parallel
  L7-L9 SQL frontend      -> presto_tpu.sql
  L12 connectors          -> presto_tpu.connectors
"""

__version__ = "0.1.0"

# SQL semantics demand 64-bit: BIGINT is int64, exact DECIMAL sums
# accumulate in int64 (spi/type/BigintType.java; DOUBLE is IEEE 754
# 64-bit).  jax defaults to 32-bit — opt the process into x64 before
# any array is created.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from presto_tpu.types import (  # noqa: F401
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    VARCHAR,
    DecimalType,
    Type,
    common_super_type,
    parse_type,
)
from presto_tpu.page import Block, Dictionary, Page  # noqa: F401
