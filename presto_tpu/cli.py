"""Interactive SQL console.

Reference analog: ``presto-cli`` (``cli/Console.java`` — jline REPL
with aligned table output and \\-commands).  Runs either in-process
(embedded QueryRunner over the TPC-H catalog) or against a coordinator
via --server.

Usage:
  python -m presto_tpu.cli [--server URI] [--sf 0.01] [-e "SQL"]
"""

from __future__ import annotations

import argparse
import sys
import time


def format_table(names, rows, max_rows: int = 200) -> str:
    cols = [str(n) for n in names]
    shown = rows[:max_rows]
    cells = [[("NULL" if v is None else str(v)) for v in r] for r in shown]
    widths = [
        max(len(cols[i]), *(len(r[i]) for r in cells)) if cells else len(cols[i])
        for i in range(len(cols))
    ]
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(c.ljust(w) for c, w in zip(cols, widths)), sep]
    for r in cells:
        out.append(" | ".join(c.rjust(w) for c, w in zip(r, widths)))
    if len(rows) > max_rows:
        out.append(f"... ({len(rows) - max_rows} more rows)")
    return "\n".join(out)


def format_output(names, rows, fmt: str) -> str:
    """ALIGNED (default) | CSV | TSV | JSON — the reference CLI's
    --output-format set (cli/OutputFormat subset)."""
    if fmt == "ALIGNED":
        return format_table(names, rows)
    if fmt in ("CSV", "TSV"):
        import csv
        import io

        buf = io.StringIO()
        w = csv.writer(buf, delimiter="," if fmt == "CSV" else "\t",
                       lineterminator="\n")
        w.writerow(names)
        for r in rows:
            w.writerow(["" if v is None else v for v in r])
        return buf.getvalue().rstrip("\n")
    if fmt == "JSON":
        import json

        return "\n".join(
            json.dumps(dict(zip(names, r)), default=str) for r in rows)
    raise SystemExit(f"unknown output format {fmt!r}")


def _progress_text(stats: dict) -> str:
    """One-line render of statement-protocol progress stats (the
    reference CLI's status bar): queue position while waiting for
    admission, then percentage + the busiest stage."""
    parts = []
    if stats.get("state") == "QUEUED":
        pos = stats.get("queuePosition")
        parts.append(f"queued #{pos}" if pos is not None else "queued")
    pct = stats.get("progressPercentage")
    if pct is not None:
        parts.append(f"{pct:5.1f}%")
    stages = stats.get("stages") or []
    running = [s for s in stages if s.get("state") == "RUNNING"]
    show = (running or stages)[-1:]
    for s in show:
        tot = s.get("splitsTotal")
        parts.append(f"{s['stage']} {s['splitsDone']}/{tot if tot is not None else '?'}")
    return " ".join(parts)


class _ProgressLine:
    """Carriage-return progress line on stderr (suppressed when stderr
    is not a terminal unless --progress forces it)."""

    def __init__(self, enabled: bool):
        self.enabled = enabled
        self._width = 0

    def update(self, stats: dict) -> None:
        if not self.enabled:
            return
        text = _progress_text(stats)
        pad = max(self._width - len(text), 0)
        sys.stderr.write("\r" + text + " " * pad)
        sys.stderr.flush()
        self._width = len(text)

    def clear(self) -> None:
        if self.enabled and self._width:
            sys.stderr.write("\r" + " " * self._width + "\r")
            sys.stderr.flush()
            self._width = 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu")
    ap.add_argument("--server", help="coordinator URI (default: embedded engine)")
    ap.add_argument("--sf", type=float, default=0.01, help="embedded TPC-H scale factor")
    ap.add_argument("-e", "--execute", help="run one statement and exit")
    ap.add_argument("--output-format", default="ALIGNED",
                    choices=["ALIGNED", "CSV", "TSV", "JSON"],
                    help="result rendering (reference --output-format)")
    ap.add_argument("--progress", action="store_true",
                    help="render a live progress line even when stderr "
                         "is not a terminal")
    ap.add_argument("--platform", default=None,
                    help="force the jax backend (e.g. cpu) — useful when "
                         "the accelerator tunnel is unreachable")
    ap.add_argument("--doctor", action="store_true",
                    help="print the query doctor's ranked bottleneck "
                         "findings after each statement")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    show_progress = args.progress or sys.stderr.isatty()

    if args.server:
        from presto_tpu.client import StatementClient

        client = StatementClient(args.server)

        def run(sql, line):
            columns, rows = client.execute(
                sql, on_progress=line.update if line.enabled else None)
            findings = None
            if args.doctor and client.last_query_id:
                try:
                    findings = client.doctor(
                        client.last_query_id).get("findings")
                except Exception:
                    findings = None  # no telemetry (DDL, old server)
            return [c["name"] for c in columns], rows, findings
    else:
        from presto_tpu.catalog import Catalog
        from presto_tpu.connectors.tpch import Tpch
        from presto_tpu.runner import QueryRunner

        catalog = Catalog()
        catalog.register("tpch", Tpch(sf=args.sf))
        runner = QueryRunner(catalog)

        def run(sql, line):
            if not line.enabled:
                res = runner.execute(sql)
                return res.names, res.rows, getattr(res, "findings", None)
            # embedded: execute on a worker thread and poll the
            # process progress registry from here (the same numbers
            # the statement protocol serves)
            import threading
            import uuid

            from presto_tpu import obs

            qid = "cli_" + uuid.uuid4().hex[:12]
            box = {}

            def go():
                try:
                    box["res"] = runner.execute(sql, query_id=qid)
                except BaseException as e:
                    box["err"] = e

            t = threading.Thread(target=go, daemon=True,
                                 name=f"cli-query-{qid}")
            t.start()
            while t.is_alive():
                t.join(timeout=0.1)
                prog = obs.progress_for(qid)
                if prog is not None:
                    line.update(prog.snapshot())
            if "err" in box:
                raise box["err"]
            res = box["res"]
            return res.names, res.rows, getattr(res, "findings", None)

    def run_one(sql: str) -> int:
        t0 = time.perf_counter()
        line = _ProgressLine(show_progress)
        try:
            names, rows, findings = run(sql, line)
        except Exception as e:
            line.clear()
            print(f"error: {e}", file=sys.stderr)
            return 1
        line.clear()
        print(format_output(names, rows, args.output_format))
        if args.output_format == "ALIGNED":
            print(f"({len(rows)} rows, {time.perf_counter() - t0:.2f}s)")
        if args.doctor and findings is not None:
            from presto_tpu.obs.doctor import format_findings

            print(format_findings(findings), file=sys.stderr)
        return 0

    if args.execute:
        return run_one(args.execute)

    print(f"presto-tpu console ({'server ' + args.server if args.server else f'embedded tpch sf={args.sf}'})")
    buf = ""
    while True:
        try:
            line = input("... " if buf else "presto-tpu> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not buf and line.strip().lower() in ("quit", "exit", "\\q"):
            return 0
        buf = (buf + "\n" + line) if buf else line
        if buf.strip().endswith(";") or line == "":
            sql = buf.strip().rstrip(";")
            buf = ""
            if sql:
                run_one(sql)


if __name__ == "__main__":
    sys.exit(main())
