"""Test/bench harnesses: single-process engine rigs.

Reference analogs: ``testing/LocalQueryRunner.java:207`` (the
full-pipeline in-process harness behind most reference tests and
benchmarks) and ``presto-tests/.../DistributedQueryRunner.java:69``
(one coordinator + N workers booted inside one JVM on real HTTP —
the cluster-without-a-cluster correctness rig).
"""

from __future__ import annotations

from typing import List, Optional

from presto_tpu.catalog import Catalog
from presto_tpu.runner import QueryRunner


def tpch_catalog(sf: float = 0.01, split_rows: int = 1 << 14,
                 aligned_buckets: bool = False) -> Catalog:
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.tpch import Tpch

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=sf, split_rows=split_rows,
                                  aligned_buckets=aligned_buckets))
    catalog.register("mem", MemoryConnector(), writable=True)
    return catalog


class LocalQueryRunner(QueryRunner):
    """SQL in, rows out, fully in-process over the TPC-H generator
    (LocalQueryRunner.java analog)."""

    def __init__(self, sf: float = 0.01, catalog: Optional[Catalog] = None,
                 **kw):
        super().__init__(catalog or tpch_catalog(sf=sf), **kw)


class DistributedQueryRunner:
    """One coordinator + N workers in THIS process on real HTTP ports
    (DistributedQueryRunner.java:69 analog): the full statement + task
    protocols run end to end, splits fan out over the workers, and a
    worker kill exercises failover — no cluster required.

    Usage::

        with DistributedQueryRunner(n_workers=2, sf=0.01) as dqr:
            rows = dqr.execute("SELECT count(*) FROM lineitem")
    """

    def __init__(self, n_workers: int = 2, sf: float = 0.01,
                 catalog: Optional[Catalog] = None, split_rows: int = 1 << 12):
        from presto_tpu.parallel.multihost import MultiHostRunner
        from presto_tpu.server.coordinator import CoordinatorServer
        from presto_tpu.server.worker import WorkerServer

        self.catalog = catalog or tpch_catalog(sf=sf, split_rows=split_rows)
        self.workers: List[WorkerServer] = []
        for _ in range(n_workers):
            w = WorkerServer(self.catalog)
            w.start()
            self.workers.append(w)
        self.runner = QueryRunner(self.catalog)
        # ONE failure detector for the whole rig: the multihost runner
        # builds it (fed by fragment traffic + its pings) and the
        # coordinator shares it — so /v1/worker, system_runtime_workers
        # and the scheduler's circuit breaker all describe the same
        # state machine, and the coordinator wires its transitions into
        # the runner's event pipeline exactly once
        self.multihost = MultiHostRunner(
            self.catalog, [w.uri for w in self.workers])
        self.coordinator = CoordinatorServer(
            self.runner, worker_uris=[w.uri for w in self.workers],
            detector=self.multihost.detector)
        self.coordinator.start()
        from presto_tpu.client import StatementClient

        self.client = StatementClient(self.coordinator.uri)

    # -- execution ----------------------------------------------------------
    def execute(self, sql: str) -> List[tuple]:
        """Through the full REST protocol (client -> coordinator)."""
        _, rows = self.client.execute(sql)
        return rows

    def execute_multihost(self, sql: str) -> List[tuple]:
        """Fan the leaf scan over the HTTP workers (task protocol)."""
        plan = self.runner.plan(sql)
        return self.multihost.run(plan).rows

    # -- chaos --------------------------------------------------------------
    def kill_worker(self, index: int = 0) -> None:
        self.workers[index].stop()

    def arm_fault(self, point: str, worker: Optional[int] = None, **kw):
        """Arm a deterministic fault point (testing_faults.py) scoped
        to one worker of this rig (``worker=None`` = any node).
        ``net.*`` points evaluate on the CLIENT side of a pull, where
        only the worker's URI is known — scope them by port, which both
        the URI and the server-side node id carry."""
        from presto_tpu.testing_faults import FAULTS

        node = None
        if worker is not None:
            node = (f":{self.workers[worker].port}"
                    if point.startswith("net.")
                    else self.workers[worker].node_id)
        return FAULTS.arm(point, node=node, **kw)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        self.coordinator.stop()
        for w in self.workers:
            try:
                w.stop()
            except Exception:
                pass

    def __enter__(self) -> "DistributedQueryRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
