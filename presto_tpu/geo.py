"""Geospatial support: WKT geometries, ST_* kernels, spatial index.

Reference analog: ``presto-geospatial`` / ``presto-geospatial-toolkit``
(GeoFunctions.java ST_* scalar functions over an ESRI/JTS geometry
type) and the spatial join tier (operator/SpatialJoinOperator.java:38
with PagesRTreeIndex.java).

TPU re-design: geometries are WKT strings riding the engine's
dictionary-coded VARCHAR columns (parse once per distinct value,
host-side), while the per-row hot paths — point-in-polygon tests and
point distances — run as vectorized device kernels: a polygon is a
static (nv, 2) vertex array, and ray-casting over N probe points is a
single broadcast compare/accumulate that XLA fuses.  The spatial join
prefilters with bounding boxes (the R-tree's role: cheap candidate
rejection) and runs one fused PIP kernel per build geometry.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# WKT parsing (host; once per distinct geometry string)
# ---------------------------------------------------------------------------

_WKT_CACHE: Dict[str, "Geometry"] = {}


class Geometry:
    """Parsed geometry: kind + rings (list of (nv, 2) float arrays).
    POINT -> one 1-vertex ring; POLYGON -> outer ring + holes;
    MULTIPOLYGON -> list of (outer, holes) groups flattened with signs.
    """

    __slots__ = ("kind", "rings", "holes", "bbox")

    def __init__(self, kind: str, rings: List[np.ndarray], holes: List[bool]):
        self.kind = kind
        self.rings = rings
        self.holes = holes
        if rings:
            allv = np.concatenate(rings, axis=0)
            self.bbox = (float(allv[:, 0].min()), float(allv[:, 1].min()),
                         float(allv[:, 0].max()), float(allv[:, 1].max()))
        else:
            self.bbox = (math.inf, math.inf, -math.inf, -math.inf)

    @property
    def point(self) -> Tuple[float, float]:
        assert self.kind == "POINT"
        return float(self.rings[0][0, 0]), float(self.rings[0][0, 1])


def _parse_ring(text: str) -> np.ndarray:
    pts = []
    for pair in text.split(","):
        xy = pair.strip().split()
        pts.append((float(xy[0]), float(xy[1])))
    return np.asarray(pts, dtype=np.float64)


def parse_wkt(wkt: str) -> Geometry:
    """POINT / POLYGON / MULTIPOLYGON subset of GeoFunctions'
    ST_GeometryFromText surface."""
    cached = _WKT_CACHE.get(wkt)
    if cached is not None:
        return cached
    s = wkt.strip()
    m = re.match(r"(?is)^\s*POINT\s*\(\s*([-\d.eE]+)\s+([-\d.eE]+)\s*\)\s*$", s)
    if m:
        g = Geometry("POINT", [np.asarray([[float(m.group(1)), float(m.group(2))]])], [False])
        _WKT_CACHE[wkt] = g
        return g
    m = re.match(r"(?is)^\s*POLYGON\s*\((.*)\)\s*$", s)
    if m:
        rings, holes = _parse_poly_body(m.group(1))
        g = Geometry("POLYGON", rings, holes)
        _WKT_CACHE[wkt] = g
        return g
    m = re.match(r"(?is)^\s*MULTIPOLYGON\s*\((.*)\)\s*$", s)
    if m:
        body = m.group(1)
        rings: List[np.ndarray] = []
        holes: List[bool] = []
        for poly in _split_top(body):
            poly = poly.strip()
            if poly.startswith("("):
                poly = poly[1:-1]
            r, h = _parse_poly_body(poly)
            rings.extend(r)
            holes.extend(h)
        g = Geometry("MULTIPOLYGON", rings, holes)
        _WKT_CACHE[wkt] = g
        return g
    raise ValueError(f"unsupported WKT: {wkt[:40]!r}")


def _split_top(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _parse_poly_body(body: str):
    """'(ring1),(ring2)...' -> rings + hole flags (first ring = shell)."""
    rings, holes = [], []
    for i, ring in enumerate(_split_top(body)):
        ring = ring.strip()
        if ring.startswith("("):
            ring = ring[1:-1]
        rings.append(_parse_ring(ring))
        holes.append(i > 0)
    return rings, holes


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def points_in_geometry(g: Geometry, xs: jax.Array, ys: jax.Array) -> jax.Array:
    """Vectorized point-in-polygon over N probe points: even-odd
    ray casting per ring, XOR of shells and holes (the PIP hot loop of
    the reference's EsriGeometry contains, vectorized).  Boundary
    points follow the even-odd rule's edge convention."""
    if g.kind == "POINT":
        px, py = g.point
        return (xs == px) & (ys == py)
    inside = jnp.zeros(xs.shape[0], dtype=jnp.bool_)
    for ring in g.rings:
        vx = jnp.asarray(ring[:, 0])
        vy = jnp.asarray(ring[:, 1])
        vx2 = jnp.roll(vx, -1)
        vy2 = jnp.roll(vy, -1)
        # edge crosses the horizontal ray at y if one endpoint is above
        # and the other at-or-below; x-intersection right of the point
        cond = (vy[None, :] > ys[:, None]) != (vy2[None, :] > ys[:, None])
        denom = vy2[None, :] - vy[None, :]
        t = jnp.where(cond, (ys[:, None] - vy[None, :]) / jnp.where(denom == 0, 1.0, denom), 0.0)
        xint = vx[None, :] + t * (vx2[None, :] - vx[None, :])
        crossings = jnp.sum((cond & (xint > xs[:, None])).astype(jnp.int32), axis=1)
        inside = inside ^ (crossings % 2 == 1)
    return inside


def point_distance(x1, y1, x2, y2):
    return jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)


def bbox_mask(bbox, xs: jax.Array, ys: jax.Array) -> jax.Array:
    x0, y0, x1, y1 = bbox
    return (xs >= x0) & (xs <= x1) & (ys >= y0) & (ys <= y1)


# ---------------------------------------------------------------------------
# host-side geometry scalar ops (per distinct WKT; dictionary LUT path)
# ---------------------------------------------------------------------------

def st_area(wkt: str) -> float:
    g = parse_wkt(wkt)
    total = 0.0
    for ring, hole in zip(g.rings, g.holes):
        x, y = ring[:, 0], ring[:, 1]
        a = 0.5 * abs(float(np.dot(x, np.roll(y, -1)) - np.dot(np.roll(x, -1), y)))
        total += -a if hole else a
    return total


def st_x(wkt: str) -> Optional[float]:
    g = parse_wkt(wkt)
    return g.point[0] if g.kind == "POINT" else None


def st_y(wkt: str) -> Optional[float]:
    g = parse_wkt(wkt)
    return g.point[1] if g.kind == "POINT" else None


def st_contains_host(outer_wkt: str, inner_wkt: str) -> bool:
    """Host fallback for geometry×geometry containment: inner POINT
    only (the engine's device path covers point probes; polygon-in-
    polygon is out of the v1 subset)."""
    inner = parse_wkt(inner_wkt)
    if inner.kind != "POINT":
        raise ValueError("ST_Contains inner operand must be a POINT")
    g = parse_wkt(outer_wkt)
    x, y = inner.point
    return bool(np.asarray(points_in_geometry(
        g, jnp.asarray([x]), jnp.asarray([y])))[0])


# ---------------------------------------------------------------------------
# spatial join (SpatialJoinOperator + PagesRTreeIndex analog)
# ---------------------------------------------------------------------------

class SpatialIndex:
    """Build-side index: parsed geometries + bboxes.  The R-tree's job
    (reject distant candidates cheaply) is done by the vectorized bbox
    mask; each surviving geometry runs one fused PIP kernel."""

    def __init__(self, wkts: Sequence[str]):
        self.geoms = [parse_wkt(w) if w is not None else None for w in wkts]

    def probe(self, xs: jax.Array, ys: jax.Array) -> List[Tuple[int, jax.Array]]:
        """-> [(build_index, bool mask over probe rows)] for geometries
        with any bbox-candidate points."""
        out = []
        for i, g in enumerate(self.geoms):
            if g is None:
                continue
            cand = bbox_mask(g.bbox, xs, ys)
            hit = cand & points_in_geometry(g, xs, ys)
            out.append((i, hit))
        return out
