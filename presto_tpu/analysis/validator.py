"""Plan/IR validator pass.

Walks a bound logical plan bottom-up, assigns every node a stable
pre-order name (``AggregationNode#2``), and runs every rule in
:mod:`presto_tpu.analysis.rules` against it.  Diagnostics come back as
:class:`Issue` lists; :func:`assert_valid` raises
:class:`PlanValidationError` when any error-severity issue survives —
the form ``EXPLAIN (TYPE VALIDATE)`` and the ``validate_plans``
session property consume.

The walker is defensive by design: a rule (or a node's ``channels``
property) that *crashes* becomes a diagnostic naming the node rather
than an anonymous traceback — the validator's whole purpose is turning
"raw ``KeyError`` three layers deep at execution time" into "node X
violates invariant Y" before any kernel runs.
"""

from __future__ import annotations

from typing import Dict, List

from presto_tpu.analysis.rules import ALL_RULES, Issue
from presto_tpu.planner.plan import PlanNode

__all__ = ["Issue", "PlanValidationError", "validate_plan", "assert_valid"]


class PlanValidationError(Exception):
    """A plan failed static validation; ``issues`` carries the full
    diagnostic list (each naming its node and rule)."""

    def __init__(self, issues: List[Issue]):
        self.issues = list(issues)
        lines = "\n".join(f"  {i}" for i in self.issues)
        super().__init__(
            f"plan failed validation ({len(self.issues)} issue"
            f"{'s' if len(self.issues) != 1 else ''}):\n{lines}")


class _Context:
    """Per-validation memo: stable node names + channel lists (channels
    properties rebuild on every access; UnionNode's merge work should
    run once, and a crashing derivation should crash once)."""

    def __init__(self):
        self._names: Dict[int, str] = {}
        self._channels: Dict[int, list] = {}
        self._chan_errors: Dict[int, Exception] = {}
        self._counter = 0

    def register(self, node: PlanNode) -> str:
        if id(node) not in self._names:
            self._names[id(node)] = f"{type(node).__name__}#{self._counter}"
            self._counter += 1
        return self._names[id(node)]

    def name(self, node: PlanNode) -> str:
        return self._names.get(id(node)) or self.register(node)

    def channels(self, node: PlanNode) -> list:
        key = id(node)
        if key in self._chan_errors:
            return []
        if key not in self._channels:
            try:
                self._channels[key] = list(node.channels)
            except Exception as e:
                self._chan_errors[key] = e
                return []
        return self._channels[key]

    def channel_error(self, node: PlanNode):
        if id(node) not in self._channels and id(node) not in self._chan_errors:
            self.channels(node)
        return self._chan_errors.get(id(node))


def _walk(node: PlanNode, ctx: _Context, seen: set, order: List[PlanNode]):
    if id(node) in seen:
        return
    seen.add(id(node))
    ctx.register(node)
    for s in node.sources:
        _walk(s, ctx, seen, order)
    order.append(node)  # bottom-up: leaves first, diagnostics at cause


def validate_plan(plan: PlanNode) -> List[Issue]:
    """All diagnostics for ``plan``, bottom-up (a broken leaf reports
    before the nodes it confuses downstream)."""
    ctx = _Context()
    order: List[PlanNode] = []
    _walk(plan, ctx, set(), order)
    issues: List[Issue] = []
    for node in order:
        err = ctx.channel_error(node)
        if err is not None:
            issues.append(Issue(
                "type-consistency", ctx.name(node),
                f"channel derivation raised {type(err).__name__}: {err}"))
            continue  # downstream rules would re-crash on the same hole
        for rule in ALL_RULES:
            try:
                issues.extend(rule(node, ctx))
            except Exception as e:  # a crashing rule is itself a finding
                issues.append(Issue(
                    rule.__name__.replace("check_", "").replace("_", "-"),
                    ctx.name(node),
                    f"validator rule crashed: {type(e).__name__}: {e}"))
    return issues


def assert_valid(plan: PlanNode) -> List[Issue]:
    """Raise :class:`PlanValidationError` on any error-severity issue;
    returns the (possibly empty) warning list otherwise."""
    issues = validate_plan(plan)
    errors = [i for i in issues if i.severity == "error"]
    if errors:
        raise PlanValidationError(errors)
    return [i for i in issues if i.severity != "error"]
