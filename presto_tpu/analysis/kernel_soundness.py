"""Expression-tier kernel-soundness checker.

Runs the :mod:`presto_tpu.analysis.ranges` abstract interpreter over
every compiled expression in a bound plan and reports, with node-level
attribution (reusing the validator's stable ``NodeType#k`` names):

``overflow``
    int / short-decimal ops whose *raw* result interval escapes the
    device lane width the kernel computes in (the wrap point the
    reference's checked bytecode raises ARITHMETIC_OVERFLOW at),
    including aggregation accumulators folded over the row-count
    bounds of :func:`analysis.properties.derive_properties` — the
    SF100 ``sum(l_extendedprice * (1 - l_discount))`` class.

``null-policy``
    every scalar kernel family must declare its mask behavior in
    ``expr.compile.NULL_POLICY`` (strict / preserving / generating —
    the expression-level analogue of ``rules.NULL_MASK_POLICY``), and
    the declaration must agree with this module's *independent*
    structural model (:func:`ranges.null_effect`).  A kernel that
    nulls lanes its declaration doesn't admit (or an undeclared
    kernel) is an error: downstream mask reasoning would be wrong.

``lossy-cast`` / ``division``
    truncating casts reachable with provably out-of-range intervals,
    and divisions whose divisor interval contains zero (lanes NULL at
    runtime where the reference raises DIVISION_BY_ZERO; a *literal*
    zero divisor is an error, a possible one is a warning).

Severity discipline: a finding is an **error** only when backed by
evidence (``AbstractValue.known`` — literals, VALUES rows, zone-map
domains, known row bounds); type-contract-only escapes surface as
warnings at aggregation folds and are silent elsewhere (every int64
add "may" overflow by type bounds alone — flagging that would bury
the real findings).  ``assert_kernel_sound`` raises only on errors, so
the TPC-H/TPC-DS corpus gate stays clean while still proving the
evidence-backed cases.

The same channel-interval propagation feeds the runtime cross-check:
``PRESTO_TPU_RANGE_SANITIZER=1`` (exec/local.py) samples observed
column min/max at page boundaries and fails loudly when a value
escapes its predicted interval — transfer functions must be sound,
not just plausible.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from presto_tpu.analysis import ranges
from presto_tpu.analysis.ranges import AbstractValue, eval_expr, top
from presto_tpu.analysis.rules import Issue, _node_exprs, _walk_exprs
from presto_tpu.analysis.validator import _Context, _walk
from presto_tpu.expr.ir import AggCall, Call, ColumnRef, Expr
from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)

__all__ = [
    "KernelSoundnessError",
    "analyze_kernels",
    "assert_kernel_sound",
    "predicted_intervals",
]

_I64_MAX = (1 << 63) - 1

#: to_sum_limbs splits each (hi, lo) pair into 4 base-1e9 digits whose
#: per-digit segment sums stay < 2^63 for ~9.2e9 addends (see
#: ops/decimal128.to_sum_limbs) — row bounds beyond this make even the
#: limb accumulator suspect
_LIMB_SAFE_ROWS = 9_200_000_000


class KernelSoundnessError(Exception):
    """A plan failed kernel-soundness analysis; ``issues`` carries the
    error-severity findings (each naming its node and checker)."""

    def __init__(self, issues: List[Issue]):
        self.issues = list(issues)
        lines = "\n".join(f"  {i}" for i in self.issues)
        super().__init__(
            f"plan failed kernel-soundness analysis ({len(self.issues)} "
            f"issue{'s' if len(self.issues) != 1 else ''}):\n{lines}")


# ---------------------------------------------------------------------------
# channel-interval propagation (bottom-up over the plan DAG)
# ---------------------------------------------------------------------------

def _scan_values(node: TableScanNode, ctx: _Context) -> List[AbstractValue]:
    out = [ranges.channel_value_of_channel(c) for c in ctx.channels(node)]
    if not node.constraints:
        return out
    by_name = {c.name: i for i, c in enumerate(ctx.channels(node))}
    for col, op, v in node.constraints:
        i = by_name.get(col)
        if i is None:
            continue
        a = out[i]
        # a pushed-down conjunct is evidence: surviving rows satisfy it
        if op == "eq":
            out[i] = AbstractValue(v, v, may_null=False, known=True)
        elif op in ("lt", "le"):
            out[i] = AbstractValue(a.lo, min(a.hi, v), may_null=False,
                                   known=True)
        elif op in ("gt", "ge"):
            out[i] = AbstractValue(max(a.lo, v), a.hi, may_null=False,
                                   known=True)
    return out


def _values_values(node: ValuesNode) -> List[AbstractValue]:
    out = []
    for j, t in enumerate(node.types):
        cells = [r[j] for r in node.rows]
        nums = [c for c in cells if isinstance(c, (int, float))
                and not isinstance(c, bool)]
        has_null = any(c is None for c in cells)
        if nums and len(nums) + sum(c is None for c in cells) == len(cells) \
                and t.value_shape == ():
            out.append(AbstractValue(min(nums), max(nums),
                                     may_null=has_null, known=True))
        else:
            out.append(top(t))
    return out


def _agg_output_values(node: AggregationNode, env: List[AbstractValue],
                       ctx: _Context) -> List[AbstractValue]:
    from presto_tpu.analysis.properties import derive_properties
    from presto_tpu.ops.aggregate import output_type

    keys = [eval_expr(e, env) for e in node.group_exprs]
    try:
        hi_rows = derive_properties(node.source).hi
    except Exception:
        hi_rows = None
    outs = []
    for agg in node.aggs:
        t = output_type(agg)
        if agg.fn in ("count", "count_star"):
            outs.append(AbstractValue(
                0, hi_rows if hi_rows is not None else ranges.INF,
                may_null=False, known=hi_rows is not None))
        elif agg.fn in ("min", "max", "avg", "arbitrary", "any_value") \
                and agg.arg is not None and t.value_shape == ():
            a = eval_expr(agg.arg, env)
            # min/max/avg outputs lie inside the argument interval
            outs.append(AbstractValue(a.lo, a.hi, may_null=True,
                                      known=a.known))
        elif agg.fn in ("sum", "sum0") and t.value_shape == ():
            a = eval_expr(agg.arg, env)
            m = max(abs(a.lo), abs(a.hi))
            bound = ranges.INF if hi_rows is None else m * hi_rows
            outs.append(AbstractValue(-bound, bound, may_null=True,
                                      known=a.known and hi_rows is not None))
        else:
            outs.append(top(t))
    if node.step == "partial":
        # partial layout is keys + state columns; states are checked by
        # the accumulator rule, not propagated as intervals
        return keys + [top(c.type) for c in ctx.channels(node)[len(keys):]]
    return keys + outs


def channel_values(node: PlanNode, ctx: _Context,
                   memo: Dict[int, List[AbstractValue]]) -> List[AbstractValue]:
    """Per-output-channel abstract values of ``node``, id-memoized.

    Sound over-approximation at every node kind; anything without a
    precise rule falls back to the type contract (assumed, which the
    checkers and the sanitizer both skip)."""
    key = id(node)
    if key in memo:
        return memo[key]
    memo[key] = [top(c.type) for c in ctx.channels(node)]  # cycle guard

    if isinstance(node, TableScanNode):
        vals = _scan_values(node, ctx)
    elif isinstance(node, ValuesNode):
        vals = _values_values(node)
    elif isinstance(node, (FilterNode, LimitNode, SortNode, TopNNode,
                           OutputNode)):
        vals = list(channel_values(node.source, ctx, memo))
        if isinstance(node, OutputNode):
            vals = vals[:len(ctx.channels(node))]
    elif isinstance(node, ProjectNode):
        env = channel_values(node.source, ctx, memo)
        vals = [eval_expr(e, env) for e in node.projections]
    elif isinstance(node, AggregationNode):
        env = channel_values(node.source, ctx, memo)
        vals = _agg_output_values(node, env, ctx)
    elif isinstance(node, GroupIdNode):
        env = channel_values(node.source, ctx, memo)
        keys = [eval_expr(e, env) for e in node.key_exprs]
        # replicas mask inactive keys to NULL
        keys = [AbstractValue(k.lo, k.hi, may_null=True, may_nan=k.may_nan,
                              known=k.known) for k in keys]
        gid = AbstractValue(0, max(len(node.set_masks) - 1, 0),
                            may_null=False, known=True)
        vals = list(env) + keys + [gid]
    elif isinstance(node, JoinNode):
        lv = channel_values(node.left, ctx, memo)
        if node.kind in ("semi", "anti"):
            vals = list(lv)
        elif node.kind == "mark":
            vals = list(lv) + [AbstractValue(0, 1, may_null=True, known=True)]
        else:
            rv = channel_values(node.right, ctx, memo)
            # outer joins null the unmatched side; forcing may_null on
            # every output keeps this sound for all kinds
            vals = [AbstractValue(v.lo, v.hi, True, v.may_nan, v.known)
                    for v in lv + rv]
    elif isinstance(node, CrossSingleNode):
        vals = (list(channel_values(node.left, ctx, memo))
                + list(channel_values(node.right, ctx, memo)))
    elif isinstance(node, UnionNode):
        arms = [channel_values(s, ctx, memo) for s in node.inputs]
        n = min(len(a) for a in arms) if arms else 0
        merged_chans = ctx.channels(node)
        vals = []
        for i in range(n):
            t = merged_chans[i].type if i < len(merged_chans) else None
            if t is not None and t.is_string:
                # dictionary merge re-codes: computed code intervals
                # from the arms don't survive; the merged channel's own
                # domain does
                vals.append(ranges.channel_value_of_channel(merged_chans[i]))
            else:
                v = arms[0][i]
                for a in arms[1:]:
                    v = v.join(a[i])
                vals.append(v)
    elif isinstance(node, WindowNode):
        env = channel_values(node.source, ctx, memo)
        vals = list(env) + [top(f.type) for f in node.funcs]
    else:
        vals = memo[key]  # type contract per channel

    # channel-count mismatches (broken plans) fall back to the contract
    chans = ctx.channels(node)
    if len(vals) != len(chans):
        vals = [top(c.type) for c in chans]
    memo[key] = vals
    return vals


# ---------------------------------------------------------------------------
# checkers
# ---------------------------------------------------------------------------

def _fmt_iv(iv: Tuple[float, float]) -> str:
    lo, hi = iv
    return f"[{lo}, {hi}]"


def _check_exprs(node: PlanNode, ctx: _Context,
                 memo: Dict[int, List[AbstractValue]]) -> List[Issue]:
    issues: List[Issue] = []
    name = ctx.name(node)
    for root, src, label in _node_exprs(node):
        env = channel_values(src, ctx, memo)

        def hazard(kind, e, raw, bounds, known, _label=label):
            if kind == "overflow":
                if not known:
                    return  # type-contract-only escape: see module doc
                issues.append(Issue(
                    "overflow", name,
                    f"{_label}: {e.fn} over {e.type!r} can reach "
                    f"{_fmt_iv(raw)}, outside the device lane "
                    f"{_fmt_iv(bounds)} — lanes NULL at runtime "
                    f"(reference raises ARITHMETIC_OVERFLOW)"))
            elif kind == "division":
                sev = "error" if known else "warning"
                issues.append(Issue(
                    "division", name,
                    f"{_label}: {e.fn} divisor interval {_fmt_iv(raw)} "
                    f"contains zero — lanes NULL at runtime (reference "
                    f"raises DIVISION_BY_ZERO)", severity=sev))
            elif kind == "lossy-cast":
                if not known:
                    return
                issues.append(Issue(
                    "lossy-cast", name,
                    f"{_label}: {e.fn} to {e.type!r} reachable with "
                    f"{_fmt_iv(raw)}, outside {_fmt_iv(bounds)} — "
                    f"out-of-range lanes NULL at runtime (reference "
                    f"raises INVALID_CAST_ARGUMENT)"))

        eval_expr(root, env, hazard)
        issues.extend(_check_null_policy(root, name, label))
    return issues


def _check_null_policy(root: Expr, node_name: str, label: str) -> List[Issue]:
    """Cross-check every Call's declared mask behavior against the
    structural model.  Two independently-maintained tables: the kernel
    author declares (expr.compile.NULL_POLICY), the analyzer models
    (ranges.null_effect); disagreement or a missing declaration is an
    error with node attribution."""
    from presto_tpu.expr.compile import NULL_POLICY

    issues: List[Issue] = []
    seen = set()
    for e, _in_lambda in _walk_exprs(root):
        if not isinstance(e, Call) or e.fn in seen:
            continue
        seen.add(e.fn)
        declared = NULL_POLICY.get(e.fn)
        modeled = ranges.null_effect(e.fn)
        if declared is None:
            issues.append(Issue(
                "null-policy", node_name,
                f"{label}: kernel '{e.fn}' declares no null policy "
                f"(expr.compile.NULL_POLICY); model says '{modeled}'"))
        elif declared != modeled:
            issues.append(Issue(
                "null-policy", node_name,
                f"{label}: kernel '{e.fn}' declares null policy "
                f"'{declared}' but the structural model derives "
                f"'{modeled}' — masks would not flow as declared"))
    return issues


def _check_accumulators(node: AggregationNode, ctx: _Context,
                        memo: Dict[int, List[AbstractValue]]) -> List[Issue]:
    """Fold each sum/avg accumulator's per-row interval over the
    subtree's row-count bound; an int64-lane state that can escape 2^63
    is the silent-wrap class the reference's checked accumulators
    raise on."""
    from presto_tpu.analysis.properties import derive_properties
    from presto_tpu.ops.aggregate import state_types

    if node.step == "final":
        return []  # the partial stage below already checked the fold
    issues: List[Issue] = []
    env = channel_values(node.source, ctx, memo)
    try:
        hi_rows = derive_properties(node.source).hi
    except Exception:
        hi_rows = None
    for i, agg in enumerate(node.aggs):
        if agg.fn not in ("sum", "sum0", "avg"):
            continue
        try:
            st = state_types(agg)[0]
        except Exception:
            continue
        if st.name == "double" or st.name.startswith("interval"):
            continue
        label = f"agg[{i}]"
        a = eval_expr(agg.arg, env)
        if st.is_long_decimal:
            # base-1e9 limb accumulation: sound up to ~9.2e9 addends
            if hi_rows is not None and hi_rows > _LIMB_SAFE_ROWS:
                issues.append(Issue(
                    "overflow", ctx.name(node),
                    f"{label}: {agg.fn} limb accumulator is sound to "
                    f"~{_LIMB_SAFE_ROWS} rows but the subtree bound is "
                    f"{hi_rows}", severity="warning"))
            continue
        m = max(abs(a.lo), abs(a.hi))
        worst = ranges.INF if hi_rows is None else m * hi_rows
        if worst <= _I64_MAX:
            continue
        evidence = a.known and hi_rows is not None
        rows_s = "unbounded" if hi_rows is None else str(hi_rows)
        issues.append(Issue(
            "overflow", ctx.name(node),
            f"{label}: {agg.fn} accumulates {agg.arg.type!r} in "
            f"{st!r} (int64 lanes); per-row magnitude ≤ {m} over "
            f"{rows_s} rows can escape 2^63 and wrap silently "
            f"(reference raises ARITHMETIC_OVERFLOW)",
            severity="error" if evidence else "warning"))
    return issues


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_kernels(plan: PlanNode) -> List[Issue]:
    """All kernel-soundness diagnostics for ``plan``, bottom-up."""
    from presto_tpu.obs.metrics import METRICS

    ctx = _Context()
    order: List[PlanNode] = []
    _walk(plan, ctx, set(), order)
    memo: Dict[int, List[AbstractValue]] = {}
    issues: List[Issue] = []
    for node in order:
        if ctx.channel_error(node) is not None:
            continue  # the plan validator owns broken-channel reporting
        try:
            issues.extend(_check_exprs(node, ctx, memo))
            if isinstance(node, AggregationNode):
                issues.extend(_check_accumulators(node, ctx, memo))
        except Exception as e:  # a crashing checker is itself a finding
            issues.append(Issue(
                "kernel-soundness", ctx.name(node),
                f"checker crashed: {type(e).__name__}: {e}"))
    n_over = sum(1 for i in issues if i.rule in ("overflow", "lossy-cast",
                                                 "division"))
    n_null = sum(1 for i in issues if i.rule == "null-policy")
    if n_over:
        METRICS.counter("kernel.overflow_hazards").inc(n_over)
    if n_null:
        METRICS.counter("kernel.null_violations").inc(n_null)
    return issues


def assert_kernel_sound(plan: PlanNode) -> List[Issue]:
    """Raise :class:`KernelSoundnessError` on any error-severity
    finding; return the (possibly empty) warning list otherwise."""
    issues = analyze_kernels(plan)
    errors = [i for i in issues if i.severity == "error"]
    if errors:
        raise KernelSoundnessError(errors)
    return [i for i in issues if i.severity != "error"]


def predicted_intervals(plan: PlanNode) -> Dict[int, List[Optional[Tuple]]]:
    """Per-node predicted output intervals for the runtime range
    sanitizer: ``{id(node): [(lo, hi) | None per channel]}``.  Only
    evidence-backed (``known``) finite intervals of scalar integer-lane
    channels are emitted — those are hard predictions a single escaped
    value falsifies; type-contract intervals can't be escaped and float
    lanes have no wrap point."""
    ctx = _Context()
    order: List[PlanNode] = []
    _walk(plan, ctx, set(), order)
    memo: Dict[int, List[AbstractValue]] = {}
    out: Dict[int, List[Optional[Tuple]]] = {}
    for node in order:
        if ctx.channel_error(node) is not None:
            continue
        vals = channel_values(node, ctx, memo)
        chans = ctx.channels(node)
        preds: List[Optional[Tuple]] = []
        for v, c in zip(vals, chans):
            t = c.type
            if (v.known and v.lo != -ranges.INF and v.hi != ranges.INF
                    and t.value_shape == ()
                    and t.name not in ("double", "real")
                    and ranges.device_int_bounds(t) is not None):
                preds.append((int(v.lo), int(v.hi)))
            else:
                preds.append(None)
        out[id(node)] = preds
    return out
