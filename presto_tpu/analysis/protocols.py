"""Protocol soundness tier: spec automata + runtime conformance.

Four distributed protocols carry this engine's fault-tolerance story —
the token-acked streaming exchange (server/buffers.py +
parallel/streams.py + server/shuffle_client.py), the
ALIVE/SUSPECT/DEAD/RECOVERED failure detector (parallel/failure.py),
the bounded fragment-retry budget with watermark replay
(parallel/multihost.py), and the admission ticket lifecycle
(serving/admission.py).  Their single-threaded behavior is pinned by
unit tests; their *interleavings* are exactly what ROADMAP item 5
(dynamic membership, straggler speculation) will stress.

This module is the spec half of the tier:

- **Spec automata** — one acceptor per protocol
  (:class:`ExchangeAutomaton`, :class:`DetectorAutomaton`,
  :class:`RetryAutomaton`, :class:`AdmissionAutomaton`) consuming the
  protocol's event vocabulary and flagging violations of the *named
  invariant catalog* (the ``INV_*`` constants below).  The same
  acceptors serve two masters: the bounded schedule explorer
  (analysis/mcheck.py) checks every interleaving it enumerates, and
  the runtime conformance half checks event traces recorded from the
  real implementation — so spec and implementation cannot drift.

- **Runtime recorder** — :data:`RECORDER`, the protocol twin of
  ``sync.WATCHER``: emission sites in the real code are one
  ``RECORDER.enabled`` attribute read when tracing is off (the
  production default), and append cheap event tuples when
  ``PRESTO_TPU_PROTOCOL_TRACE=1`` (or :func:`set_protocol_trace`)
  arms them.  :func:`check_trace` replays the recorded events through
  the spec automata; ``tools/protocol_check.py`` does exactly that
  after a real 2-worker faulted run and fails CI on any rejection.

Inspired by stateless model checking with dynamic partial-order
reduction (Flanagan & Godefroid) and FoundationDB-style deterministic
simulation: the explorer proves the spec's invariants over bounded
schedules, the conformance half proves the implementation speaks the
spec's language.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from presto_tpu.envflag import EnvFlag

# ---------------------------------------------------------------------------
# the named invariant catalog (docs/static-analysis.md "Protocol
# soundness"); explorer counterexamples and conformance rejections both
# cite these names, and the seeded-mutation tests assert on them
# ---------------------------------------------------------------------------

#: each page sequence number reaches the consumer at most once
INV_AT_MOST_ONCE = "exchange.at-most-once-delivery"
#: the acked watermark never regresses, and only served tokens ack
INV_ACK_MONOTONIC = "exchange.ack-monotonic"
#: the server never re-serves a token below the acked watermark
INV_NO_REPLAY_PAST_ACK = "exchange.no-replay-past-ack"
#: a GET serves only pages that were actually enqueued, in order
INV_SERVE_BOUNDS = "exchange.serve-within-produced"
#: the consumer's delivered pages are exactly the canonical prefix
#: 0,1,2,... — replayed incarnations must re-produce the same prefix
INV_REPLAY_PREFIX = "exchange.replay-prefix-equality"
#: aborting a drained, complete stream (or aborting twice) is a no-op:
#: the abort-after-final-ack race must not retroactively fail a query
INV_ABORT_DRAINED = "exchange.abort-after-drain-noop"

#: detector edges come only from the reference state machine
INV_DET_EDGE = "detector.legal-edge"
#: DEAD -> RECOVERED requires recover_after consecutive successes
INV_DET_RECOVER_GATE = "detector.recover-after-gate"
#: fragments are never assigned to a DEAD worker
INV_DET_NO_DEAD_SCHEDULE = "detector.no-dead-schedule"

#: per-stage fragment retries never exceed the configured budget
INV_RETRY_BUDGET = "retry.budget-bounded"
#: a replayed fragment skips exactly its delivered watermark
INV_RETRY_PREFIX = "retry.replay-prefix-equality"
#: coordinator-local fallback only when no survivor or budget spent
INV_RETRY_LOCAL = "retry.local-only-when-spent"

#: tickets move QUEUED -> ADMITTED -> RELEASED (or one terminal
#: rejection/cancellation) — never skip, repeat, or resurrect
INV_ADM_LIFECYCLE = "admission.ticket-lifecycle"
#: running + queued + resolved == issued, and slots track admissions
INV_ADM_SLOTS = "admission.slot-conservation"
#: no admit while projected headroom is negative (unless idle-pool)
INV_ADM_HEADROOM = "admission.headroom-nonnegative"
#: a ticket canceled before the admit decision never admits
INV_ADM_CANCEL = "admission.no-admit-after-cancel"

ALL_INVARIANTS = frozenset({
    INV_AT_MOST_ONCE, INV_ACK_MONOTONIC, INV_NO_REPLAY_PAST_ACK,
    INV_SERVE_BOUNDS, INV_REPLAY_PREFIX, INV_ABORT_DRAINED,
    INV_DET_EDGE, INV_DET_RECOVER_GATE, INV_DET_NO_DEAD_SCHEDULE,
    INV_RETRY_BUDGET, INV_RETRY_PREFIX, INV_RETRY_LOCAL,
    INV_ADM_LIFECYCLE, INV_ADM_SLOTS, INV_ADM_HEADROOM, INV_ADM_CANCEL,
})


class ProtocolEvent(NamedTuple):
    """One recorded protocol action.  ``protocol`` selects the
    automaton, ``key`` the instance (one automaton run per key), and
    ``fields`` carries the action's observed arguments."""

    seq: int
    protocol: str       # "exchange" | "detector" | "retry" | "admission"
    key: str            # instance identity (buffer id, worker uri, ...)
    action: str
    fields: tuple       # sorted (name, value) pairs — hashable

    def get(self, name: str, default=None):
        for k, v in self.fields:
            if k == name:
                return v
        return default


class Violation(NamedTuple):
    invariant: str
    key: str
    seq: int            # event sequence number that tripped the check
    message: str

    def __str__(self) -> str:
        return f"[{self.invariant}] {self.key} @#{self.seq}: {self.message}"


# ---------------------------------------------------------------------------
# spec automata
# ---------------------------------------------------------------------------

class _Automaton:
    """Base acceptor: feeds events to per-action ``on_<action>``
    handlers; unknown actions are conformance rejections (the spec's
    vocabulary is closed)."""

    def __init__(self, key: str):
        self.key = key
        self.violations: List[Violation] = []

    def flag(self, invariant: str, seq: int, message: str) -> None:
        self.violations.append(Violation(invariant, self.key, seq, message))

    def step(self, ev: ProtocolEvent) -> None:
        handler = getattr(self, f"on_{ev.action}", None)
        if handler is None:
            self.flag("protocol.unknown-action", ev.seq,
                      f"spec automaton has no action {ev.action!r}")
            return
        handler(ev)


class ExchangeAutomaton(_Automaton):
    """Token/ack/abort acceptor for ONE buffer or pull stream.

    Server-side events (TaskOutputBuffer): ``enqueue(seq)``,
    ``complete``, ``fail``, ``get(token, served_to, done)``,
    ``ack(token, acked)``, ``abort(changed, drained)``.

    Client-side events (shuffle_client / multihost pullers):
    ``recv(token, next, done)`` — a response arrival, possibly a
    duplicate (network artifact, acceptable) — and ``deliver(seq)``,
    a page handed to the consumer, which must be exactly-once and in
    canonical order no matter how delivery raced or replayed.
    """

    def __init__(self, key: str):
        super().__init__(key)
        self.produced = 0       # pages enqueued (server side)
        self.acked = 0          # acked watermark
        self.max_served = 0     # highest token ever served by a get
        self.complete = False
        self.aborted = False
        self.next_deliver = 0   # consumer's canonical next sequence

    # -- server side --------------------------------------------------------
    def on_enqueue(self, ev: ProtocolEvent) -> None:
        seq = ev.get("seq", self.produced)
        if self.aborted:
            self.flag(INV_SERVE_BOUNDS, ev.seq,
                      "enqueue on an aborted buffer")
        if seq != self.produced:
            self.flag(INV_SERVE_BOUNDS, ev.seq,
                      f"page enqueued at {seq}, expected {self.produced} "
                      "(pages must append in token order)")
        self.produced = max(self.produced, seq + 1)

    def on_complete(self, ev: ProtocolEvent) -> None:
        self.complete = True

    def on_fail(self, ev: ProtocolEvent) -> None:
        self.complete = True

    def on_get(self, ev: ProtocolEvent) -> None:
        token = ev.get("token", 0)
        served_to = ev.get("served_to", token)
        done = bool(ev.get("done", False))
        if token < self.acked:
            self.flag(INV_NO_REPLAY_PAST_ACK, ev.seq,
                      f"get at token {token} below acked watermark "
                      f"{self.acked}")
        if served_to < token or served_to > self.produced:
            self.flag(INV_SERVE_BOUNDS, ev.seq,
                      f"get served [{token}, {served_to}) with only "
                      f"{self.produced} pages produced")
        if done and (not self.complete or served_to < self.produced):
            self.flag(INV_SERVE_BOUNDS, ev.seq,
                      "done=True before the producer completed or with "
                      f"unserved pages ({served_to} < {self.produced})")
        self.max_served = max(self.max_served, served_to)

    def on_ack(self, ev: ProtocolEvent) -> None:
        token = ev.get("token", 0)
        acked = ev.get("acked", token)
        if acked < self.acked:
            self.flag(INV_ACK_MONOTONIC, ev.seq,
                      f"acked watermark regressed {self.acked} -> {acked}")
        if token > self.max_served and token > self.produced:
            self.flag(INV_ACK_MONOTONIC, ev.seq,
                      f"ack of unserved token {token} "
                      f"(max served {self.max_served})")
        self.acked = max(self.acked, acked)

    def on_abort(self, ev: ProtocolEvent) -> None:
        changed = bool(ev.get("changed", True))
        drained = bool(ev.get("drained", False))
        if changed and self.aborted:
            self.flag(INV_ABORT_DRAINED, ev.seq,
                      "second abort was not a no-op")
        if changed and drained:
            self.flag(INV_ABORT_DRAINED, ev.seq,
                      "abort of a drained, complete stream was not a "
                      "no-op (the abort-after-final-ack race)")
        if changed:
            self.aborted = True

    # -- client side --------------------------------------------------------
    def on_recv(self, ev: ProtocolEvent) -> None:
        # response arrivals may duplicate or reorder (network); only
        # what gets DELIVERED is constrained
        pass

    def on_deliver(self, ev: ProtocolEvent) -> None:
        seq = ev.get("seq", -1)
        if seq < self.next_deliver:
            self.flag(INV_AT_MOST_ONCE, ev.seq,
                      f"page {seq} delivered again (consumer already at "
                      f"{self.next_deliver})")
        elif seq > self.next_deliver:
            self.flag(INV_REPLAY_PREFIX, ev.seq,
                      f"delivery gap: got page {seq}, expected "
                      f"{self.next_deliver} (replayed prefix must be "
                      "canonical)")
        self.next_deliver = max(self.next_deliver, seq + 1)

    def on_replay(self, ev: ProtocolEvent) -> None:
        skip = ev.get("skip", 0)
        if skip != self.next_deliver:
            self.flag(INV_RETRY_PREFIX, ev.seq,
                      f"replay skips {skip} pages but the consumer's "
                      f"delivered watermark is {self.next_deliver}")


_ALIVE, _SUSPECT, _DEAD, _RECOVERED = "ALIVE", "SUSPECT", "DEAD", "RECOVERED"

#: the reference edge set (parallel/failure.py's diagram)
_DET_EDGES = frozenset({
    (_ALIVE, _SUSPECT), (_RECOVERED, _SUSPECT),   # failures accumulate
    (_SUSPECT, _DEAD),                            # more failures
    (_DEAD, _RECOVERED),                          # sustained probes
    (_SUSPECT, _ALIVE), (_RECOVERED, _ALIVE),     # success restores
})


class DetectorAutomaton(_Automaton):
    """Failure-detector acceptor for ONE worker.  Events:
    ``watch(suspect_after, dead_after, recover_after)`` (thresholds),
    ``probe_ok`` / ``probe_fail`` (heartbeat outcomes),
    ``transition(old, new)``, and ``assign(state)`` (a fragment was
    scheduled onto this worker while it was in ``state``)."""

    def __init__(self, key: str):
        super().__init__(key)
        self.state = _ALIVE
        self.cf = 0             # consecutive failures
        self.cs = 0             # consecutive successes
        self.suspect_after = 1
        self.dead_after = 3
        self.recover_after = 2

    def on_watch(self, ev: ProtocolEvent) -> None:
        self.suspect_after = ev.get("suspect_after", self.suspect_after)
        self.dead_after = ev.get("dead_after", self.dead_after)
        self.recover_after = ev.get("recover_after", self.recover_after)

    def on_probe_ok(self, ev: ProtocolEvent) -> None:
        self.cf = 0
        self.cs += 1

    def on_probe_fail(self, ev: ProtocolEvent) -> None:
        self.cs = 0
        self.cf += 1

    def on_transition(self, ev: ProtocolEvent) -> None:
        old, new = ev.get("old"), ev.get("new")
        if old != self.state:
            self.flag(INV_DET_EDGE, ev.seq,
                      f"transition from {old} but the spec state is "
                      f"{self.state}")
        if (old, new) not in _DET_EDGES:
            self.flag(INV_DET_EDGE, ev.seq,
                      f"illegal detector edge {old} -> {new}")
        elif new == _SUSPECT and self.cf < self.suspect_after:
            self.flag(INV_DET_EDGE, ev.seq,
                      f"-> SUSPECT after {self.cf} failures "
                      f"(suspect_after={self.suspect_after})")
        elif new == _DEAD and self.cf < self.dead_after:
            self.flag(INV_DET_EDGE, ev.seq,
                      f"-> DEAD after {self.cf} failures "
                      f"(dead_after={self.dead_after})")
        elif old == _DEAD and self.cs < self.recover_after:
            self.flag(INV_DET_RECOVER_GATE, ev.seq,
                      f"re-admitted after {self.cs} consecutive "
                      f"successes (recover_after={self.recover_after})")
        self.state = new

    def on_assign(self, ev: ProtocolEvent) -> None:
        state = ev.get("state", self.state)
        if state == _DEAD or self.state == _DEAD:
            self.flag(INV_DET_NO_DEAD_SCHEDULE, ev.seq,
                      "fragment assigned to a DEAD worker")


class RetryAutomaton(_Automaton):
    """Fragment-retry acceptor for ONE failover drain.  Events:
    ``begin(budget)``, ``retry(used)``, ``local(survivors,
    budget_left)``."""

    def __init__(self, key: str):
        super().__init__(key)
        self.budget: Optional[int] = None
        self.used = 0

    def on_begin(self, ev: ProtocolEvent) -> None:
        self.budget = ev.get("budget", 0)

    def on_retry(self, ev: ProtocolEvent) -> None:
        self.used += 1
        if self.budget is not None and self.used > self.budget:
            self.flag(INV_RETRY_BUDGET, ev.seq,
                      f"{self.used} retries exceed the stage budget "
                      f"{self.budget}")

    def on_local(self, ev: ProtocolEvent) -> None:
        survivors = ev.get("survivors", 0)
        budget_left = ev.get("budget_left", 0)
        if survivors > 0 and budget_left > 0:
            self.flag(INV_RETRY_LOCAL, ev.seq,
                      f"coordinator-local fallback with {survivors} "
                      f"survivors and {budget_left} retries left")


class AdmissionAutomaton(_Automaton):
    """Admission-lifecycle acceptor for ONE controller.  Events carry
    ``qid``; the automaton books every ticket: ``queued``,
    ``admitted(reserved, inflight, need, cap, idle)``,
    ``rejected(reason)``, ``cancel``, ``released``."""

    QUEUED, ADMITTED, DONE = "QUEUED", "ADMITTED", "DONE"

    def __init__(self, key: str):
        super().__init__(key)
        self.tickets: Dict[str, str] = {}
        self.canceled: Dict[str, bool] = {}
        self.issued = 0
        self.resolved = 0

    def _conserved(self, ev: ProtocolEvent) -> None:
        running = sum(1 for s in self.tickets.values() if s == self.ADMITTED)
        queued = sum(1 for s in self.tickets.values() if s == self.QUEUED)
        if running + queued + self.resolved != self.issued:
            self.flag(INV_ADM_SLOTS, ev.seq,
                      f"slot books diverged: running={running} "
                      f"queued={queued} resolved={self.resolved} "
                      f"issued={self.issued}")

    def on_queued(self, ev: ProtocolEvent) -> None:
        qid = ev.get("qid")
        if self.tickets.get(qid) is not None:
            self.flag(INV_ADM_LIFECYCLE, ev.seq,
                      f"ticket {qid} queued twice")
            return
        self.tickets[qid] = self.QUEUED
        self.issued += 1
        self._conserved(ev)

    def on_admitted(self, ev: ProtocolEvent) -> None:
        qid = ev.get("qid")
        if self.tickets.get(qid) != self.QUEUED:
            self.flag(INV_ADM_LIFECYCLE, ev.seq,
                      f"ticket {qid} admitted from state "
                      f"{self.tickets.get(qid)!r} (must be QUEUED)")
        if self.canceled.get(qid):
            self.flag(INV_ADM_CANCEL, ev.seq,
                      f"ticket {qid} admitted after cancellation")
        cap = ev.get("cap")
        if cap is not None and not ev.get("idle", False):
            reserved = ev.get("reserved", 0)
            inflight = ev.get("inflight", 0)
            need = ev.get("need", 0)
            if reserved + inflight + need > cap:
                self.flag(INV_ADM_HEADROOM, ev.seq,
                          f"admitted {qid} with negative projected "
                          f"headroom ({reserved} reserved + {inflight} "
                          f"inflight + {need} needed > {cap})")
        self.tickets[qid] = self.ADMITTED
        self._conserved(ev)

    def on_rejected(self, ev: ProtocolEvent) -> None:
        qid = ev.get("qid")
        if self.tickets.get(qid) != self.QUEUED:
            self.flag(INV_ADM_LIFECYCLE, ev.seq,
                      f"ticket {qid} rejected from state "
                      f"{self.tickets.get(qid)!r} (must be QUEUED)")
        self.tickets[qid] = self.DONE
        self.resolved += 1
        self._conserved(ev)

    def on_cancel(self, ev: ProtocolEvent) -> None:
        self.canceled[ev.get("qid")] = True

    def on_released(self, ev: ProtocolEvent) -> None:
        qid = ev.get("qid")
        if self.tickets.get(qid) != self.ADMITTED:
            self.flag(INV_ADM_LIFECYCLE, ev.seq,
                      f"ticket {qid} released from state "
                      f"{self.tickets.get(qid)!r} (must be ADMITTED — "
                      "release is exactly-once)")
        self.tickets[qid] = self.DONE
        self.resolved += 1
        self._conserved(ev)


AUTOMATA: Dict[str, Callable[[str], _Automaton]] = {
    "exchange": ExchangeAutomaton,
    "detector": DetectorAutomaton,
    "retry": RetryAutomaton,
    "admission": AdmissionAutomaton,
}


def check_trace(events) -> List[Violation]:
    """Replay recorded events through the spec automata — one
    automaton instance per (protocol, key) — and return every
    violation.  The runtime-conformance entry point
    (tools/protocol_check.py and the conformance tests)."""
    runs: Dict[Tuple[str, str], _Automaton] = {}
    for ev in events:
        make = AUTOMATA.get(ev.protocol)
        if make is None:
            continue
        a = runs.get((ev.protocol, ev.key))
        if a is None:
            a = runs[(ev.protocol, ev.key)] = make(ev.key)
        a.step(ev)
    out: List[Violation] = []
    for a in runs.values():
        out.extend(a.violations)
    out.sort(key=lambda v: v.seq)
    return out


# ---------------------------------------------------------------------------
# runtime recorder (the sync.WATCHER idiom: inert by default, one
# attribute read per emission site when off)
# ---------------------------------------------------------------------------

_PROTOCOL_TRACE = EnvFlag("PRESTO_TPU_PROTOCOL_TRACE", default=False)


def protocol_trace_enabled() -> bool:
    return _PROTOCOL_TRACE()


class ProtocolRecorder:
    """Process-global, bounded protocol event log.  Emission sites in
    the real code guard on the ``enabled`` attribute (a plain read —
    the production fast path) and call :meth:`record` only when a
    conformance run armed tracing.  The recorder's own lock is a bare
    ``threading.Lock`` and the record path never calls out, so it is
    safe to emit while holding any engine lock (event order then
    matches the critical-section order the automata assume)."""

    #: hard cap — a runaway workload degrades to a truncated (and
    #: reported) trace instead of unbounded memory
    MAX_EVENTS = 500_000

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[ProtocolEvent] = []
        self._seq = 0
        self.dropped = 0
        self.enabled = _PROTOCOL_TRACE()

    def record(self, protocol: str, key: str, action: str, **fields) -> None:
        with self._lock:
            self._seq += 1
            if len(self._events) >= self.MAX_EVENTS:
                self.dropped += 1
                return
            self._events.append(ProtocolEvent(
                self._seq, protocol, key, action,
                tuple(sorted(fields.items()))))

    def events(self) -> List[ProtocolEvent]:
        with self._lock:
            return list(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self.dropped = 0

    def check(self) -> List[Violation]:
        """Conformance verdict over everything recorded so far."""
        return check_trace(self.events())


#: the process-wide recorder every emission site consults
RECORDER = ProtocolRecorder()


def set_protocol_trace(value: Optional[bool]) -> None:
    """Test/tool override (``None`` re-resolves from the environment).
    Unlike the lock sanitizer this flips LIVE: emission sites re-read
    ``RECORDER.enabled`` on every pass, so no reconstruction window
    exists."""
    _PROTOCOL_TRACE.set(value)
    RECORDER.enabled = _PROTOCOL_TRACE()
