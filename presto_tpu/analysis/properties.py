"""Logical-property derivation for plan subtrees.

Reference analog: the property framework behind
``sql/planner/optimizations/`` (LogicalPropertiesProviderImpl /
StreamPropertyDerivations / LocalProperties) — the facts an optimizer
rule is allowed to rely on and therefore must not destroy.  Derived
bottom-up for any subtree, id-memoized across a DAG:

- **schema**: output channel names + types (positional; a rewrite that
  drops/retypes a channel breaks every consumer above it);
- **keys**: sets of output channel indices whose tuples are provably
  unique (``iterative._provably_distinct`` generalized to per-node
  propagation: scan primary keys, grouped-aggregation keys, survival
  through filters/limits/1:1 joins, remapping through ColumnRef
  projections).  A relation with at most one row carries the universal
  key ``frozenset()``;
- **ordering**: sort keys guaranteed on the output stream, each
  canonicalized by inlining through projection chains below the sort
  so the same physical ordering compares equal across rewrites;
- **row bounds**: ``[lo, hi]`` plus ``exact`` when the cardinality is
  statically known (Values, Limit over known input, zero-Sample);
- **determinism**: whether any expression in the subtree calls a
  nondeterministic function, and how many such call sites exist (a
  rewrite that *duplicates* a ``random()`` changes semantics even
  though each copy is "equally nondeterministic").

The per-rewrite checkers in ``analysis/soundness.py`` compare these
properties across a ``Rule.apply`` — see that module for the gate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

from presto_tpu.expr.ir import AggCall, Call, ColumnRef, Expr, LambdaExpr
from presto_tpu.planner.iterative import _NONDETERMINISTIC, _subst
from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    ValuesNode,
    WindowNode,
)

#: one guaranteed sort key: (canonical expression, ascending,
#: nulls_first — None when the node didn't specify)
OrderingKey = Tuple[str, bool, Optional[bool]]


@dataclasses.dataclass
class LogicalProperties:
    """Facts about one subtree's output, derived bottom-up."""

    names: Tuple[str, ...]
    types: Tuple[object, ...]
    #: each member is a set of output channel indices forming a unique
    #: key; ``frozenset()`` is the universal key (at most one row)
    keys: FrozenSet[FrozenSet[int]] = frozenset()
    ordering: Tuple[OrderingKey, ...] = ()
    lo: int = 0
    hi: Optional[int] = None  # None = unbounded
    exact: Optional[int] = None
    #: nondeterministic call sites in the subtree's expressions
    nondet_sites: int = 0

    @property
    def deterministic(self) -> bool:
        return self.nondet_sites == 0

    @property
    def scalar(self) -> bool:
        """At most one output row."""
        return self.exact is not None and self.exact <= 1


def _nondet_sites(e: Optional[Expr]) -> int:
    if isinstance(e, Call):
        own = 1 if e.fn in _NONDETERMINISTIC else 0
        return own + sum(_nondet_sites(a) for a in e.args)
    if isinstance(e, LambdaExpr):
        return _nondet_sites(e.body)
    return 0


def _agg_exprs(a: AggCall) -> List[Expr]:
    return [e for e in (a.arg, a.arg2, a.arg3, a.filter) if e is not None]


def node_exprs(node: PlanNode) -> List[Expr]:
    """Every expression a node evaluates over its sources' channels."""
    if isinstance(node, FilterNode):
        return [node.predicate]
    if isinstance(node, ProjectNode):
        return list(node.projections)
    if isinstance(node, AggregationNode):
        out = list(node.group_exprs)
        for a in node.aggs:
            out.extend(_agg_exprs(a))
        return out
    if isinstance(node, GroupIdNode):
        return list(node.key_exprs)
    if isinstance(node, JoinNode):
        return list(node.left_keys) + list(node.right_keys)
    if isinstance(node, (SortNode, TopNNode)):
        return list(node.sort_exprs)
    if isinstance(node, UnnestNode):
        return list(node.unnest_exprs)
    if isinstance(node, WindowNode):
        out = list(node.partition_exprs) + list(node.order_exprs)
        for f in node.funcs:
            arg = getattr(f, "arg", None)
            if arg is not None:
                out.append(arg)
        return out
    return []


def _canon_sort_key(e: Expr, src: PlanNode) -> str:
    """Canonical form of a sort key: inline through projection chains
    and descend through channel-preserving nodes below ``src`` so the
    same physical ordering yields the same string regardless of where a
    rewrite left the Sort/TopN relative to its projections."""
    while True:
        if isinstance(src, ProjectNode):
            e = _subst(e, list(src.projections))
            src = src.source
        elif isinstance(src, (FilterNode, LimitNode, SortNode, TopNNode)):
            src = src.source
        else:
            return repr(e)


def _ordering_of(node, src: PlanNode) -> Tuple[OrderingKey, ...]:
    nf = node.nulls_first
    return tuple(
        (_canon_sort_key(e, src), bool(asc),
         None if nf is None else bool(nf[i]))
        for i, (e, asc) in enumerate(zip(node.sort_exprs, node.ascending)))


def _remap_keys(keys: FrozenSet[FrozenSet[int]],
                projections: List[Expr]) -> FrozenSet[FrozenSet[int]]:
    """Keys surviving a projection: every member channel must be kept
    by a plain ColumnRef output (renames are fine, computed columns are
    not — uniqueness of f(x) does not follow from uniqueness of x)."""
    outmap: Dict[int, int] = {}
    for j, e in enumerate(projections):
        if isinstance(e, ColumnRef) and e.index not in outmap:
            outmap[e.index] = j
    out = set()
    for k in keys:
        if all(i in outmap for i in k):
            out.add(frozenset(outmap[i] for i in k))
    return frozenset(out)


def _mul(a: Optional[int], b: Optional[int]) -> Optional[int]:
    return None if a is None or b is None else a * b


def _min_opt(a: Optional[int], b: int) -> Optional[int]:
    return b if a is None else min(a, b)


def derive_properties(node: PlanNode,
                      memo: Optional[Dict[int, LogicalProperties]] = None
                      ) -> LogicalProperties:
    """Bottom-up property derivation, id-memoized (plan nodes are
    identity-hashed DAG nodes; shared subtrees derive once per call)."""
    if memo is None:
        memo = {}
    got = memo.get(id(node))
    if got is not None:
        return got
    props = _derive(node, memo)
    if props.scalar:
        # at most one row: universally unique, any ordering holds
        props.keys = props.keys | {frozenset()}
    memo[id(node)] = props
    return props


def _derive(node: PlanNode, memo) -> LogicalProperties:
    ch = node.channels
    names = tuple(c.name for c in ch)
    types = tuple(c.type for c in ch)
    srcs = [derive_properties(s, memo) for s in node.sources]
    nondet = sum(_nondet_sites(e) for e in node_exprs(node)) \
        + sum(s.nondet_sites for s in srcs)
    p = LogicalProperties(names=names, types=types, nondet_sites=nondet)

    if isinstance(node, ValuesNode):
        n = len(node.rows)
        p.lo = p.hi = p.exact = n
        return p

    if isinstance(node, TableScanNode):
        rc = getattr(node.handle, "row_count", None)
        known = isinstance(rc, int) and rc >= 0
        if known:
            p.hi = rc
            if (not node.constraints and node.sample is None
                    and node.splits is None):
                if node.limit is None:
                    p.lo = p.exact = rc
                else:
                    # a limit-annotated scan stops producing splits
                    # once satisfied but still emits at least
                    # min(rc, limit) rows — the Limit above it keeps
                    # its exact count through PushLimitIntoTableScan
                    p.lo = min(rc, node.limit)
        pk = node.handle.primary_key
        if pk:
            sel = [node.handle.columns[i].name for i in node.columns]
            if all(k in sel for k in pk):
                p.keys = frozenset({frozenset(sel.index(k) for k in pk)})
        return p

    if isinstance(node, FilterNode):
        s = srcs[0]
        p.hi = s.hi
        p.exact = 0 if s.hi == 0 else None
        p.keys = s.keys
        p.ordering = s.ordering
        return p

    if isinstance(node, ProjectNode):
        s = srcs[0]
        p.lo, p.hi, p.exact = s.lo, s.hi, s.exact
        p.ordering = s.ordering
        p.keys = _remap_keys(s.keys, list(node.projections))
        return p

    if isinstance(node, OutputNode):
        s = srcs[0]
        p.lo, p.hi, p.exact = s.lo, s.hi, s.exact
        p.ordering = s.ordering
        p.keys = s.keys
        return p

    if isinstance(node, AggregationNode):
        s = srcs[0]
        if not node.group_exprs:
            if node.step in ("single", "final"):
                p.lo = p.hi = p.exact = 1
            # partial global: one state row per split — count unknown
            return p
        p.hi = s.hi
        if node.step in ("single", "final"):
            p.keys = frozenset({frozenset(range(len(node.group_exprs)))})
            if node.step == "single" and s.lo > 0:
                p.lo = 1
        return p

    if isinstance(node, GroupIdNode):
        s = srcs[0]
        n = max(len(node.set_masks), 1)
        p.lo = s.lo * n
        p.hi = _mul(s.hi, n)
        p.exact = _mul(s.exact, n)
        return p

    if isinstance(node, JoinNode):
        left, right = srcs
        if node.kind == "mark":
            # exactly one output row per probe row
            p.lo, p.hi, p.exact = left.lo, left.hi, left.exact
            p.keys = left.keys
            p.ordering = left.ordering
        elif node.kind in ("semi", "anti"):
            p.hi = left.hi
            p.keys = left.keys
            p.ordering = left.ordering
        elif node.kind == "left":
            p.lo = left.lo  # unmatched probes null-extend, never drop
            if node.unique_build:
                p.hi, p.exact = left.hi, left.exact
                p.keys = left.keys
                p.ordering = left.ordering
            else:
                # an empty build still yields one null-extended row per
                # probe row, hence max(right.hi, 1)
                p.hi = (None if left.hi is None or right.hi is None
                        else left.hi * max(right.hi, 1))
        elif node.kind == "inner":
            if node.unique_build:
                p.hi = left.hi
                p.keys = left.keys
            else:
                p.hi = _mul(left.hi, right.hi)
        return p

    if isinstance(node, CrossSingleNode):
        left = srcs[0]
        # the right side is a guaranteed single-row relation
        p.lo, p.hi, p.exact = left.lo, left.hi, left.exact
        p.keys = left.keys
        p.ordering = left.ordering
        return p

    if isinstance(node, UnnestNode):
        s = srcs[0]
        p.hi = _mul(s.hi, node.max_elems)
        return p

    if isinstance(node, SortNode):
        s = srcs[0]
        p.lo, p.hi, p.exact = s.lo, s.hi, s.exact
        p.keys = s.keys
        p.ordering = _ordering_of(node, node.source)
        return p

    if isinstance(node, TopNNode):
        s = srcs[0]
        p.lo = min(s.lo, node.count)
        p.hi = _min_opt(s.hi, node.count)
        if s.exact is not None:
            p.exact = min(s.exact, node.count)
        elif s.lo >= node.count:
            p.exact = node.count
        p.keys = s.keys
        p.ordering = _ordering_of(node, node.source)
        return p

    if isinstance(node, LimitNode):
        s = srcs[0]
        p.lo = min(s.lo, node.count)
        p.hi = _min_opt(s.hi, node.count)
        if s.exact is not None:
            p.exact = min(s.exact, node.count)
        elif s.lo >= node.count:
            p.exact = node.count
        p.keys = s.keys
        p.ordering = s.ordering
        return p

    if isinstance(node, UnionNode):
        p.lo = sum(s.lo for s in srcs)
        hi = 0
        exact: Optional[int] = 0
        for s in srcs:
            hi = None if (hi is None or s.hi is None) else hi + s.hi
            exact = (None if (exact is None or s.exact is None)
                     else exact + s.exact)
        p.hi, p.exact = hi, exact
        return p

    if isinstance(node, WindowNode):
        s = srcs[0]
        p.lo, p.hi, p.exact = s.lo, s.hi, s.exact
        p.keys = s.keys  # channels appended, indices unchanged
        return p

    # RemoteSourceNode, PrecomputedNode, unknown extensions: no claims
    return p
