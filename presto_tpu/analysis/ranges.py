"""Interval × null-state × NaN abstract domain over the expression IR.

Reference analog: the soundness the reference gets from *checked*
bytecode — every generated arithmetic op raises ARITHMETIC_OVERFLOW /
DIVISION_BY_ZERO / INVALID_CAST_ARGUMENT instead of wrapping
(sql/gen/ExpressionCompiler + operator/scalar/*Operators.java).  Our
jnp kernels can't raise from inside a jitted program, so the same
guarantee is split in two: kernels NULL the offending lanes (the
engine's established deviation family, like div-by-zero), and THIS
module proves where that can happen before execution.

:class:`AbstractValue` is one lattice element: a closed interval
``[lo, hi]`` over the *device representation* (scaled ints for short
decimals, epoch days/micros for DATE/TIMESTAMP, dictionary codes for
varchar), a ``may_null`` bit, a ``may_nan`` bit for floats, and a
``known`` evidence bit — True when the interval derives from actual
evidence (literals, connector zone-map domains, VALUES rows), False
when it is merely the type contract.  Checkers only *fail* on known
intervals; assumed ones widen conservatively and surface as warnings
at aggregation folds (see kernel_soundness.py).

Every transfer function here MUST over-approximate its kernel: the
``PRESTO_TPU_RANGE_SANITIZER=1`` runtime cross-check samples observed
column min/max at page boundaries and fails loudly when a value
escapes its predicted interval, so an under-approximating rule is a
caught bug, not a silent soundness hole.

Pure python (no jax import): the analyzer runs at plan time, host-side.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence

from presto_tpu.expr.ir import (
    AggCall,
    Call,
    ColumnRef,
    Expr,
    LambdaExpr,
    LambdaVar,
    Literal,
)
from presto_tpu.types import Type

INF = math.inf

# device-width integer bounds (the wrap points of the jnp kernels —
# distinct from declared SQL bounds: a DECIMAL(12,2) column is stored
# in int64 lanes and physically wraps at I64, not at 10^12)
I8 = (-(1 << 7), (1 << 7) - 1)
I16 = (-(1 << 15), (1 << 15) - 1)
I32 = (-(1 << 31), (1 << 31) - 1)
I64 = (-(1 << 63), (1 << 63) - 1)


@dataclasses.dataclass(frozen=True)
class AbstractValue:
    """One element of the interval × null × nan lattice."""

    lo: float  # -inf = unbounded below (finite bounds stay exact ints)
    hi: float  # +inf = unbounded above
    may_null: bool = True
    may_nan: bool = False
    #: evidence bit: True = derived from literals/stats, False = the
    #: type contract alone (checkers do not fail on assumed intervals)
    known: bool = False

    def join(self, other: "AbstractValue") -> "AbstractValue":
        """Least upper bound (CASE/COALESCE/UNION branches)."""
        return AbstractValue(
            min(self.lo, other.lo), max(self.hi, other.hi),
            self.may_null or other.may_null,
            self.may_nan or other.may_nan,
            self.known and other.known)

    def contains(self, v) -> bool:
        return self.lo <= v <= self.hi


def top(t: Type, may_null: bool = True) -> AbstractValue:
    """The type contract alone (assumed, not evidence)."""
    lo, hi = type_bounds(t)
    return AbstractValue(lo, hi, may_null=may_null,
                         may_nan=t.name in ("double", "real"), known=False)


def type_bounds(t: Type):
    """Representable device-repr bounds of ``t`` (see module doc)."""
    n = t.name
    if n == "boolean":
        return (0, 1)
    if n == "tinyint":
        return I8
    if n == "smallint":
        return I16
    if n in ("integer", "date"):
        return I32
    if t.is_decimal:
        # declared bound, clipped to the storage width: short decimals
        # live in int64 lanes, long/wide in limb vectors that cover p
        m = 10 ** (t.precision or 38) - 1
        if not t.is_long_decimal:
            m = min(m, I64[1])
        return (-m, m)
    if n in ("bigint", "timestamp", "time") or n.startswith("interval"):
        return I64
    if n in ("double", "real"):
        return (-INF, INF)
    if t.is_string and not t.is_raw_string:
        return (0, INF)  # dictionary codes are non-negative
    return (-INF, INF)


def device_int_bounds(t: Type):
    """Where the kernel physically wraps: the int lane width backing
    ``t``, or None for types whose ops don't wrap (floats, limbs)."""
    n = t.name
    if n == "tinyint":
        return I8
    if n == "smallint":
        return I16
    if n in ("integer", "date"):
        return I32
    if t.is_decimal and not t.is_long_decimal:
        return I64
    if n in ("bigint", "timestamp", "time") or n.startswith("interval"):
        return I64
    return None


def from_literal(e: Literal) -> AbstractValue:
    v = e.value
    if v is None:
        return AbstractValue(0, 0, may_null=True, known=True)
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, float):
        if math.isnan(v):
            return AbstractValue(-INF, INF, may_null=False, may_nan=True,
                                 known=True)
        return AbstractValue(v, v, may_null=False, known=True)
    if isinstance(v, int):
        return AbstractValue(v, v, may_null=False, known=True)
    # strings resolve to dictionary codes at compile time — unknown here
    return top(e.type, may_null=False)


def from_channel(t: Type, domain=None) -> AbstractValue:
    """Scan-channel seed: zone-map ``Channel.domain`` is evidence (the
    connector's declared min/max in device repr), the bare type is not."""
    if domain is not None:
        lo, hi = domain
        return AbstractValue(lo, hi, may_null=True, known=True)
    return top(t)


# ---------------------------------------------------------------------------
# None-free interval arithmetic (±inf sentinels, exact ints when finite)
# ---------------------------------------------------------------------------

def _times(x, y):
    # standard interval convention: 0 × ±inf = 0 (the unbounded
    # directions are covered by the other corner products)
    if x == 0 or y == 0:
        return 0
    return x * y


def iv_add(a: AbstractValue, b: AbstractValue):
    return (a.lo + b.lo, a.hi + b.hi)


def iv_sub(a: AbstractValue, b: AbstractValue):
    return (a.lo - b.hi, a.hi - b.lo)


def iv_mul(a: AbstractValue, b: AbstractValue):
    c = [_times(a.lo, b.lo), _times(a.lo, b.hi),
         _times(a.hi, b.lo), _times(a.hi, b.hi)]
    return (min(c), max(c))


def iv_neg(a: AbstractValue):
    return (-a.hi, -a.lo)


def iv_abs(a: AbstractValue):
    if a.lo >= 0:
        return (a.lo, a.hi)
    if a.hi <= 0:
        return (-a.hi, -a.lo)
    return (0, max(-a.lo, a.hi))


def iv_div(a: AbstractValue, b: AbstractValue, trunc: bool):
    """Quotient interval EXCLUDING the zero divisor (those lanes are
    NULLed by the kernel guard; reference raises DIVISION_BY_ZERO)."""
    blo, bhi = b.lo, b.hi
    if blo == 0 and bhi == 0:
        return (0, 0)  # every lane nulls
    # divisor magnitude >= 1 once 0 is excluded (integer/scaled lanes)
    cands = []
    for bb in {blo, bhi, -1 if blo < 0 < bhi or blo == 0 or bhi == 0 else None,
               1 if blo < 0 < bhi or blo == 0 or bhi == 0 else None}:
        if bb is None or bb == 0:
            continue
        for aa in (a.lo, a.hi):
            if aa in (-INF, INF):
                cands.append(-INF if (aa < 0) == (bb > 0) else INF)
            elif bb in (-INF, INF):
                cands.append(0)
            else:
                q = abs(aa) // abs(bb)
                cands.append(-q if (aa < 0) != (bb < 0) else q)
    if not cands:
        return (0, 0)
    return (min(cands), max(cands))


def iv_mod(a: AbstractValue, b: AbstractValue):
    """SQL mod takes the dividend's sign; |r| < |b|."""
    m = max(abs(b.lo), abs(b.hi))
    if m in (0,):
        return (0, 0)
    m = m - 1 if m not in (INF,) else INF
    m = min(m, max(abs(a.lo), abs(a.hi)))
    lo = -m if a.lo < 0 else 0
    hi = m if a.hi > 0 else 0
    return (lo, hi)


# ---------------------------------------------------------------------------
# per-fn transfer catalog
# ---------------------------------------------------------------------------

#: calendar-field output ranges (exact by construction of the civil
#: calendar kernels in expr/compile.py)
_DATEPART_RANGES = {
    "year": (-290308, 294247),  # int64 micros span
    "month": (1, 12), "day": (1, 31), "quarter": (1, 4),
    "day_of_week": (1, 7), "day_of_year": (1, 366),
    "week": (1, 53), "year_of_week": (-290308, 294247),
    "hour": (0, 23), "minute": (0, 59), "second": (0, 59),
    "millisecond": (0, 999),
}

_BOOL_FNS = frozenset({
    "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
    "like", "in", "between", "is_null", "not_null",
    "regexp_like", "starts_with", "ends_with", "is_json_scalar",
    "is_nan", "is_finite", "is_infinite", "contains", "arrays_overlap",
    "any_match", "none_match", "all_match", "st_contains",
})

_UNIT_FRACTION_FNS = frozenset({"rand", "random"})


def _scale_of(t: Type) -> int:
    return t.scale if t.is_decimal else 0


def _rescale_iv(lo, hi, from_scale: int, to_scale: int):
    if to_scale > from_scale:
        f = 10 ** (to_scale - from_scale)
        return (_times(lo, f), _times(hi, f))
    if to_scale < from_scale:
        f = 10 ** (from_scale - to_scale)
        return (-(abs(lo) // f) if lo < 0 else lo // f,
                -(abs(hi) // f) if hi < 0 else hi // f)
    return (lo, hi)


def transfer(fn: str, out_type: Type, args: Sequence[AbstractValue],
             arg_types: Sequence[Type]):
    """Raw (pre-clamp) result interval of ``fn`` plus null/nan bits, as
    an AbstractValue whose interval may ESCAPE ``out_type``'s device
    bounds — the caller compares against :func:`device_int_bounds` to
    flag overflow hazards, then clamps (escaped lanes are NULLed by the
    kernel guards, so in-flight values stay inside the clamp).
    """
    known = all(a.known for a in args) if args else False
    strict_null = any(a.may_null for a in args)
    nan_in = any(a.may_nan for a in args)

    if fn == "try":
        # runtime identity — trapped lanes surface as NULL
        return dataclasses.replace(args[0], may_null=True)

    if fn in _BOOL_FNS:
        # 3VL and/or can absorb NULL (definite false/true resurrects);
        # is_null/not_null never return NULL
        may_null = strict_null and fn not in ("is_null", "not_null")
        return AbstractValue(0, 1, may_null=may_null, known=known)

    if fn in ("add", "sub", "mul", "neg", "abs"):
        a = args[0]
        if fn == "neg":
            lo, hi = iv_neg(a)
        elif fn == "abs":
            lo, hi = iv_abs(a)
        else:
            b = args[1]
            sa, sb = _scale_of(arg_types[0]), _scale_of(arg_types[1])
            so = _scale_of(out_type)
            if fn == "mul":
                lo, hi = iv_mul(a, b)  # scales add: sa+sb == so
            else:
                ra = AbstractValue(*_rescale_iv(a.lo, a.hi, sa, so),
                                   may_null=a.may_null, known=a.known)
                rb = AbstractValue(*_rescale_iv(b.lo, b.hi, sb, so),
                                   may_null=b.may_null, known=b.known)
                lo, hi = iv_add(ra, rb) if fn == "add" else iv_sub(ra, rb)
        return AbstractValue(lo, hi, may_null=strict_null,
                             may_nan=nan_in, known=known)

    if fn == "div":
        if out_type.name in ("double", "real"):
            return AbstractValue(-INF, INF, may_null=True, may_nan=True,
                                 known=False)
        lo, hi = iv_div(args[0], args[1], trunc=True)
        return AbstractValue(lo, hi, may_null=True, known=known)
    if fn == "mod":
        lo, hi = iv_mod(args[0], args[1])
        return AbstractValue(lo, hi, may_null=True, known=known)

    if fn in ("cast_bigint", "cast_smallint", "cast_tinyint"):
        a = args[0]
        t0 = arg_types[0]
        if t0.is_string or t0.name in ("double", "real"):
            # parse/round casts: bounded by the target width only;
            # unparseable strings NULL (documented deviation)
            return AbstractValue(*type_bounds(out_type),
                                 may_null=True, known=False)
        lo, hi = _rescale_iv(a.lo, a.hi, _scale_of(t0), 0)
        if t0.is_decimal:
            # HALF_UP rounding can move one unit away from zero
            lo, hi = lo - 1, hi + 1
        return AbstractValue(lo, hi, may_null=strict_null, known=a.known)
    if fn == "cast_decimal":
        a = args[0]
        t0 = arg_types[0]
        if t0.name in ("double", "real") or t0.is_string:
            return AbstractValue(*type_bounds(out_type),
                                 may_null=strict_null, known=False)
        lo, hi = _rescale_iv(a.lo, a.hi, _scale_of(t0), out_type.scale)
        return AbstractValue(lo, hi, may_null=strict_null, known=a.known)
    if fn in ("cast_double", "to_unixtime"):
        a = args[0]
        s = 10.0 ** _scale_of(arg_types[0]) if arg_types[0].is_decimal else 1.0
        if fn == "to_unixtime":
            s = 1e6 if arg_types[0].name != "date" else 1.0 / 86400.0
        lo = a.lo / s if a.lo not in (-INF, INF) else a.lo
        hi = a.hi / s if a.hi not in (-INF, INF) else a.hi
        return AbstractValue(lo, hi, may_null=strict_null,
                             may_nan=nan_in, known=a.known)
    if fn == "cast_real":
        return AbstractValue(-INF, INF, may_null=strict_null, may_nan=True,
                             known=False)
    if fn in ("cast_date", "cast_timestamp", "cast_time", "from_unixtime",
              "date_trunc", "date_add", "date_add_days", "date_add_months",
              "ts_add_micros", "ts_add_months"):
        # calendar moves: conservative type contract (trunc shrinks,
        # adds shift by data-dependent amounts)
        return AbstractValue(*type_bounds(out_type), may_null=strict_null,
                             known=False)

    if fn in _DATEPART_RANGES:
        lo, hi = _DATEPART_RANGES[fn]
        return AbstractValue(lo, hi, may_null=strict_null, known=True)
    if fn == "last_day_of_month":
        return AbstractValue(*I32, may_null=strict_null, known=False)

    if fn == "sign":
        return AbstractValue(-1, 1, may_null=strict_null, known=True)
    if fn in ("ceil", "ceiling", "floor", "round", "truncate"):
        a = args[0]
        t0 = arg_types[0]
        if t0.is_decimal:
            lo, hi = _rescale_iv(a.lo, a.hi, t0.scale, _scale_of(out_type))
            lo, hi = lo - 1, hi + 1  # rounding slack
            return AbstractValue(lo, hi, may_null=strict_null, known=a.known)
        if t0.name in ("double", "real"):
            return AbstractValue(a.lo - 1, a.hi + 1, may_null=strict_null,
                                 may_nan=nan_in, known=a.known)
        return AbstractValue(a.lo, a.hi, may_null=strict_null, known=a.known)
    if fn == "sqrt":
        return AbstractValue(0, INF, may_null=strict_null, may_nan=True,
                             known=False)
    if fn in ("exp", "cosh"):
        return AbstractValue(0, INF, may_null=strict_null, may_nan=nan_in,
                             known=False)
    if fn in ("sin", "cos", "tanh"):
        return AbstractValue(-1, 1, may_null=strict_null, may_nan=True,
                             known=False)
    if fn in ("asin", "acos", "atan", "atan2"):
        return AbstractValue(-math.pi, math.pi, may_null=strict_null,
                             may_nan=True, known=False)
    if fn in ("ln", "log10", "log2", "cbrt", "tan", "sinh",
              "degrees", "radians", "power", "pow", "nan", "infinity"):
        return AbstractValue(-INF, INF, may_null=strict_null, may_nan=True,
                             known=False)
    if fn == "width_bucket":
        return AbstractValue(0, INF, may_null=strict_null, known=False)

    if fn in ("greatest", "least"):
        lo = (max if fn == "greatest" else min)(a.lo for a in args)
        hi = (max if fn == "greatest" else min)(a.hi for a in args)
        # NULL if ANY argument is NULL (kernel parity)
        return AbstractValue(lo, hi, may_null=strict_null,
                             may_nan=nan_in, known=known)

    if fn == "coalesce":
        out = args[0]
        for a in args[1:]:
            out = out.join(a)
        return AbstractValue(out.lo, out.hi,
                             may_null=all(a.may_null for a in args),
                             may_nan=out.may_nan, known=known)
    if fn == "if":
        # args: cond, then, else?  missing else -> NULL
        branches = list(args[1:]) or [AbstractValue(0, 0, may_null=True)]
        out = branches[0]
        for a in branches[1:]:
            out = out.join(a)
        may_null = (any(a.may_null for a in branches) or len(args) < 3
                    or args[0].may_null)
        return AbstractValue(out.lo, out.hi, may_null=may_null,
                             may_nan=out.may_nan, known=known)
    if fn == "nullif":
        a = args[0]
        return AbstractValue(a.lo, a.hi, may_null=True,
                             may_nan=a.may_nan, known=a.known)

    if fn in ("length", "strpos", "codepoint", "json_array_length",
              "url_extract_port", "levenshtein_distance",
              "hamming_distance", "json_size", "cardinality", "bit_count",
              "from_base", "hll_bucket", "hll_rho"):
        hi = 64 if fn == "bit_count" else INF
        lo, may_null = (0, strict_null)
        if fn in ("json_array_length", "url_extract_port", "from_base",
                  "json_size"):
            may_null = True  # parse failures NULL
        if fn == "from_base":
            lo, hi = I64
        return AbstractValue(lo, hi, may_null=may_null, known=False)

    if fn in ("bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
              "bitwise_shift_left", "bitwise_shift_right",
              "crc32", "xxhash64", "checksum"):
        return AbstractValue(*I64, may_null=strict_null, known=False)

    # default: the output type contract, strict nulls — a sound
    # over-approximation for every remaining scalar kernel
    return AbstractValue(*type_bounds(out_type), may_null=True,
                         may_nan=out_type.name in ("double", "real"),
                         known=False)


# ---------------------------------------------------------------------------
# null-effect model (the analyzer's independent view of each kernel
# family's mask behavior; cross-checked against the declared
# expr.compile.NULL_POLICY table by kernel_soundness.check_null_policy)
# ---------------------------------------------------------------------------

#: kernels that can produce NULL from all-non-NULL inputs (overflow /
#: zero-divisor / parse-failure / out-of-range guards NULL the lane —
#: the engine's documented deviation family where the reference raises)
NULL_GENERATING_FNS = frozenset({
    "add", "sub", "mul", "neg", "abs",       # overflow -> NULL
    "div", "mod",                            # zero divisor -> NULL
    "cast_smallint", "cast_tinyint",         # out-of-range -> NULL
    "cast_bigint", "cast_double",            # varchar parse -> NULL
    "nullif",
    "subscript", "element_at",               # out-of-bounds -> NULL
    "json_extract", "json_extract_scalar", "json_array_length",
    "json_size", "json_parse",
    "url_extract_host", "url_extract_path", "url_extract_port",
    "url_extract_protocol", "url_extract_query", "url_decode",
    "regexp_extract", "from_base", "date_parse", "from_iso8601_date",
    "split_part", "array_min", "array_max", "array_sum", "array_average",
    "reduce", "map_concat", "strpos", "width_bucket", "from_unixtime",
})

#: kernels whose output validity is DERIVED, not intersected: they can
#: return non-NULL from NULL inputs (3VL short-circuits, conditionals,
#: null tests)
NULL_ABSORBING_FNS = frozenset({
    "and", "or", "coalesce", "if", "case",
    "is_null", "not_null",
    # compiles to and(ge, le): the 3VL short-circuit can produce FALSE
    # from a NULL bound when the other comparison already fails
    "between",
})


def null_effect(fn: str) -> str:
    """The model's minimal policy class for ``fn``:
    ``generating`` | ``preserving`` | ``strict``."""
    if fn in NULL_GENERATING_FNS:
        return "generating"
    if fn in NULL_ABSORBING_FNS:
        return "preserving"
    return "strict"


# ---------------------------------------------------------------------------
# expression evaluation
# ---------------------------------------------------------------------------

def eval_expr(e: Expr, env: List[AbstractValue],
              on_hazard: Optional[Callable] = None) -> AbstractValue:
    """Abstract value of ``e`` over per-channel values ``env``.

    ``on_hazard(kind, expr, raw, bounds)`` fires for every device-width
    escape found along the way (``kind`` ∈ {"overflow", "lossy-cast",
    "division"}); the returned value is already clamped to the device
    width (escaped lanes NULL at runtime, so in-flight values can't
    exceed it)."""
    if isinstance(e, Literal):
        return from_literal(e)
    if isinstance(e, ColumnRef):
        if 0 <= e.index < len(env):
            return env[e.index]
        return top(e.type)
    if isinstance(e, LambdaVar):
        return top(e.type)
    if isinstance(e, LambdaExpr):
        if e.body is not None:
            # element lanes are unknown: evaluate the body over TOP so
            # nested hazards (literal div 0 inside a lambda) still fire
            eval_expr(e.body, [], on_hazard)
        return top(e.type)
    if isinstance(e, AggCall):
        for sub in (e.arg, e.arg2, e.arg3, e.filter):
            if sub is not None:
                eval_expr(sub, env, on_hazard)
        return top(e.type)
    if not isinstance(e, Call):
        return top(e.type)

    if e.fn == "try":
        # TRY subtree: the reference returns NULL exactly where our
        # kernels NULL the lane, so trappable escapes beneath are not
        # deviations — evaluate without hazard reporting
        v = eval_expr(e.args[0], env, None)
        return dataclasses.replace(v, may_null=True)

    args = [eval_expr(a, env, on_hazard) for a in e.args]
    arg_types = [a.type for a in e.args]
    raw = transfer(e.fn, e.type, args, arg_types)

    if on_hazard is not None:
        _report_hazards(e, args, arg_types, raw, on_hazard)

    # clamp to the device width: escaped lanes are NULLed by the kernel
    # guards, so downstream propagation stays inside the lane bounds
    dev = device_int_bounds(e.type)
    if dev is not None and (raw.lo < dev[0] or raw.hi > dev[1]):
        raw = AbstractValue(max(raw.lo, dev[0]), min(raw.hi, dev[1]),
                            may_null=True, may_nan=raw.may_nan,
                            known=raw.known)
    return raw


def _report_hazards(e: Call, args, arg_types, raw: AbstractValue,
                    on_hazard) -> None:
    fn = e.fn
    if fn in ("add", "sub", "mul", "neg", "abs"):
        dev = device_int_bounds(e.type)
        if dev is not None and (raw.lo < dev[0] or raw.hi > dev[1]):
            on_hazard("overflow", e, (raw.lo, raw.hi), dev,
                      known=raw.known)
    elif fn in ("div", "mod") and e.type.name not in ("double", "real"):
        b = args[1]
        if b.lo <= 0 <= b.hi:
            on_hazard("division", e, (b.lo, b.hi), (0, 0),
                      known=b.known and b.lo == b.hi == 0)
    elif fn in ("cast_bigint", "cast_smallint", "cast_tinyint",
                "cast_decimal"):
        t0 = arg_types[0]
        if t0.is_string or t0.name in ("double", "real"):
            return
        a = args[0]
        lo, hi = _rescale_iv(a.lo, a.hi, _scale_of(t0),
                             _scale_of(e.type))
        if fn != "cast_decimal":
            lo, hi = lo - 1, hi + 1  # rounding slack
        tb = type_bounds(e.type)
        if lo < tb[0] or hi > tb[1]:
            on_hazard("lossy-cast", e, (lo, hi), tb, known=a.known)


def channel_value_of_channel(ch) -> AbstractValue:
    """Abstract value of one plan-node output channel (planner.plan
    Channel): zone-map domain when present, else the type contract."""
    t = ch.type
    if getattr(ch, "domain", None) is not None and t.value_shape == () \
            and not t.is_raw_string:
        return from_channel(t, ch.domain)
    return top(t)
