"""Bounded schedule explorer over the protocol models.

The model-checking half of the protocol soundness tier
(analysis/protocols.py holds the spec automata + runtime recorder).
Each :class:`Model` is a small-scope abstraction of one real protocol
— the streaming exchange (server/buffers.py + shuffle_client.py), the
failure detector (parallel/failure.py), fragment retry
(parallel/multihost.py), admission (serving/admission.py) — whose
``apply`` checks the named invariants from the shared catalog inline.
:func:`explore` enumerates every interleaving of enabled protocol
actions to a bounded depth, with visited-state dedup plus DPOR-style
sleep sets (Flanagan & Godefroid): when two enabled actions provably
commute *at this state* (applying them in either order reaches the
same abstract state with the same violations), only one order is
explored.  Commutativity is decided semantically and memoized, not
assumed from an independence relation — slower, but sound by
construction for these tiny state spaces.

Counterexamples are replayable: a :class:`Counterexample` carries the
exact action trace, :func:`replay` re-runs it deterministically, and
the regression tests pin the traces the explorer found against the
pre-fix implementation semantics (the ``bugs`` flags below reproduce
each fixed bug in the model so its counterexample stays checkable).

Small-scope sizing: 2-3 pages, 2 fragments, 2 workers, 2-3 queries.
Every interleaving bug this tier targets (duplicate delivery,
ack regression, replay past ack, abort-after-drain, eager re-admit,
budget overspend, off-by-one watermark, headroom race, slot leak,
cancel/admit race) manifests within these bounds — the point of
small-scope model checking is that protocol bugs don't need big
instances, they need the *right interleaving*.
"""

from __future__ import annotations

import random
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Tuple

from presto_tpu.analysis.protocols import (
    INV_ABORT_DRAINED,
    INV_ACK_MONOTONIC,
    INV_ADM_CANCEL,
    INV_ADM_HEADROOM,
    INV_ADM_LIFECYCLE,
    INV_ADM_SLOTS,
    INV_AT_MOST_ONCE,
    INV_DET_EDGE,
    INV_DET_NO_DEAD_SCHEDULE,
    INV_DET_RECOVER_GATE,
    INV_NO_REPLAY_PAST_ACK,
    INV_REPLAY_PREFIX,
    INV_RETRY_BUDGET,
    INV_RETRY_LOCAL,
    INV_RETRY_PREFIX,
)

Action = Tuple  # ("name", arg0, arg1, ...) — hashable, sortable
Fault = Tuple[str, str]  # (invariant name, message)


class Model:
    """A protocol as a small labeled transition system.

    Subclasses define ``initial()``, ``actions(state)`` (enabled
    actions, deterministic order), and ``apply(state, action)`` →
    ``(new_state, faults)`` where faults are ``(invariant, message)``
    pairs for every named invariant the step violates.  States and
    actions must be hashable; ``apply`` must be pure (the explorer
    replays it freely).  ``bugs`` switches on seeded mutations that
    reproduce real (fixed) implementation bugs for the mutation tests.
    """

    name = "model"

    def __init__(self, bugs: FrozenSet[str] = frozenset()):
        self.bugs = frozenset(bugs)

    def initial(self):
        raise NotImplementedError

    def actions(self, state) -> List[Action]:
        raise NotImplementedError

    def apply(self, state, action) -> Tuple[object, List[Fault]]:
        raise NotImplementedError

    def key(self, state):
        return state


class Counterexample(NamedTuple):
    model: str
    trace: Tuple[Action, ...]   # replay(model, trace) reproduces it
    faults: Tuple[Fault, ...]
    seed: int

    def __str__(self) -> str:
        steps = " ; ".join("(" + ",".join(map(str, a)) + ")"
                           for a in self.trace)
        why = "; ".join(f"[{i}] {m}" for i, m in self.faults)
        return f"{self.model} seed={self.seed}: {steps} => {why}"


class ExploreResult(NamedTuple):
    model: str
    states: int                 # distinct abstract states visited
    transitions: int            # apply() steps taken on the main walk
    max_depth: int
    seed: int
    hit_state_cap: bool
    counterexamples: Tuple[Counterexample, ...]

    @property
    def ok(self) -> bool:
        return not self.counterexamples


def replay(model: Model, trace) -> List[Fault]:
    """Deterministically re-run a counterexample trace; returns every
    fault the trace trips (empty ⇒ the model no longer exhibits it)."""
    state = model.initial()
    faults: List[Fault] = []
    for action in trace:
        state, f = model.apply(state, action)
        faults.extend(f)
    return faults


def explore(model: Model, max_depth: int = 14, seed: int = 0,
            max_states: int = 200_000,
            stop_at_first: bool = False) -> ExploreResult:
    """Bounded DFS over every interleaving of enabled actions.

    Dedups on ``model.key(state)`` (re-entering a visited abstract
    state explores nothing new — ``apply`` is pure) and prunes with
    sleep sets: having explored action ``a`` from a state, ``a`` is
    put to sleep for the sibling subtrees of every action that
    commutes with it there, so commuting schedules are enumerated
    once.  ``seed`` shuffles action order deterministically — runs
    with different seeds walk different schedule orders first, which
    is what makes ``stop_at_first`` counterexamples varied yet
    replayable (the trace + seed fully determine the run).
    """
    rng = random.Random(seed)
    visited: Dict[object, int] = {}   # abstract state -> min depth seen
    commute_cache: Dict[Tuple, bool] = {}
    counterexamples: List[Counterexample] = []
    transitions = 0
    hit_cap = False

    def commutes(state, skey, a, b) -> bool:
        ck = (skey, a, b) if a <= b else (skey, b, a)
        hit = commute_cache.get(ck)
        if hit is not None:
            return hit
        try:
            s_ab, f1 = model.apply(state, a)
            s_ab, f2 = model.apply(s_ab, b)
            s_ba, f3 = model.apply(state, b)
            s_ba, f4 = model.apply(s_ba, a)
        except Exception:
            commute_cache[ck] = False
            return False
        ok = (model.key(s_ab) == model.key(s_ba)
              and sorted(f1 + f2) == sorted(f3 + f4))
        commute_cache[ck] = ok
        return ok

    # stack entries: (state, depth, trace, sleep_set)
    stack = [(model.initial(), 0, (), frozenset())]
    while stack:
        state, depth, trace, sleep = stack.pop()
        skey = model.key(state)
        prev = visited.get(skey)
        if prev is not None and prev <= depth:
            continue   # already explored from here with >= remaining depth
        visited[skey] = depth
        if len(visited) >= max_states:
            hit_cap = True
            break
        if depth >= max_depth:
            continue
        enabled = [a for a in model.actions(state) if a not in sleep]
        if seed:
            rng.shuffle(enabled)
        explored: List[Action] = []
        for action in enabled:
            new_state, faults = model.apply(state, action)
            transitions += 1
            new_trace = trace + (action,)
            if faults:
                counterexamples.append(Counterexample(
                    model.name, new_trace, tuple(faults), seed))
                if stop_at_first:
                    return ExploreResult(
                        model.name, len(visited), transitions, max_depth,
                        seed, hit_cap, tuple(counterexamples))
                continue  # don't explore past a violated state
            # sleep-set: siblings already explored that commute with
            # `action` here need not be re-ordered inside its subtree
            child_sleep = frozenset(
                x for x in explored if commutes(state, skey, x, action))
            stack.append((new_state, depth + 1, new_trace, child_sleep))
            explored.append(action)
    return ExploreResult(model.name, len(visited), transitions, max_depth,
                         seed, hit_cap, tuple(counterexamples))


# ---------------------------------------------------------------------------
# 1. streaming exchange: token / ack / abort + client pull
# ---------------------------------------------------------------------------

class _ExState(NamedTuple):
    produced: int
    complete: bool
    aborted: bool
    acked: int
    ctoken: int                 # client's next-token cursor
    next_deliver: int           # consumer's canonical next page seq
    inflight: Tuple[Tuple[int, int, bool], ...]  # (token, next, done)
    acks: Tuple[int, ...]       # ack messages in flight to the server
    client_done: bool
    dups_injected: int


class ExchangeModel(Model):
    """Token-acked exchange with an explicit in-flight network.

    Responses sit in ``inflight`` until a ``recv`` consumes them — the
    schedule chooses WHICH, so delayed/duplicated/reordered responses
    are just interleavings.  Page batch size 1 keeps tokens == seqs.

    Bug flags (each reproduces a fixed implementation bug):

    - ``no_dedupe``   — client yields every page of every response
      without the seq >= cursor check (shuffle_client.pull_pages
      before this PR) → duplicate delivery under dup/reorder.
    - ``ack_regress`` — server applies ``acked = token`` instead of
      ``max(acked, token)`` → watermark regression when two in-flight
      ack messages arrive at the server out of order.
    - ``replay_past_ack`` — client may re-GET an already-acked token
      (no KeyError guard) → replay below the watermark.
    - ``abort_clears_drained`` — abort unconditionally clears state
      (TaskOutputBuffer.abort before this PR) → the
      abort-after-final-ack race retroactively fails a drained query.
    """

    name = "exchange"
    MAX_PAGES = 3
    MAX_INFLIGHT = 2
    MAX_ACKS = 2

    def __init__(self, bugs=frozenset(), faults: bool = True):
        super().__init__(bugs)
        self.faults = faults

    def initial(self):
        return _ExState(0, False, False, 0, 0, 0, (), (), False, 0)

    def actions(self, s: _ExState) -> List[Action]:
        out: List[Action] = []
        if not s.aborted and not s.complete and s.produced < self.MAX_PAGES:
            out.append(("enqueue",))
        if not s.aborted and not s.complete:
            out.append(("complete",))
        if (not s.aborted and not s.client_done
                and len(s.inflight) < self.MAX_INFLIGHT):
            out.append(("request",))
            if "replay_past_ack" in self.bugs and s.acked > 0:
                out.append(("re_get_old",))
        if self.faults and s.inflight and s.dups_injected < 1:
            out.append(("dup_response", 0))
        for i in range(len(s.inflight)):
            out.append(("recv", i, True))
            if self.faults:
                out.append(("recv", i, False))   # ack lost en route
        for i in range(len(s.acks)):
            out.append(("ack_arrive", i))        # any arrival order
        out.append(("abort",))
        return out

    def _serve(self, s: _ExState, token: int):
        faults: List[Fault] = []
        if token < s.acked:
            faults.append((INV_NO_REPLAY_PAST_ACK,
                           f"server served token {token} < acked {s.acked}"))
        nxt = min(token + 1, s.produced) if token < s.produced else token
        done = s.complete and nxt >= s.produced
        return (token, nxt, done), faults

    def apply(self, s: _ExState, action: Action):
        kind = action[0]
        faults: List[Fault] = []
        if kind == "enqueue":
            return s._replace(produced=s.produced + 1), faults
        if kind == "complete":
            return s._replace(complete=True), faults
        if kind == "request":
            resp, faults = self._serve(s, s.ctoken)
            return s._replace(inflight=s.inflight + (resp,)), faults
        if kind == "re_get_old":
            resp, faults = self._serve(s, s.acked - 1)
            return s._replace(inflight=s.inflight + (resp,)), faults
        if kind == "dup_response":
            resp = s.inflight[action[1]]
            return s._replace(inflight=s.inflight + (resp,),
                              dups_injected=s.dups_injected + 1), faults
        if kind == "recv":
            idx, ack_ok = action[1], action[2]
            token, nxt, done = s.inflight[idx]
            inflight = s.inflight[:idx] + s.inflight[idx + 1:]
            next_deliver, ctoken = s.next_deliver, s.ctoken
            for seq in range(token, nxt):
                if "no_dedupe" not in self.bugs and seq < ctoken:
                    continue        # client dedupe: stale page, drop
                if seq < next_deliver:
                    faults.append((INV_AT_MOST_ONCE,
                                   f"page {seq} delivered twice"))
                elif seq > next_deliver:
                    faults.append((INV_REPLAY_PREFIX,
                                   f"gap: delivered {seq}, expected "
                                   f"{next_deliver}"))
                next_deliver = max(next_deliver, seq + 1)
            ctoken = max(ctoken, nxt)
            acks = s.acks
            if ack_ok and len(acks) < self.MAX_ACKS:
                acks = acks + (ctoken,)   # ack rides the network too
            return s._replace(inflight=inflight, ctoken=ctoken,
                              next_deliver=next_deliver, acks=acks,
                              client_done=s.client_done or done), faults
        if kind == "ack_arrive":
            idx = action[1]
            token = s.acks[idx]
            acks = s.acks[:idx] + s.acks[idx + 1:]
            if "ack_regress" in self.bugs:
                if token < s.acked:
                    faults.append((INV_ACK_MONOTONIC,
                                   f"acked regressed {s.acked} -> "
                                   f"{token}"))
                acked = token
            else:
                acked = max(s.acked, token)
            return s._replace(acks=acks, acked=acked), faults
        if kind == "abort":
            drained = s.complete and s.acked >= s.produced
            if "abort_clears_drained" in self.bugs:
                changed = True       # legacy: abort always clears
            else:
                changed = not s.aborted and not drained
            if changed and s.aborted:
                faults.append((INV_ABORT_DRAINED,
                               "second abort was not a no-op"))
            if changed and drained:
                faults.append((INV_ABORT_DRAINED,
                               "abort of a drained stream cleared it"))
            return s._replace(aborted=s.aborted or changed), faults
        raise ValueError(f"unknown action {action!r}")


# ---------------------------------------------------------------------------
# 2. failure detector
# ---------------------------------------------------------------------------

_ALIVE, _SUSPECT, _DEAD, _RECOVERED = "ALIVE", "SUSPECT", "DEAD", "RECOVERED"


class _DetState(NamedTuple):
    state: str
    cf: int
    cs: int


class DetectorModel(Model):
    """One worker under the ALIVE/SUSPECT/DEAD/RECOVERED machine
    (small thresholds: suspect_after=1, dead_after=2, recover_after=2
    — the gates, not the exact production counts, are the invariant).

    Bug flags: ``eager_readmit`` (DEAD -> RECOVERED on the first
    success), ``skip_suspect`` (ALIVE -> DEAD without passing
    SUSPECT), ``schedule_dead`` (fragments assignable to DEAD).
    """

    name = "detector"
    SUSPECT_AFTER, DEAD_AFTER, RECOVER_AFTER = 1, 2, 2

    def initial(self):
        return _DetState(_ALIVE, 0, 0)

    def actions(self, s: _DetState) -> List[Action]:
        out: List[Action] = [("ok",), ("fail",)]
        if s.state != _DEAD or "schedule_dead" in self.bugs:
            out.append(("assign",))
        return out

    def apply(self, s: _DetState, action: Action):
        kind = action[0]
        faults: List[Fault] = []
        if kind == "assign":
            if s.state == _DEAD:
                faults.append((INV_DET_NO_DEAD_SCHEDULE,
                               "fragment assigned to a DEAD worker"))
            return s, faults
        if kind == "ok":
            cf, cs = 0, s.cs + 1
            new = s.state
            if s.state == _DEAD:
                gate = (1 if "eager_readmit" in self.bugs
                        else self.RECOVER_AFTER)
                if cs >= gate:
                    new = _RECOVERED
                    if cs < self.RECOVER_AFTER:
                        faults.append((INV_DET_RECOVER_GATE,
                                       f"re-admitted after {cs} successes"
                                       f" (recover_after="
                                       f"{self.RECOVER_AFTER})"))
            elif s.state in (_SUSPECT, _RECOVERED):
                new = _ALIVE
            return _DetState(new, cf, cs), faults
        if kind == "fail":
            cf, cs = s.cf + 1, 0
            new = s.state
            if s.state in (_ALIVE, _RECOVERED):
                if "skip_suspect" in self.bugs and cf >= self.SUSPECT_AFTER:
                    new = _DEAD
                    faults.append((INV_DET_EDGE,
                                   f"illegal edge {s.state} -> DEAD "
                                   "(must pass SUSPECT)"))
                elif cf >= self.SUSPECT_AFTER:
                    new = _SUSPECT
            elif s.state == _SUSPECT and cf >= self.DEAD_AFTER:
                new = _DEAD
            return _DetState(new, cf, cs), faults
        raise ValueError(f"unknown action {action!r}")


# ---------------------------------------------------------------------------
# 3. fragment retry with watermark replay
# ---------------------------------------------------------------------------

class _Frag(NamedTuple):
    status: str          # "running" | "failed" | "done"
    worker: int          # -1 = coordinator-local
    consumer_next: int   # consumer watermark: next expected page seq
    attempt_pos: int     # next seq the current attempt will emit


class _RetryState(NamedTuple):
    frags: Tuple[_Frag, ...]
    alive: Tuple[bool, ...]
    budget_used: int


class RetryModel(Model):
    """Two fragments on two workers, PAGES pages each, retry budget 1.
    Fragments fail two ways: worker death (``die`` — fragments on the
    worker fail and it leaves the survivor set) and transient stream
    breaks (``break`` — the _StreamBroken path; the worker lives).

    Bug flags: ``overspend`` (redispatch ignores the exhausted
    budget), ``skip_off_by_one`` (replay skips delivered-1 pages, the
    classic watermark off-by-one → one duplicate page), ``eager_local``
    (coordinator-local fallback while survivors and budget remain).
    """

    name = "retry"
    PAGES = 2
    BUDGET = 1

    def initial(self):
        return _RetryState((_Frag("running", 0, 0, 0),
                            _Frag("running", 1, 0, 0)),
                           (True, True), 0)

    def actions(self, s: _RetryState) -> List[Action]:
        out: List[Action] = []
        for i, f in enumerate(s.frags):
            if f.status == "running" and (f.worker < 0 or s.alive[f.worker]):
                out.append(("page", i))
                out.append(("break", i))   # transient stream break
            if f.status == "failed":
                survivors = any(s.alive)
                if survivors and (s.budget_used < self.BUDGET
                                  or "overspend" in self.bugs):
                    out.append(("redispatch", i))
                if (not survivors or s.budget_used >= self.BUDGET
                        or "eager_local" in self.bugs):
                    out.append(("local", i))
        for w, up in enumerate(s.alive):
            if up:
                out.append(("die", w))
        return out

    def _set(self, s: _RetryState, i: int, f: _Frag) -> _RetryState:
        return s._replace(frags=s.frags[:i] + (f,) + s.frags[i + 1:])

    def apply(self, s: _RetryState, action: Action):
        kind = action[0]
        faults: List[Fault] = []
        if kind == "die":
            w = action[1]
            alive = tuple(up and i != w for i, up in enumerate(s.alive))
            frags = tuple(
                f._replace(status="failed") if (f.status == "running"
                                                and f.worker == w) else f
                for f in s.frags)
            return s._replace(frags=frags, alive=alive), faults
        i = action[1]
        f = s.frags[i]
        if kind == "break":
            # stream broke (timeout, reset) but the worker lives on —
            # the _StreamBroken path, distinct from worker death
            return self._set(s, i, f._replace(status="failed")), faults
        if kind == "page":
            seq = f.attempt_pos
            if seq < f.consumer_next:
                faults.append((INV_RETRY_PREFIX,
                               f"fragment {i} re-emitted page {seq} "
                               f"(watermark {f.consumer_next})"))
            elif seq > f.consumer_next:
                faults.append((INV_RETRY_PREFIX,
                               f"fragment {i} skipped to page {seq} "
                               f"(watermark {f.consumer_next})"))
            nxt = max(f.consumer_next, seq + 1)
            done = nxt >= self.PAGES
            return self._set(s, i, f._replace(
                status="done" if done else f.status,
                consumer_next=nxt, attempt_pos=f.attempt_pos + 1)), faults
        if kind == "redispatch":
            if s.budget_used >= self.BUDGET:
                faults.append((INV_RETRY_BUDGET,
                               f"retry {s.budget_used + 1} exceeds "
                               f"budget {self.BUDGET}"))
            skip = f.consumer_next
            if "skip_off_by_one" in self.bugs:
                skip = max(0, skip - 1)
            target = next(w for w, up in enumerate(s.alive) if up)
            return self._set(
                s._replace(budget_used=s.budget_used + 1), i,
                f._replace(status="running", worker=target,
                           attempt_pos=skip)), faults
        if kind == "local":
            survivors = any(s.alive)
            if survivors and s.budget_used < self.BUDGET:
                faults.append((INV_RETRY_LOCAL,
                               "local fallback with survivors and "
                               f"budget left ({self.BUDGET - s.budget_used})"))
            return self._set(s, i, f._replace(
                status="done", worker=-1,
                consumer_next=self.PAGES,
                attempt_pos=self.PAGES)), faults
        raise ValueError(f"unknown action {action!r}")


# ---------------------------------------------------------------------------
# 4. admission ticket lifecycle
# ---------------------------------------------------------------------------

class _Ticket(NamedTuple):
    state: str           # "NONE" | "QUEUED" | "ADMITTED" | "DONE"
    canceled: bool


class _AdmState(NamedTuple):
    tickets: Tuple[_Ticket, ...]
    reserved: int        # committed pool bytes (abstract units)
    inflight: int        # projected bytes of admitted-not-yet-reserved
    issued: int
    resolved: int


class AdmissionModel(Model):
    """Two queries, each needing NEED of CAP pool units (two can't
    both fit), one admission slot semantics via the headroom check.

    Bug flags: ``headroom_race`` (admit gate ignores
    inflight-projected bytes — the double-admit race), ``slot_leak``
    (finish forgets to mark the ticket resolved), ``admit_canceled``
    (the cancel flag is not re-checked inside the admit critical
    section).
    """

    name = "admission"
    QUERIES = 2
    CAP = 10
    NEED = 6

    def initial(self):
        return _AdmState((_Ticket("NONE", False),) * self.QUERIES,
                         0, 0, 0, 0)

    def actions(self, s: _AdmState) -> List[Action]:
        out: List[Action] = []
        for q, t in enumerate(s.tickets):
            if t.state == "NONE":
                out.append(("submit", q))
            if t.state == "QUEUED":
                if not t.canceled or "admit_canceled" in self.bugs:
                    gate = s.reserved + self.NEED <= self.CAP
                    if "headroom_race" not in self.bugs:
                        gate = (s.reserved + s.inflight + self.NEED
                                <= self.CAP)
                    idle = s.reserved <= 0 and s.inflight == 0
                    if gate or idle:
                        out.append(("admit", q))
                out.append(("timeout", q))
                if not t.canceled:
                    out.append(("cancel", q))
            if t.state == "ADMITTED":
                out.append(("reserve", q))
                out.append(("finish", q))
        return out

    def _set(self, s: _AdmState, q: int, t: _Ticket) -> _AdmState:
        return s._replace(tickets=s.tickets[:q] + (t,) + s.tickets[q + 1:])

    def _conserve(self, s: _AdmState, faults: List[Fault]) -> None:
        running = sum(1 for t in s.tickets if t.state == "ADMITTED")
        queued = sum(1 for t in s.tickets if t.state == "QUEUED")
        if running + queued + s.resolved != s.issued:
            faults.append((INV_ADM_SLOTS,
                           f"running={running} queued={queued} "
                           f"resolved={s.resolved} != issued={s.issued}"))

    def apply(self, s: _AdmState, action: Action):
        kind, q = action[0], action[1]
        t = s.tickets[q]
        faults: List[Fault] = []
        if kind == "submit":
            s = self._set(s._replace(issued=s.issued + 1), q,
                          _Ticket("QUEUED", False))
        elif kind == "cancel":
            s = self._set(s, q, t._replace(canceled=True))
        elif kind == "admit":
            if t.state != "QUEUED":
                faults.append((INV_ADM_LIFECYCLE,
                               f"admit from {t.state}"))
            if t.canceled:
                faults.append((INV_ADM_CANCEL,
                               f"query {q} admitted after cancel"))
            idle = s.reserved <= 0 and s.inflight == 0
            if (not idle
                    and s.reserved + s.inflight + self.NEED > self.CAP):
                faults.append((INV_ADM_HEADROOM,
                               f"admitted with reserved={s.reserved} "
                               f"inflight={s.inflight} need={self.NEED}"
                               f" > cap={self.CAP}"))
            s = self._set(s._replace(inflight=s.inflight + self.NEED),
                          q, t._replace(state="ADMITTED"))
        elif kind == "reserve":
            s = s._replace(inflight=s.inflight - self.NEED,
                           reserved=s.reserved + self.NEED)
        elif kind == "timeout":
            if t.state != "QUEUED":
                faults.append((INV_ADM_LIFECYCLE,
                               f"reject from {t.state}"))
            s = self._set(s._replace(resolved=s.resolved + 1), q,
                          t._replace(state="DONE"))
        elif kind == "finish":
            if t.state != "ADMITTED":
                faults.append((INV_ADM_LIFECYCLE,
                               f"release from {t.state} (release is "
                               "exactly-once)"))
            freed = s.reserved - self.NEED if s.reserved >= self.NEED \
                else s.reserved
            infl = s.inflight if s.reserved >= self.NEED \
                else s.inflight - self.NEED
            resolved = s.resolved
            if "slot_leak" not in self.bugs:
                resolved += 1
            s = self._set(s._replace(reserved=freed, inflight=infl,
                                     resolved=resolved), q,
                          t._replace(state="DONE"))
        else:
            raise ValueError(f"unknown action {action!r}")
        self._conserve(s, faults)
        return s, faults


#: the four protocols at their pinned exploration depths — what the
#: ci.sh protocol leg and tools/protocol_check.py sweep
PINNED_DEPTHS: Dict[str, int] = {
    "exchange": 12,
    "detector": 10,
    "retry": 12,
    "admission": 12,
}

MODELS = {
    "exchange": ExchangeModel,
    "detector": DetectorModel,
    "retry": RetryModel,
    "admission": AdmissionModel,
}


def explore_all(seed: int = 0,
                depths: Optional[Dict[str, int]] = None
                ) -> Dict[str, ExploreResult]:
    """Run every protocol model at its pinned depth (the CI sweep)."""
    depths = depths or PINNED_DEPTHS
    out: Dict[str, ExploreResult] = {}
    for name, make in MODELS.items():
        out[name] = explore(make(), max_depth=depths[name], seed=seed)
    return out
