"""Static plan/IR analysis tier.

Reference analog: ``EXPLAIN (TYPE VALIDATE)`` + the soundness
guarantees the reference gets for free from its JIT boundary
(``sql/gen/ExpressionCompiler``): generated operator bytecode cannot
type-mismatch its inputs because javac/asm would reject it.  This
engine compiles expressions to jnp closures instead — nothing rejects
a plan whose channel types drifted out of sync until a kernel produces
garbage (or XLA crashes) at execution time.  The validator walks the
bound logical plan + expression IR *before* execution and checks the
invariants the executor assumes:

- type consistency at every node boundary (ColumnRef indexes/types
  against the source's channels, predicate/key types, UNION arm
  unification);
- super-type unification sanity (reflexive over containers — the r5
  "no common super type for array(bigint) and array(bigint)" bug
  class);
- null-mask propagation: every plan-node type declares whether it
  preserves / derives / drops row validity (rules.NULL_MASK_POLICY);
- shape-ladder conformance: baked capacities (aggregation
  ``max_groups``) must be ladder values so structural program
  signatures stay finite (exec/programs.py + bucket_capacity);
- program-signature determinism: a node's structural signature must be
  hashable, stable across computations, and NaN-free (a NaN literal
  key never equals itself — every registry lookup would miss and
  recompile).

Enablement: ``EXPLAIN (TYPE VALIDATE)`` always runs it; the
``validate_plans`` session property (``query.validate-plans`` config
key / ``PRESTO_TPU_VALIDATE_PLANS`` env, resolved once per process
with an override hook) makes it always-on, which the test harness uses
so every tier-1 query validates for free.
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.analysis.validator import (  # noqa: F401
    Issue,
    PlanValidationError,
    assert_valid,
    validate_plan,
)

# resolved ONCE per process (the engine-lint env-read-hot-path rule:
# plan validation runs per query, not a place for repeated env reads);
# set_validation overrides for tests/tools.
from presto_tpu.envflag import EnvFlag

_VALIDATION = EnvFlag("PRESTO_TPU_VALIDATE_PLANS", default=False)


def validation_enabled() -> bool:
    """Process-wide always-on validation switch
    (``PRESTO_TPU_VALIDATE_PLANS`` env; the per-session
    ``validate_plans`` property ORs on top in the runner)."""
    return _VALIDATION()


def set_validation(value: Optional[bool]) -> None:
    """Override hook (None re-resolves from the environment)."""
    _VALIDATION.set(value)


# per-REWRITE soundness gating (analysis/soundness.py): same enablement
# shape as plan validation — session property ``validate_rewrites`` /
# config ``query.validate-rewrites`` / env, resolved once per process
_REWRITES = EnvFlag("PRESTO_TPU_VALIDATE_REWRITES", default=False)


def rewrite_validation_enabled() -> bool:
    """Process-wide switch for per-rewrite soundness checking in the
    iterative optimizer (``PRESTO_TPU_VALIDATE_REWRITES`` env; the
    per-session ``validate_rewrites`` property ORs on top in the
    binder)."""
    return _REWRITES()


def set_rewrite_validation(value: Optional[bool]) -> None:
    """Override hook (None re-resolves from the environment)."""
    _REWRITES.set(value)


# expression-tier kernel-soundness gating (analysis/kernel_soundness.py):
# same enablement shape — session property ``validate_kernels`` / config
# ``query.validate-kernels`` / env, resolved once per process
_KERNELS = EnvFlag("PRESTO_TPU_VALIDATE_KERNELS", default=False)


def kernel_validation_enabled() -> bool:
    """Process-wide switch for the expression-tier abstract
    interpreter (``PRESTO_TPU_VALIDATE_KERNELS`` env; the per-session
    ``validate_kernels`` property ORs on top in the runner)."""
    return _KERNELS()


def set_kernel_validation(value: Optional[bool]) -> None:
    """Override hook (None re-resolves from the environment)."""
    _KERNELS.set(value)


# runtime cross-check for the interval domain: sample observed column
# min/max at page boundaries and fail loudly on any escape from the
# statically predicted interval (exec/local.py consumes this; the
# concurrency sanitizer's PRESTO_TPU_LOCK_SANITIZER is the shape model)
_RANGE_SANITIZER = EnvFlag("PRESTO_TPU_RANGE_SANITIZER", default=False)


def range_sanitizer_enabled() -> bool:
    """Process-wide switch for the runtime range sanitizer
    (``PRESTO_TPU_RANGE_SANITIZER`` env)."""
    return _RANGE_SANITIZER()


def set_range_sanitizer(value: Optional[bool]) -> None:
    """Override hook (None re-resolves from the environment)."""
    _RANGE_SANITIZER.set(value)


from presto_tpu.analysis.properties import (  # noqa: E402,F401
    LogicalProperties,
    derive_properties,
)
from presto_tpu.analysis.soundness import (  # noqa: E402,F401
    RewriteSoundnessError,
    RewriteViolation,
    check_rewrite,
    plan_shape_lines,
    plan_shape_str,
)
from presto_tpu.analysis.kernel_soundness import (  # noqa: E402,F401
    KernelSoundnessError,
    analyze_kernels,
    assert_kernel_sound,
)
from presto_tpu.analysis.ranges import AbstractValue  # noqa: E402,F401

# protocol soundness tier (analysis/protocols.py + analysis/mcheck.py):
# spec automata + runtime conformance recorder
# (PRESTO_TPU_PROTOCOL_TRACE env) and the bounded schedule explorer
from presto_tpu.analysis.protocols import (  # noqa: E402,F401
    RECORDER,
    ProtocolEvent,
    Violation,
    check_trace,
    protocol_trace_enabled,
    set_protocol_trace,
)
from presto_tpu.analysis.mcheck import (  # noqa: E402,F401
    Counterexample,
    ExploreResult,
    explore,
    explore_all,
    replay,
)
