"""Per-rewrite soundness gate for the iterative optimizer.

Reference analog: ``sql/planner/sanity/PlanSanityChecker`` — the
reference runs ValidateDependenciesChecker / NoDuplicatePlanNodeIds /
TypeValidator *between* optimizer passes so an unsound rewrite fails
loudly at plan time instead of as a wrong answer.  Here the gate is
finer-grained: ``IterativeOptimizer`` calls :func:`check_rewrite`
around every successful ``Rule.apply`` (when the ``validate_rewrites``
session property / ``query.validate-rewrites`` config /
``PRESTO_TPU_VALIDATE_REWRITES`` env switch is on), comparing the
logical properties (analysis/properties.py) of the matched subtree
against its replacement.

Checker catalog (each violation carries the checker name + the
applied rule, so a failing corpus query names its culprit):

- ``output-schema``      channel names/types must match exactly
- ``row-count``          bounds must stay consistent: the before/after
                         ``[lo, hi]`` intervals must intersect, and an
                         exact count may tighten under a new Limit but
                         never silently change
- ``ordering``           a guaranteed output ordering must survive
                         (the after-ordering keeps the before-ordering
                         as a prefix; trivially true for <=1-row
                         results)
- ``keys``               every provably-unique key set must still be
                         implied by some after-key
- ``determinism``        nondeterministic call sites must not increase
                         (a hoist that duplicates ``random()`` changes
                         semantics)
- ``duplicate-node``     a node *introduced* by the rewrite must not
                         appear in two source positions (plan nodes
                         are identity-keyed; aliasing one double-counts
                         its rows and breaks per-node bookkeeping).
                         Nodes that already existed before the rewrite
                         may stay legitimately shared (DAG reuse)
- ``dangling-columnref`` every ColumnRef in the replacement subtree
                         must index a real source channel
- ``sources-replaced``   raised by the optimizer itself when
                         ``_replace_sources`` fails to take effect (the
                         in-place mutation class of bug)
- ``properties``         property derivation crashed on the
                         replacement subtree (itself a malformation)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from presto_tpu.analysis.properties import derive_properties, node_exprs
from presto_tpu.expr.ir import Call, ColumnRef, Expr, LambdaExpr
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    ValuesNode,
    WindowNode,
)


@dataclasses.dataclass(frozen=True)
class RewriteViolation:
    checker: str
    rule: str
    message: str

    def __str__(self) -> str:
        return f"[{self.checker}] rule {self.rule}: {self.message}"


class RewriteSoundnessError(Exception):
    """An optimizer rule produced an unsound rewrite.  Carries the rule
    name, the per-checker violations, and before/after plan snippets."""

    def __init__(self, rule: str, violations: List[RewriteViolation],
                 before: Optional[PlanNode] = None,
                 after: Optional[PlanNode] = None):
        self.rule = rule
        self.violations = violations
        lines = [f"unsound rewrite by {rule}:"]
        lines.extend(f"  {v}" for v in violations)
        if before is not None:
            lines.append("before:")
            lines.extend("  " + s for s in plan_shape_lines(before)[:12])
        if after is not None and after is not before:
            lines.append("after:")
            lines.extend("  " + s for s in plan_shape_lines(after)[:12])
        super().__init__("\n".join(lines))


# ---------------------------------------------------------------------------
# canonical plan-shape rendering (shared with tools/plan_diff.py)
# ---------------------------------------------------------------------------


def _shape_detail(node: PlanNode) -> str:
    """Deterministic one-line description of a node: everything that
    defines the plan's *shape*, nothing that depends on object
    identity, scale factor statistics, or process state."""
    if isinstance(node, TableScanNode):
        cols = [node.handle.columns[i].name for i in node.columns]
        out = f"{node.handle.table} cols={cols}"
        if node.constraints:
            out += f" constraints={sorted(node.constraints)}"
        if node.limit is not None:
            out += f" limit={node.limit}"
        if node.sample is not None:
            out += f" sample={node.sample}"
        return out
    if isinstance(node, FilterNode):
        return repr(node.predicate)
    if isinstance(node, ProjectNode):
        return f"{list(node.names)} = {node.projections!r}"
    if isinstance(node, AggregationNode):
        out = (f"[{node.step}] keys={node.group_exprs!r} "
               f"aggs={node.aggs!r}")
        if node.presorted:
            out += " presorted"
        return out
    if isinstance(node, GroupIdNode):
        return f"keys={node.key_exprs!r} sets={node.set_masks}"
    if isinstance(node, JoinNode):
        out = f"[{node.kind}] {node.left_keys!r} = {node.right_keys!r}"
        for flag in ("unique_build", "use_index", "null_safe_keys",
                     "null_aware"):
            if getattr(node, flag):
                out += f" {flag}"
        return out
    if isinstance(node, UnnestNode):
        out = f"{node.unnest_exprs!r}"
        if node.ordinality:
            out += " ordinality"
        return out
    if isinstance(node, SortNode):
        return (f"keys={node.sort_exprs!r} asc={node.ascending} "
                f"nulls_first={node.nulls_first}")
    if isinstance(node, TopNNode):
        return (f"{node.count} keys={node.sort_exprs!r} "
                f"asc={node.ascending} nulls_first={node.nulls_first}")
    if isinstance(node, LimitNode):
        return str(node.count)
    if isinstance(node, ValuesNode):
        types = [str(t) for t in node.types]
        return f"rows={len(node.rows)} {list(node.names)} {types}"
    if isinstance(node, WindowNode):
        kinds = [getattr(f, "kind", type(f).__name__) for f in node.funcs]
        return (f"partition={node.partition_exprs!r} "
                f"order={node.order_exprs!r} funcs={kinds}")
    if isinstance(node, OutputNode):
        return str(list(node.names))
    if isinstance(node, UnionNode):
        return f"{len(node.inputs)} arms"
    return ""


def plan_shape_lines(node: PlanNode, indent: int = 0) -> List[str]:
    """Canonical EXPLAIN-like rendering without stats/estimates — the
    stable form behind golden plan fingerprints and violation
    snippets."""
    name = type(node).__name__.replace("Node", "")
    detail = _shape_detail(node)
    out = ["  " * indent + f"- {name}" + (f" {detail}" if detail else "")]
    for s in node.sources:
        out.extend(plan_shape_lines(s, indent + 1))
    return out


def plan_shape_str(node: PlanNode) -> str:
    return "\n".join(plan_shape_lines(node))


# ---------------------------------------------------------------------------
# structural well-formedness
# ---------------------------------------------------------------------------


def _walk_ids(node: PlanNode, acc: Set[int]) -> None:
    if id(node) in acc:
        return
    acc.add(id(node))
    for s in node.sources:
        _walk_ids(s, acc)


#: nodes whose expressions read a source other than sources[0]
def _expr_source_counts(node: PlanNode) -> List[int]:
    """Channel count of the source each expression list reads — used
    for the dangling-ColumnRef bound check."""
    if isinstance(node, JoinNode):
        return [len(node.left.channels), len(node.right.channels)]
    if node.sources:
        return [len(node.sources[0].channels)]
    return []


def _expr_refs_shallow(e: Expr) -> List[int]:
    if isinstance(e, ColumnRef):
        return [e.index]
    if isinstance(e, Call):
        return [r for a in e.args for r in _expr_refs_shallow(a)]
    if isinstance(e, LambdaExpr):
        return _expr_refs_shallow(e.body)
    return []


def _check_structure(rule: str, before: PlanNode,
                     after: PlanNode) -> List[RewriteViolation]:
    violations: List[RewriteViolation] = []

    before_ids: Set[int] = set()
    _walk_ids(before, before_ids)

    # duplicate-node: a FRESH node referenced from >1 source position
    seen_edges: Dict[int, int] = {}
    dup_reported: Set[int] = set()

    def walk(n: PlanNode) -> None:
        count = seen_edges.get(id(n), 0) + 1
        seen_edges[id(n)] = count
        if count > 1:
            if id(n) not in before_ids and id(n) not in dup_reported:
                dup_reported.add(id(n))
                violations.append(RewriteViolation(
                    "duplicate-node", rule,
                    f"rewrite introduces {type(n).__name__} aliased into "
                    f"{count}+ source positions — identity-keyed plan "
                    "nodes must not be shared by a rewrite that created "
                    "them"))
            return  # already visited: stop (also bounds DAG traversal)
        for s in n.sources:
            walk(s)

    walk(after)

    # dangling-columnref: every expression must index a real channel
    checked: Set[int] = set()

    def check_refs(n: PlanNode) -> None:
        if id(n) in checked:
            return
        checked.add(id(n))
        try:
            bounds = _expr_source_counts(n)
        except Exception:
            bounds = []
        if isinstance(n, JoinNode):
            groups = [(list(n.left_keys), bounds[0] if bounds else None),
                      (list(n.right_keys),
                       bounds[1] if len(bounds) > 1 else None)]
        else:
            groups = [(node_exprs(n), bounds[0] if bounds else None)]
        for exprs, limit in groups:
            if limit is None:
                continue
            for e in exprs:
                for r in _expr_refs_shallow(e):
                    if r >= limit or r < 0:
                        violations.append(RewriteViolation(
                            "dangling-columnref", rule,
                            f"{type(n).__name__} references channel ${r} "
                            f"but its source has {limit} channels"))
        for s in n.sources:
            check_refs(s)

    check_refs(after)
    return violations


# ---------------------------------------------------------------------------
# property checks
# ---------------------------------------------------------------------------


def _keys_implied(required: frozenset, available) -> bool:
    return any(k <= required for k in available)


def check_rewrite(rule: str, before: PlanNode,
                  after: PlanNode) -> List[RewriteViolation]:
    """All soundness violations of replacing ``before`` with ``after``
    (empty list = the rewrite is consistent with every derivable
    property).  The caller attributes them to ``rule``."""
    violations = _check_structure(rule, before, after)
    if violations:
        return violations  # property derivation needs a sane tree

    memo: Dict[int, object] = {}
    try:
        b = derive_properties(before, memo)
        a = derive_properties(after, memo)
    except Exception as e:  # malformed replacement: derivation crashed
        return [RewriteViolation(
            "properties", rule,
            f"property derivation failed on the rewritten subtree: "
            f"{type(e).__name__}: {e}")]

    if b.names != a.names or b.types != a.types:
        violations.append(RewriteViolation(
            "output-schema", rule,
            f"output schema changed: "
            f"{list(zip(b.names, map(str, b.types)))} -> "
            f"{list(zip(a.names, map(str, a.types)))}"))

    # row bounds must intersect; exact counts must agree
    if (b.hi is not None and a.lo > b.hi) or \
            (a.hi is not None and b.lo > a.hi):
        violations.append(RewriteViolation(
            "row-count", rule,
            f"row bounds disjoint: before [{b.lo}, {b.hi}] vs "
            f"after [{a.lo}, {a.hi}]"))
    elif b.exact is not None and a.exact is not None and b.exact != a.exact:
        violations.append(RewriteViolation(
            "row-count", rule,
            f"exact row count changed: {b.exact} -> {a.exact}"))

    if b.ordering and not a.scalar \
            and a.ordering[:len(b.ordering)] != b.ordering:
        violations.append(RewriteViolation(
            "ordering", rule,
            f"guaranteed ordering lost: before {list(b.ordering)}, "
            f"after {list(a.ordering)}"))

    for k in b.keys:
        if not _keys_implied(k, a.keys):
            violations.append(RewriteViolation(
                "keys", rule,
                f"uniqueness of channels {sorted(k)} no longer provable "
                f"(after-keys: {[sorted(x) for x in a.keys]})"))

    if a.nondet_sites > b.nondet_sites:
        violations.append(RewriteViolation(
            "determinism", rule,
            f"nondeterministic call sites increased "
            f"{b.nondet_sites} -> {a.nondet_sites} — the rewrite "
            "duplicates a nondeterministic expression"))

    return violations
