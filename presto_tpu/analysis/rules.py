"""The individual validator rules.

Each rule is a function ``(node, ctx) -> Iterator[Issue]`` run against
every node of the plan by :mod:`presto_tpu.analysis.validator`.  Rules
are conservative: they only flag states the executor genuinely cannot
handle (a wrong flag here fails EXPLAIN (TYPE VALIDATE) on a healthy
query, and the whole TPC-H + TPC-DS corpora run with validation on in
the test harness).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterator, List, Optional

from presto_tpu.expr.ir import (
    CMP,
    LOGIC,
    AggCall,
    Call,
    ColumnRef,
    Expr,
    LambdaExpr,
)
from presto_tpu.planner.plan import (
    AggregationNode,
    Channel,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    RemoteSourceNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    ValuesNode,
    WindowNode,
)
from presto_tpu.types import Type, common_super_type


@dataclasses.dataclass
class Issue:
    """One validator diagnostic, anchored to a named plan node."""

    rule: str      # type-consistency | null-mask | shape-ladder | signature
    node: str      # e.g. "AggregationNode#2"
    message: str
    severity: str = "error"  # error | warning

    def __str__(self) -> str:
        return f"[{self.rule}] {self.node}: {self.message}"


# ---------------------------------------------------------------------------
# null-mask propagation policy
# ---------------------------------------------------------------------------

#: Every Block-producing plan-node type declares how it treats row
#: validity: ``preserves`` (output channels are the source's channels —
#: same count, same per-column validity), ``derives`` (computes fresh
#: validity from its inputs: projections, aggregates, outer-join null
#: extension, NULL-masked grouping sets), or ``source`` (leaf — validity
#: originates here).  An undeclared node type is itself a finding: the
#: executor's kernels assume one of these three contracts, and a new
#: node that never picked one is exactly how silent validity corruption
#: ships (the mutation tests seed that case).
NULL_MASK_POLICY = {
    TableScanNode: "source",
    ValuesNode: "source",
    PrecomputedNode: "source",
    RemoteSourceNode: "source",
    FilterNode: "preserves",
    SortNode: "preserves",
    TopNNode: "preserves",
    LimitNode: "preserves",
    UnionNode: "preserves",   # per-position validity concatenates
    OutputNode: "preserves",
    ProjectNode: "derives",
    AggregationNode: "derives",
    GroupIdNode: "derives",   # masks inactive keys to NULL per set
    JoinNode: "derives",      # outer/semi variants extend validity
    CrossSingleNode: "derives",
    UnnestNode: "derives",    # element liveness = j < len[row]
    WindowNode: "derives",
}

#: ``preserves`` nodes whose output legitimately narrows/renames but
#: keeps per-channel validity untouched (OutputNode renames, UnionNode
#: concatenates N same-shaped inputs).
_PRESERVES_EXEMPT_COUNT = (UnionNode, OutputNode)


def check_null_mask(node: PlanNode, ctx) -> Iterator[Issue]:
    policy = NULL_MASK_POLICY.get(type(node))
    if policy is None:
        yield Issue(
            "null-mask", ctx.name(node),
            f"plan-node type {type(node).__name__} declares no null-mask "
            "policy (preserves/derives/source) — register it in "
            "analysis.rules.NULL_MASK_POLICY before executing it")
        return
    if policy == "preserves" and not isinstance(node, _PRESERVES_EXEMPT_COUNT):
        src = node.sources
        if len(src) == 1:
            n_out = len(ctx.channels(node))
            n_in = len(ctx.channels(src[0]))
            if n_out != n_in:
                yield Issue(
                    "null-mask", ctx.name(node),
                    f"declared validity-preserving but emits {n_out} "
                    f"channels over a {n_in}-channel source — a "
                    "preserving node must pass its source's channels "
                    "through unchanged")


# ---------------------------------------------------------------------------
# type consistency
# ---------------------------------------------------------------------------

def _types_compatible(expr_t: Optional[Type], chan_t: Optional[Type]) -> bool:
    """Loose structural agreement between an expression's declared type
    and the channel it reads.  Names must match; decimals must agree on
    scale (the scaled-int representation); containers recurse on their
    element types.  Precision/raw-width/dictionary flags may differ —
    projections retype those legitimately."""
    if expr_t is None or chan_t is None:
        return True
    if expr_t.name != chan_t.name:
        return False
    if expr_t.is_decimal and (expr_t.scale or 0) != (chan_t.scale or 0):
        return False
    if expr_t.name == "array":
        return _types_compatible(expr_t.element, chan_t.element)
    if expr_t.name == "map":
        return (_types_compatible(expr_t.key_element, chan_t.key_element)
                and _types_compatible(expr_t.element, chan_t.element))
    return True


def _walk_exprs(e, in_lambda: bool = False):
    """(expr, in_lambda) pairs over an IR tree; lambda bodies reference
    binder-allocated slots, not source channels, so ColumnRef bounds
    checks do not apply inside them."""
    if e is None:
        return
    if isinstance(e, AggCall):
        for sub in (e.arg, e.arg2, e.arg3, e.filter):
            yield from _walk_exprs(sub, in_lambda)
        return
    if not isinstance(e, Expr):
        return
    yield e, in_lambda
    if isinstance(e, LambdaExpr):
        yield from _walk_exprs(e.body, True)
    elif isinstance(e, Call):
        for a in e.args:
            yield from _walk_exprs(a, in_lambda)


def _node_exprs(node: PlanNode):
    """(expr, source_node, label) triples for every expression a node
    evaluates, paired with the source whose channels it reads."""
    if isinstance(node, FilterNode):
        yield node.predicate, node.source, "predicate"
    elif isinstance(node, ProjectNode):
        for i, e in enumerate(node.projections):
            yield e, node.source, f"projection[{i}]"
    elif isinstance(node, AggregationNode):
        for i, e in enumerate(node.group_exprs):
            yield e, node.source, f"group[{i}]"
        for i, a in enumerate(node.aggs):
            yield a, node.source, f"agg[{i}]"
    elif isinstance(node, GroupIdNode):
        for i, e in enumerate(node.key_exprs):
            yield e, node.source, f"key[{i}]"
    elif isinstance(node, JoinNode):
        for i, e in enumerate(node.left_keys):
            yield e, node.left, f"left_key[{i}]"
        for i, e in enumerate(node.right_keys):
            yield e, node.right, f"right_key[{i}]"
    elif isinstance(node, (SortNode, TopNNode)):
        for i, e in enumerate(node.sort_exprs):
            yield e, node.source, f"sort[{i}]"
    elif isinstance(node, WindowNode):
        for i, e in enumerate(node.partition_exprs):
            yield e, node.source, f"partition[{i}]"
        for i, e in enumerate(node.order_exprs):
            yield e, node.source, f"order[{i}]"
    elif isinstance(node, UnnestNode):
        for i, e in enumerate(node.unnest_exprs):
            yield e, node.source, f"unnest[{i}]"


def check_type_consistency(node: PlanNode, ctx) -> Iterator[Issue]:
    for root, src, label in _node_exprs(node):
        src_channels = ctx.channels(src)
        for e, in_lambda in _walk_exprs(root):
            if isinstance(e, ColumnRef) and not in_lambda:
                if not (0 <= e.index < len(src_channels)):
                    yield Issue(
                        "type-consistency", ctx.name(node),
                        f"{label}: ColumnRef ${e.index} out of bounds "
                        f"(source has {len(src_channels)} channels)")
                    continue
                ct = src_channels[e.index].type
                if not _types_compatible(e.type, ct):
                    yield Issue(
                        "type-consistency", ctx.name(node),
                        f"{label}: ColumnRef ${e.index} declares "
                        f"{e.type!r} but the source channel is {ct!r}")
            elif isinstance(e, Call) and (e.fn in CMP or e.fn in LOGIC):
                if e.type.name != "boolean":
                    yield Issue(
                        "type-consistency", ctx.name(node),
                        f"{label}: {e.fn}(...) must type as boolean, "
                        f"got {e.type!r}")

    # node-shape checks -----------------------------------------------------
    if isinstance(node, FilterNode):
        # integer-like predicates are legal: some binder lowerings
        # (CASE-with-boolean-branches) type the 0/1 device repr
        if node.predicate.type.name not in (
                "boolean", "bigint", "integer", "smallint", "tinyint"):
            yield Issue(
                "type-consistency", ctx.name(node),
                f"filter predicate types as {node.predicate.type!r}, "
                "not boolean")
    elif isinstance(node, ProjectNode):
        if len(node.projections) != len(node.names):
            yield Issue(
                "type-consistency", ctx.name(node),
                f"{len(node.projections)} projections vs "
                f"{len(node.names)} names")
    elif isinstance(node, AggregationNode):
        if len(node.aggs) != len(node.agg_names):
            yield Issue("type-consistency", ctx.name(node),
                        f"{len(node.aggs)} aggregates vs "
                        f"{len(node.agg_names)} names")
        if len(node.group_exprs) != len(node.group_names):
            yield Issue("type-consistency", ctx.name(node),
                        f"{len(node.group_exprs)} group exprs vs "
                        f"{len(node.group_names)} names")
        if node.step not in ("single", "partial", "final"):
            yield Issue("type-consistency", ctx.name(node),
                        f"unknown aggregation step {node.step!r}")
    elif isinstance(node, JoinNode):
        if len(node.left_keys) != len(node.right_keys):
            yield Issue("type-consistency", ctx.name(node),
                        f"{len(node.left_keys)} probe keys vs "
                        f"{len(node.right_keys)} build keys")
        if node.kind not in ("inner", "left", "full", "semi", "anti",
                             "mark", "cross"):
            yield Issue("type-consistency", ctx.name(node),
                        f"unknown join kind {node.kind!r}")
        for i, (lk, rk) in enumerate(zip(node.left_keys, node.right_keys)):
            yield from _check_unifies(
                lk.type, rk.type, ctx.name(node), f"key[{i}]")
    elif isinstance(node, UnionNode):
        arities = {len(ctx.channels(s)) for s in node.inputs}
        if len(arities) > 1:
            yield Issue("type-consistency", ctx.name(node),
                        f"UNION arms emit differing channel counts "
                        f"{sorted(arities)}")
        else:
            base = ctx.channels(node.inputs[0])
            for s in node.inputs[1:]:
                for i, (a, b) in enumerate(zip(base, ctx.channels(s))):
                    yield from _check_unifies(
                        a.type, b.type, ctx.name(node), f"column[{i}]")
    elif isinstance(node, (SortNode, TopNNode)):
        if len(node.sort_exprs) != len(node.ascending):
            yield Issue("type-consistency", ctx.name(node),
                        f"{len(node.sort_exprs)} sort exprs vs "
                        f"{len(node.ascending)} directions")
        if isinstance(node, TopNNode) and node.count < 0:
            yield Issue("type-consistency", ctx.name(node),
                        f"negative TopN count {node.count}")
    elif isinstance(node, ValuesNode):
        for i, row in enumerate(node.rows):
            if len(row) != len(node.types):
                yield Issue("type-consistency", ctx.name(node),
                            f"row {i} has {len(row)} cells for "
                            f"{len(node.types)} columns")
                break
    elif isinstance(node, OutputNode):
        n_src = len(ctx.channels(node.source))
        if len(node.names) > n_src:
            yield Issue("type-consistency", ctx.name(node),
                        f"{len(node.names)} output names over a "
                        f"{n_src}-channel source")
    elif isinstance(node, UnnestNode):
        for i, e in enumerate(node.unnest_exprs):
            if not (e.type.is_array or e.type.is_map):
                yield Issue("type-consistency", ctx.name(node),
                            f"unnest[{i}] argument is {e.type!r}, "
                            "not ARRAY or MAP")


def _check_unifies(a: Type, b: Type, node_name: str, label: str):
    """Key/column pairs must unify, and unification must be sane:
    reflexive (T unify T == T — the r5 container bug produced 'no
    common super type for array(bigint) and array(bigint)') and
    symmetric."""
    try:
        ab = common_super_type(a, b)
    except Exception as e:
        yield Issue("type-consistency", node_name,
                    f"{label}: {a!r} and {b!r} do not unify ({e})")
        return
    try:
        ba = common_super_type(b, a)
    except Exception as e:
        yield Issue("type-consistency", node_name,
                    f"{label}: unification is asymmetric — {a!r}/{b!r} "
                    f"unify to {ab!r} but the reverse raises ({e})")
        return
    if ab != ba:
        yield Issue("type-consistency", node_name,
                    f"{label}: asymmetric unification {ab!r} vs {ba!r}")
    for t in (a, b):
        try:
            if common_super_type(t, t) != t:
                yield Issue(
                    "type-consistency", node_name,
                    f"{label}: unification is not reflexive for {t!r}")
        except Exception as e:
            yield Issue(
                "type-consistency", node_name,
                f"{label}: {t!r} does not unify with itself ({e}) — "
                "container super-type bug class")


# ---------------------------------------------------------------------------
# shape-ladder conformance
# ---------------------------------------------------------------------------

def _is_ladder(n: int) -> bool:
    """True when ``n`` is a fixed point of the executor's capacity
    ladder (exec/local.bucket_capacity): a power of two below 64K, a
    64K multiple above."""
    if n <= 0:
        return False
    if n >= (1 << 16):
        return n % (1 << 16) == 0
    return (n & (n - 1)) == 0


def check_shape_ladder(node: PlanNode, ctx) -> Iterator[Issue]:
    if isinstance(node, AggregationNode):
        mg = node.max_groups
        if not isinstance(mg, int) or not _is_ladder(mg):
            yield Issue(
                "shape-ladder", ctx.name(node),
                f"max_groups={mg!r} is not a capacity-ladder value "
                "(pow2 / 64K multiple) — every off-ladder capacity "
                "bakes a fresh XLA program (route through "
                "bucket_capacity or a pow2 estimate)")
        elif mg > (1 << 26):
            yield Issue(
                "shape-ladder", ctx.name(node),
                f"max_groups={mg} exceeds MAX_AGG_GROUPS (1<<26)")
    if isinstance(node, PrecomputedNode):
        page = node.page
        cap = getattr(page, "capacity", None)
        if isinstance(cap, int) and cap > 0 and not _is_ladder(cap):
            # materialized intermediates re-enter chains; an off-ladder
            # capacity costs one extra program but is not unsound
            yield Issue(
                "shape-ladder", ctx.name(node),
                f"materialized page capacity {cap} is off the ladder "
                "(pad_page_pow2 before splicing to share programs)",
                severity="warning")


# ---------------------------------------------------------------------------
# program-signature determinism
# ---------------------------------------------------------------------------

_SIG_SCALARS = (type(None), bool, int, float, str, bytes)


def _sig_view(v):
    """Signature-safe view of a node parameter: IR values (scalars,
    Types, Dictionaries, Expr/AggCall trees) pass through; anything
    opaque (materialized Pages of device arrays, connector handles)
    collapses to its class name.  Routing opaque objects into
    ``ir_signature`` would pin them — strong references in its
    process-global identity-token table — for up to 4096 evictions;
    the determinism check only needs the IR-shaped parts anyway."""
    from presto_tpu.expr.ir import AggCall as _AggCall, Expr as _Expr
    from presto_tpu.page import Dictionary as _Dictionary

    if isinstance(v, _SIG_SCALARS) or isinstance(
            v, (Type, _Dictionary, _Expr, _AggCall)):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_sig_view(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(map(repr, v)))
    return type(v).__name__


def _signature_params(node: PlanNode) -> List:
    """The node's baked (non-source) parameters — what a structural
    program signature embeds."""
    out = []
    if dataclasses.is_dataclass(node):
        srcs = set(map(id, node.sources))
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if id(v) in srcs or (isinstance(v, (list, tuple))
                                 and any(id(x) in srcs for x in v)):
                continue
            out.append(_sig_view(v))
    return out


def _find_nan(sig, path: str = "") -> Optional[str]:
    if isinstance(sig, float) and math.isnan(sig):
        return path or "<root>"
    if isinstance(sig, tuple):
        for i, x in enumerate(sig):
            hit = _find_nan(x, f"{path}[{i}]")
            if hit:
                return hit
    return None


def check_signature_determinism(node: PlanNode, ctx) -> Iterator[Issue]:
    from presto_tpu.exec.programs import ir_signature

    params = _signature_params(node)
    try:
        s1 = ir_signature(params)
        s2 = ir_signature(params)
    except Exception as e:
        yield Issue(
            "signature", ctx.name(node),
            f"structural signature raised {type(e).__name__}: {e}")
        return
    try:
        hash(s1)
    except TypeError as e:
        yield Issue("signature", ctx.name(node),
                    f"structural signature is unhashable ({e}) — it "
                    "cannot key the program registry")
        return
    if s1 != s2:
        yield Issue(
            "signature", ctx.name(node),
            "structural signature is nondeterministic (two computations "
            "differ) — registry lookups would never hit")
        return
    nan_at = _find_nan(s1)
    if nan_at:
        # warning, not error: same-object NaN tuples still compare
        # equal (identity shortcut), so cached-plan reuse works — but
        # a structurally identical plan from different SQL text can
        # never share the program (nan() literals are legal SQL)
        yield Issue(
            "signature", ctx.name(node),
            f"NaN baked into the program signature at {nan_at} — "
            "structural twins of this node can never share a compiled "
            "program (NaN != NaN across plans)",
            severity="warning")


ALL_RULES = (
    check_type_consistency,
    check_null_mask,
    check_shape_ladder,
    check_signature_determinism,
)
