"""Static concurrency sanitizer for the distributed tier.

The source-level companion of the plan validator: a whole-repo AST
pass over every module that touches ``threading`` — scheduler pools,
heartbeat threads, token-acked exchange buffers, memory-pool gauges —
checking the invariants Python's memory model does NOT give us for
free the way the reference engine's Java tier gets them (``
OutputBuffer`` long-poll, task executors: happens-before by
``synchronized``/volatile construction).

Detectors (rule names are what ``tools/engine_lint.py --check`` and
the suppression file use):

lock-order          A cycle in the whole-repo lock-acquisition graph.
                    Nodes are lock NAMES (``module.Class.attr`` — the
                    same scheme presto_tpu/sync.py names instrumented
                    locks, so the runtime cross-check lines up);
                    an edge A->B means some code path acquires B while
                    holding A, including interprocedurally through
                    direct method/function calls.  A cycle is a
                    potential deadlock: two threads entering it from
                    different arcs can block forever.
blocking-in-lock    A blocking call while holding a lock: network I/O
                    (net.py helpers, urlopen), ``time.sleep``,
                    ``Future.result``, untimed ``queue.get``/
                    ``Condition.wait`` on a DIFFERENT condition,
                    ``Thread.join``, device syncs (``device_get``,
                    ``block_until_ready``).  Every waiter on that lock
                    stalls for the full I/O latency — the classic
                    serving-tier lockup.
untimed-wait        ``Condition.wait()`` / ``Event.wait()`` with no
                    timeout.  A missed notify (or a peer that died
                    before notifying) parks the thread forever, and
                    shutdown paths cannot reap it.  Notify-driven
                    waits whose every producer notifies under the same
                    lock are legitimate — suppress with a justification.
shared-state-race   An attribute written both from thread-target /
                    executor-submitted code and from coordinator paths,
                    with at least one write outside any lock.  Plain
                    constant stores (``self.done = True``) are exempt —
                    GIL-atomic flag handoffs are idiomatic; read-modify-
                    write (``+=``) and computed stores are not.
thread-leak         A non-daemon ``threading.Thread`` with no
                    ``join()`` reachable in its module.  Leaked
                    non-daemon threads block interpreter exit and pile
                    up under concurrent queries.
executor-leak       A ``ThreadPoolExecutor`` neither used as a context
                    manager nor ``shutdown()`` anywhere in its module.
unbounded-queue     ``queue.Queue()`` (or LifoQueue) without a
                    ``maxsize`` — producers outrunning a consumer grow
                    it without bound; the memory plane cannot see it.
unnamed-thread      ``threading.Thread(...)`` without ``name=``.
                    Sanitizer reports, trace exports, and py-spy dumps
                    identify threads by name; anonymous ``Thread-12``
                    is unattributable in a 40-thread coordinator.
server-leak         A ``ThreadingHTTPServer`` whose module never calls
                    ``server_close()`` — leaks the listening socket.

Everything is a heuristic over the AST — no imports are executed.  The
analyzer is deliberately dependency-free (stdlib ``ast`` only) so
``tools/engine_lint.py`` can load it without pulling in jax.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, NamedTuple, Optional, Set, Tuple

#: constructors that create a mutex-like object
_LOCK_CTORS = {"Lock", "RLock", "named_lock"}
_COND_CTORS = {"Condition", "named_condition"}
#: blocking call names (resolved by bare/attr name)
_BLOCKING_NET = {"urlopen", "request_json", "request_bytes", "http_retry",
                 "getaddrinfo", "create_connection"}
_BLOCKING_SYNC = {"sleep", "device_get", "block_until_ready"}


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


CONCURRENCY_RULES = {
    "lock-order", "blocking-in-lock", "untimed-wait", "shared-state-race",
    "thread-leak", "executor-leak", "unbounded-queue", "unnamed-thread",
    "server-leak",
}


def _mod_name(path: str) -> str:
    return os.path.splitext(os.path.basename(path))[0]


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _target_names(node: ast.AST) -> List[str]:
    """'x' for ``x = ...``, 'self.x' for ``self.x = ...``."""
    out = []
    if isinstance(node, ast.Name):
        out.append(node.id)
    elif isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        out.append(f"self.{node.attr}")
    return out


# ---------------------------------------------------------------------------
# model extraction
# ---------------------------------------------------------------------------


class _FuncInfo:
    """One function/method's concurrency-relevant summary."""

    __slots__ = ("key", "node", "cls", "module", "acquires", "calls",
                 "is_thread_entry")

    def __init__(self, key: Tuple[str, Optional[str], str],
                 node: ast.AST, cls: Optional[str], module: "_ModuleInfo"):
        self.key = key
        self.node = node
        self.cls = cls
        self.module = module
        #: lock names directly acquired anywhere in the body
        self.acquires: Set[str] = set()
        #: callee keys of direct calls (resolved later)
        self.calls: Set[Tuple[str, Optional[str], str]] = set()
        self.is_thread_entry = False


class _ModuleInfo:
    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        #: lock-NAMING module name: the basename, because that is the
        #: scheme presto_tpu/sync.py names runtime locks with
        #: (``module.Class.attr``) and the cross-check must line up
        self.name = _mod_name(path)
        #: repo-model KEY: the normalized path, because basenames
        #: collide (memory.py, metrics.py exist twice) and a dict
        #: keyed on them silently drops whole files from analysis
        self.key = os.path.normpath(os.path.abspath(path))
        self.tree = tree
        self.lines = source.splitlines()
        #: lock-ish value names in scope -> canonical lock name
        #: keys: "self.attr" (per class: ("Cls", "self.attr")), module
        #: globals, and function-local vars (("fn", "var"))
        self.locks: Dict[Tuple[Optional[str], str], str] = {}
        #: conditions share their lock's canonical name when built on one
        self.classes: Dict[str, ast.ClassDef] = {}
        self.functions: Dict[Tuple[Optional[str], str], _FuncInfo] = {}
        #: self.attr -> class name (for self.buffer.enqueue resolution)
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: queue-typed names (for queue.get blocking checks)
        self.queue_names: Set[str] = set()
        #: thread-typed names (for .join classification)
        self.thread_names: Set[str] = set()
        #: names holding a list of Threads (list/listcomp of Thread
        #: calls, or an annotation mentioning Thread) — for-loop
        #: targets over them are thread-typed too
        self.thread_collections: Set[str] = set()
        #: ThreadPoolExecutor-typed names
        self.executor_names: Set[str] = set()
        #: typed lifecycle evidence: a join/shutdown call on a
        #: THREAD/EXECUTOR-typed receiver somewhere in the module.  A
        #: raw substring scan is blind-spot bait: ``", ".join(cols)``
        #: and ``httpd.shutdown()`` must not satisfy the leak checks.
        self.has_thread_join = False
        self.has_executor_shutdown = False


class _Repo:
    """The whole-repo model: modules, a class index, the lock graph."""

    def __init__(self):
        self.modules: Dict[str, _ModuleInfo] = {}
        #: class name -> module KEY (repo-wide; first definition wins)
        self.class_index: Dict[str, str] = {}
        self.findings: List[Finding] = []
        #: (holder, acquired) -> witness (path, line)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}


def _is_lock_ctor(call: ast.Call) -> Optional[str]:
    """'lock'/'cond' when the call constructs a mutex/condition."""
    name = _call_name(call)
    if name in _LOCK_CTORS:
        return "lock"
    if name in _COND_CTORS:
        return "cond"
    return None


def _scan_module(path: str, tree: ast.Module, source: str) -> _ModuleInfo:
    """Pass 1: classes, functions, lock declarations, attr types."""
    mi = _ModuleInfo(path, tree, source)

    def record_lock(scope: Optional[str], target: str, canonical: str):
        mi.locks[(scope, target)] = canonical

    def scan_assign(node, scope: Optional[str], cls: Optional[str]):
        if isinstance(node, ast.AnnAssign):
            # `self._threads: List[threading.Thread] = []` — the
            # annotation types the collection
            names = _target_names(node.target)
            try:
                ann = ast.unparse(node.annotation)
            except Exception:
                ann = ""
            if "Thread" in ann and "Executor" not in ann:
                mi.thread_collections.update(names)
            elif "Executor" in ann:
                mi.executor_names.update(names)
            return
        call = node.value
        coll_elt = None
        if isinstance(call, ast.ListComp):
            coll_elt = call.elt
        elif isinstance(call, ast.List) and call.elts:
            coll_elt = call.elts[0]
        if isinstance(coll_elt, ast.Call) \
                and _call_name(coll_elt) == "Thread":
            for t in node.targets:
                mi.thread_collections.update(_target_names(t))
            return
        if isinstance(call, ast.IfExp):
            # `self._lock = parent._lock if parent else Condition()`
            # (resource_groups): the ctor lives in a ternary branch —
            # whichever branch constructs a primitive names the lock
            for branch in (call.body, call.orelse):
                if isinstance(branch, ast.Call) \
                        and _is_lock_ctor(branch) is not None:
                    call = branch
                    break
        if not isinstance(call, ast.Call):
            return
        kind = _is_lock_ctor(call)
        names = [n for t in node.targets for n in _target_names(t)]
        if kind is not None:
            for n in names:
                if n.startswith("self.") and cls:
                    canonical = f"{mi.name}.{cls}.{n[5:]}"
                elif scope is None:
                    canonical = f"{mi.name}.{n}"
                else:
                    canonical = f"{mi.name}.{scope}.{n}"
                # Condition(existing_lock) aliases that lock's name —
                # acquiring the condition IS acquiring the lock.  The
                # lock may sit in ANY positional slot or the lock=
                # kwarg (named_condition(name, lock) puts it second)
                if kind == "cond":
                    lock_args = list(call.args)
                    lk = _kwarg(call, "lock")
                    if lk is not None:
                        lock_args.append(lk)
                    for arg in lock_args:
                        for a in _target_names(arg):
                            key = ((cls, a) if a.startswith("self.")
                                   else (scope, a))
                            alias = (mi.locks.get(key)
                                     or mi.locks.get((None, a)))
                            if alias:
                                canonical = alias
                record_lock(cls if names and names[0].startswith("self.")
                            else scope, names[0], canonical)
                for n2 in names[1:]:
                    record_lock(cls if n2.startswith("self.") else scope,
                                n2, canonical)
            return
        ctor = _call_name(call)
        if ctor in ("Queue", "LifoQueue", "SimpleQueue", "PriorityQueue"):
            mi.queue_names.update(names)
        if ctor == "Thread":
            mi.thread_names.update(names)
        if ctor == "ThreadPoolExecutor":
            mi.executor_names.update(names)
        if ctor and cls:
            for n in names:
                if n.startswith("self."):
                    mi.attr_types[(cls, n[5:])] = ctor

    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            scan_assign(node, None, None)
        elif isinstance(node, ast.ClassDef):
            mi.classes[node.name] = node
            for sub in node.body:
                if isinstance(sub, ast.Assign):
                    # class-level lock attrs (TaskHandle._seq_lock)
                    if isinstance(sub.value, ast.Call) \
                            and _is_lock_ctor(sub.value):
                        for t in sub.targets:
                            for n in _target_names(t):
                                mi.locks[(node.name, f"self.{n}")] = \
                                    f"{mi.name}.{node.name}.{n}"
                elif isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    key = (mi.key, node.name, sub.name)
                    mi.functions[(node.name, sub.name)] = _FuncInfo(
                        key, sub, node.name, mi)
                    for stmt in ast.walk(sub):
                        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                            scan_assign(stmt, sub.name, node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = (mi.key, None, node.name)
            mi.functions[(None, node.name)] = _FuncInfo(key, node, None, mi)
            for stmt in ast.walk(node):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    scan_assign(stmt, node.name, None)
    _collect_lifecycle_evidence(mi)
    return mi


def _collect_lifecycle_evidence(mi: _ModuleInfo) -> None:
    """Typed join/shutdown evidence for the leak detectors: only a
    call on a thread/executor-typed receiver counts (a for-loop target
    iterating a thread collection is thread-typed too)."""
    threadish = set(mi.thread_names) | set(mi.thread_collections)
    execish = set(mi.executor_names)
    for (cls, attr), ctor in mi.attr_types.items():
        if ctor == "Thread":
            threadish.add(f"self.{attr}")
        elif ctor == "ThreadPoolExecutor":
            execish.add(f"self.{attr}")
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.For) \
                and any(n in threadish
                        for n in _target_names(node.iter)):
            threadish.update(_target_names(node.target))
    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        recv = _target_names(node.func.value)
        if node.func.attr == "join" \
                and any(r in threadish for r in recv):
            mi.has_thread_join = True
        elif node.func.attr == "shutdown" \
                and any(r in execish for r in recv):
            mi.has_executor_shutdown = True


# ---------------------------------------------------------------------------
# per-function walk: acquisitions, edges, blocking calls
# ---------------------------------------------------------------------------


class _FuncWalker(ast.NodeVisitor):
    """Walk one function with a running held-lock stack.  Nested
    function definitions are walked in the SAME instance (they close
    over the same self and usually run on a different thread — their
    bodies still belong to this lexical scope for lock naming)."""

    def __init__(self, repo: _Repo, fi: _FuncInfo):
        self.repo = repo
        self.fi = fi
        self.mi = fi.module
        self.held: List[str] = []
        #: (held_tuple, callee_key) — interprocedural edges resolved
        #: in the propagation pass
        self.calls_under: List[Tuple[Tuple[str, ...],
                                     Tuple[str, Optional[str], str],
                                     int]] = []
        self.scope_names: List[str] = [getattr(fi.node, "name",
                                               "<module>")]

    # -- lock name resolution -------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        for name in _target_names(expr):
            if name.startswith("self.") and self.fi.cls:
                hit = self.mi.locks.get((self.fi.cls, name))
                if hit:
                    return hit
            for scope in (*reversed(self.scope_names), None):
                hit = self.mi.locks.get((scope, name))
                if hit:
                    return hit
        return None

    # -- emission --------------------------------------------------------
    def _acquire(self, lock: str, node: ast.AST):
        self.fi.acquires.add(lock)
        for h in self.held:
            if h != lock:
                self.repo.edges.setdefault(
                    (h, lock), (self.mi.path, node.lineno))

    def _finding(self, node: ast.AST, rule: str, msg: str):
        self.repo.findings.append(
            Finding(self.mi.path, node.lineno, rule, msg))

    # -- visitors --------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            # `with ThreadPoolExecutor(...) as ex:` IS the bounded
            # lifecycle — mark before the context expr is visited
            if isinstance(item.context_expr, ast.Call):
                item.context_expr._in_with = True
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self._acquire(lock, node)
                self.held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self.held.remove(lock)
        # context expressions may contain calls too
        for item in node.items:
            self.visit(item.context_expr)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested def: walked here with an EMPTY held stack of its own —
        # it executes later (usually on another thread), not at the
        # definition point where outer locks may be held
        outer_held, self.held = self.held, []
        self.scope_names.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self.scope_names.pop()
        self.held = outer_held

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        held = tuple(self.held)

        # direct .acquire() on a known lock
        if name == "acquire" and isinstance(node.func, ast.Attribute):
            lock = self._lock_of(node.func.value)
            if lock is not None:
                self._acquire(lock, node)

        # Condition/Event .wait()
        if name == "wait" and isinstance(node.func, ast.Attribute):
            has_timeout = bool(node.args) or _has_kwarg(node, "timeout")
            waited_on = self._lock_of(node.func.value)
            if not has_timeout:
                self._finding(
                    node, "untimed-wait",
                    f"{ast.unparse(node.func)}() without a timeout — a "
                    "missed/raced notify parks this thread forever and "
                    "shutdown cannot reap it; pass a timeout and re-check "
                    "the predicate (suppress with a justification when "
                    "every producer provably notifies under this lock)")
            other_held = [h for h in held if h != waited_on]
            if other_held and waited_on is not None:
                self._finding(
                    node, "blocking-in-lock",
                    f"waiting on {waited_on} while still holding "
                    f"{other_held[-1]} — wait() only releases its own "
                    "lock; every waiter on the held lock stalls until "
                    "this thread is notified")

        # blocking calls while holding a lock
        if held:
            blocking = None
            if name in _BLOCKING_NET:
                blocking = f"network I/O ({name})"
            elif name == "sleep":
                blocking = "time.sleep"
            elif name in ("device_get", "block_until_ready"):
                blocking = f"device sync ({name})"
            elif name == "result" and not node.args \
                    and not node.keywords:
                blocking = "Future.result() (unbounded)"
            elif name == "join" and isinstance(node.func, ast.Attribute):
                has_timeout = bool(node.args) or _has_kwarg(node, "timeout")
                base_names = _target_names(node.func.value)
                threadish = any(
                    b in self.mi.thread_names
                    or (b.startswith("self.") and self.fi.cls
                        and self.mi.attr_types.get(
                            (self.fi.cls, b[5:])) == "Thread")
                    for b in base_names)
                if threadish and not has_timeout:
                    blocking = "Thread.join() (unbounded)"
            elif name in ("get", "put") \
                    and isinstance(node.func, ast.Attribute):
                base_names = _target_names(node.func.value)
                queueish = any(
                    b in self.mi.queue_names
                    or (b.startswith("self.") and self.fi.cls
                        and self.mi.attr_types.get((self.fi.cls, b[5:]))
                        in ("Queue", "LifoQueue", "PriorityQueue"))
                    for b in base_names)
                if queueish and not _has_kwarg(node, "timeout"):
                    blocking = f"queue.{name}() (unbounded)"
            if blocking is not None:
                self._finding(
                    node, "blocking-in-lock",
                    f"{blocking} while holding {held[-1]} — every "
                    "waiter on that lock stalls for the full blocking "
                    "latency; move the call outside the critical "
                    "section")

        # thread / executor / queue / server construction
        if name == "Thread":
            self._check_thread(node)
        elif name == "ThreadPoolExecutor":
            self._check_executor(node)
        elif name in ("Queue", "LifoQueue", "PriorityQueue"):
            if not node.args and not _has_kwarg(node, "maxsize"):
                self._finding(
                    node, "unbounded-queue",
                    f"queue.{name}() without maxsize — a producer "
                    "outrunning its consumer grows it without bound, "
                    "invisible to the memory plane; pass a bounded, "
                    "config-derived maxsize")
        elif name == "ThreadingHTTPServer":
            if not self._module_has("server_close"):
                self._finding(
                    node, "server-leak",
                    "ThreadingHTTPServer with no server_close() in this "
                    "module — the listening socket leaks on shutdown")

        # record call edges for interprocedural propagation
        callee = self._resolve_callee(node)
        if callee is not None:
            self.fi.calls.add(callee)
            if held:
                self.calls_under.append((held, callee, node.lineno))

        self.generic_visit(node)

    # -- thread/executor lifecycle ---------------------------------------
    def _module_has(self, needle: str) -> bool:
        return any(needle in ln for ln in self.mi.lines)

    def _check_thread(self, node: ast.Call) -> None:
        if not _has_kwarg(node, "target") and not node.args:
            return  # bare Thread subclass/annotation use
        if not _has_kwarg(node, "name"):
            self._finding(
                node, "unnamed-thread",
                "Thread without name= — sanitizer reports, trace "
                "exports, and stack dumps cannot attribute anonymous "
                "threads; name it after its role")
        daemon = _kwarg(node, "daemon")
        is_daemon = isinstance(daemon, ast.Constant) and \
            daemon.value is True
        if not is_daemon and not self.mi.has_thread_join:
            self._finding(
                node, "thread-leak",
                "non-daemon Thread with no join() reachable in this "
                "module — it blocks interpreter exit and accumulates "
                "under concurrent queries; join it on every path "
                "(try/finally) or mark it daemon with a bounded-work "
                "argument")

    def _check_executor(self, node: ast.Call) -> None:
        # used as a context manager right here?
        if getattr(node, "_in_with", False):
            return
        if not self.mi.has_executor_shutdown:
            self._finding(
                node, "executor-leak",
                "ThreadPoolExecutor neither used as a context manager "
                "nor shutdown() anywhere in this module — its worker "
                "threads leak past the owning scope")

    # -- callee resolution ------------------------------------------------
    def _resolve_callee(self, node: ast.Call) \
            -> Optional[Tuple[str, Optional[str], str]]:
        fn = node.func
        if isinstance(fn, ast.Name):
            # module-local function or repo class constructor
            if (None, fn.id) in self.mi.functions:
                return (self.mi.key, None, fn.id)
            cls_mod = self.repo.class_index.get(fn.id)
            if cls_mod is not None:
                return (cls_mod, fn.id, "__init__")
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and self.fi.cls:
                if (self.fi.cls, fn.attr) in self.mi.functions:
                    return (self.mi.key, self.fi.cls, fn.attr)
                return None
            # self.<attr>.<method>() where attr's class is known
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and self.fi.cls:
                cls = self.mi.attr_types.get((self.fi.cls, base.attr))
                if cls:
                    mod = self.repo.class_index.get(cls)
                    if mod is not None:
                        return (mod, cls, fn.attr)
        return None


# ---------------------------------------------------------------------------
# race detection
# ---------------------------------------------------------------------------


class _RaceScanner:
    """Per class: find attributes written from both thread-context and
    coordinator-context with at least one unlocked write."""

    def __init__(self, repo: _Repo, mi: _ModuleInfo, cls: ast.ClassDef):
        self.repo = repo
        self.mi = mi
        self.cls = cls
        #: attr -> (lineno, in_thread, protected, is_const_store, is_rmw)
        self.writes: Dict[str, List[Tuple[int, bool, bool, bool,
                                          bool]]] = {}

    def _thread_entry_names(self) -> Tuple[Set[str], bool]:
        """(names passed as Thread target= / executor .submit() inside
        this class, whether entries can run CONCURRENTLY — several
        construction sites, or construction inside a loop/
        comprehension)."""
        out: Set[str] = set()
        sites = 0
        looped = False

        def scan(node: ast.AST, in_loop: bool) -> None:
            nonlocal sites, looped
            for child in ast.iter_child_nodes(node):
                child_in_loop = in_loop or isinstance(
                    node, (ast.For, ast.While, ast.ListComp, ast.SetComp,
                           ast.GeneratorExp))
                if isinstance(child, ast.Call):
                    name = _call_name(child)
                    tgt = None
                    if name == "Thread":
                        tgt = _kwarg(child, "target")
                    elif name == "submit" and child.args:
                        tgt = child.args[0]
                    if tgt is not None:
                        sites += 1
                        looped = looped or child_in_loop
                        if isinstance(tgt, ast.Name):
                            out.add(tgt.id)
                        elif isinstance(tgt, ast.Attribute):
                            out.add(tgt.attr)
                scan(child, child_in_loop)

        scan(self.cls, False)
        return out, (sites > 1 or looped)

    def scan(self) -> None:
        has_lock = any(c == self.cls.name for (c, _a) in self.mi.locks)
        entries, concurrent = self._thread_entry_names()
        if not entries:
            return  # no threads started by this class: nothing to race

        # thread-context closure: entry methods plus same-class methods
        # they (transitively) call — those writes also run on the
        # spawned thread
        thread_methods = set(entries)
        changed = True
        while changed:
            changed = False
            for (cls, fname), fi in self.mi.functions.items():
                if cls != self.cls.name or fname not in thread_methods:
                    continue
                for (cm, cc, cf) in fi.calls:
                    if cm == self.mi.key and cc == self.cls.name \
                            and cf not in thread_methods:
                        thread_methods.add(cf)
                        changed = True

        for sub in self.cls.body:
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if sub.name == "__init__":
                continue
            self._scan_function(sub, sub.name in thread_methods,
                                thread_methods)

        lockhint = ("declare/extend a lock around every access"
                    if has_lock else "the class declares no lock at all")
        for attr, ws in self.writes.items():
            in_thread = [w for w in ws if w[1]]
            in_coord = [w for w in ws if not w[1]]
            unprotected = [w for w in ws if not w[2] and not w[3]]
            if in_thread and in_coord and unprotected:
                w = unprotected[0]
                self.repo.findings.append(Finding(
                    self.mi.path, w[0], "shared-state-race",
                    f"self.{attr} is written from both thread-target "
                    f"and coordinator code, and this write holds no "
                    f"lock — a read-modify-write here loses updates; "
                    f"{lockhint}"))
                continue
            if not concurrent:
                continue
            # several thread instances share self: an unprotected
            # read-modify-write races its siblings even with no
            # coordinator-side writer (AugAssign only — w[4])
            rmw = [w for w in in_thread if not w[2] and w[4]]
            if rmw:
                self.repo.findings.append(Finding(
                    self.mi.path, rmw[0][0], "shared-state-race",
                    f"self.{attr} takes an unlocked read-modify-write "
                    f"from a thread entry this class runs CONCURRENTLY "
                    f"(multiple workers) — += is not atomic; updates "
                    f"are lost under contention; {lockhint}"))

    def _scan_function(self, fn: ast.AST, in_thread: bool,
                       thread_methods: Set[str]) -> None:
        """Record self.X writes with their lock protection; nested
        defs are thread context when their name was a Thread target."""
        cls_name = self.cls.name

        class W(ast.NodeVisitor):
            def __init__(w, mi: _ModuleInfo, outer: "_RaceScanner"):
                w.mi = mi
                w.outer = outer
                w.held = 0
                w.thread_ctx = in_thread
                w.scope = fn.name

            def visit_With(w, node: ast.With) -> None:
                lockish = 0
                for item in node.items:
                    for name in _target_names(item.context_expr):
                        if ((cls_name, name) in w.mi.locks
                                or (w.scope, name) in w.mi.locks
                                or (None, name) in w.mi.locks):
                            lockish += 1
                            break
                w.held += 1 if lockish else 0
                w.generic_visit(node)
                w.held -= 1 if lockish else 0

            def visit_FunctionDef(w, node: ast.FunctionDef) -> None:
                prev_ctx, prev_held = w.thread_ctx, w.held
                if node.name in thread_methods:
                    w.thread_ctx = True
                w.held = 0  # nested def runs later: locks not held
                w.generic_visit(node)
                w.thread_ctx, w.held = prev_ctx, prev_held

            visit_AsyncFunctionDef = visit_FunctionDef

            def _record(w, target: ast.AST, lineno: int,
                        const: bool, rmw: bool) -> None:
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self":
                    w.outer.writes.setdefault(target.attr, []).append(
                        (lineno, w.thread_ctx, w.held > 0, const, rmw))

            def visit_Assign(w, node: ast.Assign) -> None:
                const = isinstance(node.value, ast.Constant)
                for t in node.targets:
                    w._record(t, node.lineno, const, False)
                w.generic_visit(node)

            def visit_AugAssign(w, node: ast.AugAssign) -> None:
                w._record(node.target, node.lineno, False, True)
                w.generic_visit(node)

        W(self.mi, self).visit(fn)


# ---------------------------------------------------------------------------
# whole-repo driver
# ---------------------------------------------------------------------------


def iter_py_files(root: str) -> Iterator[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def build_repo(paths) -> _Repo:
    repo = _Repo()
    for root in paths:
        for path in iter_py_files(root):
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue
            if not any(marker in source for marker in
                       ("threading", "Thread", "queue",
                        "named_lock", "named_condition")):
                continue  # no concurrency surface: skip the walks
            mi = _scan_module(path, tree, source)
            repo.modules[mi.key] = mi
            for cls in mi.classes:
                repo.class_index.setdefault(cls, mi.key)
    return repo


def analyze(paths) -> Tuple[List[Finding], dict]:
    """Run every detector over ``paths``.  Returns (findings, report);
    the report carries the lock graph + cycles for the runtime
    cross-check (tools/lock_sanitizer.py)."""
    repo = build_repo(paths)

    # pass 2: per-function walks (edges, blocking, lifecycle)
    walkers: Dict[Tuple[str, Optional[str], str], _FuncWalker] = {}
    for mi in repo.modules.values():
        for fi in mi.functions.values():
            w = _FuncWalker(repo, fi)
            for stmt in fi.node.body:
                w.visit(stmt)
            walkers[fi.key] = w
        # module scope is a pseudo-function too (import-time Thread /
        # Queue / server constructions); class and def bodies are
        # walked above, so only bare top-level statements go here
        mod_fi = _FuncInfo((mi.key, None, "<module>"), mi.tree, None, mi)
        w = _FuncWalker(repo, mod_fi)
        w.scope_names = ["<module>"]
        for stmt in mi.tree.body:
            if not isinstance(stmt, (ast.ClassDef, ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                w.visit(stmt)
        walkers[mod_fi.key] = w

    # pass 3: interprocedural lock-set propagation.  may_acquire(f) =
    # direct acquires + union over callees, to a fixed point
    may_acquire: Dict[Tuple[str, Optional[str], str], Set[str]] = {
        k: set(walkers[k].fi.acquires) for k in walkers}
    changed = True
    while changed:
        changed = False
        for k, w in walkers.items():
            acc = may_acquire[k]
            before = len(acc)
            for callee in w.fi.calls:
                acc |= may_acquire.get(callee, set())
            if len(acc) != before:
                changed = True

    # edges through calls: held locks at a call site reach everything
    # the callee may acquire
    for k, w in walkers.items():
        for held, callee, lineno in w.calls_under:
            for lock in may_acquire.get(callee, ()):
                for h in held:
                    if h != lock:
                        repo.edges.setdefault(
                            (h, lock), (w.mi.path, lineno))

    # pass 4: cycles in the lock graph
    cycles = _find_cycles(repo.edges)
    for cyc in cycles:
        witness = repo.edges[(cyc[0], cyc[1 % len(cyc)])]
        chain = " -> ".join(cyc + [cyc[0]])
        repo.findings.append(Finding(
            witness[0], witness[1], "lock-order",
            f"potential deadlock: lock-acquisition cycle {chain} — "
            "impose one global order (or collapse to one lock); run "
            "tools/lock_sanitizer.py to check whether the runtime "
            "observes this cycle"))

    # pass 5: races
    for mi in repo.modules.values():
        for cls in mi.classes.values():
            _RaceScanner(repo, mi, cls).scan()

    report = {
        "edges": sorted([a, b, list(repo.edges[(a, b)])]
                        for (a, b) in repo.edges),
        "cycles": [list(c) for c in cycles],
        "locks": sorted({n for e in repo.edges for n in e}
                        | {a for mi in repo.modules.values()
                           for a in mi.locks.values()}),
    }
    repo.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return repo.findings, report


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]) \
        -> List[List[str]]:
    """Simple cycles in the lock graph, deduped by canonical ROTATION
    (smallest node first) — not by node set: a->b->c->a and
    a->c->b->a are two distinct deadlock cycles over the same locks,
    and the runtime cross-check must see both orientations.  The
    graphs are tiny (tens of nodes), so a DFS per node is fine."""
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    cycles: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            visited: Set[str]) -> None:
        for nxt in adj.get(node, ()):
            if nxt == start and len(path) > 1:
                # canonical rotation: smallest node first
                i = path.index(min(path))
                key = tuple(path[i:] + path[:i])
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(key))
            elif nxt not in visited and nxt > start:
                # only explore nodes > start: each cycle found once,
                # from its smallest node
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return cycles


def crosscheck(static_report: dict, runtime_report: dict) -> dict:
    """Compare the static lock graph against a runtime observation
    (presto_tpu.sync.WATCHER.report()).  For each static cycle:

    - **confirmed**: the cycle closes in the observed graph — every
      arc directly observed, or every arc completed by an observed
      transitive path (a runtime-cyclic ordering over these locks
      either way): the deadlock is one unlucky interleaving away;
    - **refuted**: every arc was either observed directly or DIRECTLY
      contradicted (its reverse edge observed), and the whole doesn't
      close — the runtime walked each leg of the cycle and took a
      consistent, acyclic order: evidence, not proof, that the static
      cycle is a false-positive of path-insensitivity.  Partial
      observation is NOT refutation — a cycle with 2 of 3 arcs
      observed and the third leg never exercised is one interleaving
      short of confirmed, not dismissed (and transitive orientation
      doesn't count here: the observed prefix of ANY partial cycle
      trivially orients its own missing arc);
    - **unobserved**: the test run never exercised enough of the cycle
      to say either way.
    """
    observed = {(a, b) for a, b, _n in runtime_report.get("edges", [])}
    adj: Dict[str, List[str]] = {}
    for a, b in observed:
        adj.setdefault(a, []).append(b)

    def reach(src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            nxt = []
            for n in frontier:
                for m in adj.get(n, ()):
                    if m == dst:
                        return True
                    if m not in seen:
                        seen.add(m)
                        nxt.append(m)
            frontier = nxt
        return False

    out = {"cycles": [], "inversions": runtime_report.get("inversions", []),
           "observed_edges": len(observed)}
    for cyc in static_report.get("cycles", []):
        arcs = [(cyc[i], cyc[(i + 1) % len(cyc)]) for i in range(len(cyc))]
        hit = sum(1 for a in arcs if a in observed)
        if all(reach(u, v) for u, v in arcs):
            # direct or transitive, the observed order closes the cycle
            verdict = "confirmed"
        elif all(((u, v) in observed) != ((v, u) in observed)
                 for u, v in arcs):
            # every leg exercised, each in exactly one direction, and
            # the whole doesn't close: a consistent global order
            verdict = "refuted"
        else:
            verdict = "unobserved"
        out["cycles"].append({"cycle": cyc, "edges_observed": hit,
                              "edges_total": len(arcs),
                              "verdict": verdict})
    return out
