"""Pushdown predicate language: Domain / TupleDomain.

Reference analog: ``presto-spi/.../spi/predicate/`` — ``TupleDomain``
(column -> Domain map, the engine<->connector pushdown contract),
``Domain`` (value set + nullability) and ``Range``.  Collapsed to the
ordered-range form the TPU engine's device representations use: every
column value is an int/float in device space (epoch days, scaled
decimals, dictionary codes), so a Domain is a list of closed numeric
ranges plus a null flag.

Used by the planner to summarize scan conjuncts, by split pruning
(min/max stats vs domain overlap) and by connectors that can skip or
pre-filter data (the ConnectorTableLayout / constraint path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

_NEG_INF = float("-inf")
_POS_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class Range:
    """Closed numeric interval [low, high] in device value space."""

    low: float = _NEG_INF
    high: float = _POS_INF

    def overlaps(self, other: "Range") -> bool:
        return self.low <= other.high and other.low <= self.high

    def intersect(self, other: "Range") -> Optional["Range"]:
        lo, hi = max(self.low, other.low), min(self.high, other.high)
        return Range(lo, hi) if lo <= hi else None


@dataclasses.dataclass(frozen=True)
class Domain:
    """Allowed values of one column: union of ranges + NULL flag
    (spi/predicate/Domain.java)."""

    ranges: Tuple[Range, ...] = (Range(),)
    null_allowed: bool = False

    @classmethod
    def all(cls) -> "Domain":
        return cls((Range(),), True)

    @classmethod
    def single(cls, value) -> "Domain":
        v = float(value)
        return cls((Range(v, v),), False)

    @classmethod
    def range(cls, low=None, high=None) -> "Domain":
        return cls((Range(_NEG_INF if low is None else float(low),
                          _POS_INF if high is None else float(high)),), False)

    @classmethod
    def only_null(cls) -> "Domain":
        return cls((), True)

    @property
    def is_none(self) -> bool:
        """Provably empty: no ranges and no NULL."""
        return not self.ranges and not self.null_allowed

    def intersect(self, other: "Domain") -> "Domain":
        out: List[Range] = []
        for a in self.ranges:
            for b in other.ranges:
                got = a.intersect(b)
                if got is not None:
                    out.append(got)
        return Domain(tuple(out), self.null_allowed and other.null_allowed)

    def union(self, other: "Domain") -> "Domain":
        return Domain(tuple(self.ranges) + tuple(other.ranges),
                      self.null_allowed or other.null_allowed)

    def overlaps_stats(self, lo, hi) -> bool:
        """Could any value in [lo, hi] satisfy this domain? (split
        pruning: ORC stripe-stats role)."""
        if self.null_allowed:
            return True  # stats say nothing about nulls
        probe = Range(float(lo), float(hi))
        return any(r.overlaps(probe) for r in self.ranges)

    def contains_value(self, v) -> bool:
        v = float(v)
        return any(r.low <= v <= r.high for r in self.ranges)


@dataclasses.dataclass(frozen=True)
class TupleDomain:
    """Per-column Domain conjunction (spi/predicate/TupleDomain.java).
    Columns absent from the map are unconstrained."""

    domains: Tuple[Tuple[str, Domain], ...] = ()

    @classmethod
    def all(cls) -> "TupleDomain":
        return cls(())

    @classmethod
    def of(cls, mapping: Dict[str, Domain]) -> "TupleDomain":
        return cls(tuple(sorted(mapping.items())))

    def as_dict(self) -> Dict[str, Domain]:
        return dict(self.domains)

    @property
    def is_none(self) -> bool:
        return any(d.is_none for _, d in self.domains)

    def domain(self, column: str) -> Domain:
        for c, d in self.domains:
            if c == column:
                return d
        return Domain.all()

    def intersect(self, other: "TupleDomain") -> "TupleDomain":
        merged = self.as_dict()
        for c, d in other.domains:
            merged[c] = merged[c].intersect(d) if c in merged else d
        return TupleDomain.of(merged)

    def overlaps_split_stats(self, stats: Dict[str, Tuple[float, float]]) -> bool:
        """False when the split's min/max stats prove no row matches."""
        for col, dom in self.domains:
            st = stats.get(col)
            if st is None:
                continue
            if not dom.overlaps_stats(st[0], st[1]):
                return False
        return True

    @classmethod
    def from_constraints(
        cls, constraints: Sequence[Tuple[str, str, float]]
    ) -> "TupleDomain":
        """Build from the planner's (col, op, value) conjunct triples."""
        merged: Dict[str, Domain] = {}
        for col, op, v in constraints:
            if op == "eq":
                d = Domain.single(v)
            elif op in ("lt", "le"):
                d = Domain.range(high=v)
            elif op in ("gt", "ge"):
                d = Domain.range(low=v)
            else:
                continue
            merged[col] = merged[col].intersect(d) if col in merged else d
        return cls.of(merged)
