"""Transaction management.

Reference analog: ``transaction/TransactionManager.java`` — per-query
transactions with connector-scoped ``ConnectorTransactionHandle``s,
autocommit for standalone statements, and explicit
START TRANSACTION / COMMIT / ROLLBACK driven through the session
(Session.java's transactionId).  Isolation here is snapshot-free
read-committed over the engine's immutable pages: reads see published
table state; writes stage per-transaction and publish atomically at
commit.

Connectors opt in by implementing the duck-typed hooks
``begin_transaction() -> handle``, ``commit_transaction(handle)`` and
``rollback_transaction(handle)``; connectors without the hooks behave
as autocommit-only (the reference's ConnectorMetadata.beginQuery
no-op default).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, Optional

from presto_tpu.sync import named_lock


class TransactionError(Exception):
    pass


class Transaction:
    """One open transaction: connector name -> connector tx handle."""

    def __init__(self, tx_id: str, read_only: bool = False):
        self.tx_id = tx_id
        self.read_only = read_only
        self.created_at = time.time()
        self.handles: Dict[str, object] = {}
        self._connectors: Dict[str, object] = {}

    def handle_for(self, connector_name: str, connector) -> Optional[object]:
        """Lazily open the connector-side transaction the first time a
        statement inside this tx touches that connector."""
        if connector_name not in self.handles:
            begin = getattr(connector, "begin_transaction", None)
            self.handles[connector_name] = begin() if begin else None
            self._connectors[connector_name] = connector
        return self.handles[connector_name]

    def commit(self) -> None:
        for name, handle in self.handles.items():
            conn = self._connectors[name]
            fn = getattr(conn, "commit_transaction", None)
            if fn and handle is not None:
                fn(handle)

    def rollback(self) -> None:
        for name, handle in self.handles.items():
            conn = self._connectors[name]
            fn = getattr(conn, "rollback_transaction", None)
            if fn and handle is not None:
                fn(handle)


class TransactionManager:
    """Registry of open transactions (TransactionManager.java analog).
    One open transaction per session at most; autocommit transactions
    are created and resolved around a single statement."""

    def __init__(self):
        self._open: Dict[str, Transaction] = {}
        self._lock = named_lock("transaction.TransactionManager._lock")

    def begin(self, read_only: bool = False) -> Transaction:
        tx = Transaction(f"tx_{uuid.uuid4().hex[:12]}", read_only)
        with self._lock:
            self._open[tx.tx_id] = tx
        return tx

    def get(self, tx_id: str) -> Transaction:
        with self._lock:
            tx = self._open.get(tx_id)
        if tx is None:
            raise TransactionError(f"unknown or closed transaction {tx_id}")
        return tx

    def commit(self, tx_id: str) -> None:
        tx = self.get(tx_id)
        try:
            tx.commit()
        finally:
            with self._lock:
                self._open.pop(tx_id, None)

    def rollback(self, tx_id: str) -> None:
        tx = self.get(tx_id)
        try:
            tx.rollback()
        finally:
            with self._lock:
                self._open.pop(tx_id, None)

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)
