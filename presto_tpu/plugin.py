"""Plugin loading: connector factories from external module files.

Reference analog: ``server/PluginManager.java`` + ``spi/Plugin.java`` —
each plugin directory's jar exposes a Plugin whose factories register
into the engine.  Python version: each ``*.py`` file in the plugin
directory is imported as its own module (namespaced under
``presto_tpu_plugins.<file>`` — the classloader-isolation analog is
module-namespace isolation; python cannot isolate transitive imports
the way PluginClassLoader does) and must define::

    PLUGIN = {
        "name": "my-plugin",
        "connector_factories": {"mykind": lambda props: MyConnector(...)},
    }

``EngineConfig.build_catalog`` consults registered factories for any
``connector.name`` the builtins don't know.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import Callable, Dict, List, Optional


class PluginManager:
    def __init__(self):
        self.plugins: List[dict] = []
        self.connector_factories: Dict[str, Callable] = {}

    def load_directory(self, plugin_dir: str) -> List[str]:
        """Import every *.py in ``plugin_dir`` as an isolated module and
        register its PLUGIN declaration; returns loaded plugin names."""
        loaded = []
        if not os.path.isdir(plugin_dir):
            return loaded
        for fn in sorted(os.listdir(plugin_dir)):
            if not fn.endswith(".py") or fn.startswith("_"):
                continue
            name = fn[:-3]
            loaded.append(self.load_file(os.path.join(plugin_dir, fn), name))
        return loaded

    def load_file(self, path: str, name: Optional[str] = None) -> str:
        modname = f"presto_tpu_plugins.{name or os.path.basename(path)[:-3]}"
        spec = importlib.util.spec_from_file_location(modname, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[modname] = mod
        spec.loader.exec_module(mod)
        decl = getattr(mod, "PLUGIN", None)
        if not isinstance(decl, dict) or "name" not in decl:
            raise ValueError(f"{path}: no PLUGIN declaration")
        self.plugins.append(decl)
        for kind, factory in decl.get("connector_factories", {}).items():
            if kind in self.connector_factories:
                raise ValueError(f"duplicate connector factory {kind!r}")
            self.connector_factories[kind] = factory
        return decl["name"]

    def make_connector(self, kind: str, props: Dict[str, str]):
        factory = self.connector_factories.get(kind)
        if factory is None:
            raise KeyError(kind)
        return factory(props)
