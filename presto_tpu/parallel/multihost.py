"""Multi-host distributed execution over HTTP workers (the DCN tier).

Reference analog: the coordinator's distributed scheduling stack —
``SqlQueryScheduler.java:441`` (stage scheduling), split placement
(``scheduler/NodeScheduler.java``), ``HttpRemoteTask.java:99`` with
``RequestErrorTracker``/``Backoff`` (transient RPC tolerance), and
``failureDetector/HeartbeatFailureDetector.java:77`` (exclude dead
nodes from scheduling).

TPU framing: the ICI tier (parallel/dist.py) shards a query across the
chips of one slice; THIS tier fans leaf fragments out across hosts
(each host owning its own slice/chip) and merges partial aggregation
states at the coordinator — i.e. the cross-slice exchange rides DCN as
serialized partial-state pages, while intra-fragment work stays
all-XLA on each host.  Unlike the reference (any task failure fails
the query, SURVEY.md §2.2 recovery row), leaf fragments here are pure
functions of (table, splits), so a failed worker's splits are
re-scheduled on the survivors.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time

import numpy as np
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

from presto_tpu.analysis.protocols import RECORDER
from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner, MaterializedResult, concat_pages_device
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    WindowNode,
)
from presto_tpu.server.serde import deserialize_page, plan_to_json
from presto_tpu.sync import named_lock

_log = logging.getLogger("presto_tpu.multihost")

#: distinguishes concurrent failover drains in one process — each gets
#: its own retry spec-automaton run (conformance tracing only)
_FAILOVER_SEQ = itertools.count(1)


class TaskFailed(Exception):
    """The remote task hit a deterministic query error (its fragment
    raised) — distinct from worker/transport failure, so the caller
    neither retries nor excludes the worker."""


class _StageCapacity(Exception):
    """A stage-2 task overflowed its group capacity; the caller doubles
    max_groups and re-runs the stage."""


#: error-text markers that mean a WORKER/transport fault (fall back to
#: a degraded path) rather than a deterministic query failure
_TRANSPORT_MARKERS = ("URLError", "Connection refused", "ConnectionRefused",
                      "RemoteDisconnected", "TimeoutError", "timed out",
                      "no progress",
                      # CRC damage in a worker-to-worker shuffle pull
                      # surfaces inside the stage-2 task's error text;
                      # the fragment is pure, so it recomputes (net.py
                      # classifies PageIntegrityError transient)
                      "PageIntegrityError")


class MultiHostUnsupported(Exception):
    pass


class _StreamBroken(ConnectionError):
    """A producing worker died mid-stream AFTER the consumer took
    ``delivered`` pages: the failover re-run must replay from that
    watermark (skip the first ``delivered`` pages) instead of
    recomputing into duplicates — the streaming twin of the
    all-or-nothing fragment retry."""

    def __init__(self, delivered: int, cause: BaseException):
        super().__init__(f"{type(cause).__name__}: {cause}")
        self.delivered = delivered


class WorkerClient:
    """One remote worker (HttpRemoteTask + Backoff analog). Results
    stream through the worker's acked pull buffers: long-poll GETs with
    token acknowledgement (ExchangeClient/HttpPageBufferClient.java:291
    sendGetResults + .../acknowledge), so large shuffles never hold a
    whole task's output in one response and the producer sees
    backpressure from unacknowledged bytes."""

    def __init__(self, uri: str, max_attempts: int = 3, timeout: float = 300.0,
                 detector=None):
        self.uri = uri.rstrip("/")
        self.max_attempts = max_attempts
        self.timeout = timeout
        self.alive = True
        # failure detector sink (parallel/failure.py): every real
        # protocol outcome feeds the same state machine the background
        # heartbeat does, so the circuit breaker sees fragment traffic
        self.detector = detector
        # request-correlation token stamped by the runner before a
        # fan-out (X-Presto-Trace-Token, the reference's
        # GenerateTraceTokenRequestFilter contract): every task POST
        # carries it so worker-side spans stitch into the query's trace
        self.trace_token: Optional[str] = None
        # estimate-vs-actual roll-up: when the runner installs a sink,
        # every task POST asks the worker to record per-operator
        # actuals, and delete_task fetches the FINISHED task's stats
        # snapshot before dropping it — delete is the one chokepoint
        # every task path (streamed, two-stage, retried) goes through
        self.collect_stats = False
        self.stats_sink = None  # (task id, wire entries) -> None

    def _ok(self) -> None:
        self.alive = True
        if self.detector is not None:
            self.detector.record_success(self.uri)

    def _failed(self, exc: BaseException) -> None:
        self.alive = False
        if self.detector is not None:
            self.detector.record_failure(
                self.uri, f"{type(exc).__name__}: {exc}")

    def ping(self, timeout: float = 5.0) -> bool:
        """Heartbeat probe with CLASSIFIED failure handling: each
        failure increments the per-reason net.errors_* counters and
        worker.ping_errors; state-transition logging is the failure
        detector's (once per edge, never per poll)."""
        from presto_tpu.net import request_json

        try:
            # site= counts ONCE per failure (worker.ping_errors +
            # net.errors_<reason>) inside the request helper
            request_json(f"{self.uri}/v1/info", timeout=timeout,
                         site="worker.ping_errors")
            self._ok()
        except Exception as e:
            self._failed(e)
        return self.alive

    def run_fragment(self, fragment_json: dict) -> List[bytes]:
        from presto_tpu.net import is_transient

        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                # a fresh task id per attempt: fragments are pure, so a
                # retried task simply recomputes (at-least-once overall,
                # de-duplicated by task id server-side)
                out = self._pull_task(fragment_json)
                self._ok()
                return out
            except TaskFailed:
                # a deterministic query error, NOT a worker fault:
                # retrying recomputes the same failure and blaming the
                # worker would poison failover
                raise
            except Exception as e:
                if not is_transient(e):
                    # deterministic by classification (net.py): never
                    # retried, never blamed on the worker
                    raise TaskFailed(f"{type(e).__name__}: {e}") from e
                last = e
                time.sleep(min(0.1 * (2 ** attempt), 2.0))
        self._failed(last)
        raise ConnectionError(f"worker {self.uri} failed: {last}")

    def create_task(self, fragment_json: dict,
                    output_spec: Optional[dict] = None) -> str:
        """POST a task and return its id WITHOUT pulling results — the
        two-stage path's stage-1 tasks are drained by stage-2 workers,
        not by the coordinator (HttpRemoteTask's create half)."""
        import uuid

        tid = uuid.uuid4().hex[:16]
        body_dict = {"fragment": fragment_json}
        if output_spec is not None:
            body_dict["output"] = output_spec
        if self.collect_stats:
            body_dict["collect_stats"] = True
        body = json.dumps(body_dict).encode()
        headers = {"Content-Type": "application/json"}
        if self.trace_token:
            headers["X-Presto-Trace-Token"] = self.trace_token
        req = urllib.request.Request(
            f"{self.uri}/v1/task/{tid}", data=body, method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            json.load(resp)
        return tid

    def pull_results(self, tid: str) -> List[bytes]:
        """Drain buffer 0 of an already-created task (the pull half).
        Task failure surfaces through the pull itself: a failed task's
        buffer answers 500 with the error payload, and pull_pages also
        consults /v1/task/{id} on error (the continuous status
        fetcher's role, ContinuousTaskStatusFetcher analog, without a
        dedicated polling thread per pull)."""
        from presto_tpu.net import PageIntegrityError
        from presto_tpu.server.serde import verify_page
        from presto_tpu.server.shuffle_client import TaskPullFailed, pull_pages

        try:
            raws = list(pull_pages(self.uri, tid, 0, timeout=self.timeout))
        except TaskPullFailed as e:
            if "PageIntegrityError" in str(e):
                # the task failed because its INPUT page arrived
                # damaged — a transport fault, not a query error.
                # Retrying is safe for every fragment run_fragment
                # ships (scan-leaf and pre-chunk inputs travel INSIDE
                # the fragment, so a retry re-serializes fresh bytes);
                # RemoteSource consumers never come through here —
                # they run via _fan_out_stage2, whose transport-marker
                # triage falls back to a coordinator-merge that
                # recomputes from base tables rather than re-pulling a
                # drained upstream buffer
                raise PageIntegrityError(str(e)) from e
            raise TaskFailed(str(e)) from e
        for r in raws:
            # CRC check at the pull boundary: a damaged page raises
            # PageIntegrityError (transient) HERE, inside the caller's
            # retry loop, instead of poisoning the stage-level decode
            verify_page(r)
        return raws

    def delete_task(self, tid: str) -> None:
        if self.stats_sink is not None:
            # fetch-before-delete: only a FINISHED task's snapshot
            # merges (a retried attempt's partial stats would double-
            # count rows the fresh attempt recounts); best-effort like
            # the delete itself
            try:
                req = urllib.request.Request(f"{self.uri}/v1/task/{tid}")
                with urllib.request.urlopen(req, timeout=10.0) as resp:
                    status = json.load(resp)
                if status.get("state") == "FINISHED" and status.get("stats"):
                    self.stats_sink(tid, status["stats"])
            except Exception:
                pass
        try:
            req = urllib.request.Request(
                f"{self.uri}/v1/task/{tid}", method="DELETE")
            urllib.request.urlopen(req, timeout=10.0).close()
        except Exception:
            pass

    def _pull_task(self, fragment_json: dict) -> List[bytes]:
        """create + drain + delete, composed from the shared protocol
        pieces (one implementation of the token/ack long-poll loop:
        server/shuffle_client.pull_pages)."""
        tid = self.create_task(fragment_json)
        try:
            return self.pull_results(tid)
        finally:
            self.delete_task(tid)


class MultiHostRunner:
    """Fans leaf-fragment execution out to HTTP workers.

    Supported plan shape (same as DistributedRunner): post-agg nodes
    over a single-step aggregation over a scan-rooted chain.  The
    chain + partial aggregation run on workers over disjoint split
    assignments; final merge + post-processing run at the coordinator.
    """

    def __init__(self, catalog: Catalog, worker_uris: Sequence[str],
                 broadcast_threshold: Optional[int] = None,
                 worker_locations: Optional[dict] = None,
                 max_splits_per_node: int = 0,
                 execution_policy: str = "phased",
                 detector=None, events=None,
                 max_fragment_retries: Optional[int] = None,
                 exchange_streaming: Optional[bool] = None):
        from presto_tpu.parallel.failure import FailureDetector
        from presto_tpu.parallel.fragment import DEFAULT_BROADCAST_THRESHOLD

        self.catalog = catalog
        # failure detector: one state machine per worker, fed by every
        # ping AND every real fragment outcome; DEAD workers are
        # excluded from assignment until their backoff window lets one
        # optimistic probe through (the circuit breaker)
        self.detector = detector or FailureDetector(worker_uris)
        if events is not None:
            import time as _time

            from presto_tpu.events import WorkerStateChangeEvent

            self.detector.add_transition_listener(
                lambda uri, old, new, reason: events.worker_state_changed(
                    WorkerStateChangeEvent(
                        uri=uri, old_state=old, new_state=new,
                        reason=reason, change_time=_time.time())))
        self.workers = [WorkerClient(u, detector=self.detector)
                        for u in worker_uris]
        # per-stage fragment re-dispatch budget: bounds how long a
        # query chases a flapping cluster before the coordinator-local
        # fallback finishes the work itself
        self.max_fragment_retries = (max(4, 2 * len(self.workers))
                                     if max_fragment_retries is None
                                     else max_fragment_retries)
        # the coordinator-local fallback (and glue execution) runs its
        # scan splits through the morsel scheduler like every other
        # LocalRunner; worker-side fragments get it inside
        # server/worker.py's runner (same exec/tasks.py pool knobs)
        self.local = LocalRunner(catalog)
        self.broadcast_threshold = (DEFAULT_BROADCAST_THRESHOLD
                                    if broadcast_threshold is None
                                    else broadcast_threshold)
        # scheduling policies (scheduler.py): split placement locality
        # keyed by worker URI, per-node split backpressure, and the
        # build-before-probe stage launch ordering
        if execution_policy not in ("phased", "all_at_once"):
            raise ValueError(
                f"execution_policy must be 'phased' or 'all_at_once', "
                f"got {execution_policy!r}")
        locs = {k.rstrip("/"): v for k, v in (worker_locations or {}).items()}
        self.worker_locations = {w: locs.get(w.uri) for w in self.workers}
        self.max_splits_per_node = max_splits_per_node
        self.execution_policy = execution_policy
        # stage-DAG knobs/observability (mirrors DistributedRunner)
        from presto_tpu.parallel.fragment import DEFAULT_MIN_STAGE_ROWS
        from presto_tpu.parallel.streams import (
            exchange_buffer_bytes_default, exchange_streaming_default,
        )

        self.min_stage_rows = DEFAULT_MIN_STAGE_ROWS
        # streaming page exchange (parallel/streams.py): worker pages
        # reach the consumer as they land in the producer's output
        # buffer; off = drain-everything-then-continue (the A/B leg)
        self.exchange_streaming = (exchange_streaming_default()
                                   if exchange_streaming is None
                                   else bool(exchange_streaming))
        self.exchange_buffer_bytes = exchange_buffer_bytes_default()
        self.merge_fanin = 8
        # stage-overlap evidence of the last streamed gather (A/B tool)
        self.last_exchange_stats: Dict[str, float] = {}
        self.last_stage_count = 0
        self.last_gather_rows = 0
        # observability: last split placement per stage-launch
        # ({worker uri: [split ids]})
        self.last_assignments: Dict[str, List[int]] = {}
        # local-execution fallback accounting (VERDICT weak #8: the
        # silent MultiHostUnsupported catch hid that queries never
        # left the coordinator) — mirrors DistributedRunner's loud
        # fallback contract and feeds system_runtime_queries /
        # query-JSON stats
        self.fallback_count = 0
        self.last_fallback_reason: Optional[str] = None
        # estimate-vs-actual plane: a caller-provided QueryStats that
        # worker-task snapshots merge into (see run()); None = off
        self.stats = None

    def run(self, plan: PlanNode, stats=None) -> MaterializedResult:
        from presto_tpu.obs import METRICS, current_tracer

        self.last_gather_rows = 0  # rows pulled to the coordinator
        self.last_stage_count = 0
        self.last_fallback_reason = None
        # stamp the active query's trace token on every worker client
        # so fan-out task POSTs carry X-Presto-Trace-Token and the
        # distributed stages stitch into one trace (best-effort under
        # concurrency: the token is per-runner, like last_assignments)
        tr = current_tracer()
        token = tr.trace_token if tr is not None else None
        # distributed actuals roll-up: workers record per-operator
        # stats (one device sync per page — opt-in), and every task's
        # FINISHED snapshot merges here by structural key.  Dedupe by
        # task id: retried fragments use fresh tids, but a double
        # delete of one tid must not double-count.
        qstats = stats if stats is not None else self.stats
        if qstats is not None:
            qstats.register_plan(plan)  # idempotent — shared key space
        seen_tids = set()

        def sink(tid: str, entries) -> None:
            if tid in seen_tids:
                return
            seen_tids.add(tid)
            qstats.merge_wire(entries)

        for w in self.workers:
            w.trace_token = token
            w.collect_stats = qstats is not None
            w.stats_sink = sink if qstats is not None else None
        if qstats is not None:
            # coordinator-side halves (glue breakers, residual root,
            # final merges) record through the local runner's per-
            # thread sink on THIS thread
            self.local.stats = qstats
        try:
            # per-run outcome rides the RESULT (dist_stages attached by
            # _run_distributed from its local stage count): concurrent
            # queries on one runner must not swap each other's stats
            out = self._run_distributed(plan, qstats)
            out.dist_fallback = None
            # per-run count off the RESULT, not the shared field a
            # concurrent run may have reset (same rule as dist_stages)
            METRICS.counter("multihost.stages_total").inc(
                out.dist_stages or 0)
            return out
        except MultiHostUnsupported as e:
            reason = str(e) or type(e).__name__
            self.last_fallback_reason = reason
            self.fallback_count += 1
            METRICS.counter("multihost.fallbacks").inc()
            _log.warning(
                "multi-host execution fell back to local: %s", reason)
            out = self.local.run(plan)
            out.dist_stages = 0
            out.dist_fallback = reason
            return out
        finally:
            if qstats is not None:
                self.local.stats = None
            for w in self.workers:
                w.collect_stats = False
                w.stats_sink = None

    def _live_workers(self) -> List["WorkerClient"]:
        """Workers eligible for fragment assignment: the failure
        detector's circuit breaker skips DEAD workers whose backoff
        window has not elapsed (no connect attempt at all), and a ping
        confirms the rest — feeding the same detector, so a recovered
        worker re-admits here."""
        alive = []
        for w in self.workers:
            if not self.detector.is_schedulable(w.uri) \
                    and not self.detector.probe_due(w.uri):
                continue  # circuit open: skip without a connect attempt
            # the ping feeds the detector; the SECOND is_schedulable
            # check enforces recover_after — a DEAD worker's first
            # successful probe leaves it DEAD (not yet re-admitted),
            # so placement waits for sustained recovery
            if w.ping() and self.detector.is_schedulable(w.uri):
                alive.append(w)
        return alive

    # ------------------------------------------------------------------
    def _run_distributed(self, plan: PlanNode,
                         qstats=None) -> MaterializedResult:
        """Generalized stage-DAG execution at the DCN tier — the same
        bottom-up ``lower_stages`` decomposition the mesh tier runs
        (PlanFragmenter.java:84 + SqlQueryScheduler.java:441):
        aggregation stages and streaming-chain stages execute as HTTP
        worker fragments (leaves are table scans OR re-chunked
        materialized intermediates of earlier stages), glue breakers
        (sort/union/limit/window) evaluate on the coordinator between
        stages, and the residual root runs locally over the spliced
        results."""
        from presto_tpu.parallel.fragment import (
            lower_stages, set_child, undistributable_reason,
        )

        def staged(node, run):
            """Run one stage, recording its output rows onto the
            ORIGINAL plan node when nothing else did: worker fragments
            whose root is structurally the coordinator's node (chain
            stages) already merged by key, but rebuilt-shape stages
            (partial/final agg splits, per-shard window/sort) report
            under their own signatures — the stage boundary is the one
            place the original node's actual is observable."""
            t0 = time.perf_counter()
            page = run()
            if qstats is not None and qstats.actual_rows(node) is None:
                rows = int(np.asarray(page.row_mask).sum())
                try:
                    from presto_tpu.memory import page_bytes
                    nb = page_bytes(page)
                except Exception:
                    nb = 0
                qstats.record(node, time.perf_counter() - t0, rows, nb)
            return page

        def run_agg(node: AggregationNode) -> PrecomputedNode:
            page = staged(node, lambda: self._stage_agg(node))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_chain(node: PlanNode, bound=None) -> PrecomputedNode:
            page = staged(node, lambda: self._stage_chain(node, bound))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def eval_glue(node: PlanNode) -> PrecomputedNode:
            # runs through self.local on this thread — the per-thread
            # stats sink records it like any local operator
            page = self.local.run_to_page(node)
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_window(node) -> PrecomputedNode:
            page = staged(node, lambda: self._stage_window(node))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_sort(node) -> PrecomputedNode:
            page = staged(node, lambda: self._stage_sort(node))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_union(node) -> PrecomputedNode:
            page = staged(node, lambda: self._stage_union(node))
            return PrecomputedNode(page=page, channel_list=node.channels)

        splices: List = []
        try:
            n_stages, root = lower_stages(
                plan, run_agg, run_chain, eval_glue, splices,
                min_stage_rows=self.min_stage_rows,
                run_window=run_window, run_sort=run_sort,
                run_union=run_union)
            if n_stages == 0:
                raise MultiHostUnsupported(undistributable_reason(plan))
            self.last_stage_count = n_stages
            out = self.local.run(root)
            if root is not plan:
                out.names, out.types = plan.output_names, plan.output_types
            # per-run stage count from the LOCAL n_stages, not the
            # shared field a concurrent run may have reset
            out.dist_stages = n_stages
            return out
        finally:
            for parent, slot, old in reversed(splices):
                set_child(parent, slot, old)

    # -- stage executors ------------------------------------------------
    def _stage_agg(self, agg: AggregationNode):
        """Aggregation stage: scan-leaf chains go through the two-stage
        worker shuffle / coordinator-merge retry machinery; chains over
        a materialized intermediate run worker-side partials over
        re-chunked input with a coordinator merge."""
        if agg.step != "single":
            raise MultiHostUnsupported("non-single aggregation stage")
        if any(a.fn == "evaluate_classifier_predictions" for a in agg.aggs):
            raise MultiHostUnsupported(
                "evaluate_classifier_predictions is local-only")
        from presto_tpu.obs import span

        leaf = self.local._chain_leaf(agg.source)
        with span("mh_stage:aggregation", cat="exchange"):
            if isinstance(leaf, TableScanNode):
                return self._run_agg_with_retry(agg, leaf)
            if isinstance(leaf, PrecomputedNode):
                return self._run_agg_over_pre(agg, leaf)
            raise MultiHostUnsupported("aggregation stage leaf is neither "
                                       "scan nor materialized input")

    def _stage_chain(self, chain_root: PlanNode, bound=None):
        """Streaming-chain stage (SOURCE fragment).  A consuming
        TopN/Limit ``bound`` ships as part of the fragment so each
        WORKER truncates to ``count`` rows before the gather moves
        O(workers x count) rows instead of the full selectivity
        (CreatePartialTopN.java / per-shard bound at the DCN tier);
        the coordinator's own bound node still does the global pick."""
        from presto_tpu.page import concat_pages_host

        leaf = self.local._chain_leaf(chain_root)
        frag: PlanNode = chain_root
        if isinstance(bound, TopNNode):
            frag = TopNNode(source=chain_root,
                            sort_exprs=list(bound.sort_exprs),
                            ascending=list(bound.ascending),
                            count=bound.count,
                            nulls_first=bound.nulls_first)
        elif isinstance(bound, LimitNode):
            frag = LimitNode(source=chain_root, count=bound.count)
        from presto_tpu.obs import span

        with span("mh_stage:chain", cat="exchange"):
            if isinstance(leaf, TableScanNode):
                pages = self._run_fragments(frag, leaf)
            elif isinstance(leaf, PrecomputedNode):
                pages = self._run_fragments_pre(frag, leaf)
            else:
                raise MultiHostUnsupported("chain stage leaf is neither "
                                           "scan nor materialized input")
        for p in pages:
            self.last_gather_rows += int(np.asarray(p.row_mask).sum())
        if not pages:  # an empty intermediate produced zero chunks
            from presto_tpu.page import Page

            return Page.empty([c.type for c in chain_root.channels], 1)
        return concat_pages_host(pages)

    def _stage_window(self, wnode: WindowNode):
        """Distributed window stage: stage-1 tasks run the source chain
        with hash-partitioned output on the PARTITION BY keys (one
        buffer per consumer — PartitionedOutputBuffer); stage-2 worker
        k pulls partition k from EVERY stage-1 task while stage 1 is
        still producing (the streaming stage overlap) and runs
        ``ops/window.py`` over its complete partitions; the coordinator
        drains only the window outputs.  Degrades to a distributed
        source gather + coordinator window when fewer than two workers
        survive or the shuffle dies mid-flight."""
        from presto_tpu.obs import span

        leaf = self.local._chain_leaf(wnode.source)
        with span("mh_stage:window", cat="exchange"):
            alive = self._live_workers()
            if len(alive) >= 2 and isinstance(leaf, TableScanNode):
                try:
                    return self._run_window_two_stage(wnode, leaf, alive)
                except ConnectionError as e:
                    # degrade below (gather + coordinator window) — loud:
                    # the operator must be able to see stage-1 re-scans
                    from presto_tpu.obs import METRICS

                    METRICS.counter(
                        "multihost.window_shuffle_degraded").inc()
                    _log.warning(
                        "window shuffle lost a worker mid-flight (%s); "
                        "degrading to gather + coordinator window", e)
            src_page = self._stage_chain(wnode.source)
            pre = PrecomputedNode(page=src_page,
                                  channel_list=wnode.source.channels)
            orig = wnode.source
            try:
                wnode.source = pre
                return self.local.run_to_page(wnode)
            finally:
                wnode.source = orig

    def _run_window_two_stage(self, wnode: WindowNode, scan: TableScanNode,
                              alive: List["WorkerClient"]):
        from presto_tpu.page import Page, concat_pages_host
        from presto_tpu.planner.plan import RemoteSourceNode

        kidx = [e.index for e in wnode.partition_exprs]
        kd = wnode.partition_domains
        stage1 = self._launch_stage1(wnode.source, scan, kidx, kd, alive)
        stage2: List[tuple] = []
        try:
            upstream = [(w.uri, tid) for w, tid in stage1]
            final = WindowNode(
                source=RemoteSourceNode(producer=wnode.source,
                                        tasks=upstream, buffer_id=0),
                partition_exprs=list(wnode.partition_exprs),
                order_exprs=list(wnode.order_exprs),
                ascending=list(wnode.ascending),
                funcs=list(wnode.funcs),
                func_names=list(wnode.func_names),
            )
            base = plan_to_json(final)

            def make_frag(k: int) -> dict:
                frag = json.loads(json.dumps(base))
                _set_remote_buffers(frag, k)
                return frag

            results = self._fan_out_stage2(alive, make_frag, stage2)
            dicts = [c.dictionary for c in wnode.channels]
            pages = [deserialize_page(r, dicts, verify=False)
                     for r in results]
            if not pages:
                return Page.empty([c.type for c in wnode.channels], 1)
            return concat_pages_host(pages)
        finally:
            for w, tid in stage1 + stage2:
                w.delete_task(tid)

    def _stage_sort(self, snode: SortNode):
        """Distributed ORDER BY: each worker's fragment sorts its own
        split subset (the SortNode ships inside the fragment), sorted
        runs stream back, and the coordinator finishes with the k-way
        order-preserving merge (ops/merge.py) — it never re-sorts the
        full relation."""
        from presto_tpu.obs import span
        from presto_tpu.ops.merge import merge_sorted_pages
        from presto_tpu.page import Page

        leaf = self.local._chain_leaf(snode.source)
        with span("mh_stage:sort", cat="exchange"):
            if isinstance(leaf, TableScanNode):
                pages = self._run_fragments(snode, leaf)
            elif isinstance(leaf, PrecomputedNode):
                pages = self._run_fragments_pre(snode, leaf)
            else:
                raise MultiHostUnsupported("sort stage leaf is neither "
                                           "scan nor materialized input")
        for p in pages:
            self.last_gather_rows += int(np.asarray(p.row_mask).sum())
        if not pages:
            return Page.empty([c.type for c in snode.channels], 1)
        sort_args = (list(snode.sort_exprs), list(snode.ascending),
                     snode.nulls_first)
        # fold in exchange_merge_fanin-sized batches so each k-way
        # merge's k (and its resident runs) stays bounded
        runs = list(pages)
        while len(runs) > self.merge_fanin:
            runs = [merge_sorted_pages(runs[i:i + self.merge_fanin],
                                       *sort_args)
                    for i in range(0, len(runs), self.merge_fanin)]
        return merge_sorted_pages(runs, *sort_args)

    def _stage_union(self, unode):
        """UNION legs as concurrent producer stages draining into ONE
        streaming exchange: leg k's pages carry its dictionary-code
        offsets; the consumer applies them and concatenates in leg
        order.  With exchange_streaming off the legs run sequentially
        (the materialized A/B leg)."""
        from presto_tpu.obs import span
        from presto_tpu.page import Page, concat_pages_host
        from presto_tpu.parallel.fragment import (
            is_agg_stage, remap_union_leg_page,
        )
        from presto_tpu.parallel.streams import (
            StreamingExchange, page_nbytes,
        )

        chans = unode.channels
        offsets = unode.code_offsets
        with span("mh_stage:union", cat="exchange"):
            ex = StreamingExchange(
                "union", "mh:union", streaming=self.exchange_streaming,
                max_bytes=self.exchange_buffer_bytes)
            stream = ex.stream(producers=len(unode.inputs))

            def make_producer(k: int, leg: PlanNode):
                def produce(st):
                    if is_agg_stage(leg, self.min_stage_rows):
                        page = self._stage_agg(leg)
                    else:
                        page = self._stage_chain(leg)
                    st.put((k, page), nbytes=page_nbytes(page))

                return produce

            for k, leg in enumerate(unode.inputs):
                ex.run(stream, make_producer(k, leg))
            by_leg: Dict[int, List] = {}
            try:
                for k, p in stream.drain():
                    by_leg.setdefault(k, []).append(
                        remap_union_leg_page(p, offsets[k], chans))
            except BaseException:
                ex.abort()
                raise
            finally:
                ex.join()
            out = [p for k in sorted(by_leg) for p in by_leg[k]]
            if not out:
                return Page.empty([c.type for c in chans], 1)
            return concat_pages_host(out)

    def _run_agg_over_pre(self, agg: AggregationNode, pre: PrecomputedNode):
        """Distributed aggregation whose input is a previous stage's
        materialized output: re-chunk the page across workers, run the
        partial aggregation worker-side, merge on the coordinator with
        the usual truncation-detect-and-double protocol."""
        from presto_tpu.exec.local import MAX_AGG_GROUPS, GroupCapacityExceeded

        mg = self.local._max_groups(agg)
        check = bool(agg.group_exprs) and not self.local._exact_capacity(
            agg, mg)
        while True:
            partial = AggregationNode(
                source=agg.source, group_exprs=agg.group_exprs,
                group_names=agg.group_names, aggs=agg.aggs,
                agg_names=agg.agg_names, step="partial", max_groups=mg,
            )
            pages = self._run_fragments_pre(partial, pre)
            if not pages:  # empty intermediate: no partial states
                from presto_tpu.page import Page

                pages = [Page.empty([c.type for c in partial.channels], 1)]
            if check and any(
                int(np.asarray(p.row_mask).sum()) >= mg for p in pages
            ):
                if mg >= MAX_AGG_GROUPS:
                    raise RuntimeError("aggregation capacity ceiling")
                mg *= 2
                continue
            merge_mg = mg
            while True:
                final = AggregationNode(
                    source=PrecomputedNode(
                        page=concat_pages_device(pages),
                        channel_list=partial.channels,
                    ),
                    group_exprs=[_key_ref(partial, i)
                                 for i in range(len(agg.group_exprs))],
                    group_names=agg.group_names, aggs=agg.aggs,
                    agg_names=agg.agg_names, step="final",
                    max_groups=merge_mg,
                )
                try:
                    return self.local._execute_to_page(final)
                except GroupCapacityExceeded:
                    if merge_mg >= MAX_AGG_GROUPS:
                        raise RuntimeError("aggregation capacity ceiling")
                    merge_mg *= 2

    def _run_fragments_pre(self, fragment_root: PlanNode,
                           pre: PrecomputedNode) -> List["Page"]:
        """Ship a fragment whose chain leaf is a materialized page:
        the page re-chunks row-wise across live workers and each chunk
        travels INSIDE its worker's fragment (serde "pre" node).  A
        failed worker's chunk re-runs on a survivor; with no survivors
        (or a spent retry budget) remaining chunks run on the
        coordinator — the fragment is pure, so local execution is
        always a correct last resort."""
        alive = self._live_workers()
        if not alive:
            raise MultiHostUnsupported("no live workers")
        chunks = _chunk_page(pre.page, len(alive))
        dictionaries = [c.dictionary for c in fragment_root.channels]

        results: List[bytes] = []
        lock = named_lock("multihost._run_fragments_pre.lock")
        failed: List[tuple] = []

        def make_fragment(chunk) -> dict:
            original = pre.page
            try:
                pre.page = chunk
                return plan_to_json(fragment_root)
            finally:
                pre.page = original

        if self.exchange_streaming:
            return self._stream_fragment_pairs(
                fragment_root, list(zip(alive, chunks)), make_fragment,
                run_local=lambda chunk, skip: self._run_chunk_local(
                    fragment_root, pre, chunk)[skip:])

        errors: List[BaseException] = []
        # timeline captured on the scheduling thread: run_on executes on
        # mh-chunk-* threads, which never inherit the recording TLS
        from presto_tpu.obs import current_timeline

        tl = current_timeline()

        def run_on(w: WorkerClient, chunk, fragment: dict):
            t0 = time.perf_counter()
            try:
                raws = w.run_fragment(fragment)
                with lock:
                    results.extend(raws)
                if tl is not None:
                    tl.extend("fragment_ms", w.uri,
                              (time.perf_counter() - t0) * 1e3)
            except ConnectionError:
                with lock:
                    failed.append(chunk)
            except BaseException as e:  # deterministic query error:
                with lock:              # must FAIL the query, not drop
                    errors.append(e)    # the chunk's rows silently

        def launch(pairs):
            # daemon + named (sanitizer thread-leak/unnamed-thread): a
            # worker POST wedged past its timeouts must not pin
            # interpreter exit, and reports need attributable names
            threads = [
                threading.Thread(target=run_on, args=(w, c,
                                                      make_fragment(c)),
                                 daemon=True, name=f"mh-chunk-{i}")
                for i, (w, c) in enumerate(pairs) if c is not None
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        launch(list(zip(alive, chunks)))
        local_pages = self._failover(
            failed, alive, errors,
            # rotate retried chunks across survivors, not just [0]
            lambda chunk, survivors, rr: launch(
                [(survivors[rr % len(survivors)], chunk)]),
            lambda chunk: self._run_chunk_local(fragment_root, pre, chunk))

        return [deserialize_page(r, dictionaries, verify=False)
                for r in results] + local_pages

    def _run_agg_with_retry(self, agg: AggregationNode, scan: TableScanNode):
        """Grouped aggregations with >=2 live workers run the full
        two-stage shuffle (partial on all workers -> hash-partitioned
        final on all workers, coordinator receives only the root);
        otherwise (or on worker failure mid-shuffle) the
        coordinator-merge fallback below.  A chain containing a join
        whose build side is too large to broadcast repartitions BOTH
        join sides across workers first (the DCN shuffle join)."""
        alive = self._live_workers()
        if len(alive) >= 2:
            join = self._partitionable_join(agg.source)
            if join is not None:
                try:
                    return self._run_agg_partitioned_join(agg, join, alive)
                except ConnectionError:
                    pass  # workers died mid-shuffle; fall back
        if agg.group_exprs and len(alive) >= 2:
            try:
                return self._run_agg_two_stage(agg, scan, alive)
            except ConnectionError:
                pass  # workers died mid-shuffle; fall back
        return self._run_agg_coordinator_merge(agg, scan)

    # ------------------------------------------------------------------
    # cross-host repartitioned join (the DCN analog of parallel/dist.py's
    # FIXED_HASH joins: optimizations/AddExchanges.java:738 choosing a
    # partitioned distribution + PartitionedOutputBuffer feeding the
    # consumer stage's ExchangeOperator)
    # ------------------------------------------------------------------
    def _partitionable_join(self, chain: PlanNode):
        """Outermost join on the probe spine that the distribution
        decision repartitions and whose both sides are scan-rooted
        chains with plain column keys (partitioning needs key channel
        indices and per-worker split assignment on each side)."""
        from presto_tpu.expr.ir import ColumnRef
        from presto_tpu.parallel.fragment import decide_join_distribution
        from presto_tpu.planner.plan import CrossSingleNode, JoinNode

        node = chain
        while True:
            if isinstance(node, (FilterNode, ProjectNode)):
                node = node.source
            elif isinstance(node, AggregationNode) and node.step == "partial":
                node = node.source
            elif isinstance(node, CrossSingleNode):
                node = node.left
            elif isinstance(node, JoinNode):
                if node.kind in ("full",) or node.use_index:
                    node = node.left
                    continue
                mode, _ = decide_join_distribution(
                    node, self.broadcast_threshold, catalog=self.catalog)
                ok = (
                    mode == "partitioned"
                    and all(isinstance(e, ColumnRef) for e in node.left_keys)
                    and all(isinstance(e, ColumnRef) for e in node.right_keys)
                    and isinstance(self.local._chain_leaf(node.left),
                                   TableScanNode)
                    and isinstance(self.local._chain_leaf(node.right),
                                   TableScanNode)
                )
                if ok:
                    return node
                node = node.left
            else:
                return None

    def _await_finished(self, tasks: List[tuple],
                        timeout: float = 120.0) -> None:
        """Poll task status until every task leaves RUNNING (the phased
        gate between build and probe stages).  Bounded: on timeout the
        next phase launches anyway — the pull buffers' backpressure
        keeps a still-running build correct, just un-phased."""
        deadline = time.monotonic() + timeout
        for w, tid in tasks:
            while time.monotonic() < deadline:
                try:
                    req = urllib.request.Request(f"{w.uri}/v1/task/{tid}")
                    with urllib.request.urlopen(req, timeout=10.0) as resp:
                        state = json.load(resp).get("state")
                except Exception:
                    return  # worker fault: surfaced by the next pull
                if state != "RUNNING":
                    break
                time.sleep(0.02)

    def _fan_out_stage2(self, alive: List["WorkerClient"], make_frag,
                        stage2: List[tuple]) -> List[bytes]:
        """Create + drain one stage-2 task per worker concurrently
        (make_frag(k) -> fragment json for worker k; created tasks are
        appended to ``stage2`` for caller cleanup).  Error triage is
        shared by every shuffle tier: GroupCapacityExceeded anywhere ->
        _StageCapacity (caller doubles and re-runs); transport faults ->
        ConnectionError (caller falls back to a degraded path);
        deterministic task errors -> TaskFailed."""
        results: List[bytes] = []
        errors: List[Exception] = []
        lock = named_lock("multihost._fan_out_stage2.lock")
        # timeline captured on the scheduling thread: run_one executes
        # on mh-stage2-* threads, which never inherit the recording TLS
        from presto_tpu.obs import current_timeline

        tl = current_timeline()

        def run_one(w: WorkerClient, k: int):
            t0 = time.perf_counter()
            try:
                tid = w.create_task(make_frag(k))
                with lock:
                    stage2.append((w, tid))
                raws = w.pull_results(tid)
                with lock:
                    results.extend(raws)
                if tl is not None:
                    tl.extend("fragment_ms", w.uri,
                              (time.perf_counter() - t0) * 1e3)
            except Exception as e:
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=run_one, args=(w, k),
                                    daemon=True, name=f"mh-stage2-{k}")
                   for k, w in enumerate(alive)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        if errors:
            msg = " ".join(str(e) for e in errors)
            if "GroupCapacityExceeded" in msg:
                raise _StageCapacity(msg)
            # a worker dying mid-shuffle surfaces as transport errors
            # INSIDE a task's error text (a stage-2 pull hit
            # connection-refused); that is a cluster fault, not a
            # deterministic query failure
            if any(t in msg for t in _TRANSPORT_MARKERS):
                raise ConnectionError(msg)
            for e in errors:
                if isinstance(e, TaskFailed):
                    raise e
            raise ConnectionError(msg)
        return results

    def _launch_stage1(self, subtree: PlanNode, scan: TableScanNode,
                       key_indices: List[int], key_domains,
                       alive: List["WorkerClient"]) -> List[tuple]:
        """Run ``subtree`` on every worker over disjoint split subsets,
        each task hash-partitioning its output rows on ``key_indices``
        into one buffer per worker.  ``key_domains`` must be the JOIN's
        union domains so both sides pack (and therefore route)
        identically."""
        K = len(alive)
        spec = {
            "partitions": K,
            "key_indices": list(key_indices),
            "domains": [list(d) if d is not None else None
                        for d in key_domains],
        }
        n_splits = scan.handle.num_splits
        split_sets = [list(range(n_splits))[i::K] for i in range(K)]
        tasks: List[tuple] = []
        for w, splits in zip(alive, split_sets):
            original = scan.splits
            try:
                scan.splits = splits
                frag = plan_to_json(subtree)
            finally:
                scan.splits = original
            tasks.append((w, w.create_task(frag, spec)))
        return tasks

    def _run_agg_partitioned_join(self, agg: AggregationNode, join,
                                  alive: List["WorkerClient"]):
        """Shuffle join over DCN: stage 1 scans each side and
        hash-partitions rows on the join key into K buffers; stage-2
        worker k pulls partition k of BOTH sides from every stage-1
        task, builds the join over its build shard, probes, and runs the
        partial aggregation; the coordinator merges the K partial
        outputs."""
        import numpy as np

        from presto_tpu.exec.local import (
            MAX_AGG_GROUPS,
            GroupCapacityExceeded,
        )
        from presto_tpu.planner.plan import RemoteSourceNode

        K = len(alive)
        kd = join.key_domains
        lidx = [e.index for e in join.left_keys]
        ridx = [e.index for e in join.right_keys]
        probe_scan = self.local._chain_leaf(join.left)
        build_scan = self.local._chain_leaf(join.right)
        mg = self.local._max_groups(agg)

        while True:
            # re-derive per retry: once mg covers the exact key-domain
            # product, a full partial page means completeness, not
            # overflow (stale check caused needless two-sided rescans)
            check = bool(agg.group_exprs) and not self.local._exact_capacity(
                agg, mg)
            stage1: List[tuple] = []
            stage2: List[tuple] = []
            try:
                # stage launch order comes from the schedule policy
                # (scheduler.py): phased gates the probe side on the
                # build side's tasks FINISHING (builds are fully
                # buffered before probes start scanning — the
                # PhasedExecutionSchedule.java property); all_at_once
                # launches both sides immediately
                from presto_tpu.parallel.scheduler import (
                    AllAtOnceExecutionSchedule,
                    PhasedExecutionSchedule,
                )

                class _Side:
                    def __init__(self, name, args, children=()):
                        self.name = name
                        self.args = args
                        self.children = list(children)

                build_side = _Side(
                    "build", (join.right, build_scan, ridx))
                probe_side = _Side(
                    "probe", (join.left, probe_scan, lidx), [build_side])
                sched_cls = (PhasedExecutionSchedule
                             if self.execution_policy == "phased"
                             else AllAtOnceExecutionSchedule)
                launched: Dict[str, List[tuple]] = {}
                phases = sched_cls([probe_side]).phases()
                for pi, phase in enumerate(phases):
                    for side in phase:
                        subtree, scan_, idx_ = side.args
                        tasks = self._launch_stage1(
                            subtree, scan_, idx_, kd, alive)
                        launched[side.name] = tasks
                        stage1 += tasks
                    if pi + 1 < len(phases):
                        self._await_finished(launched["build"])
                build_tasks = launched["build"]
                probe_tasks = launched["probe"]

                partial = AggregationNode(
                    source=agg.source, group_exprs=agg.group_exprs,
                    group_names=agg.group_names, aggs=agg.aggs,
                    agg_names=agg.agg_names, step="partial", max_groups=mg,
                )
                orig_left, orig_right = join.left, join.right
                try:
                    join.left = RemoteSourceNode(
                        producer=orig_left,
                        tasks=[(w.uri, t) for w, t in probe_tasks])
                    join.right = RemoteSourceNode(
                        producer=orig_right,
                        tasks=[(w.uri, t) for w, t in build_tasks])
                    frag_base = plan_to_json(partial)
                finally:
                    join.left, join.right = orig_left, orig_right

                def make_frag(k: int) -> dict:
                    frag = json.loads(json.dumps(frag_base))
                    _set_remote_buffers(frag, k)
                    return frag

                try:
                    results = self._fan_out_stage2(alive, make_frag, stage2)
                except _StageCapacity:
                    if mg >= MAX_AGG_GROUPS:
                        raise RuntimeError(
                            f"distributed aggregation exceeded "
                            f"{MAX_AGG_GROUPS} groups")
                    mg *= 2
                    continue

                dicts = [c.dictionary for c in partial.channels]
                pages = [deserialize_page(r, dicts, verify=False) for r in results]
                if not pages:
                    from presto_tpu.page import Page

                    pages = [Page.empty(
                        [c.type for c in partial.channels], 1)]
                if check and any(
                    int(np.asarray(p.row_mask).sum()) >= mg for p in pages
                ):
                    if mg >= MAX_AGG_GROUPS:
                        raise RuntimeError("aggregation capacity ceiling")
                    mg *= 2
                    continue

                # group keys were hash-partitioned on the JOIN key, not
                # the group key, so partitions may share groups: finish
                # with the coordinator merge (cheap — inputs are K
                # partial states)
                merge_mg = mg
                while True:
                    final = AggregationNode(
                        source=PrecomputedNode(
                            page=concat_pages_device(pages),
                            channel_list=partial.channels,
                        ),
                        group_exprs=[_key_ref(partial, i)
                                     for i in range(len(agg.group_exprs))],
                        group_names=agg.group_names, aggs=agg.aggs,
                        agg_names=agg.agg_names, step="final",
                        max_groups=merge_mg,
                    )
                    try:
                        return self.local._execute_to_page(final)
                    except GroupCapacityExceeded:
                        if merge_mg >= MAX_AGG_GROUPS:
                            raise RuntimeError(
                                "aggregation capacity ceiling")
                        merge_mg *= 2
            finally:
                for w, tid in stage1 + stage2:
                    w.delete_task(tid)

    def _run_agg_two_stage(self, agg: AggregationNode, scan: TableScanNode,
                           alive: List[WorkerClient]):
        """Worker-to-worker partitioned exchange: stage-1 tasks produce
        hash-partitioned partial-aggregation pages into K per-partition
        buffers; stage-2 task k (on worker k) pulls partition k from
        EVERY stage-1 task via a RemoteSource leaf and finishes the
        aggregation there.  The coordinator drains only stage-2 outputs
        — traffic proportional to the RESULT, not the data (reference:
        PartitionedOutputBuffer.java + ExchangeOperator.java:36;
        previously the coordinator merged every partial state itself,
        the scalability ceiling VERDICT r2 flagged)."""
        import numpy as np

        from presto_tpu.exec.local import MAX_AGG_GROUPS
        from presto_tpu.planner.plan import RemoteSourceNode

        K = len(alive)
        num_keys = len(agg.group_exprs)
        mg = self.local._max_groups(agg)

        n_splits = scan.handle.num_splits
        split_sets = [list(range(n_splits))[i::K] for i in range(K)]

        while True:
            partial = AggregationNode(
                source=agg.source, group_exprs=agg.group_exprs,
                group_names=agg.group_names, aggs=agg.aggs,
                agg_names=agg.agg_names, step="partial", max_groups=mg,
            )
            pch = partial.channels
            output_spec = {
                "partitions": K,
                "key_indices": list(range(num_keys)),
                "domains": [list(d) if d is not None else None
                            for d in (pch[i].domain for i in range(num_keys))],
            }

            stage1: List[tuple] = []  # (worker, task_id)
            stage2: List[tuple] = []
            try:
                for w, splits in zip(alive, split_sets):
                    original = scan.splits
                    try:
                        scan.splits = splits
                        frag = plan_to_json(partial)
                    finally:
                        scan.splits = original
                    stage1.append((w, w.create_task(frag, output_spec)))

                upstream = [(w.uri, tid) for w, tid in stage1]
                final = AggregationNode(
                    source=RemoteSourceNode(producer=partial, tasks=upstream,
                                            buffer_id=0),
                    group_exprs=[_key_ref(partial, i) for i in range(num_keys)],
                    group_names=agg.group_names, aggs=agg.aggs,
                    agg_names=agg.agg_names, step="final", max_groups=mg,
                )
                fin_base = plan_to_json(final)

                def make_frag(k: int) -> dict:
                    fin = json.loads(json.dumps(fin_base))
                    fin["src"]["buffer"] = k
                    return fin

                try:
                    results = self._fan_out_stage2(alive, make_frag, stage2)
                except _StageCapacity:
                    if mg >= MAX_AGG_GROUPS:
                        raise RuntimeError(
                            f"distributed aggregation exceeded "
                            f"{MAX_AGG_GROUPS} groups")
                    mg *= 2
                    continue

                dicts = [c.dictionary for c in final.channels]
                pages = [deserialize_page(r, dicts, verify=False) for r in results]
                if not pages:
                    from presto_tpu.page import Page

                    return Page.empty(final.output_types, 1)
                # stage-2 outputs are disjoint partitions: concatenation
                # IS the final result (no re-merge needed)
                merged = concat_pages_device(pages)
                # defensive: a stage-2 task at full capacity may have
                # truncated (its own _check_overflow raises before this,
                # but verify the invariant cheaply) — except for
                # exact-capacity aggs, where a full page is completeness
                if not self.local._exact_capacity(agg, mg) and any(
                    int(np.asarray(p.row_mask).sum()) >= mg for p in pages
                ):
                    if mg >= MAX_AGG_GROUPS:
                        raise RuntimeError("aggregation capacity ceiling")
                    mg *= 2
                    continue
                return merged
            finally:
                for w, tid in stage1 + stage2:
                    w.delete_task(tid)

    def _run_agg_coordinator_merge(self, agg: AggregationNode, scan: TableScanNode):
        """Worker partial aggs truncate silently at max_groups (static
        shapes), so the coordinator checks every returned partial page's
        live-row count and the final merge's capacity, retrying the
        whole stage with doubled max_groups — the DCN counterpart of
        LocalRunner._check_overflow."""
        import numpy as np

        from presto_tpu.exec.local import MAX_AGG_GROUPS, GroupCapacityExceeded

        def grow(mg: int) -> int:
            if mg >= MAX_AGG_GROUPS:
                raise RuntimeError(
                    f"distributed aggregation exceeded {MAX_AGG_GROUPS} groups"
                )
            return mg * 2

        mg = self.local._max_groups(agg)
        check = bool(agg.group_exprs) and not self.local._exact_capacity(agg, mg)
        while True:
            partial = AggregationNode(
                source=agg.source, group_exprs=agg.group_exprs,
                group_names=agg.group_names, aggs=agg.aggs, agg_names=agg.agg_names,
                step="partial", max_groups=mg,
            )
            partial_pages = self._run_fragments(partial, scan)
            if check and any(
                int(np.asarray(p.row_mask).sum()) >= mg for p in partial_pages
            ):
                mg = grow(mg)
                continue

            # partial pages stay valid at any larger merge capacity, so
            # a final-merge overflow only re-runs the (cheap) merge —
            # not the distributed scan fragments
            merge_mg = mg
            while True:
                final = AggregationNode(
                    source=PrecomputedNode(
                        page=concat_pages_device(partial_pages),
                        channel_list=partial.channels,
                    ),
                    group_exprs=[
                        _key_ref(partial, i) for i in range(len(agg.group_exprs))
                    ],
                    group_names=agg.group_names, aggs=agg.aggs,
                    agg_names=agg.agg_names, step="final", max_groups=merge_mg,
                )
                try:
                    return self.local._execute_to_page(final)
                except GroupCapacityExceeded:
                    merge_mg = grow(merge_mg)

    def _leaf_scan(self, node: PlanNode) -> TableScanNode:
        n = self.local._chain_leaf(node)
        if not isinstance(n, TableScanNode):
            raise MultiHostUnsupported("chain leaf is not a table scan")
        return n

    # ------------------------------------------------------------------
    def _run_fragments(self, fragment_root: PlanNode, scan: TableScanNode):
        """Schedule split ranges across live workers; reassign a failed
        worker's splits to survivors (elastic leaf recovery) under a
        bounded per-stage retry budget, and finish remaining splits
        with coordinator-local execution when no worker can run them.
        The shipped fragment is ``fragment_root``'s subtree with the
        scan's split list swapped per assignment."""
        alive = self._live_workers()
        if not alive:
            raise MultiHostUnsupported("no live workers")

        from presto_tpu.parallel.scheduler import NodeSelector

        conn = self.catalog.connector(scan.handle.connector_name)
        n_splits = scan.handle.num_splits
        # live progress: the DCN fan-out is the long pole of a
        # multi-host query — publish splits-done/total as worker tasks
        # land (the stage scheduler's completedDrivers analog)
        from presto_tpu.obs import current_progress

        prog = current_progress()
        prog_stage = None
        if prog is not None:
            prog_stage = prog.new_stage_name(
                f"mh:{scan.handle.table}")
            prog.stage(prog_stage, splits_total=n_splits)
        preferred = None
        if hasattr(conn, "split_location"):
            preferred = {s: conn.split_location(scan.handle.table, s)
                         for s in range(n_splits)}
        selector = NodeSelector(
            alive, max_splits_per_node=self.max_splits_per_node,
            locations={id(w): self.worker_locations.get(w)
                       for w in alive})
        assignments: Dict[WorkerClient, List[int]] = selector.assign(
            range(n_splits), preferred)
        self.last_assignments = {w.uri: list(s)
                                 for w, s in assignments.items()}

        results: List[bytes] = []
        lock = named_lock("multihost._run_fragments.lock")
        failed: List[tuple] = []

        dictionaries = [c.dictionary for c in fragment_root.channels]

        def make_fragment(splits: List[int]) -> dict:
            # serialize on the scheduling thread — the splits field is
            # set transiently on the shared scan node
            original = scan.splits
            try:
                scan.splits = splits
                return plan_to_json(fragment_root)
            finally:
                scan.splits = original

        if self.exchange_streaming:
            pages = self._stream_fragment_pairs(
                fragment_root, list(assignments.items()), make_fragment,
                run_local=lambda splits, skip: self._run_splits_local(
                    fragment_root, scan, splits)[skip:],
                prog=prog, prog_stage=prog_stage, prog_n=len)
            if prog is not None:
                prog.finish_stage(prog_stage)
            return pages

        errors: List[BaseException] = []
        # timeline captured on the scheduling thread: run_on executes on
        # mh-fragment-* threads, which never inherit the recording TLS
        from presto_tpu.obs import current_timeline

        tl = current_timeline()

        def run_on(w: WorkerClient, splits: List[int], fragment: dict):
            t0 = time.perf_counter()
            try:
                raws = w.run_fragment(fragment)
                with lock:
                    results.extend(raws)
                if tl is not None:
                    # per-worker wall time: the doctor's straggler
                    # evidence (fragment_ms keyed by worker uri)
                    tl.extend("fragment_ms", w.uri,
                              (time.perf_counter() - t0) * 1e3)
                if prog is not None:
                    prog.split_done(prog_stage, n=len(splits),
                                    nbytes=sum(len(r) for r in raws))
            except ConnectionError:
                with lock:
                    failed.append((w, splits))
            except BaseException as e:  # deterministic query error:
                with lock:              # fail the query rather than
                    errors.append(e)    # silently dropping the splits

        def launch(pairs):
            threads = [
                threading.Thread(target=run_on, args=(w, s,
                                                      make_fragment(s)),
                                 daemon=True, name=f"mh-fragment-{i}")
                for i, (w, s) in enumerate(pairs) if s
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        launch(assignments.items())

        # failover: re-run dead workers' splits on survivors (striped
        # across all of them), spending the bounded per-stage retry
        # budget; when the budget is gone or no worker survives, the
        # coordinator runs the remaining splits itself (fragments are
        # pure — local execution is the always-correct last resort,
        # used ONLY when no worker can)
        def redispatch(item, survivors, _rr):
            _w_dead, splits = item
            chunks = [splits[i :: len(survivors)]
                      for i in range(len(survivors))]
            launch(list(zip(survivors, chunks)))

        def run_local(item):
            _w_dead, splits = item
            pages = self._run_splits_local(fragment_root, scan, splits)
            if prog is not None:
                prog.split_done(prog_stage, n=len(splits))
            return pages

        local_pages = self._failover(failed, alive, errors,
                                     redispatch, run_local)

        if prog is not None:
            prog.finish_stage(prog_stage)
        return [deserialize_page(r, dictionaries, verify=False)
                for r in results] + local_pages


    # -- streaming fragment fan-out ------------------------------------
    def _pull_fragment_pages(self, w: "WorkerClient", fragment: dict, emit,
                             dicts, skip: int = 0) -> int:
        """Create + drain one fragment task, emitting each verified,
        deserialized page as it lands in the worker's output buffer
        (``emit(page, nbytes)``) — the streaming twin of
        WorkerClient.run_fragment, with the same transient/deterministic
        triage.  ``skip`` pages are discarded first: the consumer
        already took them from a previous incarnation of this fragment
        (replay from the last acked token; fragments are pure and page
        order deterministic, so the re-run's prefix is byte-equal).
        Returns the delivered-page watermark; raises _StreamBroken
        (carrying it) when the worker dies mid-stream, TaskFailed on a
        deterministic query error."""
        from presto_tpu.net import is_transient
        from presto_tpu.obs import METRICS
        from presto_tpu.server.serde import deserialize_page, verify_page
        from presto_tpu.server.shuffle_client import (
            TaskPullFailed, pull_pages,
        )

        delivered = skip
        last: Optional[BaseException] = None
        if RECORDER.enabled and self.detector is not None:
            self.detector.note_assignment(w.uri)
        for attempt in range(w.max_attempts):
            if delivered > 0 and (attempt > 0 or skip > 0):
                # this task re-produces pages the consumer already has
                METRICS.counter("exchange.stream_replays_total").inc()
            tid = None
            skip_target = delivered  # prefix this incarnation replays
            skipped = 0
            try:
                tid = w.create_task(fragment)
                for raw in pull_pages(w.uri, tid, 0, timeout=w.timeout):
                    if skipped < skip_target:
                        skipped += 1
                        continue
                    verify_page(raw)
                    emit(deserialize_page(raw, dicts, verify=False),
                         len(raw))
                    delivered += 1
                w._ok()
                return delivered
            except TaskPullFailed as e:
                if "PageIntegrityError" not in str(e):
                    # deterministic query error: it travels; the worker
                    # is not to blame and a retry recomputes the same
                    raise TaskFailed(str(e)) from e
                last = e  # damaged in-fragment input page: recompute
            except TaskFailed:
                raise
            except Exception as e:
                if not is_transient(e):
                    raise TaskFailed(f"{type(e).__name__}: {e}") from e
                last = e
            finally:
                if tid is not None:
                    w.delete_task(tid)
            time.sleep(min(0.1 * (2 ** attempt), 2.0))
        w._failed(last)
        raise _StreamBroken(delivered, last)

    def _stream_fragment_pairs(self, fragment_root: PlanNode, pairs,
                               make_fragment, run_local,
                               prog=None, prog_stage=None,
                               prog_n=lambda item: 1) -> List["Page"]:
        """Streaming fan-out driver shared by the scan-leaf and
        pre-chunk fragment paths: one puller thread per (worker, item)
        feeds a token-acked PageStream and the consumer takes pages the
        moment the FIRST producer emits — stage k+1 overlaps stage k.
        Mid-stream producer death re-dispatches the SAME fragment onto
        a survivor with the delivered-page watermark (replay), under
        the usual bounded retry budget, finishing coordinator-local
        (``run_local(item, skip)``) when no worker can.

        Pages travel tagged (producer slot, sequence) and the returned
        list is reassembled in assignment order — byte-identical to the
        materialized gather — so order-carrying inputs (a chain stage
        over a sorted intermediate) survive arrival-order races; the
        overlap (pull + verify + deserialize while producers still run)
        is unaffected."""
        from presto_tpu.parallel.streams import PageStream

        dicts = [c.dictionary for c in fragment_root.channels]
        live = [(slot, w, item, make_fragment(item))
                for slot, (w, item) in enumerate(p for p in pairs if p[1])]
        stream = PageStream(max_bytes=self.exchange_buffer_bytes,
                            producers=max(len(live), 1), name="mh:gather")
        slotted: List[tuple] = []  # (slot, seq, page)
        failed: List[tuple] = []
        errors: List[BaseException] = []
        lock = named_lock("multihost._stream_fragment_pairs.lock")

        def emit_into(put, slot: int, start: int = 0):
            seq = [start]
            pk = f"mh:{id(stream):x}:{slot}"

            def emit(page, nbytes):
                if RECORDER.enabled:
                    # per-slot canonical sequencing: the spec automaton
                    # checks exactly-once delivery + replay-prefix
                    # equality across fragment re-incarnations
                    RECORDER.record("exchange", pk, "deliver", seq=seq[0])
                put((slot, seq[0], page), nbytes=nbytes)
                seq[0] += 1

            return emit

        # timeline captured on the consumer thread: run_on executes on
        # mh-stream-pull-* threads, which never inherit the recording TLS
        from presto_tpu.obs import current_timeline

        tl = current_timeline()

        def run_on(slot: int, w: WorkerClient, item, fragment: dict):
            t0 = time.perf_counter()
            try:
                self._pull_fragment_pages(
                    w, fragment, emit_into(stream.put, slot), dicts)
                if tl is not None:
                    tl.extend("fragment_ms", w.uri,
                              (time.perf_counter() - t0) * 1e3)
                if prog is not None:
                    prog.split_done(prog_stage, n=prog_n(item))
            except _StreamBroken as e:
                with lock:
                    failed.append((slot, item, fragment, e.delivered))
            except ConnectionError:
                with lock:
                    failed.append((slot, item, fragment, 0))
            except BaseException as e:  # deterministic query error:
                with lock:              # fail the query rather than
                    errors.append(e)    # silently dropping the rows
            finally:
                stream.producer_done()

        if not live:
            stream.producer_done()
        threads = [threading.Thread(target=run_on, args=t, daemon=True,
                                    name=f"mh-stream-pull-{t[0]}")
                   for t in live]
        for t in threads:
            t.start()
        try:
            for tagged in stream.drain():
                slotted.append(tagged)
        finally:
            # join in a finally (sanitizer thread-leak): a consumer-side
            # error (kill/abort raising out of drain) must still reap
            # the pullers — drain's early-close abort has already
            # unblocked any producer stuck on the byte cap
            for t in threads:
                t.join(timeout=30.0)
        self.last_exchange_stats = {
            "pages": float(stream.pages_in),
            "bytes": float(stream.bytes_in),
            "peak_buffered_bytes": float(stream.peak_bytes),
            "first_page_at": stream.first_page_at or 0.0,
            "producers_done_at": stream.completed_at or 0.0,
        }

        def redispatch(item4, survivors, rr):
            slot, item, fragment, delivered = item4
            w = survivors[rr % len(survivors)]
            if RECORDER.enabled:
                # skip must equal the consumer's delivered watermark —
                # the automaton cross-checks it against its own count
                RECORDER.record("exchange", f"mh:{id(stream):x}:{slot}",
                                "replay", skip=delivered)
            emit = emit_into(
                lambda tagged, nbytes: slotted.append(tagged), slot,
                start=delivered)
            try:
                self._pull_fragment_pages(w, fragment, emit, dicts,
                                          skip=delivered)
                if prog is not None:
                    prog.split_done(prog_stage, n=prog_n(item))
            except _StreamBroken as e:
                with lock:
                    failed.append((slot, item, fragment, e.delivered))
            except ConnectionError:
                with lock:
                    failed.append((slot, item, fragment, delivered))
            except BaseException as e:
                with lock:
                    errors.append(e)

        def run_local_item(item4):
            slot, item, _fragment, delivered = item4
            out = run_local(item, delivered)
            if prog is not None:
                prog.split_done(prog_stage, n=prog_n(item))
            if RECORDER.enabled:
                pk = f"mh:{id(stream):x}:{slot}"
                RECORDER.record("exchange", pk, "replay", skip=delivered)
                for i in range(len(out)):
                    RECORDER.record("exchange", pk, "deliver",
                                    seq=delivered + i)
            return [(slot, delivered + i, p) for i, p in enumerate(out)]

        slotted.extend(self._failover(
            failed, [w for _, w, _, _ in live], errors, redispatch,
            run_local_item))
        slotted.sort(key=lambda t: (t[0], t[1]))
        return [p for _, _, p in slotted]

    # -- shared failover driver ----------------------------------------
    def _failover(self, failed: List, alive: List["WorkerClient"],
                  errors: List[BaseException], redispatch, run_local):
        """Drain the ``failed`` work list: re-dispatch each item onto
        survivors under the bounded per-stage retry budget
        (``redispatch(item, survivors, attempt_index)``), falling back
        to coordinator-local execution (``run_local(item)`` -> pages)
        when no worker survives or the budget is spent.  Raises the
        first deterministic error instead of dropping rows; returns
        the locally recovered pages."""
        from presto_tpu.obs import METRICS

        local_pages: List = []
        budget = self.max_fragment_retries
        pkey = None
        if RECORDER.enabled:
            pkey = f"fo:{id(self):x}:{next(_FAILOVER_SEQ)}"
            RECORDER.record("retry", pkey, "begin", budget=budget)
        rr = 0
        while failed:
            if errors:
                break
            item = failed.pop()
            survivors = [w for w in alive if w.alive]
            if not survivors or budget <= 0:
                if pkey is not None:
                    RECORDER.record("retry", pkey, "local",
                                    survivors=len(survivors),
                                    budget_left=max(budget, 0))
                local_pages.extend(run_local(item))
                continue
            budget -= 1
            METRICS.counter("retry.fragments_total").inc()
            if pkey is not None:
                RECORDER.record("retry", pkey, "retry",
                                used=self.max_fragment_retries - budget)
            redispatch(item, survivors, rr)
            rr += 1
        if errors:
            raise errors[0]
        return local_pages

    # -- coordinator-local last resort ---------------------------------
    def _local_fragment_pages(self, fragment_root: PlanNode):
        """Run a fragment on the coordinator's own LocalRunner, round-
        tripping the wire serde so downstream merging sees exactly
        what a worker would have shipped."""
        from presto_tpu.server.serde import serialize_page

        raws = [serialize_page(p)
                for p in self.local._pages(fragment_root)]
        dicts = [c.dictionary for c in fragment_root.channels]
        return [deserialize_page(r, dicts, verify=False) for r in raws]

    def _run_splits_local(self, fragment_root: PlanNode,
                          scan: TableScanNode, splits: List[int]):
        """Execute a scan-leaf fragment's splits on the coordinator —
        the terminal fallback when every worker is dead or the retry
        budget is spent."""
        from presto_tpu.obs import METRICS

        METRICS.counter("retry.splits_recovered_local").inc(len(splits))
        _log.warning(
            "no worker available for %d split(s) of %s; finishing them "
            "on the coordinator", len(splits), scan.handle.table)
        original = scan.splits
        try:
            scan.splits = list(splits)
            return self._local_fragment_pages(fragment_root)
        finally:
            scan.splits = original

    def _run_chunk_local(self, fragment_root: PlanNode,
                         pre: PrecomputedNode, chunk):
        """_run_splits_local for a materialized-intermediate chunk."""
        from presto_tpu.obs import METRICS

        METRICS.counter("retry.splits_recovered_local").inc()
        _log.warning("no worker available for an intermediate chunk; "
                     "finishing it on the coordinator")
        original = pre.page
        try:
            pre.page = chunk
            return self._local_fragment_pages(fragment_root)
        finally:
            pre.page = original


def _chunk_page(page, k: int):
    """Row-chunk a (possibly device) page into ``k`` contiguous
    host-side pieces for re-distribution; dead rows are dropped first
    so chunk sizes reflect live data."""
    from presto_tpu.page import Block, Page

    p = page.compact_host()
    n = int(np.asarray(p.row_mask).sum())
    bounds = [round(i * n / k) for i in range(k + 1)]
    chunks = []
    for lo, hi in zip(bounds, bounds[1:]):
        if hi == lo:
            chunks.append(None)
            continue
        blocks = tuple(
            Block(b.data[lo:hi], b.valid[lo:hi], b.type, b.dictionary)
            for b in p.blocks)
        chunks.append(Page(blocks, p.row_mask[lo:hi]))
    return chunks


def _key_ref(partial: AggregationNode, i: int):
    from presto_tpu.expr.ir import ColumnRef

    ch = partial.channels[i]
    return ColumnRef(type=ch.type, index=i)


def _set_remote_buffers(frag_json: dict, k: int) -> None:
    """Point every RemoteSource leaf in a serialized fragment at
    partition buffer ``k`` (stage-2 task k consumes partition k of
    every upstream side)."""
    if isinstance(frag_json, dict):
        if frag_json.get("k") == "remote":
            frag_json["buffer"] = k
        for v in frag_json.values():
            _set_remote_buffers(v, k)
    elif isinstance(frag_json, list):
        for v in frag_json:
            _set_remote_buffers(v, k)
