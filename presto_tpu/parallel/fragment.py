"""Plan fragmenter: splits a plan into distributable fragments at
exchange boundaries, with partitioning handles and the
broadcast-vs-repartition join decision.

Reference analog: ``sql/planner/PlanFragmenter.java:84`` (SubPlan tree
of PlanFragments), ``sql/planner/SystemPartitioningHandle.java:58-66``
(SINGLE / FIXED_HASH / FIXED_BROADCAST / SOURCE), the physical
distribution pass ``optimizations/AddExchanges.java:738`` and the CBO
rule ``iterative/rule/DetermineJoinDistributionType.java:33``
(broadcast small build sides, repartition large ones).

TPU framing: a fragment is one SPMD region — its operators fuse into a
single ``shard_map``'d XLA program per wave; fragment boundaries are
the collectives (``all_to_all`` for FIXED_HASH, ``all_gather``/
replication for BROADCAST, host gather for SINGLE).  The fragmenter is
the single source of truth the distributed runner consults for join
distribution modes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)

# Partitioning handle kinds (SystemPartitioningHandle.java:58-66; the
# colocated kind is the bucket-aligned no-exchange placement the
# reference expresses via connector partitioning handles)
SINGLE = "SINGLE"
FIXED_HASH = "FIXED_HASH"
BROADCAST = "BROADCAST"
SOURCE = "SOURCE"
COLOCATED = "COLOCATED"

# Build sides at or below this estimated row count replicate to every
# device (join_distribution_type=AUTOMATIC's size cutoff; the reference
# default is a byte threshold, join-max-broadcast-table-size)
DEFAULT_BROADCAST_THRESHOLD = 1 << 16


@dataclasses.dataclass
class Partitioning:
    kind: str
    keys: Tuple = ()  # key exprs for FIXED_HASH

    def __str__(self) -> str:
        if self.kind == FIXED_HASH and self.keys:
            return f"{self.kind}({len(self.keys)} keys)"
        return self.kind


@dataclasses.dataclass
class Fragment:
    """One distributable unit (PlanFragment analog): ``root``'s subtree
    down to (but excluding) child-fragment boundaries."""

    fid: int
    root: PlanNode
    distribution: Partitioning  # how this fragment's work is spread
    output: Partitioning  # how its output reaches the parent
    children: List["Fragment"] = dataclasses.field(default_factory=list)

    def tree_str(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [
            f"{pad}Fragment {self.fid} [{self.distribution}] "
            f"=> output [{self.output}] root={type(self.root).__name__}"
        ]
        for ch in self.children:
            lines.append(ch.tree_str(indent + 1))
        return "\n".join(lines)


_SHARED_CALC = None


def estimate_rows(node: PlanNode, calc=None) -> Optional[int]:
    """Row-count estimate via the shared stats calculator
    (planner/stats.py — cost/StatsCalculator.java analog), so the
    broadcast-vs-partitioned distribution decision and the binder's join
    ordering act on the same numbers. A process-wide calculator memoizes
    across the repeated per-join calls of a fragmentation pass (safe:
    the memo holds node references, so recycled ids can't alias)."""
    if isinstance(node, PrecomputedNode):
        return None
    global _SHARED_CALC
    if calc is None:
        if _SHARED_CALC is None:
            from presto_tpu.planner.stats import StatsCalculator

            _SHARED_CALC = StatsCalculator()
        calc = _SHARED_CALC
    return int(calc.rows(node))


def build_side_chainable(node: PlanNode) -> bool:
    """True when the build side can wave-scan on the mesh: a streaming
    chain (filter/project/partial-agg/streaming-join probes) rooted at
    a table scan.  Mirrors LocalRunner._chain_leaf's descent."""
    if isinstance(node, (FilterNode, ProjectNode)):
        return build_side_chainable(node.source)
    if isinstance(node, AggregationNode) and node.step == "partial":
        return build_side_chainable(node.source)
    if isinstance(node, CrossSingleNode):
        return build_side_chainable(node.left)
    if isinstance(node, JoinNode) and (
        node.kind in ("semi", "anti", "mark") or node.unique_build
    ):
        return build_side_chainable(node.left)
    return isinstance(node, TableScanNode)


def _trace_to_scan_columns(node: PlanNode, keys) -> Optional[Tuple[PlanNode, List[str]]]:
    """Map ColumnRef join keys through filter/pass-through-projection
    chains to (leaf scan, column names); None when any key derives."""
    from presto_tpu.expr.ir import ColumnRef

    remap = None
    cur = node
    while True:
        if isinstance(cur, FilterNode):
            cur = cur.source
        elif isinstance(cur, ProjectNode):
            proj_map = {i: p.index for i, p in enumerate(cur.projections)
                        if isinstance(p, ColumnRef)}
            src_items = (remap.items() if remap is not None else
                         ((i, i) for i in range(len(cur.channels))))
            remap = {o: proj_map[i] for o, i in src_items if i in proj_map}
            cur = cur.source
        else:
            break
    if not isinstance(cur, TableScanNode):
        return None
    names = []
    for k in keys:
        if not isinstance(k, ColumnRef):
            return None
        idx = k.index if remap is None else remap.get(k.index)
        if idx is None or idx >= len(cur.columns):
            return None
        names.append(cur.handle.columns[cur.columns[idx]].name)
    return cur, names


def colocated_join_scans(jnode, catalog) -> Optional[Tuple[PlanNode, PlanNode]]:
    """(probe_scan, build_scan) when both join sides are scan chains of
    compatibly bucketed tables joined exactly on the bucket columns —
    the shuffle-free colocated join (colocated_join session property +
    NodePartitioningManager bucket-to-node alignment in the reference).
    Bucket id = split index on both sides, so the wave scheduler's
    'device d takes split w*n+d' placement already colocates them."""
    if isinstance(jnode, CrossSingleNode) or catalog is None:
        return None
    left = _trace_to_scan_columns(jnode.left, jnode.left_keys)
    right = _trace_to_scan_columns(jnode.right, jnode.right_keys)
    if left is None or right is None:
        return None
    (lscan, lcols), (rscan, rcols) = left, right
    try:
        lconn = catalog.connector(lscan.handle.connector_name)
        rconn = catalog.connector(rscan.handle.connector_name)
    except KeyError:
        return None
    lb = lconn.bucketing(lscan.handle.table) if hasattr(lconn, "bucketing") else None
    rb = rconn.bucketing(rscan.handle.table) if hasattr(rconn, "bucketing") else None
    if lb is None or rb is None:
        return None
    if lb[1] != rb[1] or lb[2] != rb[2]:
        return None  # different alignment or bucket counts
    if lcols != lb[0] or rcols != rb[0]:
        return None  # join keys must be exactly the bucket columns
    return lscan, rscan


def decide_join_distribution(
    jnode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None, forced: str = "AUTOMATIC", allow_colocated: bool = True,
) -> Tuple[str, Optional[int]]:
    """(mode, estimated build rows): 'colocated' joins bucket-aligned
    scans with no exchange at all; 'broadcast' replicates the build to
    every device; 'partitioned' hash-exchanges both sides on the join
    key (DetermineJoinDistributionType.java:33 —
    AUTOMATIC chooses by build size; the session's
    join_distribution_type forces BROADCAST/PARTITIONED).  Build sides
    that can't wave-scan on the mesh downgrade to broadcast — the
    decision here is the single source of truth for both EXPLAIN
    rendering and execution."""
    if isinstance(jnode, CrossSingleNode):
        return "broadcast", 1
    est = estimate_rows(jnode.right)
    if forced == "BROADCAST":
        return "broadcast", est
    chainable = build_side_chainable(jnode.right)
    if forced == "PARTITIONED":
        return ("partitioned" if chainable else "broadcast"), est
    if (allow_colocated and chainable
            and colocated_join_scans(jnode, catalog) is not None):
        return "colocated", est
    if est is None or est <= broadcast_threshold:
        return "broadcast", est
    if not chainable:
        return "broadcast", est
    return "partitioned", est


def fragment_plan(
    plan: PlanNode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None,
) -> Fragment:
    """Lower a plan into a SubPlan-style fragment tree.  Fragments are
    created at the distributed runner's exchange points: the SINGLE
    coordinator fragment above the final exchange, a FIXED_HASH merge
    fragment per distributed aggregation, SOURCE leaf fragments over
    scans, and one fragment per join build side (BROADCAST or
    FIXED_HASH by the distribution decision)."""
    counter = [0]

    def next_id() -> int:
        fid = counter[0]
        counter[0] += 1
        return fid

    def build_fragments(node: PlanNode) -> List[Fragment]:
        """Fragments feeding ``node``'s streaming chain (build sides +
        nested breakers)."""
        out: List[Fragment] = []
        if isinstance(node, (FilterNode, ProjectNode)):
            out += build_fragments(node.source)
        elif isinstance(node, AggregationNode) and node.step == "partial":
            out += build_fragments(node.source)
        elif isinstance(node, (JoinNode, CrossSingleNode)):
            out += build_fragments(node.left)
            mode, _ = decide_join_distribution(node, broadcast_threshold, catalog=catalog)
            right = node.right
            if mode == "broadcast":
                kind = BROADCAST
            elif mode == "colocated":
                kind = COLOCATED
            else:
                kind = FIXED_HASH
            keys = tuple(getattr(node, "right_keys", ()))
            out.append(
                Fragment(
                    next_id(),
                    right,
                    distribution=_leaf_distribution(right),
                    output=Partitioning(kind, keys if kind == FIXED_HASH else ()),
                    children=build_fragments(right),
                )
            )
        return out

    def _leaf_distribution(node: PlanNode) -> Partitioning:
        n = node
        while True:
            if isinstance(n, TableScanNode):
                return Partitioning(SOURCE)
            srcs = n.sources
            if not srcs:
                return Partitioning(SINGLE)
            n = srcs[0]

    # peel coordinator-side nodes down to the root aggregation
    node = plan
    while not isinstance(node, AggregationNode) and node.sources:
        if isinstance(
            node, (OutputNode, ProjectNode, FilterNode, SortNode, TopNNode, LimitNode,
                   WindowNode)
        ):
            node = node.source
        else:
            break

    if isinstance(node, AggregationNode) and node.step == "single":
        agg = node
        keys = tuple(agg.group_exprs)
        leaf_frag = Fragment(
            next_id(),
            agg.source,
            distribution=_leaf_distribution(agg.source),
            output=Partitioning(FIXED_HASH, keys) if keys else Partitioning(SINGLE),
            children=build_fragments(agg.source),
        )
        merge_frag = Fragment(
            next_id(),
            agg,
            distribution=Partitioning(FIXED_HASH, keys) if keys else Partitioning(SINGLE),
            output=Partitioning(SINGLE),
            children=[leaf_frag],
        )
        root = Fragment(
            next_id(), plan, distribution=Partitioning(SINGLE),
            output=Partitioning(SINGLE), children=[merge_frag],
        )
        return root

    # non-aggregation-rooted plan: single fragment (runs locally)
    return Fragment(
        next_id(), plan, distribution=Partitioning(SINGLE),
        output=Partitioning(SINGLE), children=build_fragments(plan),
    )


def explain_distributed(
    plan: PlanNode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None,
) -> str:
    return fragment_plan(plan, broadcast_threshold, catalog=catalog).tree_str()
