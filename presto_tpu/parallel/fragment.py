"""Plan fragmenter: splits a plan into distributable fragments at
exchange boundaries, with partitioning handles and the
broadcast-vs-repartition join decision.

Reference analog: ``sql/planner/PlanFragmenter.java:84`` (SubPlan tree
of PlanFragments), ``sql/planner/SystemPartitioningHandle.java:58-66``
(SINGLE / FIXED_HASH / FIXED_BROADCAST / SOURCE), the physical
distribution pass ``optimizations/AddExchanges.java:738`` and the CBO
rule ``iterative/rule/DetermineJoinDistributionType.java:33``
(broadcast small build sides, repartition large ones).

TPU framing: a fragment is one SPMD region — its operators fuse into a
single ``shard_map``'d XLA program per wave; fragment boundaries are
the collectives (``all_to_all`` for FIXED_HASH, ``all_gather``/
replication for BROADCAST, host gather for SINGLE).  The fragmenter is
the single source of truth the distributed runner consults for join
distribution modes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    ValuesNode,
    WindowNode,
)

# Partitioning handle kinds (SystemPartitioningHandle.java:58-66; the
# colocated kind is the bucket-aligned no-exchange placement the
# reference expresses via connector partitioning handles)
SINGLE = "SINGLE"
FIXED_HASH = "FIXED_HASH"
BROADCAST = "BROADCAST"
SOURCE = "SOURCE"
COLOCATED = "COLOCATED"

# Build sides at or below this estimated row count replicate to every
# device (join_distribution_type=AUTOMATIC's size cutoff; the reference
# default is a byte threshold, join-max-broadcast-table-size)
DEFAULT_BROADCAST_THRESHOLD = 1 << 16


@dataclasses.dataclass
class Partitioning:
    kind: str
    keys: Tuple = ()  # key exprs for FIXED_HASH

    def __str__(self) -> str:
        if self.kind == FIXED_HASH and self.keys:
            return f"{self.kind}({len(self.keys)} keys)"
        return self.kind


def _keys_str(keys) -> str:
    """Human-readable partition-key list for EXPLAIN's exchange edges."""
    from presto_tpu.expr.ir import ColumnRef

    names = []
    for k in keys:
        if isinstance(k, ColumnRef):
            names.append(k.name or f"#{k.index}")
        else:
            names.append(str(k))
    return ",".join(names)


@dataclasses.dataclass
class Fragment:
    """One distributable unit (PlanFragment analog): ``root``'s subtree
    down to (but excluding) child-fragment boundaries."""

    fid: int
    root: PlanNode
    distribution: Partitioning  # how this fragment's work is spread
    output: Partitioning  # how its output reaches the parent
    children: List["Fragment"] = dataclasses.field(default_factory=list)
    # per-shard row bound from a TopN/Limit consumer (CreatePartialTopN)
    shard_bound: Optional[int] = None
    # exchange kind on the edge to the parent (hash / gather / merge /
    # broadcast); None derives from the output partitioning
    exchange_kind: Optional[str] = None
    exchange_keys: Tuple = ()

    def exchange_str(self) -> str:
        """The stage-edge exchange EXPLAIN prints: how this fragment's
        pages travel to the consumer (streaming page exchange kinds)."""
        kind = self.exchange_kind
        if kind is None:
            kind = {FIXED_HASH: "hash", BROADCAST: "broadcast",
                    COLOCATED: "colocated"}.get(self.output.kind, "gather")
        keys = self.exchange_keys or (
            self.output.keys if kind == "hash" else ())
        return f"{kind}[{_keys_str(keys)}]" if keys else kind

    def tree_str(self, indent: int = 0) -> str:
        pad = "  " * indent
        bound = "" if self.shard_bound is None \
            else f" shard_bound={self.shard_bound}"
        # stats-calculator row estimate on the stage edge: what the
        # planner believes travels over this exchange (estimate-vs-
        # actual closes the loop in EXPLAIN ANALYZE; this is the est
        # half at fragment granularity)
        try:
            est = estimate_rows(self.root)
        except Exception:
            est = None
        est_s = "" if est is None else f" ~{est} rows"
        lines = [
            f"{pad}Fragment {self.fid} [{self.distribution}] "
            f"=> output [{self.output}] via {self.exchange_str()} "
            f"root={type(self.root).__name__}"
            f"{bound}{est_s}"
        ]
        for ch in self.children:
            lines.append(ch.tree_str(indent + 1))
        return "\n".join(lines)


_SHARED_CALC = None


def estimate_rows(node: PlanNode, calc=None) -> Optional[int]:
    """Row-count estimate via the shared stats calculator
    (planner/stats.py — cost/StatsCalculator.java analog), so the
    broadcast-vs-partitioned distribution decision and the binder's join
    ordering act on the same numbers. A process-wide calculator memoizes
    across the repeated per-join calls of a fragmentation pass (safe:
    the memo holds node references, so recycled ids can't alias)."""
    if isinstance(node, PrecomputedNode):
        return None
    global _SHARED_CALC
    if calc is None:
        if _SHARED_CALC is None:
            from presto_tpu.planner.stats import StatsCalculator

            _SHARED_CALC = StatsCalculator()
        calc = _SHARED_CALC
    return int(calc.rows(node))


def build_side_chainable(node: PlanNode) -> bool:
    """True when the build side can wave-scan on the mesh: a streaming
    chain (filter/project/partial-agg/streaming-join probes) rooted at
    a table scan.  Mirrors LocalRunner._chain_leaf's descent."""
    if isinstance(node, (FilterNode, ProjectNode)):
        return build_side_chainable(node.source)
    if isinstance(node, AggregationNode) and node.step == "partial":
        return build_side_chainable(node.source)
    if isinstance(node, CrossSingleNode):
        return build_side_chainable(node.left)
    if isinstance(node, JoinNode) and (
        node.kind in ("semi", "anti", "mark") or node.unique_build
    ):
        return build_side_chainable(node.left)
    return isinstance(node, TableScanNode)


def _trace_to_scan_columns(node: PlanNode, keys) -> Optional[Tuple[PlanNode, List[str]]]:
    """Map ColumnRef join keys through filter/pass-through-projection
    chains to (leaf scan, column names); None when any key derives."""
    from presto_tpu.expr.ir import ColumnRef

    remap = None
    cur = node
    while True:
        if isinstance(cur, FilterNode):
            cur = cur.source
        elif isinstance(cur, ProjectNode):
            proj_map = {i: p.index for i, p in enumerate(cur.projections)
                        if isinstance(p, ColumnRef)}
            src_items = (remap.items() if remap is not None else
                         ((i, i) for i in range(len(cur.channels))))
            remap = {o: proj_map[i] for o, i in src_items if i in proj_map}
            cur = cur.source
        else:
            break
    if not isinstance(cur, TableScanNode):
        return None
    names = []
    for k in keys:
        if not isinstance(k, ColumnRef):
            return None
        idx = k.index if remap is None else remap.get(k.index)
        if idx is None or idx >= len(cur.columns):
            return None
        names.append(cur.handle.columns[cur.columns[idx]].name)
    return cur, names


def colocated_join_scans(jnode, catalog) -> Optional[Tuple[PlanNode, PlanNode]]:
    """(probe_scan, build_scan) when both join sides are scan chains of
    compatibly bucketed tables joined exactly on the bucket columns —
    the shuffle-free colocated join (colocated_join session property +
    NodePartitioningManager bucket-to-node alignment in the reference).
    Bucket id = split index on both sides, so the wave scheduler's
    'device d takes split w*n+d' placement already colocates them."""
    if isinstance(jnode, CrossSingleNode) or catalog is None:
        return None
    left = _trace_to_scan_columns(jnode.left, jnode.left_keys)
    right = _trace_to_scan_columns(jnode.right, jnode.right_keys)
    if left is None or right is None:
        return None
    (lscan, lcols), (rscan, rcols) = left, right
    try:
        lconn = catalog.connector(lscan.handle.connector_name)
        rconn = catalog.connector(rscan.handle.connector_name)
    except KeyError:
        return None
    lb = lconn.bucketing(lscan.handle.table) if hasattr(lconn, "bucketing") else None
    rb = rconn.bucketing(rscan.handle.table) if hasattr(rconn, "bucketing") else None
    if lb is None or rb is None:
        return None
    if lb[1] != rb[1] or lb[2] != rb[2]:
        return None  # different alignment or bucket counts
    if lcols != lb[0] or rcols != rb[0]:
        return None  # join keys must be exactly the bucket columns
    return lscan, rscan


def decide_join_distribution(
    jnode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None, forced: str = "AUTOMATIC", allow_colocated: bool = True,
) -> Tuple[str, Optional[int]]:
    """(mode, estimated build rows): 'colocated' joins bucket-aligned
    scans with no exchange at all; 'broadcast' replicates the build to
    every device; 'partitioned' hash-exchanges both sides on the join
    key (DetermineJoinDistributionType.java:33 —
    AUTOMATIC chooses by build size; the session's
    join_distribution_type forces BROADCAST/PARTITIONED).  Build sides
    that can't wave-scan on the mesh downgrade to broadcast — the
    decision here is the single source of truth for both EXPLAIN
    rendering and execution."""
    if isinstance(jnode, CrossSingleNode):
        return "broadcast", 1
    est = estimate_rows(jnode.right)
    if getattr(jnode, "null_aware", False):
        # three-valued IN/NOT IN: the "build holds a NULL key" flag is a
        # whole-relation property, so the build must be replicated — a
        # hash-partitioned build would confine the NULL to one shard
        return "broadcast", est
    if forced == "BROADCAST":
        return "broadcast", est
    chainable = build_side_chainable(jnode.right)
    if forced == "PARTITIONED":
        return ("partitioned" if chainable else "broadcast"), est
    if (allow_colocated and chainable
            and colocated_join_scans(jnode, catalog) is not None):
        return "colocated", est
    if est is None or est <= broadcast_threshold:
        return "broadcast", est
    if not chainable:
        return "broadcast", est
    return "partitioned", est


# ----------------------------------------------------------------------
# Generalized stage decomposition (PlanFragmenter.java:84 analog).
#
# A plan of ANY shape lowers into a DAG of mesh stages: each stage is a
# streaming chain (filter/project/partial-agg/join probes over a scan or
# a materialized intermediate) optionally rooted by a single-step
# aggregation.  Stage results materialize as PrecomputedNode pages that
# feed consuming stages — the role SubPlan/RemoteSourceNode boundaries
# play in the reference.  Glue breakers (sort, window, union, limit,
# unnest) between stages evaluate on the coordinator, mirroring the
# reference's SINGLE-distribution fragments.  The same traversal drives
# execution (parallel/dist.py) and EXPLAIN (TYPE DISTRIBUTED), so what
# EXPLAIN prints is what execution does.
# ----------------------------------------------------------------------

#: breakers the coordinator evaluates between mesh stages once their
#: subtree is fully materialized (SqlQueryScheduler's SINGLE fragments)
GLUE_BREAKERS = (SortNode, TopNNode, LimitNode, WindowNode, UnionNode,
                 UnnestNode)


def chain_distributable(node: PlanNode) -> Optional[str]:
    """None when ``node``'s subtree is a streaming chain the mesh tier
    compiles into one SPMD wave program; otherwise the human-readable
    reason it is not (surfaced by EXPLAIN and the fallback event)."""
    if isinstance(node, (FilterNode, ProjectNode)):
        return chain_distributable(node.source)
    if isinstance(node, AggregationNode) and node.step == "partial":
        return chain_distributable(node.source)
    if isinstance(node, CrossSingleNode):
        return chain_distributable(node.left)
    if isinstance(node, JoinNode):
        if node.kind == "full":
            return "full outer join needs cross-device unmatched-build state"
        if node.use_index:
            return "index join point-lookups do not wave-scan"
        return chain_distributable(node.left)
    if isinstance(node, TableScanNode):
        return None
    if isinstance(node, PrecomputedNode):
        return None
    return f"{type(node).__name__.replace('Node', '')} breaks the streaming chain"


#: a stage whose leaf is a materialized intermediate below this many
#: rows runs on the coordinator instead — scattering a small page over
#: the mesh is pure dispatch overhead (session property
#: distributed_min_stage_rows; 0 forces every stage onto the mesh,
#: which the dryrun/tests use to exercise multi-stage plans)
DEFAULT_MIN_STAGE_ROWS = 1 << 13


def chain_leaf_node(node: PlanNode) -> PlanNode:
    """The probe-spine leaf of a streaming chain (scan or materialized
    intermediate)."""
    while True:
        if isinstance(node, (FilterNode, ProjectNode)):
            node = node.source
        elif isinstance(node, AggregationNode) and node.step == "partial":
            node = node.source
        elif isinstance(node, (JoinNode, CrossSingleNode)):
            node = node.left
        else:
            return node


def _leaf_big_enough(node: PlanNode, min_rows: int) -> bool:
    if min_rows <= 0:
        return True
    leaf = chain_leaf_node(node)
    if not isinstance(leaf, PrecomputedNode):
        return True  # scans always distribute
    if leaf.page is not None:
        # LIVE rows, not padded capacity: per-device merge pages are
        # allocated at group capacity regardless of actual groups
        import numpy as np

        return int(np.asarray(leaf.page.num_rows())) >= min_rows
    est = getattr(leaf, "_est_rows", None)
    if est is not None:
        return est >= min_rows  # EXPLAIN simulation: planner estimate
    return True


def is_agg_stage(node: PlanNode,
                 min_precomputed_rows: int = DEFAULT_MIN_STAGE_ROWS) -> bool:
    """Root of a scan->chain->partial-agg->exchange->final-merge mesh
    stage (the reference's FIXED_HASH aggregation fragment pair)."""
    return (isinstance(node, AggregationNode) and node.step == "single"
            and not any(a.fn == "evaluate_classifier_predictions"
                        for a in node.aggs)  # host-finalized: local only
            and chain_distributable(node.source) is None
            and _leaf_big_enough(node.source, min_precomputed_rows))


def is_chain_stage(node: PlanNode,
                   min_precomputed_rows: int = DEFAULT_MIN_STAGE_ROWS) -> bool:
    """Root of a pure streaming-chain mesh stage (a SOURCE fragment
    whose consumer is the coordinator or a glue breaker).  A bare
    materialized page or literal is not a stage — re-scattering it
    would be a round trip with no work."""
    if isinstance(node, (PrecomputedNode, ValuesNode)):
        return False
    return (chain_distributable(node) is None
            and _leaf_big_enough(node, min_precomputed_rows))


def is_window_stage(node: PlanNode,
                    min_precomputed_rows: int = DEFAULT_MIN_STAGE_ROWS) -> bool:
    """Root of a distributed window stage: a hash exchange on the
    PARTITION BY keys routes every partition's rows to one shard, then
    ``ops/window.py`` runs per shard (the reference's FIXED_HASH
    WindowNode fragment, AddExchanges partitioning on
    ``WindowNode.getPartitionBy``).  Plain column keys only — both
    tiers route by key channel index; an empty PARTITION BY is a
    whole-relation window and stays on the coordinator."""
    from presto_tpu.expr.ir import ColumnRef

    return (isinstance(node, WindowNode)
            and bool(node.partition_exprs)
            and all(isinstance(e, ColumnRef) for e in node.partition_exprs)
            and chain_distributable(node.source) is None
            and _leaf_big_enough(node.source, min_precomputed_rows))


def is_sort_stage(node: PlanNode,
                  min_precomputed_rows: int = DEFAULT_MIN_STAGE_ROWS) -> bool:
    """Root of a distributed ORDER BY: each shard sorts its own rows
    (ops/sort.py inside the stage program) and the coordinator k-way
    merges the pre-sorted runs (ops/merge.py) — MergeOperator.java:45's
    shape.  Small inputs stay coordinator glue: the merge tree would
    cost more than one local sort."""
    return (isinstance(node, SortNode)
            and chain_distributable(node.source) is None
            and _leaf_big_enough(node.source, min_precomputed_rows))


def is_union_stage(node: PlanNode,
                   min_precomputed_rows: int = DEFAULT_MIN_STAGE_ROWS) -> bool:
    """A UNION whose every leg is itself a runnable stage (chain or
    aggregation): the legs execute as concurrent producer stages
    draining into ONE streaming exchange, instead of sequential
    coordinator concatenation."""
    if not isinstance(node, UnionNode) or len(node.inputs) < 2:
        return False
    return all(
        is_agg_stage(leg, min_precomputed_rows)
        or is_chain_stage(leg, min_precomputed_rows)
        for leg in node.inputs)


def remap_union_leg_page(page, offs, channels):
    """Consumer side of the union exchange, shared by both tiers:
    apply leg ``offs``'s dictionary-code offsets and retype blocks to
    the union's output ``channels`` (legs built against different
    varchar dictionaries unify here)."""
    from presto_tpu.page import Block, Page

    blocks = []
    for i, b in enumerate(page.blocks):
        data = b.data + offs[i] if offs[i] else b.data
        blocks.append(Block(data, b.valid, channels[i].type,
                            channels[i].dictionary))
    return Page(tuple(blocks), page.row_mask)


def child_slots(node: PlanNode):
    """(slot, child) edges of the node kinds the decomposition recurses
    through.  Unknown node kinds yield nothing — their subtree stays on
    the coordinator."""
    if isinstance(node, (JoinNode, CrossSingleNode)):
        return [("left", node.left), ("right", node.right)]
    if isinstance(node, UnionNode):
        return [(("inputs", i), s) for i, s in enumerate(node.inputs)]
    if isinstance(node, (FilterNode, ProjectNode, AggregationNode, SortNode,
                         TopNNode, LimitNode, WindowNode, OutputNode,
                         GroupIdNode, UnnestNode)):
        return [("source", node.source)]
    return []


def get_child(node: PlanNode, slot):
    if isinstance(slot, tuple):
        return getattr(node, slot[0])[slot[1]]
    return getattr(node, slot)


def set_child(node: PlanNode, slot, child: PlanNode) -> None:
    if isinstance(slot, tuple):
        getattr(node, slot[0])[slot[1]] = child
        if isinstance(node, UnionNode):
            # merged dictionaries/offsets were computed from the old arms
            node._channels = None
            node._offsets = None
    else:
        setattr(node, slot, child)


def fully_materialized(node: PlanNode) -> bool:
    """Every leaf below ``node`` is an already-materialized page or a
    literal: evaluating the node now (coordinator-side) is exactly what
    the final local run would do, just earlier — which is what lets an
    ancestor stage distribute over its output."""
    if isinstance(node, (PrecomputedNode, ValuesNode)):
        return True
    slots = child_slots(node)
    if not slots:
        return False
    return all(fully_materialized(c) for _, c in slots)


def _parent_fuses(parent: PlanNode, slot) -> bool:
    """True when ``parent`` would include this child edge in its own
    fused chain, so a stage must not be cut here — the outermost chain
    position (whose parent is a breaker or the root) cuts instead."""
    if isinstance(parent, (FilterNode, ProjectNode)) and slot == "source":
        return True
    if isinstance(parent, AggregationNode) and slot == "source":
        return True
    if isinstance(parent, (JoinNode, CrossSingleNode)) and slot == "left":
        return True
    return False


def lower_stages(plan: PlanNode, run_agg, run_chain, eval_glue,
                 splices: list,
                 min_stage_rows: int = DEFAULT_MIN_STAGE_ROWS,
                 run_window=None, run_sort=None, run_union=None):
    """Decompose ``plan`` into mesh stages bottom-up, splicing each
    executed stage's materialization back into the tree.  ``run_agg`` /
    ``run_chain`` execute a stage and return its PrecomputedNode;
    ``eval_glue`` evaluates a fully-materialized glue breaker on the
    coordinator (may return None to leave it in place).  ``run_window``
    / ``run_sort`` / ``run_union`` (optional — a runner that omits one
    keeps the coordinator-glue behavior for that breaker) execute the
    distributed breaker stages: hash-exchanged per-shard windows,
    per-shard sort + coordinator merge, and concurrent UNION legs into
    one exchange.  ``splices`` records (parent, slot, old_child) for
    restoration.  Returns (mesh_stage_count, lowered_root) — glue
    evaluations do not count.

    Simulation (EXPLAIN) passes callbacks that fabricate empty
    PrecomputedNodes instead of executing, walking the identical
    decomposition, so EXPLAIN (TYPE DISTRIBUTED) always describes what
    execution would actually do."""

    def breaker_stage_kind(node) -> Optional[str]:
        if run_window is not None and is_window_stage(node, min_stage_rows):
            return "window"
        if run_sort is not None and is_sort_stage(node, min_stage_rows):
            return "sort"
        if run_union is not None and is_union_stage(node, min_stage_rows):
            return "union"
        return None

    def try_stage(node, bound=None):
        """(spliced PrecomputedNode, stage count) or (None, 0)."""
        if is_agg_stage(node, min_stage_rows):
            return run_agg(node), 1
        kind = breaker_stage_kind(node)
        if kind == "window":
            return run_window(node), 1
        if kind == "sort":
            return run_sort(node), 1
        if kind == "union":
            # one producer stage per leg, all draining one exchange
            return run_union(node), len(node.inputs)
        if is_chain_stage(node, min_stage_rows):
            return run_chain(node, bound), 1
        return None, 0

    def splice(parent, slot, old, new):
        splices.append((parent, slot, old))
        set_child(parent, slot, new)

    def spine_joins(node):
        """Join/cross nodes along a chain's probe spine (their build
        sides are the chain's off-spine inputs)."""
        while True:
            if isinstance(node, (FilterNode, ProjectNode)):
                node = node.source
            elif isinstance(node, AggregationNode) and node.step == "partial":
                node = node.source
            elif isinstance(node, (JoinNode, CrossSingleNode)):
                yield node
                node = node.left
            else:
                return

    def run_stage_at(parent, slot, child) -> int:
        """Execute the stage rooted at ``child``, first lowering any
        breakers hanging off its build sides (a join build containing
        an aggregation subquery distributes as its own stage; build
        splices cannot break the probe chain)."""
        if isinstance(child, UnionNode):
            spines = [leg.source if isinstance(leg, AggregationNode) else leg
                      for leg in child.inputs]
        elif isinstance(child, (AggregationNode, WindowNode, SortNode)):
            spines = [child.source]
        else:
            spines = [child]
        n = 0
        for sp in spines:
            n += sum(lower_edge(j, "right") for j in spine_joins(sp))
        # a TopN/Limit consumer bounds each shard's output to its count
        # before the gather (CreatePartialTopN.java role) — the glue
        # breaker still runs on the coordinator for the global pick
        bound = parent if (isinstance(parent, (TopNNode, LimitNode))
                           and slot == "source") else None
        new, k = try_stage(child, bound)
        assert new is not None  # build splices never un-distribute a chain
        splice(parent, slot, child, new)
        return n + k

    def cuts_here(child, fuses: bool) -> bool:
        """Whether ``child`` roots a stage at this edge: aggregations
        and breaker stages cut regardless of the parent (they never
        fuse into an ancestor chain); a pure chain cuts only at its
        outermost position (fusing parents defer to the ancestor that
        will include this subtree in its own stage)."""
        return (is_agg_stage(child, min_stage_rows)
                or breaker_stage_kind(child) is not None
                or (not fuses and is_chain_stage(child, min_stage_rows)))

    def lower_edge(parent, slot) -> int:
        child = get_child(parent, slot)
        if (isinstance(parent, (JoinNode, CrossSingleNode)) and slot == "right"
                and build_side_chainable(child)):
            # the stage machinery wave-scans chainable build sides
            # itself (sharded/colocated builds); pre-materializing here
            # would downgrade a partitioned build to broadcast
            return 0
        fuses = _parent_fuses(parent, slot)
        if cuts_here(child, fuses):
            return run_stage_at(parent, slot, child)
        n = 0
        for cslot, _ in child_slots(child):
            n += lower_edge(child, cslot)
        if n == 0:
            return 0
        if cuts_here(child, fuses):
            # children materialized: the node became a stage root (e.g.
            # an aggregation whose chain leaf was a subquery, or a
            # window/sort over a now-materialized intermediate)
            return n + run_stage_at(parent, slot, child)
        # a glue breaker over a fully-materialized subtree evaluates on
        # the coordinator so an ANCESTOR stage can distribute over it
        if isinstance(child, GLUE_BREAKERS) and fully_materialized(child):
            new = eval_glue(child)
            if new is not None:
                splice(parent, slot, child, new)
        return n

    class _Holder:
        source = plan

    holder = _Holder()
    n = lower_edge(holder, "source")
    return n, holder.source


def fragment_plan(
    plan: PlanNode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None, min_stage_rows: int = DEFAULT_MIN_STAGE_ROWS,
) -> Fragment:
    """Lower a plan into a SubPlan-style fragment tree by SIMULATING the
    generalized stage decomposition (``lower_stages`` with fabricated
    stage outputs) — the fragment tree is therefore exactly the stage
    DAG the distributed runner would execute (for the min-stage-rows
    cutoff the simulation uses planner ROW ESTIMATES where execution
    sees actual intermediate sizes — the one adaptive decision that can
    differ).  Fragments: a SINGLE coordinator fragment at the root (and
    per glue breaker), a FIXED_HASH merge + SOURCE leaf pair per
    distributed aggregation, SOURCE chain fragments, and one fragment
    per join build side (BROADCAST / FIXED_HASH / COLOCATED by the
    distribution decision)."""
    counter = [0]

    def next_id() -> int:
        fid = counter[0]
        counter[0] += 1
        return fid

    def _leaf_distribution(node: PlanNode) -> Partitioning:
        n = node
        while True:
            if isinstance(n, TableScanNode):
                return Partitioning(SOURCE)
            srcs = n.sources
            if not srcs:
                return Partitioning(SINGLE)
            n = srcs[0]

    def collect_children(node: PlanNode) -> List[Fragment]:
        """Fragments feeding ``node``'s subtree: spliced child-stage
        fragments (tagged on their PrecomputedNodes) and join build
        fragments along streaming chains."""
        out: List[Fragment] = []
        frag = getattr(node, "_frag", None)
        if frag is not None:
            return [frag]
        if isinstance(node, (JoinNode, CrossSingleNode)):
            out += collect_children(node.left)
            mode, _ = decide_join_distribution(
                node, broadcast_threshold, catalog=catalog)
            kind = {"broadcast": BROADCAST, "colocated": COLOCATED}.get(
                mode, FIXED_HASH)
            keys = tuple(getattr(node, "right_keys", ()))
            out.append(Fragment(
                next_id(), node.right,
                distribution=_leaf_distribution(node.right),
                output=Partitioning(kind, keys if kind == FIXED_HASH else ()),
                children=collect_children(node.right),
            ))
            return out
        for _, child in child_slots(node):
            out += collect_children(child)
        return out

    def tag(node: PlanNode, frag: Fragment) -> PrecomputedNode:
        pre = PrecomputedNode(page=None, channel_list=node.channels)
        pre._frag = frag
        try:
            pre._est_rows = estimate_rows(node)
        except Exception:
            pre._est_rows = None
        return pre

    def sim_agg(node: AggregationNode) -> PrecomputedNode:
        keys = tuple(node.group_exprs)
        part = Partitioning(FIXED_HASH, keys) if keys else Partitioning(SINGLE)
        leaf = Fragment(
            next_id(), node.source,
            distribution=_leaf_distribution(node.source), output=part,
            children=collect_children(node.source),
        )
        merge = Fragment(next_id(), node, distribution=part,
                         output=Partitioning(SINGLE), children=[leaf])
        return tag(node, merge)

    def sim_chain(node: PlanNode, bound=None) -> PrecomputedNode:
        frag = Fragment(
            next_id(), node, distribution=_leaf_distribution(node),
            output=Partitioning(SINGLE), children=collect_children(node),
        )
        frag.shard_bound = None if bound is None else bound.count
        return tag(node, frag)

    def sim_glue(node: PlanNode) -> PrecomputedNode:
        frag = Fragment(
            next_id(), node, distribution=Partitioning(SINGLE),
            output=Partitioning(SINGLE), children=collect_children(node),
        )
        return tag(node, frag)

    def sim_window(node: WindowNode) -> PrecomputedNode:
        # source fragment hash-exchanges on the PARTITION BY keys; the
        # window fragment runs per shard and gathers
        keys = tuple(node.partition_exprs)
        part = Partitioning(FIXED_HASH, keys)
        leaf = Fragment(
            next_id(), node.source,
            distribution=_leaf_distribution(node.source), output=part,
            children=collect_children(node.source),
        )
        win = Fragment(next_id(), node, distribution=part,
                       output=Partitioning(SINGLE), children=[leaf])
        return tag(node, win)

    def sim_sort(node: SortNode) -> PrecomputedNode:
        # per-shard sort inside the stage; the edge to the consumer is
        # an order-preserving merge of the pre-sorted runs
        frag = Fragment(
            next_id(), node, distribution=_leaf_distribution(node.source),
            output=Partitioning(SINGLE), children=collect_children(node.source),
            exchange_kind="merge", exchange_keys=tuple(node.sort_exprs),
        )
        return tag(node, frag)

    def sim_union(node: UnionNode) -> PrecomputedNode:
        # one concurrent producer fragment per leg, all draining into
        # the union fragment's single streaming exchange
        legs = []
        for leg in node.inputs:
            legs.append(Fragment(
                next_id(), leg, distribution=_leaf_distribution(leg),
                output=Partitioning(SINGLE), children=collect_children(leg),
            ))
        frag = Fragment(next_id(), node, distribution=Partitioning(SINGLE),
                        output=Partitioning(SINGLE), children=legs,
                        exchange_kind="union")
        return tag(node, frag)

    splices: list = []
    try:
        n, root = lower_stages(plan, sim_agg, sim_chain, sim_glue, splices,
                               min_stage_rows=min_stage_rows,
                               run_window=sim_window, run_sort=sim_sort,
                               run_union=sim_union)
        out = Fragment(
            next_id(), plan, distribution=Partitioning(SINGLE),
            output=Partitioning(SINGLE), children=collect_children(root),
        )
        out.mesh_stages = n  # simulated stage count (FRAGMENTED header)
        return out
    finally:
        for parent, slot, old in reversed(splices):
            set_child(parent, slot, old)


def undistributable_reason(plan: PlanNode) -> str:
    """Why no stage distributes — the loud part of the fallback."""
    node = plan
    while isinstance(node, OutputNode):
        node = node.source
    if isinstance(node, AggregationNode) and node.step == "single":
        return chain_distributable(node.source) or "distributable"
    return chain_distributable(node) or "distributable"


def explain_distributed(
    plan: PlanNode, broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
    catalog=None, min_stage_rows: int = DEFAULT_MIN_STAGE_ROWS,
) -> str:
    """EXPLAIN (TYPE DISTRIBUTED): the FRAGMENTED header is the loud
    distributed-vs-local signal VERDICT r3 asked for — when execution
    would silently have run locally, the header says so and why."""
    frags = fragment_plan(plan, broadcast_threshold, catalog=catalog,
                          min_stage_rows=min_stage_rows)
    n = frags.mesh_stages
    if n == 0:
        header = (f"FRAGMENTED: no — {undistributable_reason(plan)}; "
                  "plan executes on the coordinator only\n")
    else:
        header = f"FRAGMENTED: yes ({n} mesh stage{'s' if n > 1 else ''})\n"
    return header + frags.tree_str()
