"""Stage scheduling policies and node selection for the DCN tier.

Reference analogs:
- ``execution/scheduler/PhasedExecutionSchedule.java`` /
  ``AllAtOnceExecutionSchedule.java`` — the ExecutionPolicy choosing
  whether every stage of the fragment DAG starts at once or in
  dependency phases (join build stages gated before their probes, so
  probe-side tasks never sit idle holding memory while builds run).
- ``execution/scheduler/NodeScheduler.java`` + ``SimpleNodeSelector`` /
  ``TopologyAwareNodeSelector`` + ``NetworkTopology`` — split->node
  placement with locality preference and max-splits-per-node
  backpressure.

TPU framing: the MESH tier needs neither (stages are phased by
construction — ``lower_stages`` materializes a stage's inputs before
the stage, and XLA owns intra-program scheduling); these policies serve
the MULTI-HOST tier, where fragments really are independent HTTP tasks
on independent machines.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class ExecutionSchedule:
    """Yields batches ("phases") of stages to launch together; the next
    batch starts when the previous one's tasks are created."""

    def __init__(self, fragments):
        self.fragments = list(fragments)

    def phases(self) -> List[List]:
        raise NotImplementedError


class AllAtOnceExecutionSchedule(ExecutionSchedule):
    """Every stage starts immediately
    (AllAtOnceExecutionSchedule.java)."""

    def phases(self) -> List[List]:
        return [self.fragments] if self.fragments else []


class PhasedExecutionSchedule(ExecutionSchedule):
    """Dependency-ordered phases: a fragment's children (its build
    sides / upstream producers) start in earlier phases than the
    fragment itself (PhasedExecutionSchedule.java's topological
    ordering over the join-build dependency graph)."""

    def phases(self) -> List[List]:
        depth: Dict[int, int] = {}

        def walk(frag) -> int:
            if id(frag) in depth:
                return depth[id(frag)]
            d = 0
            for ch in getattr(frag, "children", []):
                d = max(d, walk(ch) + 1)
            depth[id(frag)] = d
            return d

        roots = list(self.fragments)
        for f in roots:
            walk(f)
        seen = set()
        by_depth: Dict[int, List] = {}

        def collect(frag):
            if id(frag) in seen:
                return
            seen.add(id(frag))
            by_depth.setdefault(depth[id(frag)], []).append(frag)
            for ch in getattr(frag, "children", []):
                collect(ch)

        for f in roots:
            collect(f)
        # dependency-free fragments first (builds before their probes:
        # depth 0 = no children)
        return [by_depth[d] for d in sorted(by_depth)]


class NodeSelector:
    """Split->worker placement with locality preference and
    max-splits-per-node backpressure (NodeScheduler.java +
    TopologyAwareNodeSelector).

    ``locations``: optional worker -> location string (e.g. a rack id).
    A split whose connector reports a preferred location (duck-typed
    ``split_location(table, split)``) is placed on a worker in that
    location when one has headroom; otherwise the least-loaded worker
    wins (the reference's fallback through topology tiers to the
    cluster-wide pool)."""

    def __init__(self, workers: Sequence, max_splits_per_node: int = 0,
                 locations: Optional[Dict] = None):
        self.workers = list(workers)
        self.max_splits_per_node = max_splits_per_node  # 0 = unbounded
        self.locations = dict(locations or {})

    def _headroom(self, counts: Dict, w) -> bool:
        if self.max_splits_per_node <= 0:
            return True
        return counts.get(id(w), 0) < self.max_splits_per_node

    def assign(self, split_ids: Sequence[int],
               preferred: Optional[Dict[int, str]] = None) -> Dict:
        """{worker: [split ids]} — locality-preferred, then least
        loaded; backpressure spills to other nodes, and when every node
        is at its cap the caps stretch evenly (the reference queues
        instead; here fragments are batch tasks, so stretching keeps
        the whole batch schedulable)."""
        preferred = preferred or {}
        counts: Dict[int, int] = {}
        out: Dict = {w: [] for w in self.workers}

        def pick(candidates):
            pool = [w for w in candidates if self._headroom(counts, w)]
            if not pool:
                pool = list(candidates)  # all at cap: stretch evenly
            return min(pool, key=lambda w: counts.get(id(w), 0))

        for s in split_ids:
            loc = preferred.get(s)
            local = [w for w in self.workers
                     if loc is not None and self.locations.get(id(w)) == loc]
            w = pick(local) if local and any(
                self._headroom(counts, x) for x in local) else pick(self.workers)
            out[w].append(s)
            counts[id(w)] = counts.get(id(w), 0) + 1
        return out
