"""Distributed query execution over a jax device mesh.

Reference analog: the distributed tier — ``PlanFragmenter.java:84``
(stage boundaries at exchanges), ``SqlStageExecution``/``TaskExecutor``
(per-node work), and the shuffle of §2.3.  TPU redesign: a stage is ONE
SPMD program ``shard_map``-ed over the mesh; "tasks" are the per-device
shards; the shuffle is ``all_to_all`` over ICI (see exchange.py); the
scheduler is the wave loop feeding each device one split per wave
(SourcePartitionedScheduler's role).

Supported distributed shape:
    [Output/Project/Sort/TopN/Limit/Filter]*
      -> Aggregation(single)
        -> streaming chain (scan -> filter/project -> joins -> ...)
Joins distribute per the fragmenter's decision
(parallel/fragment.py, DetermineJoinDistributionType.java:33 analog):
small builds replicate to every device (BROADCAST); large builds are
hash-partitioned across devices and the probe rows ride an
``all_to_all`` on the join key inside the wave program (FIXED_HASH —
the repartitioned join of AddExchanges.java:738).  Expanding
(many-to-many) joins run in-program with static output capacities and
count-check-and-retry, like the local runner.

Post-aggregation nodes run locally on the gathered (small) result via
PrecomputedNode splicing.  Anything else falls back to LocalRunner.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

_log = logging.getLogger("presto_tpu.dist")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map moved from jax.experimental to the jax namespace around
# 0.6; resolve whichever this build ships so the mesh tier runs on both
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:  # pragma: no cover - depends on the jax build
    from jax.experimental.shard_map import shard_map

from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import (
    MAX_AGG_GROUPS,
    GroupCapacityExceeded,
    LocalRunner,
    MaterializedResult,
    QueryStats,
    concat_pages_device,
)
from presto_tpu.ops.join import JoinBuild, build_join, probe_expand, probe_join
from presto_tpu.parallel.fragment import DEFAULT_BROADCAST_THRESHOLD
from presto_tpu.expr.ir import ColumnRef
from presto_tpu.ops.aggregate import grouped_aggregate, merge_aggregate
from presto_tpu.page import Block, Page, concat_pages_host
from presto_tpu.parallel.exchange import (
    exchange_page,
    partition_for_exchange,
    partition_targets,
)
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
)


class DistributedUnsupported(Exception):
    pass


class _BuildOverflow(Exception):
    """A sharded-build exchange bucket overfilled; retry with the given
    bucket capacity."""

    def __init__(self, needed: int):
        self.needed = needed


class _ChainCtx:
    """Build-time context for a distributed chain: registered join
    builds (broadcast consts vs sharded consts) and the runtime check
    names the host must verify after each wave."""

    def __init__(self, cap: int):
        self.cap = cap  # leaf split capacity (sizes the default buckets)
        self.broadcast: Dict[str, PlanNode] = {}
        self.sharded: Dict[str, PlanNode] = {}
        self.checks: List[str] = []
        self.check_meta: List[Tuple[str, PlanNode, str]] = []
        self._i = 0

    def add_broadcast(self, node) -> str:
        key = f"build_{self._i}"
        self._i += 1
        self.broadcast[key] = node
        return key

    def add_sharded(self, node) -> str:
        key = f"sbuild_{self._i}"
        self._i += 1
        self.sharded[key] = node
        return key

    def add_check(self, node, kind: str) -> str:
        name = f"{kind}_{len(self.checks)}"
        self.checks.append(name)
        self.check_meta.append((name, node, kind))
        return name

    def sig(self, join_cfg) -> Tuple:
        """Capacity signature: compiled programs are cached per config."""
        out = []
        for name, node, _ in self.check_meta:
            cfg = join_cfg.get(node, {})
            out.append((name, cfg.get("bucket_cap"), cfg.get("out_cap")))
        return tuple(out)


def make_mesh(n_devices: Optional[int] = None, axis: str = "d") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


class _StageSource:
    """Wave-page provider for a stage leaf (the SplitSource /
    split-scheduling analog): a table scan reads connector splits
    (device d takes split w*n+d, honoring a restricted ``splits``
    assignment); a materialized intermediate (PrecomputedNode — the
    output of an upstream stage) chunks its page rows across devices,
    playing the RemoteSourceNode role between fragments."""

    def __init__(self, runner: "DistributedRunner", leaf):
        self.runner = runner
        self.leaf = leaf
        self.n = runner.n
        if isinstance(leaf, TableScanNode):
            self.conn = runner.catalog.connector(leaf.handle.connector_name)
            self.cap = runner._split_capacity(self.conn, leaf.handle.table)
            self.split_ids = (list(leaf.splits) if leaf.splits is not None
                              else list(range(leaf.handle.num_splits)))
            self.col_idx = list(leaf.columns)
            self._pre = None
        else:
            page = leaf.page
            self._pre = [
                (np.asarray(b.data), np.asarray(b.valid), b.type, b.dictionary)
                for b in page.blocks
            ]
            self._pre_mask = np.asarray(page.row_mask)
            total = int(self._pre_mask.shape[0])
            per = max(-(-total // self.n), 1)
            self.cap = 1 << (per - 1).bit_length()
            self.split_ids = list(range(max(-(-total // self.cap), 1)))
        self.n_splits = len(self.split_ids)
        self.waves = math.ceil(self.n_splits / self.n)

    def _page_for(self, i: int) -> Page:
        if self._pre is None:
            leaf = self.leaf
            s = self.split_ids[i]
            pg = self.conn.page_for_split(leaf.handle.table, s, capacity=self.cap)
            return Page(tuple(pg.blocks[c] for c in self.col_idx), pg.row_mask)
        lo = self.split_ids[i] * self.cap
        hi = lo + self.cap
        blocks = []
        for data, valid, typ, d in self._pre:
            dd, vv = data[lo:hi], valid[lo:hi]
            if dd.shape[0] < self.cap:
                pad = self.cap - dd.shape[0]
                dd = np.concatenate(
                    [dd, np.zeros((pad,) + dd.shape[1:], dd.dtype)])
                vv = np.concatenate([vv, np.zeros(pad, vv.dtype)])
            blocks.append(Block(dd, vv, typ, d))
        mask = self._pre_mask[lo:hi]
        if mask.shape[0] < self.cap:
            mask = np.concatenate(
                [mask, np.zeros(self.cap - mask.shape[0], mask.dtype)])
        return Page(tuple(blocks), mask)

    def _empty_page(self) -> Page:
        if self._pre is None:
            leaf = self.leaf
            pg = Page.empty(
                [leaf.handle.columns[c].type for c in self.col_idx], self.cap)
            return Page(
                tuple(
                    Block(b.data, b.valid, b.type,
                          leaf.handle.columns[c].dictionary)
                    for b, c in zip(pg.blocks, self.col_idx)
                ),
                pg.row_mask,
            )
        blocks = [
            Block(np.zeros((self.cap,) + data.shape[1:], data.dtype),
                  np.zeros(self.cap, valid.dtype), typ, d)
            for data, valid, typ, d in self._pre
        ]
        return Page(tuple(blocks), np.zeros(self.cap, self._pre_mask.dtype))

    def stacked_wave(self, w: int) -> Page:
        """Host-assemble wave ``w``'s one-split-per-device stacked page
        (device d takes split w*n + d; missing splits pad empty)."""
        pages = []
        for d in range(self.n):
            s = w * self.n + d
            pages.append(self._page_for(s) if s < self.n_splits
                         else self._empty_page())
        return _stack_pages(pages)


def _squeeze(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree.map(lambda x: x[None], tree)


class DistributedRunner:
    """Runs plans over a mesh; falls back to LocalRunner when the plan
    shape isn't distributable yet."""

    def __init__(
        self,
        catalog: Catalog,
        mesh: Optional[Mesh] = None,
        axis: str = "d",
        broadcast_threshold: int = DEFAULT_BROADCAST_THRESHOLD,
        session=None,
    ):
        from presto_tpu.parallel.fragment import DEFAULT_MIN_STAGE_ROWS

        self.catalog = catalog
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.broadcast_threshold = broadcast_threshold
        # session controls (SystemSessionProperties analogs)
        self.join_distribution_type = "AUTOMATIC"
        self.allow_colocated = True
        self.min_stage_rows = DEFAULT_MIN_STAGE_ROWS
        # streaming exchange knobs (parallel/streams.py): stage
        # boundaries stream pages through token-acked buffers by
        # default; off = materialize-then-consume (the A/B leg)
        from presto_tpu.parallel.streams import (
            exchange_buffer_bytes_default, exchange_streaming_default,
        )

        self.exchange_streaming = exchange_streaming_default()
        self.exchange_buffer_bytes = exchange_buffer_bytes_default()
        self.merge_fanin = 8  # sorted runs merged per consumer batch
        # serving tier: reuse warm stage intermediates at exchange
        # boundaries when signature + table versions match
        # (serving/cache.py; subplan_cache_enabled session property)
        self.subplan_cache_enabled = False
        if session is not None:
            self.join_distribution_type = session.get("join_distribution_type")
            self.allow_colocated = bool(session.get("colocated_join"))
            self.min_stage_rows = int(
                session.get("distributed_min_stage_rows"))
            self.exchange_streaming = bool(session.get("exchange_streaming"))
            eb = int(session.get("exchange_buffer_bytes"))
            if eb > 0:
                self.exchange_buffer_bytes = eb
            self.merge_fanin = max(2, int(session.get("exchange_merge_fanin")))
            self.subplan_cache_enabled = bool(
                session.get("subplan_cache_enabled"))
        # morsel-scheduler knobs flow into the mesh tier too: the local
        # fallback runner schedules its scan splits, and the wave loops
        # prefetch the next wave's host assembly while the device mesh
        # executes the current one (resolved ONCE here, per env-read)
        from presto_tpu.exec.tasks import (
            task_concurrency_default, task_prefetch_default,
        )

        tc = int(session.get("task_concurrency")) if session is not None \
            else 0
        tp = int(session.get("task_prefetch")) if session is not None else -1
        self.task_concurrency = tc if tc > 0 else task_concurrency_default()
        self.wave_prefetch = tp if tp >= 0 else task_prefetch_default()
        self.local = LocalRunner(catalog, task_concurrency=tc or None,
                                 task_prefetch=tp)
        # persistent un-jitted runner for stage building/builds: its
        # _agg_overrides must survive GroupCapacityExceeded retries
        # (a build-side aggregation overflow records its doubled
        # capacity here; a throwaway runner would loop forever)
        self._stage_runner = LocalRunner(catalog, jit=False)
        self._wave_fns: Dict[Tuple, object] = {}
        self._final_fns: Dict[Tuple, object] = {}
        self._mg_overrides: Dict[PlanNode, int] = {}
        self._join_cfg: Dict[PlanNode, Dict[str, int]] = {}
        self._sharded_builds: Dict[Tuple, JoinBuild] = {}

    @property
    def n(self) -> int:
        return self.mesh.devices.size

    # ------------------------------------------------------------------
    def run(self, plan: PlanNode,
            stats: Optional["QueryStats"] = None) -> MaterializedResult:
        """Execute distributed; on an undistributable plan fall back to
        the coordinator LOUDLY: the reason is logged, kept on
        ``last_fallback_reason``, and surfaced through query events and
        EXPLAIN (TYPE DISTRIBUTED)'s FRAGMENTED header (VERDICT r3:
        silent local fallback hid that no TPC-DS query distributed).

        ``stats``: estimate-vs-actual roll-up sink — mesh stage roots
        record their materialized output at the stage boundary, glue
        breakers and the residual root record through the coordinator
        runner's per-thread sink."""
        self.last_stage_count = 0
        self.last_fallback_reason = None
        if stats is not None:
            stats.register_plan(plan)  # idempotent — shared key space
            self.local.stats = stats
        try:
            # per-run outcome rides the RESULT (dist_stages attached by
            # _run_distributed from its local stage count): concurrent
            # queries on one runner must not report each other's stats
            out = self._run_distributed(plan, stats)
            out.dist_fallback = None
            return out
        except DistributedUnsupported as e:
            from presto_tpu.obs import METRICS

            reason = str(e) or type(e).__name__
            self.last_fallback_reason = reason
            METRICS.counter("dist.fallbacks").inc()
            _log.warning("distributed execution fell back to coordinator: %s",
                         reason)
            out = self.local.run(plan)
            out.dist_stages = 0
            out.dist_fallback = reason
            return out
        finally:
            if stats is not None:
                self.local.stats = None

    def _run_distributed(self, plan: PlanNode,
                         qstats: Optional["QueryStats"] = None,
                         ) -> MaterializedResult:
        """Generalized stage-DAG execution (PlanFragmenter.java:84 +
        SqlQueryScheduler.java:441 analog): ``lower_stages`` decomposes
        ANY plan bottom-up into mesh stages — aggregation stages and
        streaming-chain stages, whose leaves are table scans or the
        materialized output of a previously-executed stage — with glue
        breakers (sort/window/union/limit/unnest) evaluated on the
        coordinator between stages.  The residual plan (the reference's
        SINGLE root fragment) runs locally over the spliced results."""
        from presto_tpu.parallel.fragment import (
            lower_stages, undistributable_reason,
        )

        # fresh join builds per query, like LocalRunner.run_to_page's
        # per-run _builds.clear(): table data may have changed since the
        # last run (a stale build would join fresh probe rows against
        # old build rows)
        self._stage_runner._builds.clear()
        self._sharded_builds.clear()

        # live progress: one entry per mesh stage as the scheduler
        # launches it (stage-level; the scans inside each stage publish
        # their own splits-done/total through the local runner)
        from presto_tpu.obs import current_progress

        prog = current_progress()

        def _staged(prefix, node, run):
            t0 = time.perf_counter()
            if prog is None:
                page = run()
            else:
                name = prog.new_stage_name(prefix)
                prog.stage(name, splits_total=1)
                page = run()
                prog.split_done(name)
                prog.finish_stage(name)
            # estimate-vs-actual: a mesh stage's output is the one
            # place the ORIGINAL node's actual is observable (sharded
            # internals run rebuilt partial-step shapes)
            if qstats is not None and qstats.actual_rows(node) is None:
                import numpy as _np

                rows = int(_np.asarray(page.row_mask).sum())
                try:
                    from presto_tpu.memory import page_bytes
                    nb = page_bytes(page)
                except Exception:
                    nb = 0
                qstats.record(node, time.perf_counter() - t0, rows, nb)
            return page

        def run_agg(node: AggregationNode) -> PrecomputedNode:
            page = _staged("dist:aggregation", node, lambda: self._cached_stage(
                "agg", node, lambda: self.run_aggregation_stage(node)))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_chain(node: PlanNode, bound=None) -> PrecomputedNode:
            page = _staged("dist:chain", node, lambda: self._cached_stage(
                "chain", node, lambda: self.run_chain_stage(node, bound),
                bound=bound))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def eval_glue(node: PlanNode) -> PrecomputedNode:
            # runs through self.local on this thread — the per-thread
            # stats sink records it like any coordinator operator
            page = self.local.run_to_page(node)
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_window(node) -> PrecomputedNode:
            page = _staged("dist:window", node, lambda: self._cached_stage(
                "window", node, lambda: self.run_window_stage(node)))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_sort(node) -> PrecomputedNode:
            page = _staged("dist:sort", node, lambda: self._cached_stage(
                "sort", node, lambda: self.run_sort_stage(node)))
            return PrecomputedNode(page=page, channel_list=node.channels)

        def run_union(node) -> PrecomputedNode:
            page = _staged("dist:union", node,
                           lambda: self.run_union_stage(node))
            return PrecomputedNode(page=page, channel_list=node.channels)

        splices: List = []
        try:
            n_stages, root = lower_stages(
                plan, run_agg, run_chain, eval_glue, splices,
                min_stage_rows=self.min_stage_rows,
                run_window=run_window, run_sort=run_sort,
                run_union=run_union)
            if n_stages == 0:
                raise DistributedUnsupported(undistributable_reason(plan))
            from presto_tpu.obs import METRICS

            METRICS.counter("dist.stages_total").inc(n_stages)
            self.last_stage_count = n_stages
            out = self.local.run(root)
            if root is not plan:  # the whole plan was one stage
                out.names, out.types = plan.output_names, plan.output_types
            # per-run stage count from the LOCAL n_stages, not the
            # shared field a concurrent run may have reset
            out.dist_stages = n_stages
            return out
        finally:
            from presto_tpu.parallel.fragment import set_child

            for parent, slot, old in reversed(splices):
                set_child(parent, slot, old)

    # ------------------------------------------------------------------
    def _cached_stage(self, kind: str, node: PlanNode, thunk,
                      bound=None) -> Page:
        """Subplan (fragment) cache at the exchange boundary: when the
        stage rooted at ``node`` is cacheable (deterministic, leaves
        are versioned base-table scans) and its structural signature +
        table versions match a prior execution, the warm intermediate
        page is reused instead of re-executing the stage — the shared
        scan->filter->agg prefix of dashboard variants.  The mesh width
        and any consumer shard bound fold into the key (they shape the
        materialized page)."""
        if not self.subplan_cache_enabled:
            return thunk()
        from presto_tpu.exec.programs import ir_signature
        from presto_tpu.serving.cache import (
            default_subplan_cache, signature_has_identity_keys,
        )

        extra = [kind, self.n]
        if bound is not None:
            # the bound's SHALLOW shape only (count + sort spec) — its
            # source is the stage subtree prepare() signs anyway, and
            # re-walking it here would sign the whole plan twice
            bkey = (type(bound).__name__, bound.count,
                    ir_signature(tuple(getattr(bound, "sort_exprs", ())
                                       or ())),
                    ir_signature(tuple(getattr(bound, "ascending", ())
                                       or ())),
                    ir_signature(tuple(getattr(bound, "nulls_first", ())
                                       or ())))
            if signature_has_identity_keys(bkey):
                return thunk()
            extra += [bkey]
        cache = default_subplan_cache()
        prepared = cache.prepare(node, self.catalog, extra=tuple(extra))
        if prepared is not None:
            page = cache.lookup(prepared)
            if page is not None:
                return page
        page = thunk()
        cache.store(prepared, page)
        return page

    def run_chain_stage(self, chain_root: PlanNode, bound=None) -> Page:
        """Wave-execute a pure streaming chain over the mesh and gather
        its rows — a SOURCE fragment whose consumer is the coordinator
        (or a glue breaker).  ``bound`` is a consuming TopN/Limit node:
        each shard then ships only its own top/first ``bound.count``
        rows across the gather (CreatePartialTopN.java role; the glue
        breaker still runs the global pick on the coordinator)."""
        from presto_tpu.obs import span

        source = self._stage_source(chain_root)
        with span("dist_stage:chain", cat="exchange"):
            while True:
                try:
                    pages = self._run_chain_stage_once(chain_root, source,
                                                       bound)
                    break
                except GroupCapacityExceeded:
                    continue  # join capacities bumped; re-execute
            return concat_pages_host(pages)

    def _run_chain_stage_once(self, chain_root: PlanNode,
                              source: "_StageSource", bound=None,
                              sort=None, emit=None) -> List[Page]:
        """One attempt of a chain stage's wave loop.  ``sort`` (a
        SortNode consumer) appends a per-shard sort to the wave program
        so every emitted page is a pre-sorted run (the distributed-sort
        producer half, CreatePartialTopN's unbounded sibling).
        ``emit`` streams each per-device page to the consuming stage as
        soon as it is verified: immediately when the stage carries no
        runtime checks (nothing can invalidate a page), after the
        host-side check pass otherwise (an exchange-bucket overflow
        would retry the stage and re-emit)."""
        from presto_tpu.ops.sort import (
            limit_compact_page, sort_page, topn_compact_page,
        )
        from presto_tpu.planner.plan import TopNNode as _TopN

        ctx = _ChainCtx(source.cap)
        stage = self._build_dist_stage(chain_root, ctx)
        if bound is not None and bound.count >= source.cap:
            bound = None  # nothing to shrink
        runner = self._stage_runner
        consts_rep = {
            key: runner._materialize_build(j) for key, j in ctx.broadcast.items()
        }
        consts_shard = {
            key: (self._materialize_build_colocated(j)
                  if self._join_mode(j) == "colocated"
                  else self._materialize_build_sharded(j))
            for key, j in ctx.sharded.items()
        }
        mesh, axis, n = self.mesh, self.axis, self.n

        def per_device_wave(page1, consts_r, consts_s):
            page = _squeeze(page1)
            p, checks = stage(page, {**consts_r, **consts_s})
            if bound is not None:
                if isinstance(bound, _TopN):
                    p = topn_compact_page(p, bound.sort_exprs,
                                          bound.ascending, bound.count,
                                          bound.nulls_first)
                else:
                    p = limit_compact_page(p, bound.count)
            if sort is not None:
                p = sort_page(p, list(sort.sort_exprs), list(sort.ascending),
                              sort.nulls_first)
            return _unsqueeze(p), {k: v[None] for k, v in checks.items()}

        bound_key = (None if bound is None else
                     (type(bound).__name__, bound.count,
                      tuple(getattr(bound, "sort_exprs", ()) or ()),
                      tuple(getattr(bound, "ascending", ()) or ()),
                      tuple(getattr(bound, "nulls_first", ()) or ())))
        sort_key = (None if sort is None else
                    (tuple(sort.sort_exprs), tuple(sort.ascending),
                     tuple(sort.nulls_first or ())))
        fn_key = (chain_root, "chain", ctx.sig(self._join_cfg), bound_key,
                  sort_key)
        wave_fn = self._wave_fns.get(fn_key)
        if wave_fn is None:
            check_specs = {name: P(axis) for name in ctx.checks}
            wave_fn = jax.jit(
                shard_map(
                    per_device_wave, mesh=mesh,
                    in_specs=(P(axis), P(), {k: P(axis) for k in consts_shard}),
                    out_specs=(P(axis), check_specs),
                )
            )
            self._wave_fns[fn_key] = wave_fn

        sharding = NamedSharding(mesh, P(axis))
        out_pages: List[Page] = []
        wave_checks = []
        channels = chain_root.channels
        stream_now = emit is not None and not ctx.checks
        for stacked in self._wave_iter(source, sharding):
            out, cks = wave_fn(stacked, consts_rep, consts_shard)
            wave_checks.append(cks)
            pages = _unstack_pages(jax.device_get(out), channels)
            if stream_now:
                for p in pages:
                    emit(p)
            else:
                out_pages.extend(pages)
        self._verify_checks(chain_root, ctx, wave_checks, 0, False)
        if emit is not None and not stream_now:
            for p in out_pages:
                emit(p)
            return []
        return out_pages

    def _wave_iter(self, source: "_StageSource", sharding):
        """Device-placed wave pages, with the NEXT wave's host assembly
        (+ transfer) prefetched while the mesh executes the current one
        (double-buffering; wave_prefetch=0 keeps the serial loop)."""
        waves = (jax.device_put(source.stacked_wave(w), sharding)
                 for w in range(source.waves))
        if self.wave_prefetch <= 0 or self.task_concurrency <= 1:
            return waves
        from presto_tpu.exec.tasks import prefetch_iter

        return prefetch_iter(waves, depth=self.wave_prefetch,
                             name="dist-wave")

    # ------------------------------------------------------------------
    # streaming breaker stages: window / sort / union run ON the mesh
    # instead of as coordinator glue, their pages travelling through
    # the token-acked exchange (parallel/streams.py) so the consumer
    # side (bucket routing, run merging, offset mapping) overlaps the
    # producing waves
    # ------------------------------------------------------------------
    def _exchange(self, kind: str, name: str):
        from presto_tpu.parallel.streams import StreamingExchange

        return StreamingExchange(kind, name,
                                 streaming=self.exchange_streaming,
                                 max_bytes=self.exchange_buffer_bytes)

    def _produce_chain_into(self, chain_root: PlanNode, put,
                            sort=None) -> None:
        """Producer body for a streamed chain stage: wave-execute and
        put per-device pages, retrying capacity bumps internally (pages
        are only emitted once they cannot be invalidated, so a retry
        never re-emits)."""
        source = self._stage_source(chain_root)
        while True:
            try:
                self._run_chain_stage_once(chain_root, source, None,
                                           sort=sort, emit=put)
                return
            except GroupCapacityExceeded:
                continue

    def run_sort_stage(self, node) -> Page:
        """Distributed ORDER BY: every shard sorts its wave output
        in-program (ops/sort.py), the pre-sorted runs stream to the
        coordinator, and a fan-in-bounded k-way merge (ops/merge.py)
        folds runs as they arrive — MergeOperator.java:45's shape with
        the merge overlapped against still-running waves."""
        from presto_tpu.obs import span
        from presto_tpu.ops.merge import merge_sorted_pages

        sort_args = (list(node.sort_exprs), list(node.ascending),
                     node.nulls_first)
        with span("dist_stage:sort", cat="exchange"):
            ex = self._exchange("merge", "dist:sort")
            stream = ex.stream()
            ex.run(stream, lambda st: self._produce_chain_into(
                node.source, st.put, sort=node))
            runs: List[Page] = []
            try:
                for p in stream.drain():
                    runs.append(p)
                    if len(runs) >= self.merge_fanin:
                        runs = [merge_sorted_pages(runs, *sort_args)]
            except BaseException:
                ex.abort()
                raise
            finally:
                # always reap the producer thread: an orphan would keep
                # executing mesh waves into the next query's state
                ex.join()
            if not runs:
                return Page.empty([c.type for c in node.channels], 1)
            return merge_sorted_pages(runs, *sort_args)

    def run_window_stage(self, node) -> Page:
        """Distributed window: the source chain's pages stream off the
        mesh and hash-route on the PARTITION BY keys into one bucket
        per device (the FIXED_HASH exchange, host-side at this tier) —
        routing overlaps the producing waves; then one shard_map'd
        ``ops/window.py`` program evaluates every device's complete
        partitions in parallel."""
        from presto_tpu.exec.spill import make_bucket_fn
        from presto_tpu.obs import span

        n = self.n
        with span("dist_stage:window", cat="exchange"):
            ex = self._exchange("hash", "dist:window")
            stream = ex.stream()
            ex.run(stream, lambda st: self._produce_chain_into(
                node.source, st.put))
            # memoized like the window program below: a fresh jit
            # wrapper per query would recompile the hash-routing kernel
            bucket_key = (node, "window_buckets", n)
            bucket_fn = self._wave_fns.get(bucket_key)
            if bucket_fn is None:
                bucket_fn = make_bucket_fn(
                    list(node.partition_exprs), node.partition_domains, n,
                    jit=True)
                self._wave_fns[bucket_key] = bucket_fn
            buckets: List[List] = [[] for _ in range(n)]
            try:
                for p in stream.drain():
                    self._route_to_buckets(p, bucket_fn(p), buckets)
            except BaseException:
                ex.abort()
                raise
            finally:
                ex.join()
            return self._window_over_buckets(node, buckets)

    @staticmethod
    def _route_to_buckets(page: Page, bids, buckets: List[List]) -> None:
        """Append each bucket's (columns, valids, rows) slice of
        ``page`` — live rows only, hash-routed like the partitioned
        exchange write (PartitionedOutputOperator's host twin)."""
        bids_np = np.asarray(bids)
        mask = np.asarray(page.row_mask)
        datas = [np.asarray(b.data) for b in page.blocks]
        valids = [np.asarray(b.valid) for b in page.blocks]
        for k in range(len(buckets)):
            idx = np.nonzero(mask & (bids_np == k))[0]
            if idx.size:
                buckets[k].append(([d[idx] for d in datas],
                                   [v[idx] for v in valids], idx.size))

    def _window_over_buckets(self, node, buckets: List[List]) -> Page:
        """One shard_map'd window program over the stacked per-device
        bucket pages (each device holds complete partitions)."""
        from presto_tpu.exec.local import bucket_capacity
        from presto_tpu.obs import current_timeline

        src_channels = node.source.channels
        rows = [sum(r for _, _, r in parts) for parts in buckets]
        tl = current_timeline()
        if tl is not None:
            # per-partition row counts: the doctor's skew evidence
            tl.extend("partition_rows", "dist:window", rows)
        cap = bucket_capacity(max(max(rows), 1))
        # empty buckets mirror a non-empty bucket's column shapes/dtypes
        # (multi-dim blocks, e.g. long-decimal limbs, must stack evenly)
        ref = {}
        for parts in buckets:
            for p in parts:
                for i, d in enumerate(p[0]):
                    ref.setdefault(i, (d.shape[1:], d.dtype))
                break
        pages = []
        for parts in buckets:
            blocks = []
            for i, ch in enumerate(src_channels):
                if parts:
                    data = np.concatenate([p[0][i] for p in parts])
                    valid = np.concatenate([p[1][i] for p in parts])
                else:
                    shape, dtype = ref.get(i, ((), ch.type.np_dtype))
                    data = np.zeros((0,) + shape, dtype=dtype)
                    valid = np.zeros(0, np.bool_)
                pad = cap - data.shape[0]
                if pad > 0:
                    data = np.concatenate(
                        [data, np.zeros((pad,) + data.shape[1:], data.dtype)])
                    valid = np.concatenate([valid, np.zeros(pad, np.bool_)])
                blocks.append(Block(data, valid, ch.type, ch.dictionary))
            nlive = sum(r for _, _, r in parts)
            mask = np.zeros(cap, np.bool_)
            mask[:nlive] = True
            pages.append(Page(tuple(blocks), mask))
        stacked = _stack_pages(pages)

        fn_key = (node, "window", cap)
        win_fn = self._wave_fns.get(fn_key)
        if win_fn is None:
            from presto_tpu.ops.window import window_page

            partition_exprs = list(node.partition_exprs)
            order_exprs = list(node.order_exprs)
            ascending = list(node.ascending)
            funcs = list(node.funcs)
            pd = node.partition_domains
            mesh, axis = self.mesh, self.axis

            def per_device_window(page1):
                return _unsqueeze(window_page(
                    _squeeze(page1), partition_exprs, order_exprs,
                    ascending, funcs, partition_domains=pd))

            win_fn = jax.jit(
                shard_map(per_device_window, mesh=mesh, in_specs=P(axis),
                          out_specs=P(axis)))
            self._wave_fns[fn_key] = win_fn

        sharding = NamedSharding(self.mesh, P(self.axis))
        out = win_fn(jax.device_put(stacked, sharding))
        host_pages = _unstack_pages(jax.device_get(out), node.channels)
        return concat_pages_host(host_pages)

    def run_union_stage(self, node) -> Page:
        """UNION ALL as producer stages draining into ONE exchange: on
        a single mesh the legs' waves run back to back (the devices are
        shared), but their pages stream through the exchange so the
        consumer-side dictionary-offset mapping and concat overlap
        production, and the multihost tier runs the same shape with
        truly concurrent legs."""
        from presto_tpu.obs import span
        from presto_tpu.parallel.fragment import (
            is_agg_stage, remap_union_leg_page,
        )
        from presto_tpu.parallel.streams import page_nbytes

        chans = node.channels
        offsets = node.code_offsets
        with span("dist_stage:union", cat="exchange"):
            ex = self._exchange("union", "dist:union")
            stream = ex.stream()

            def produce(st):
                for k, leg in enumerate(node.inputs):
                    put = (lambda kk: lambda p: st.put(
                        (kk, p), nbytes=page_nbytes(p)))(k)
                    if is_agg_stage(leg, self.min_stage_rows):
                        put(self.run_aggregation_stage(leg))
                    else:
                        self._produce_chain_into(leg, put)

            ex.run(stream, produce)
            out: List[Page] = []
            try:
                for k, p in stream.drain():
                    out.append(remap_union_leg_page(p, offsets[k], chans))
            except BaseException:
                ex.abort()
                raise
            finally:
                ex.join()
            if not out:
                return Page.empty([c.type for c in chans], 1)
            return concat_pages_host(out)

    # ------------------------------------------------------------------
    def run_aggregation_stage(self, agg: AggregationNode) -> Page:
        """Distributed scan->chain->partial agg->exchange->final merge
        with group-overflow detection: every shard_map'd stage returns
        its live-group count (and the exchange its bucket fill); the
        host checks them and retries the stage with doubled max_groups,
        exactly as LocalRunner._check_overflow does locally (reference
        rehash: MultiChannelGroupByHash.java:138-145 tryRehash)."""
        if any(a.fn == "evaluate_classifier_predictions" for a in agg.aggs):
            # host-finalized string output: only the local runner
            # formats it after the final merge
            raise DistributedUnsupported(
                "evaluate_classifier_predictions is local-only")
        from presto_tpu.obs import span

        with span("dist_stage:aggregation", cat="exchange"):
            while True:
                try:
                    return self._run_aggregation_stage_once(agg)
                except GroupCapacityExceeded:
                    continue  # _mg_overrides updated; re-execute

    def _overflow(self, agg: AggregationNode, mg: int) -> None:
        if mg >= MAX_AGG_GROUPS:
            raise RuntimeError(
                f"distributed aggregation exceeded {MAX_AGG_GROUPS} groups per device"
            )
        self._mg_overrides[agg] = mg * 2
        self._evict_stage_fns(agg)
        raise GroupCapacityExceeded(mg * 2)

    def _evict_stage_fns(self, agg) -> None:
        """Drop compiled programs superseded by a capacity bump (their
        old (agg, mg, sig) keys are unreachable and pin executables)."""
        self._wave_fns = {k: v for k, v in self._wave_fns.items() if k[0] is not agg}
        self._final_fns = {k: v for k, v in self._final_fns.items() if k[0] is not agg}

    def _verify_checks(
        self, agg, ctx: "_ChainCtx", wave_checks, mg: int, check_groups: bool
    ) -> None:
        """Host-side verification of the wave programs' counters:
        exchange bucket fills, expanding-join totals, and live group
        counts.  Any exceeded capacity updates its config and raises
        GroupCapacityExceeded so the stage re-runs (counts are true
        totals, so one retry per knob suffices)."""
        if not wave_checks:
            return
        peaks: Dict[str, int] = {}
        for cks in wave_checks:
            for name, arr in cks.items():
                v = int(np.asarray(jax.device_get(arr)).max())
                peaks[name] = max(peaks.get(name, 0), v)
        bumped = False
        for name, jnode, kind in ctx.check_meta:
            peak = peaks.get(name, 0)
            cfg = self._join_cfg[jnode]
            if kind == "fill" and peak > cfg["bucket_cap"]:
                cfg["bucket_cap"] = 1 << (peak - 1).bit_length()
                bumped = True
            elif kind == "expand" and peak > cfg["out_cap"]:
                cfg["out_cap"] = 1 << (peak - 1).bit_length()
                bumped = True
        if check_groups and peaks.get("groups", 0) >= mg:
            self._overflow(agg, mg)  # raises
        if bumped:
            self._evict_stage_fns(agg)
            raise GroupCapacityExceeded(0)

    # ------------------------------------------------------------------
    # distributed chain compilation (joins distribute per fragmenter)
    # ------------------------------------------------------------------
    def _dist_chain_leaf(self, node: PlanNode) -> PlanNode:
        """Chain leaf for the distributed tier: descends through ALL
        joins' probe sides (expanding joins run in-program here, unlike
        the local chain)."""
        from presto_tpu.planner.plan import CrossSingleNode, JoinNode

        if isinstance(node, (FilterNode, ProjectNode)):
            return self._dist_chain_leaf(node.source)
        if isinstance(node, AggregationNode) and node.step == "partial":
            return self._dist_chain_leaf(node.source)
        if isinstance(node, CrossSingleNode):
            return self._dist_chain_leaf(node.left)
        if isinstance(node, JoinNode):
            return self._dist_chain_leaf(node.left)
        return node

    def _join_mode(self, jnode) -> str:
        """The fragmenter's broadcast-vs-repartition decision (it also
        owns the downgrade for non-chainable build sides, so EXPLAIN
        rendering and execution always agree)."""
        from presto_tpu.parallel.fragment import decide_join_distribution

        mode, _ = decide_join_distribution(
            jnode, self.broadcast_threshold, catalog=self.catalog,
            forced=self.join_distribution_type,
            allow_colocated=self.allow_colocated,
        )
        return mode

    def _join_cfg_for(self, jnode, cap: int) -> Dict[str, int]:
        """Static capacities for a partitioned/expanding join, grown by
        the check-and-retry protocol."""
        from presto_tpu.exec.local import bucket_capacity

        cfg = self._join_cfg.setdefault(jnode, {})
        n = self.n
        # bucket/out capacities ride the shared pow2/64K shape ladder:
        # raw 2*cap//n guesses are data-dependent (split row counts), so
        # every distinct table size compiled its own exchange + probe
        # programs — canonicalized caps let the registry hit instead
        cfg.setdefault("bucket_cap",
                       bucket_capacity(max(2 * cap // max(n, 1), 1024)))
        cfg.setdefault("out_cap", bucket_capacity(max(2 * cap, 4096)))
        cfg.setdefault("build_bucket_cap", 0)  # lazily set from build cap
        return cfg

    def _build_dist_stage(self, node: PlanNode, ctx: "_ChainCtx"):
        """fn(page, consts) -> (page, checks): the distributed analog of
        LocalRunner._build_stage.  ``checks`` maps check names to scalar
        counts (exchange fills, expand totals) the host verifies."""
        from presto_tpu.ops.filter_project import filter_page, project_page
        from presto_tpu.planner.plan import CrossSingleNode, JoinNode

        if isinstance(node, FilterNode):
            inner = self._build_dist_stage(node.source, ctx)
            pred = node.predicate

            def f_filter(p, c):
                q, ch = inner(p, c)
                return filter_page(q, pred), ch

            return f_filter

        if isinstance(node, ProjectNode):
            inner = self._build_dist_stage(node.source, ctx)
            projections = list(node.projections)

            def f_project(p, c):
                q, ch = inner(p, c)
                return project_page(q, projections), ch

            return f_project

        if isinstance(node, AggregationNode) and node.step == "partial":
            inner = self._build_dist_stage(node.source, ctx)
            group_exprs = list(node.group_exprs)
            aggs = list(node.aggs)
            pmg = self._stage_runner._max_groups(node)
            pkd = node.key_domains

            def f_pagg(p, c):
                q, ch = inner(p, c)
                return (
                    grouped_aggregate(
                        q, group_exprs, aggs, pmg, key_domains=pkd, mode="partial"
                    ),
                    ch,
                )

            return f_pagg

        if isinstance(node, CrossSingleNode):
            from presto_tpu.exec.local import cross_append_single

            inner = self._build_dist_stage(node.left, ctx)
            key = ctx.add_broadcast(node)

            def f_cross(p, c):
                q, ch = inner(p, c)
                return cross_append_single(q, c[key]), ch

            return f_cross

        if isinstance(node, JoinNode):
            from presto_tpu.exec.local import _is_streaming_join

            if node.kind == "full":
                # the unmatched-build tail needs cross-page (and
                # cross-device) match state; falls back to local
                raise DistributedUnsupported("full outer join")
            if node.use_index:
                # point-lookup builds don't wave-scan (IndexLoader role)
                raise DistributedUnsupported("index join")
            inner = self._build_dist_stage(node.left, ctx)
            mode = self._join_mode(node)
            left_keys = list(node.left_keys)
            kd = node.key_domains
            kind = node.kind
            ns = node.null_safe_keys
            na = getattr(node, "null_aware", False)
            build_output = list(range(len(node.right.channels)))
            streaming = _is_streaming_join(node)
            cfg = self._join_cfg_for(node, ctx.cap)
            n, axis = self.n, self.axis

            if mode == "broadcast":
                key = ctx.add_broadcast(node)
                if streaming:

                    def f_bjoin(p, c):
                        q, ch = inner(p, c)
                        return (
                            probe_join(
                                c[key], q, left_keys, key_domains=kd,
                                kind=kind, build_output=build_output,
                                null_safe=ns, null_aware=na,
                            ),
                            ch,
                        )

                    return f_bjoin

                out_cap = cfg["out_cap"]
                expand_check = ctx.add_check(node, "expand")

                def f_bexpand(p, c):
                    q, ch = inner(p, c)
                    out, total = probe_expand(
                        c[key], q, left_keys, out_cap, key_domains=kd,
                        kind=kind, build_output=build_output, null_safe=ns,
                    )
                    return out, {**ch, expand_check: total.astype(jnp.int32)}

                return f_bexpand

            if mode == "colocated":
                # bucket-aligned sides: device d already holds build
                # bucket w*n+d when probing split w*n+d — NO exchange
                # on either side (colocated_join /
                # NodePartitioningManager bucket alignment)
                key = ctx.add_sharded(node)
                if streaming:

                    def f_cjoin(p, c):
                        q, ch = inner(p, c)
                        out = probe_join(
                            _squeeze(c[key]), q, left_keys, key_domains=kd,
                            kind=kind, build_output=build_output, null_safe=ns,
                            null_aware=na,
                        )
                        return out, ch

                    return f_cjoin

                out_cap = cfg["out_cap"]
                expand_check = ctx.add_check(node, "expand")

                def f_cexpand(p, c):
                    q, ch = inner(p, c)
                    out, total = probe_expand(
                        _squeeze(c[key]), q, left_keys, out_cap, key_domains=kd,
                        kind=kind, build_output=build_output, null_safe=ns,
                    )
                    return out, {**ch, expand_check: total.astype(jnp.int32)}

                return f_cexpand

            # partitioned (repartitioned join): exchange probe rows on
            # the join key, probe the local build shard
            key = ctx.add_sharded(node)
            bucket_cap = cfg["bucket_cap"]
            fill_check = ctx.add_check(node, "fill")
            if streaming:

                def f_pjoin(p, c):
                    q, ch = inner(p, c)
                    t = partition_targets(q, left_keys, n, kd)
                    bucketized, fill = partition_for_exchange(q, t, n, bucket_cap)
                    ex = exchange_page(bucketized, axis)
                    out = probe_join(
                        _squeeze(c[key]), ex, left_keys, key_domains=kd,
                        kind=kind, build_output=build_output, null_safe=ns,
                        null_aware=na,
                    )
                    return out, {**ch, fill_check: fill}

                return f_pjoin

            out_cap = cfg["out_cap"]
            expand_check = ctx.add_check(node, "expand")

            def f_pexpand(p, c):
                q, ch = inner(p, c)
                t = partition_targets(q, left_keys, n, kd)
                bucketized, fill = partition_for_exchange(q, t, n, bucket_cap)
                ex = exchange_page(bucketized, axis)
                out, total = probe_expand(
                    _squeeze(c[key]), ex, left_keys, out_cap, key_domains=kd,
                    kind=kind, build_output=build_output, null_safe=ns,
                )
                return out, {
                    **ch, fill_check: fill, expand_check: total.astype(jnp.int32),
                }

            return f_pexpand

        # chain leaf (scan): identity
        return lambda p, c: (p, {})

    def _stage_source(self, chain_root: PlanNode) -> "_StageSource":
        leaf = self._dist_chain_leaf(chain_root)
        if not isinstance(leaf, (TableScanNode, PrecomputedNode)):
            raise DistributedUnsupported(
                f"chain leaf is {type(leaf).__name__}, not a table scan "
                "or materialized stage output")
        return _StageSource(self, leaf)

    def _run_aggregation_stage_once(self, agg: AggregationNode) -> Page:
        n = self.n
        runner = self._stage_runner

        source = self._stage_source(agg.source)
        cap = source.cap

        ctx = _ChainCtx(cap)
        stage = self._build_dist_stage(agg.source, ctx)

        # broadcast builds replicate to every device (BroadcastOutputBuffer
        # semantics); partitioned builds shard by join-key hash
        consts_rep = {
            key: runner._materialize_build(j) for key, j in ctx.broadcast.items()
        }
        consts_shard = {
            key: (self._materialize_build_colocated(j)
                  if self._join_mode(j) == "colocated"
                  else self._materialize_build_sharded(j))
            for key, j in ctx.sharded.items()
        }

        mg = self._mg_overrides.get(agg) or runner._max_groups(agg)
        # exact capacity (key-domain product fits mg) cannot truncate
        check = bool(agg.group_exprs) and not runner._exact_capacity(agg, mg)
        group_exprs = list(agg.group_exprs)
        aggs = list(agg.aggs)
        nk = len(group_exprs)
        kd = agg.key_domains
        partial_channels = AggregationNode(
            source=agg.source, group_exprs=group_exprs, group_names=agg.group_names,
            aggs=aggs, agg_names=agg.agg_names, step="partial",
        ).channels

        mesh, axis = self.mesh, self.axis

        def per_device_wave(page1, acc1, consts_r, consts_s):
            page = _squeeze(page1)
            acc = _squeeze(acc1)
            p, checks = stage(page, {**consts_r, **consts_s})
            part, c1 = grouped_aggregate(
                p, group_exprs, aggs, mg, key_domains=kd, mode="partial",
                return_count=True,
            )
            cand = concat_pages_device([acc, part])
            acc2, c2 = merge_aggregate(
                cand, nk, aggs, mg, key_domains=kd, mode="partial",
                return_count=True,
            )
            checks = dict(checks)
            checks["groups"] = jnp.maximum(c1, c2)
            return _unsqueeze(acc2), {k: v[None] for k, v in checks.items()}

        fn_key = (agg, mg, ctx.sig(self._join_cfg))
        wave_fn = self._wave_fns.get(fn_key)
        if wave_fn is None:
            check_specs = {name: P(axis) for name in ctx.checks}
            check_specs["groups"] = P(axis)
            wave_fn = jax.jit(
                shard_map(
                    per_device_wave, mesh=mesh,
                    in_specs=(
                        P(axis), P(axis), P(),
                        {k: P(axis) for k in consts_shard},
                    ),
                    out_specs=(P(axis), check_specs),
                )
            )
            self._wave_fns[fn_key] = wave_fn

        # ---- split scheduling: device d takes split w*n + d ----------
        sharding = NamedSharding(mesh, P(axis))

        acc = self._initial_acc(partial_channels, mg, n, sharding)
        wave_checks = []
        for stacked in self._wave_iter(source, sharding):
            acc, cks = wave_fn(stacked, acc, consts_rep, consts_shard)
            wave_checks.append(cks)
        self._verify_checks(agg, ctx, wave_checks, mg, check)

        # ---- exchange + final merge ----------------------------------
        if nk == 0:
            host_pages = _unstack_pages(jax.device_get(acc), partial_channels)
            cand = concat_pages_host(host_pages)
            return merge_aggregate(cand, 0, aggs, 1, key_domains=kd, mode="single")

        key_refs = [
            ColumnRef(type=partial_channels[i].type, index=i) for i in range(nk)
        ]

        def per_device_final(acc1):
            acc_l = _squeeze(acc1)
            target = partition_targets(acc_l, key_refs, n, kd)
            bucketized, fill = partition_for_exchange(acc_l, target, n, bucket_cap=mg)
            ex = exchange_page(bucketized, axis)
            merged, cnt = merge_aggregate(
                ex, nk, aggs, mg, key_domains=kd, mode="single", return_count=True
            )
            return _unsqueeze(merged), jnp.maximum(fill, cnt)[None]

        final_fn = self._final_fns.get((agg, mg))
        if final_fn is None:
            final_fn = jax.jit(
                shard_map(
                    per_device_final, mesh=mesh, in_specs=P(axis),
                    out_specs=(P(axis), P(axis)),
                )
            )
            self._final_fns[(agg, mg)] = final_fn
        out, fills = final_fn(acc)
        if check and int(np.asarray(jax.device_get(fills)).max()) >= mg:
            # a bucket overfilled in the exchange, or the post-exchange
            # merge saw >= mg distinct groups on some device
            self._overflow(agg, mg)
        out_channels = agg.channels
        host_pages = _unstack_pages(jax.device_get(out), out_channels)
        return concat_pages_host(host_pages)

    # ------------------------------------------------------------------
    def _stacked_wave(self, conn, leaf: TableScanNode, col_idx, w: int, cap: int) -> Page:
        """Host-assemble wave ``w``'s one-split-per-device stacked page
        (device d takes split w*n + d; missing splits pad empty)."""
        n = self.n
        table = leaf.handle.table
        n_splits = leaf.handle.num_splits
        pages = []
        for d in range(n):
            s = w * n + d
            if s < n_splits:
                pg = conn.page_for_split(table, s, capacity=cap)
                pg = Page(tuple(pg.blocks[i] for i in col_idx), pg.row_mask)
            else:
                pg = Page.empty([leaf.handle.columns[i].type for i in col_idx], cap)
                pg = Page(
                    tuple(
                        Block(b.data, b.valid, b.type, leaf.handle.columns[i].dictionary)
                        for b, i in zip(pg.blocks, col_idx)
                    ),
                    pg.row_mask,
                )
            pages.append(pg)
        return _stack_pages(pages)

    # ------------------------------------------------------------------
    # sharded (repartitioned) join builds
    # ------------------------------------------------------------------
    def _materialize_build_colocated(self, jnode) -> JoinBuild:
        """Build side of a colocated join: device d wave-scans its OWN
        build splits (the same w*n+d placement the probe leaf uses, so
        bucket b always lands where probe bucket b executes) — no
        exchange at all.  Reference: colocated joins over
        ConnectorNodePartitioningProvider bucketed tables."""
        key = (jnode, "colocated")
        cached = self._sharded_builds.get(key)
        if cached is not None:
            return cached
        n, mesh, axis = self.n, self.mesh, self.axis
        runner = self._stage_runner
        leaf_r = runner._chain_leaf(jnode.right)
        conn_r = self.catalog.connector(leaf_r.handle.connector_name)
        cap_r = self._split_capacity(conn_r, leaf_r.handle.table)
        joins_r: List[PlanNode] = []
        stage_r = runner._build_stage(jnode.right, joins_r)
        consts_r = {
            f"build_{i}": runner._materialize_build(j) for i, j in enumerate(joins_r)
        }
        right_keys = list(jnode.right_keys)
        kd = jnode.key_domains

        def bw(page1, crep):
            return _unsqueeze(stage_r(_squeeze(page1), crep))

        bw_fn = jax.jit(
            shard_map(bw, mesh=mesh, in_specs=(P(axis), P()),
                          out_specs=P(axis))
        )
        sharding = NamedSharding(mesh, P(axis))
        col_idx = list(leaf_r.columns)
        received: List[Page] = []
        waves = math.ceil(leaf_r.handle.num_splits / n)
        for w in range(waves):
            stacked = jax.device_put(
                self._stacked_wave(conn_r, leaf_r, col_idx, w, cap_r), sharding
            )
            received.append(bw_fn(stacked, consts_r))

        if len(received) == 1:
            big = received[0]
        else:
            b0 = received[0]
            big = Page(
                tuple(
                    Block(
                        jnp.concatenate([r.blocks[i].data for r in received], axis=1),
                        jnp.concatenate([r.blocks[i].valid for r in received], axis=1),
                        b.type,
                        b.dictionary,
                    )
                    for i, b in enumerate(b0.blocks)
                ),
                jnp.concatenate([r.row_mask for r in received], axis=1),
            )
        ns = getattr(jnode, "null_safe_keys", False)
        bj_fn = jax.jit(
            shard_map(
                lambda pg1: _unsqueeze(
                    build_join(_squeeze(pg1), right_keys, key_domains=kd,
                               null_safe=ns)
                ),
                mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            )
        )
        build = bj_fn(big)
        self._sharded_builds[key] = build
        return build

    def _materialize_build_sharded(self, jnode) -> JoinBuild:
        """Build side of a repartitioned join: wave-scan the build
        chain over the mesh, hash-exchange rows on the join key, then
        build one sorted JoinBuild per device over its key partition.
        Device p ends up holding exactly the build rows with
        hash(key) % n == p — the PartitionedLookupSourceFactory analog
        with the shuffle collapsed into ``all_to_all``."""
        runner = self._stage_runner
        leaf_r = runner._chain_leaf(jnode.right)
        conn_r = self.catalog.connector(leaf_r.handle.connector_name)
        cap_r = self._split_capacity(conn_r, leaf_r.handle.table)
        cfg = self._join_cfg.setdefault(jnode, {})
        if not cfg.get("build_bucket_cap"):
            from presto_tpu.exec.local import bucket_capacity

            cfg["build_bucket_cap"] = bucket_capacity(
                max(2 * cap_r // max(self.n, 1), 1024))
        while True:
            key = (jnode, cfg["build_bucket_cap"])
            cached = self._sharded_builds.get(key)
            if cached is not None:
                return cached
            try:
                build = self._materialize_build_sharded_once(
                    jnode, leaf_r, conn_r, cap_r, cfg["build_bucket_cap"]
                )
                self._sharded_builds[key] = build
                return build
            except _BuildOverflow as e:
                # evict the undersized build (it pins device memory and
                # its key is unreachable once the cap grows)
                self._sharded_builds.pop(key, None)
                cfg["build_bucket_cap"] = e.needed

    def _materialize_build_sharded_once(
        self, jnode, leaf_r: TableScanNode, conn_r, cap_r: int, bcap: int
    ) -> JoinBuild:
        n, mesh, axis = self.n, self.mesh, self.axis
        runner = self._stage_runner
        joins_r: List[PlanNode] = []
        stage_r = runner._build_stage(jnode.right, joins_r)
        consts_r = {
            f"build_{i}": runner._materialize_build(j) for i, j in enumerate(joins_r)
        }
        right_keys = list(jnode.right_keys)
        kd = jnode.key_domains

        def bw(page1, crep):
            page = _squeeze(page1)
            q = stage_r(page, crep)
            t = partition_targets(q, right_keys, n, kd)
            bucketized, fill = partition_for_exchange(q, t, n, bcap)
            ex = exchange_page(bucketized, axis)
            return _unsqueeze(ex), fill[None]

        bw_fn = jax.jit(
            shard_map(
                bw, mesh=mesh, in_specs=(P(axis), P()),
                out_specs=(P(axis), P(axis)),
            )
        )
        sharding = NamedSharding(mesh, P(axis))
        col_idx = list(leaf_r.columns)
        received: List[Page] = []
        fills = []
        waves = math.ceil(leaf_r.handle.num_splits / n)
        for w in range(waves):
            stacked = jax.device_put(
                self._stacked_wave(conn_r, leaf_r, col_idx, w, cap_r), sharding
            )
            rec, fill = bw_fn(stacked, consts_r)
            received.append(rec)
            fills.append(fill)
        from presto_tpu.obs import current_timeline

        fill_rows = [int(v) for f in fills
                     for v in np.asarray(jax.device_get(f)).reshape(-1)]
        peak = max(fill_rows)
        tl = current_timeline()
        if tl is not None:
            # per-device build fills after the repartitioning exchange —
            # the only host-visible per-partition counts of the sharded
            # join (the probe exchange lives inside the jitted program)
            tl.extend("partition_rows", "dist:join-build", fill_rows)
        if peak > bcap:
            raise _BuildOverflow(1 << (peak - 1).bit_length())

        if len(received) == 1:
            big = received[0]
        else:  # concat per device along the row axis (axis 0 is devices)
            b0 = received[0]
            big = Page(
                tuple(
                    Block(
                        jnp.concatenate([r.blocks[i].data for r in received], axis=1),
                        jnp.concatenate([r.blocks[i].valid for r in received], axis=1),
                        b.type,
                        b.dictionary,
                    )
                    for i, b in enumerate(b0.blocks)
                ),
                jnp.concatenate([r.row_mask for r in received], axis=1),
            )

        bj_fn = jax.jit(
            shard_map(
                lambda pg1: _unsqueeze(
                    build_join(_squeeze(pg1), right_keys, key_domains=kd)
                ),
                mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            )
        )
        return bj_fn(big)

    # ------------------------------------------------------------------
    def _split_capacity(self, conn, table: str) -> int:
        if hasattr(conn, "max_split_rows"):
            return int(conn.max_split_rows(table))
        # fall back: probe the first split's size, round up
        pg = conn.page_for_split(table, 0)
        return 1 << (max(pg.capacity - 1, 1)).bit_length()

    def _initial_acc(self, channels, mg: int, n: int, sharding) -> Page:
        blocks = []
        for ch in channels:
            shape = (n, mg)
            if ch.type.is_long_decimal:
                # widened decimal sum states ride the exchange as limb
                # matrices; all-zero limbs are the canonical combine
                # identity (ops/decimal128 layout)
                from presto_tpu.ops import decimal128 as d128

                shape += (d128.WIDE_LIMBS
                          if (ch.type.precision or 0) > 36 else 2,)
            blocks.append(
                Block(
                    jnp.zeros(shape, dtype=ch.type.np_dtype),
                    jnp.zeros((n, mg), dtype=jnp.bool_),
                    ch.type,
                    ch.dictionary,
                )
            )
        page = Page(tuple(blocks), jnp.zeros((n, mg), dtype=jnp.bool_))
        return jax.device_put(page, sharding)


def _stack_pages(pages: Sequence[Page]) -> Page:
    blocks = []
    for i in range(pages[0].num_blocks):
        b0 = pages[0].blocks[i]
        data = np.stack([np.asarray(p.blocks[i].data) for p in pages])
        valid = np.stack([np.asarray(p.blocks[i].valid) for p in pages])
        blocks.append(Block(data, valid, b0.type, b0.dictionary))
    mask = np.stack([np.asarray(p.row_mask) for p in pages])
    return Page(tuple(blocks), mask)


def _unstack_pages(stacked: Page, channels) -> List[Page]:
    n = np.asarray(stacked.row_mask).shape[0]
    out = []
    for d in range(n):
        blocks = tuple(
            Block(
                jnp.asarray(np.asarray(b.data)[d]),
                jnp.asarray(np.asarray(b.valid)[d]),
                ch.type,
                ch.dictionary,
            )
            for b, ch in zip(stacked.blocks, channels)
        )
        out.append(Page(blocks, jnp.asarray(np.asarray(stacked.row_mask)[d])))
    return out
