"""Distributed query execution over a jax device mesh.

Reference analog: the distributed tier — ``PlanFragmenter.java:84``
(stage boundaries at exchanges), ``SqlStageExecution``/``TaskExecutor``
(per-node work), and the shuffle of §2.3.  TPU redesign: a stage is ONE
SPMD program ``shard_map``-ed over the mesh; "tasks" are the per-device
shards; the shuffle is ``all_to_all`` over ICI (see exchange.py); the
scheduler is the wave loop feeding each device one split per wave
(SourcePartitionedScheduler's role).

Supported distributed shape this round (BASELINE configs Q1/Q3/Q6/Q14):
    [Output/Project/Sort/TopN/Limit/Filter]*
      -> Aggregation(single)
        -> streaming chain (scan -> filter/project -> replicated-build
           joins -> ...)
Post-aggregation nodes run locally on the gathered (small) result via
PrecomputedNode splicing.  Anything else falls back to LocalRunner.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import (
    MAX_AGG_GROUPS,
    GroupCapacityExceeded,
    LocalRunner,
    MaterializedResult,
    concat_pages_device,
)
from presto_tpu.expr.ir import ColumnRef
from presto_tpu.ops.aggregate import grouped_aggregate, merge_aggregate
from presto_tpu.page import Block, Page, concat_pages_host
from presto_tpu.parallel.exchange import (
    exchange_page,
    partition_for_exchange,
    partition_targets,
)
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
)


class DistributedUnsupported(Exception):
    pass


def make_mesh(n_devices: Optional[int] = None, axis: str = "d") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def _squeeze(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree.map(lambda x: x[None], tree)


class DistributedRunner:
    """Runs plans over a mesh; falls back to LocalRunner when the plan
    shape isn't distributable yet."""

    def __init__(self, catalog: Catalog, mesh: Optional[Mesh] = None, axis: str = "d"):
        self.catalog = catalog
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.local = LocalRunner(catalog)
        # persistent un-jitted runner for stage building/builds: its
        # _agg_overrides must survive GroupCapacityExceeded retries
        # (a build-side aggregation overflow records its doubled
        # capacity here; a throwaway runner would loop forever)
        self._stage_runner = LocalRunner(catalog, jit=False)
        self._wave_fns: Dict[Tuple[PlanNode, int], object] = {}
        self._final_fns: Dict[Tuple[PlanNode, int], object] = {}
        self._mg_overrides: Dict[PlanNode, int] = {}

    @property
    def n(self) -> int:
        return self.mesh.devices.size

    # ------------------------------------------------------------------
    def run(self, plan: PlanNode) -> MaterializedResult:
        try:
            return self._run_distributed(plan)
        except DistributedUnsupported:
            return self.local.run(plan)

    def _run_distributed(self, plan: PlanNode) -> MaterializedResult:
        # peel post-aggregation nodes
        path: List[PlanNode] = []
        node = plan
        while not isinstance(node, AggregationNode):
            if isinstance(node, (OutputNode, ProjectNode, FilterNode, SortNode, TopNNode, LimitNode)):
                path.append(node)
                node = node.source
            else:
                raise DistributedUnsupported(type(node).__name__)
        agg = node
        if agg.step != "single":
            raise DistributedUnsupported("non-single aggregation")

        merged = self.run_aggregation_stage(agg)

        pre = PrecomputedNode(page=merged, channel_list=agg.channels)
        parent = path[-1] if path else None
        if parent is None:
            out = self.local.run(pre)  # plan was the bare aggregation
            out.names, out.types = plan.output_names, plan.output_types
            return out
        original = parent.source
        try:
            parent.source = pre
            return self.local.run(plan)
        finally:
            parent.source = original

    # ------------------------------------------------------------------
    def run_aggregation_stage(self, agg: AggregationNode) -> Page:
        """Distributed scan->chain->partial agg->exchange->final merge
        with group-overflow detection: every shard_map'd stage returns
        its live-group count (and the exchange its bucket fill); the
        host checks them and retries the stage with doubled max_groups,
        exactly as LocalRunner._check_overflow does locally (reference
        rehash: MultiChannelGroupByHash.java:138-145 tryRehash)."""
        while True:
            try:
                return self._run_aggregation_stage_once(agg)
            except GroupCapacityExceeded:
                continue  # _mg_overrides updated; re-execute

    def _overflow(self, agg: AggregationNode, mg: int) -> None:
        if mg >= MAX_AGG_GROUPS:
            raise RuntimeError(
                f"distributed aggregation exceeded {MAX_AGG_GROUPS} groups per device"
            )
        self._mg_overrides[agg] = mg * 2
        self._wave_fns.pop((agg, mg), None)
        self._final_fns.pop((agg, mg), None)
        raise GroupCapacityExceeded(mg * 2)

    def _run_aggregation_stage_once(self, agg: AggregationNode) -> Page:
        n = self.n
        runner = self._stage_runner
        joins: List[PlanNode] = []
        stage = runner._build_stage(agg.source, joins)
        leaf = runner._chain_leaf(agg.source)
        if not isinstance(leaf, TableScanNode):
            raise DistributedUnsupported("chain leaf is not a table scan")
        for j in joins:
            if hasattr(j, "kind") and not (
                j.kind in ("semi", "anti") or getattr(j, "unique_build", False)
            ):
                raise DistributedUnsupported("expanding join in distributed chain")

        # replicated join builds (broadcast-join analog: every device
        # holds the full build, BroadcastOutputBuffer.java's semantics)
        consts = {
            f"build_{i}": runner._materialize_build(j) for i, j in enumerate(joins)
        }

        mg = self._mg_overrides.get(agg) or runner._max_groups(agg)
        # exact capacity (key-domain product fits mg) cannot truncate
        check = bool(agg.group_exprs) and not runner._exact_capacity(agg, mg)
        group_exprs = list(agg.group_exprs)
        aggs = list(agg.aggs)
        nk = len(group_exprs)
        kd = agg.key_domains
        partial_channels = AggregationNode(
            source=agg.source, group_exprs=group_exprs, group_names=agg.group_names,
            aggs=aggs, agg_names=agg.agg_names, step="partial",
        ).channels

        mesh, axis = self.mesh, self.axis

        def per_device_wave(page1, acc1, consts_r):
            page = _squeeze(page1)
            acc = _squeeze(acc1)
            p = stage(page, consts_r)
            part, c1 = grouped_aggregate(
                p, group_exprs, aggs, mg, key_domains=kd, mode="partial",
                return_count=True,
            )
            cand = concat_pages_device([acc, part])
            acc2, c2 = merge_aggregate(
                cand, nk, aggs, mg, key_domains=kd, mode="partial",
                return_count=True,
            )
            return _unsqueeze(acc2), jnp.maximum(c1, c2)[None]

        wave_fn = self._wave_fns.get((agg, mg))
        if wave_fn is None:
            wave_fn = jax.jit(
                jax.shard_map(
                    per_device_wave, mesh=mesh,
                    in_specs=(P(axis), P(axis), P()),
                    out_specs=(P(axis), P(axis)),
                )
            )
            self._wave_fns[(agg, mg)] = wave_fn

        # ---- split scheduling: device d takes split w*n + d ----------
        conn = self.catalog.connector(leaf.handle.connector_name)
        table = leaf.handle.table
        n_splits = leaf.handle.num_splits
        full = [ch.name for ch in leaf.handle.columns]
        col_idx = list(leaf.columns)
        cap = self._split_capacity(conn, table)
        sharding = NamedSharding(mesh, P(axis))

        acc = self._initial_acc(partial_channels, mg, n, sharding)
        waves = math.ceil(n_splits / n)
        wave_counts = []
        for w in range(waves):
            pages = []
            for d in range(n):
                s = w * n + d
                if s < n_splits:
                    pg = conn.page_for_split(table, s, capacity=cap)
                    pg = Page(tuple(pg.blocks[i] for i in col_idx), pg.row_mask)
                else:
                    pg = Page.empty([leaf.handle.columns[i].type for i in col_idx], cap)
                    pg = Page(
                        tuple(
                            Block(b.data, b.valid, b.type, leaf.handle.columns[i].dictionary)
                            for b, i in zip(pg.blocks, col_idx)
                        ),
                        pg.row_mask,
                    )
                pages.append(pg)
            stacked = jax.device_put(_stack_pages(pages), sharding)
            acc, cnts = wave_fn(stacked, acc, consts)
            wave_counts.append(cnts)
        if check and wave_counts:
            peak = max(int(np.asarray(jax.device_get(c)).max()) for c in wave_counts)
            if peak >= mg:
                self._overflow(agg, mg)

        # ---- exchange + final merge ----------------------------------
        if nk == 0:
            host_pages = _unstack_pages(jax.device_get(acc), partial_channels)
            cand = concat_pages_host(host_pages)
            return merge_aggregate(cand, 0, aggs, 1, key_domains=kd, mode="single")

        key_refs = [
            ColumnRef(type=partial_channels[i].type, index=i) for i in range(nk)
        ]

        def per_device_final(acc1):
            acc_l = _squeeze(acc1)
            target = partition_targets(acc_l, key_refs, n, kd)
            bucketized, fill = partition_for_exchange(acc_l, target, n, bucket_cap=mg)
            ex = exchange_page(bucketized, axis)
            merged, cnt = merge_aggregate(
                ex, nk, aggs, mg, key_domains=kd, mode="single", return_count=True
            )
            return _unsqueeze(merged), jnp.maximum(fill, cnt)[None]

        final_fn = self._final_fns.get((agg, mg))
        if final_fn is None:
            final_fn = jax.jit(
                jax.shard_map(
                    per_device_final, mesh=mesh, in_specs=P(axis),
                    out_specs=(P(axis), P(axis)),
                )
            )
            self._final_fns[(agg, mg)] = final_fn
        out, fills = final_fn(acc)
        if check and int(np.asarray(jax.device_get(fills)).max()) >= mg:
            # a bucket overfilled in the exchange, or the post-exchange
            # merge saw >= mg distinct groups on some device
            self._overflow(agg, mg)
        out_channels = agg.channels
        host_pages = _unstack_pages(jax.device_get(out), out_channels)
        return concat_pages_host(host_pages)

    # ------------------------------------------------------------------
    def _split_capacity(self, conn, table: str) -> int:
        if hasattr(conn, "max_split_rows"):
            return int(conn.max_split_rows(table))
        # fall back: probe the first split's size, round up
        pg = conn.page_for_split(table, 0)
        return 1 << (max(pg.capacity - 1, 1)).bit_length()

    def _initial_acc(self, channels, mg: int, n: int, sharding) -> Page:
        blocks = []
        for ch in channels:
            blocks.append(
                Block(
                    jnp.zeros((n, mg), dtype=ch.type.np_dtype),
                    jnp.zeros((n, mg), dtype=jnp.bool_),
                    ch.type,
                    ch.dictionary,
                )
            )
        page = Page(tuple(blocks), jnp.zeros((n, mg), dtype=jnp.bool_))
        return jax.device_put(page, sharding)


def _stack_pages(pages: Sequence[Page]) -> Page:
    blocks = []
    for i in range(pages[0].num_blocks):
        b0 = pages[0].blocks[i]
        data = np.stack([np.asarray(p.blocks[i].data) for p in pages])
        valid = np.stack([np.asarray(p.blocks[i].valid) for p in pages])
        blocks.append(Block(data, valid, b0.type, b0.dictionary))
    mask = np.stack([np.asarray(p.row_mask) for p in pages])
    return Page(tuple(blocks), mask)


def _unstack_pages(stacked: Page, channels) -> List[Page]:
    n = np.asarray(stacked.row_mask).shape[0]
    out = []
    for d in range(n):
        blocks = tuple(
            Block(
                jnp.asarray(np.asarray(b.data)[d]),
                jnp.asarray(np.asarray(b.valid)[d]),
                ch.type,
                ch.dictionary,
            )
            for b, ch in zip(stacked.blocks, channels)
        )
        out.append(Page(blocks, jnp.asarray(np.asarray(stacked.row_mask)[d])))
    return out
