from presto_tpu.parallel.exchange import (  # noqa: F401
    exchange_page,
    partition_for_exchange,
)
