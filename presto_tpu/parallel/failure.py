"""Coordinator-side worker failure detector.

Reference analog: ``failureDetector/HeartbeatFailureDetector.java:77``
— the coordinator heartbeats every known worker in the background,
keeps a per-worker decayed failure stat, and exposes the set of nodes
currently considered failed so the scheduler excludes them from split
placement; recovered nodes re-admit after sustained success.

Here each worker carries an explicit four-state machine::

    ALIVE ──failures──▶ SUSPECT ──more failures──▶ DEAD
      ▲                    │succ                     │ sustained succ
      └────────────────────┘          RECOVERED ◀────┘
      ▲─────────succ────────────────────│

* ALIVE / SUSPECT / RECOVERED workers are schedulable; DEAD workers
  are excluded from fragment assignment (the circuit breaker) and
  probed only on an exponential-backoff schedule so a dead host costs
  one cheap connect attempt per backoff window, not one per stage.
* DEAD → RECOVERED needs ``recover_after`` consecutive successful
  probes (the reference's sustained-recovery gate); the first
  successful *scheduled* use moves RECOVERED → ALIVE.

Transitions log ONCE per edge (not per poll) and feed the
``worker.state_transitions`` / ``worker.transitions_to_*`` counters
and the ``worker.state_*`` census gauges; ``snapshot()`` feeds the
``system_runtime_workers`` table and the web UI worker list.

Everything time-dependent takes an injectable ``clock`` (and the
jitter a seeded rng), so the state machine unit-tests run on a fake
clock with zero wallclock sleeps.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from presto_tpu.analysis.protocols import RECORDER
from presto_tpu.sync import named_lock

_log = logging.getLogger("presto_tpu.failure")

ALIVE, SUSPECT, DEAD, RECOVERED = "ALIVE", "SUSPECT", "DEAD", "RECOVERED"

#: states the scheduler may assign fragments to
SCHEDULABLE_STATES = (ALIVE, SUSPECT, RECOVERED)

#: weak reference to the detector feeding the process-wide
#: ``worker.state_*`` census gauges (last constructed wins; weak so a
#: retired detector is collectable instead of pinned by the registry)
_census_source: Optional["weakref.ref"] = None


class WorkerHealth:
    """One worker's detector state (mutated only under the detector's
    lock)."""

    __slots__ = ("uri", "state", "consecutive_failures",
                 "consecutive_successes", "last_heartbeat", "last_error",
                 "next_probe", "transitions")

    def __init__(self, uri: str):
        self.uri = uri
        self.state = ALIVE
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        # clock() of the last SUCCESSFUL heartbeat (None before any)
        self.last_heartbeat: Optional[float] = None
        self.last_error: Optional[str] = None
        # clock() before which the prober skips this worker (backoff)
        self.next_probe = 0.0
        self.transitions = 0

    def row(self, now: float) -> dict:
        """system_runtime_workers row (NULL-safe: last_heartbeat_ms is
        None until the first successful heartbeat)."""
        age_ms = (None if self.last_heartbeat is None
                  else round((now - self.last_heartbeat) * 1e3, 3))
        return {
            "node_id": self.uri,
            "uri": self.uri,
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "last_heartbeat_ms": age_ms,
            "last_error": self.last_error,
        }


def _default_probe(uri: str, timeout: float) -> None:
    """GET /v1/info (the heartbeat endpoint); raises on failure."""
    from presto_tpu.net import request_json

    request_json(f"{uri.rstrip('/')}/v1/info", timeout=timeout,
                 site="worker.ping_errors")


class FailureDetector:
    """Heartbeats a set of worker URIs and answers "may I schedule
    onto this worker?".  Passive use (record_success/record_failure
    from real fragment traffic) and active probing (probe_once / the
    background start() thread) feed the same state machine."""

    def __init__(
        self,
        uris=(),
        probe: Optional[Callable[[str, float], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        interval: float = 1.0,
        probe_timeout: float = 2.0,
        backoff_base: float = 0.5,
        backoff_max: float = 15.0,
        suspect_after: int = 1,
        dead_after: int = 3,
        recover_after: int = 2,
        jitter: float = 0.1,
        seed: int = 0,
    ):
        self._probe = probe or _default_probe
        self._clock = clock
        self.interval = interval
        self.probe_timeout = probe_timeout
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.suspect_after = max(int(suspect_after), 1)
        self.dead_after = max(int(dead_after), self.suspect_after)
        self.recover_after = max(int(recover_after), 1)
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._lock = named_lock("failure.FailureDetector._lock")
        self._workers: Dict[str, WorkerHealth] = {}
        self._listeners: List[Callable[[str, str, str, Optional[str]], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # an EMPTY detector (idle CoordinatorServer / bare rigs) must
        # not steal the census gauges from a live one — watch() wires
        # them on the first watched worker
        self._gauges_wired = False
        for u in uris:
            self.watch(u)

    # -- registration -------------------------------------------------------
    def watch(self, uri: str) -> WorkerHealth:
        uri = uri.rstrip("/")
        with self._lock:
            h = self._workers.get(uri)
            if h is None:
                h = self._workers[uri] = WorkerHealth(uri)
                if RECORDER.enabled:
                    RECORDER.record(
                        "detector", self._pkey(uri), "watch",
                        suspect_after=self.suspect_after,
                        dead_after=self.dead_after,
                        recover_after=self.recover_after)
        if not self._gauges_wired:
            self._wire_gauges()
        return h

    def _pkey(self, uri: str) -> str:
        # per-detector-instance key: two rigs watching the same uri in
        # one process must not interleave on one spec-automaton run
        return f"det:{id(self):x}:{uri}"

    def add_transition_listener(
            self, fn: Callable[[str, str, str, Optional[str]], None]) -> None:
        """``fn(uri, old_state, new_state, reason)`` — called outside
        the detector lock on every edge (event-log / metrics wiring)."""
        self._listeners.append(fn)

    def _wire_gauges(self) -> None:
        """Point the process-wide ``worker.state_*`` census gauges at
        this detector.  Last constructed wins (processes that run
        several detectors should share one — the testing rig and
        CoordinatorServer's ``detector=`` parameter exist for that);
        the gauges hold only a WEAK reference, so a retired detector
        is collectable and the census reads 0, never stale counts."""
        global _census_source
        self._gauges_wired = True
        _census_source = weakref.ref(self)
        from presto_tpu.obs import METRICS

        def census(state: str) -> Callable[[], float]:
            def count() -> float:
                det = _census_source() if _census_source is not None \
                    else None
                if det is None:
                    return 0.0
                with det._lock:
                    return float(sum(1 for h in det._workers.values()
                                     if h.state == state))
            return count

        METRICS.gauge("worker.state_alive").set_fn(census(ALIVE))
        METRICS.gauge("worker.state_suspect").set_fn(census(SUSPECT))
        METRICS.gauge("worker.state_dead").set_fn(census(DEAD))
        METRICS.gauge("worker.state_recovered").set_fn(census(RECOVERED))

    # -- state machine ------------------------------------------------------
    def _transition(self, h: WorkerHealth, new_state: str,
                    reason: Optional[str]) -> Optional[tuple]:
        if h.state == new_state:
            return None
        old = h.state
        h.state = new_state
        h.transitions += 1
        if RECORDER.enabled:
            RECORDER.record("detector", self._pkey(h.uri), "transition",
                            old=old, new=new_state)
        return (h.uri, old, new_state, reason)

    def _announce(self, edge: Optional[tuple]) -> None:
        """Log + count + notify ONE transition (outside the lock)."""
        if edge is None:
            return
        uri, old, new, reason = edge
        from presto_tpu.obs import METRICS

        METRICS.counter("worker.state_transitions").inc()
        METRICS.counter(
            f"worker.transitions_to_{new.lower()}").inc()  # metrics: allow
        level = logging.INFO if new in (ALIVE, RECOVERED) else logging.WARNING
        _log.log(level, "worker %s: %s -> %s%s", uri, old, new,
                 f" ({reason})" if reason else "")
        for fn in self._listeners:
            try:
                fn(uri, old, new, reason)
            except Exception:
                pass  # telemetry must never fail the detector

    def record_success(self, uri: str) -> None:
        h = self.watch(uri)
        now = self._clock()
        with self._lock:
            h.consecutive_failures = 0
            h.consecutive_successes += 1
            h.last_heartbeat = now
            h.last_error = None
            h.next_probe = now + self.interval
            if RECORDER.enabled:
                # inside the lock: the recorded order IS the
                # state-machine order the spec automaton assumes
                RECORDER.record("detector", self._pkey(h.uri), "probe_ok")
            if h.state == DEAD:
                edge = (self._transition(h, RECOVERED, "probe succeeded")
                        if h.consecutive_successes >= self.recover_after
                        else None)
            elif h.state in (SUSPECT, RECOVERED):
                edge = self._transition(h, ALIVE, "heartbeat restored")
            else:
                edge = None
        self._announce(edge)

    def record_failure(self, uri: str, reason: str = "") -> None:
        h = self.watch(uri)
        now = self._clock()
        with self._lock:
            h.consecutive_successes = 0
            h.consecutive_failures += 1
            h.last_error = reason or None
            backoff = min(
                self.backoff_base * (2 ** (h.consecutive_failures - 1)),
                self.backoff_max)
            h.next_probe = now + backoff * (
                1.0 + self.jitter * self._rng.random())
            if RECORDER.enabled:
                RECORDER.record("detector", self._pkey(h.uri), "probe_fail")
            edges = []
            if h.state in (ALIVE, RECOVERED) \
                    and h.consecutive_failures >= self.suspect_after:
                edges.append(self._transition(h, SUSPECT, reason))
            if h.state == SUSPECT \
                    and h.consecutive_failures >= self.dead_after:
                edges.append(self._transition(h, DEAD, reason))
        for edge in edges:
            self._announce(edge)

    # -- queries ------------------------------------------------------------
    def health(self, uri: str) -> WorkerHealth:
        return self.watch(uri)

    def state(self, uri: str) -> str:
        return self.watch(uri).state

    def is_schedulable(self, uri: str) -> bool:
        """The circuit breaker: DEAD workers are excluded from
        fragment assignment until sustained probes re-admit them."""
        return self.watch(uri).state in SCHEDULABLE_STATES

    def note_assignment(self, uri: str) -> None:
        """Conformance hook: the scheduler actually placed a fragment
        on ``uri``.  Recorded so the spec automaton can check
        detector.no-dead-schedule against the detector's own state."""
        if RECORDER.enabled:
            with self._lock:
                h = self._workers.get(uri.rstrip("/"))
                state = h.state if h is not None else ALIVE
                RECORDER.record("detector", self._pkey(uri.rstrip("/")),
                                "assign", state=state)

    def probe_due(self, uri: str) -> bool:
        """True when the backoff window for this worker has elapsed —
        schedulers may attempt one optimistic contact then."""
        return self._clock() >= self.watch(uri).next_probe

    def schedulable(self) -> List[str]:
        with self._lock:
            return [u for u, h in self._workers.items()
                    if h.state in SCHEDULABLE_STATES]

    def snapshot(self) -> List[dict]:
        """system_runtime_workers / web-UI rows."""
        now = self._clock()
        with self._lock:
            return [h.row(now) for h in self._workers.values()]

    # -- active probing -----------------------------------------------------
    def probe_once(self, force: bool = False) -> None:
        """One heartbeat pass over every worker whose backoff window
        has elapsed (all of them with ``force``).  Synchronous — the
        unit-test entry point; the background thread just loops it."""
        now = self._clock()
        with self._lock:
            due = [h.uri for h in self._workers.values()
                   if force or now >= h.next_probe]
        for uri in due:
            try:
                self._probe(uri, self.probe_timeout)
            except Exception as e:
                self.record_failure(uri, f"{type(e).__name__}: {e}")
            else:
                self.record_success(uri)

    def start(self) -> None:
        """Background heartbeat loop (HeartbeatFailureDetector's
        scheduled executor)."""
        if self._thread is not None:
            return
        # a FRESH event per generation: the old loop keeps its own
        # (already-set) event captured, so a stop()/start() cycle can
        # never revive a prior loop no matter how slowly its last
        # probe pass drains — at most one heartbeat loop ever runs
        stop = self._stop = threading.Event()

        def loop():
            while not stop.wait(self.interval):
                try:
                    self.probe_once()
                except Exception:
                    pass  # the detector outlives any single bad pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="failure-detector")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval + 1.0)  # best-effort drain
