"""Device-mesh exchange: the TPU-native shuffle.

Reference analog: the data plane of Presto's partitioned exchange —
``operator/PartitionedOutputOperator.java:48`` (hash rows to partition
buckets) + ``execution/buffer/PartitionedOutputBuffer.java`` +
``operator/ExchangeClient.java:58`` (HTTP pull).  On a TPU slice the
whole producer-buffer-consumer pipeline collapses into one collective:
each device bucketizes its rows by target partition and a single
``jax.lax.all_to_all`` over the ICI mesh delivers every bucket — no
serde, no acking, no backpressure (SPMD barrier semantics instead of
pull-based flow control; see SURVEY.md §2.3).

Bucket capacity is static (XLA shapes): each device may send at most
``bucket_cap`` rows to each target.  Overflow is detected (count
returned) and the driver re-runs the wave with a larger bucket — the
moral equivalent of the reference's bounded output buffers blocking the
producer (``OutputBufferMemoryManager``), resolved at compile-size
granularity instead of at runtime.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.ops.aggregate import _mix64, pack_or_hash_keys
from presto_tpu.page import Block, Page

_I32_MAX = jnp.iinfo(jnp.int32).max


def partition_targets(
    page: Page,
    key_exprs: Sequence[Expr],
    n_parts: int,
    key_domains=None,
) -> jax.Array:
    """Target partition per row (int32; dead rows -> n_parts).

    The hash-mix ensures partitioning is independent of the packed
    key's structure (LocalPartitionGenerator analog)."""
    c = ExprCompiler.for_page(page)
    kd = [c.compile(e)(page) for e in key_exprs]
    from presto_tpu.ops.aggregate import canonicalize_codes, expr_key_dicts

    datas = canonicalize_codes([d for d, _ in kd],
                               expr_key_dicts(page, key_exprs))
    valids = [v for _, v in kd]
    key, _ = pack_or_hash_keys(datas, valids, key_domains)
    h = _mix64(key.astype(jnp.uint64))
    t = (h % jnp.uint64(n_parts)).astype(jnp.int32)
    return jnp.where(page.row_mask, t, n_parts)


def partition_for_exchange(
    page: Page,
    target: jax.Array,
    n_parts: int,
    bucket_cap: int,
) -> Tuple[Page, jax.Array]:
    """Scatter rows into ``n_parts`` contiguous buckets of ``bucket_cap``
    rows each (output capacity n_parts*bucket_cap, bucket p occupying
    rows [p*bucket_cap, (p+1)*bucket_cap)).

    Returns (bucketized page, max bucket fill) — fill > bucket_cap
    means overflow: rows were dropped and the caller must retry with a
    larger bucket_cap."""
    cap = page.capacity
    order = jnp.argsort(target)  # groups rows by target, dead last
    sorted_t = target[order]
    idx = jnp.arange(cap)
    first = jnp.concatenate([jnp.ones(1, jnp.bool_), sorted_t[1:] != sorted_t[:-1]])
    run_start = jax.lax.associative_scan(jnp.maximum, jnp.where(first, idx, 0))
    slot = idx - run_start
    live_sorted = sorted_t < n_parts
    dest = jnp.where(
        live_sorted & (slot < bucket_cap),
        sorted_t * bucket_cap + slot,
        n_parts * bucket_cap,  # dropped (out of range)
    )
    counts = jax.ops.segment_sum(
        jnp.ones(cap, jnp.int32), jnp.where(live_sorted, sorted_t, n_parts),
        num_segments=n_parts + 1,
    )[:n_parts]
    fill = jnp.max(counts) if n_parts > 0 else jnp.zeros((), jnp.int32)

    out_cap = n_parts * bucket_cap
    blocks: List[Block] = []
    for b in page.blocks:
        # trailing dims ride along (limb matrices, raw-string lanes)
        data = jnp.zeros((out_cap,) + b.data.shape[1:],
                         dtype=b.data.dtype).at[dest].set(
            b.data[order], mode="drop"
        )
        valid = jnp.zeros((out_cap,), dtype=jnp.bool_).at[dest].set(
            b.valid[order], mode="drop"
        )
        blocks.append(Block(data, valid, b.type, b.dictionary))
    mask = jnp.zeros((out_cap,), dtype=jnp.bool_).at[dest].set(
        page.row_mask[order], mode="drop"
    )
    return Page(tuple(blocks), mask), fill


def exchange_page(page: Page, axis_name: str) -> Page:
    """All-to-all a bucketized page over the mesh axis: bucket p of
    device s arrives at device p as bucket s.  Must be called inside
    shard_map; capacity must be n_devices * bucket_cap."""

    def a2a(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=True)

    blocks = tuple(
        Block(a2a(b.data), a2a(b.valid), b.type, b.dictionary) for b in page.blocks
    )
    return Page(blocks, a2a(page.row_mask))


def broadcast_gather_page(page: Page, axis_name: str) -> Page:
    """All-gather a page over the mesh axis (broadcast exchange analog:
    execution/buffer/BroadcastOutputBuffer.java — every device ends up
    with every row)."""

    def ag(x):
        return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)

    blocks = tuple(
        Block(ag(b.data), ag(b.valid), b.type, b.dictionary) for b in page.blocks
    )
    return Page(blocks, ag(page.row_mask))
