"""In-process streaming page exchange: the pull-based, token-acked
stage boundary shared by the mesh tier (parallel/dist.py) and the DCN
tier (parallel/multihost.py).

Reference analog: the consumer half of the exchange —
``operator/ExchangeClient.java:58`` pulling
``execution/buffer/OutputBuffer.java`` pages by (token, ack) long-poll
— collapsed to an in-memory :class:`TaskOutputBuffer` when producer
and consumer share a process.  A stage's producers (mesh waves, HTTP
worker pullers, UNION legs) enqueue pages as they materialize; the
consuming stage pulls them immediately, so stage k+1 overlaps stage k
instead of waiting for a fully materialized intermediate.  The byte
cap gives pull-side backpressure: producers block (and account stall
time) when the consumer lags, bounding in-flight exchange memory.

Kill integration: every stream created inside :func:`query_scope`
registers under that query id, and :func:`abort_query` (called by
``MemoryPool.kill_query`` — deadline and low-memory kills) aborts them
so producer threads blocked in ``enqueue`` exit instead of leaking.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Callable, Dict, Iterator, List, Optional, Sequence

from presto_tpu.envflag import EnvFlag, EnvInt
from presto_tpu.server.buffers import BufferAborted, TaskOutputBuffer
from presto_tpu.sync import named_lock

#: process defaults (session properties exchange_streaming /
#: exchange_buffer_bytes override per query) — resolved once, per the
#: hot-path env-read rule
exchange_streaming_default = EnvFlag("PRESTO_TPU_EXCHANGE_STREAMING", True)
exchange_buffer_bytes_default = EnvInt(
    "PRESTO_TPU_EXCHANGE_BUFFER_BYTES", 64 << 20, floor=1 << 16)


class StreamFailed(RuntimeError):
    """A producer failed; the consumer re-raises the original error."""


def page_nbytes(page) -> int:
    """Best-effort in-memory size of a Page (backpressure accounting)."""
    try:
        from presto_tpu.memory import page_bytes

        return int(page_bytes(page))
    except Exception:
        return 1 << 12  # exotic blocks: charge a nominal page


class PageStream:
    """One token-acked stream of Page payloads over an in-memory
    buffer — the in-process twin of a worker's output buffer, with the
    exact same enqueue / get(token) / acknowledge protocol."""

    def __init__(self, max_bytes: Optional[int] = None, producers: int = 1,
                 name: str = ""):
        self.name = name
        self.buffer = TaskOutputBuffer(
            max_bytes=max_bytes or exchange_buffer_bytes_default(),
            producers=producers)
        self._exc: Optional[BaseException] = None
        # concurrent producers (union legs, per-worker pullers) share
        # one stream: the overlap stats must not drop updates
        self._stats_lock = named_lock("streams.PageStream._stats_lock")
        self.pages_in = 0
        self.bytes_in = 0
        self.peak_bytes = 0
        self.closed = False
        # query timeline, captured HERE because streams are constructed
        # on the consumer thread (inside the query's recording scope)
        # while put() runs on producer threads that never inherit the
        # activation thread-local
        from presto_tpu.obs.timeseries import current_timeline

        self._timeline = current_timeline()
        self._stall_seen = 0.0
        _LIVE.add(self)
        _register(self)

    # -- producer side -------------------------------------------------
    def put(self, page, nbytes: Optional[int] = None) -> None:
        from presto_tpu.obs import METRICS

        size = page_nbytes(page) if nbytes is None else int(nbytes)
        self.buffer.enqueue(page, nbytes=size)
        b = self.buffer.unacked_bytes
        with self._stats_lock:
            self.pages_in += 1
            self.bytes_in += size
            if b > self.peak_bytes:
                self.peak_bytes = b
        METRICS.counter("exchange.stream_pages_total").inc()
        METRICS.counter("exchange.stream_bytes_total").inc(size)
        tl = self._timeline
        if tl is not None:
            tl.record("exchange.buffered_bytes", float(b))
            # producer stall accumulates on the buffer; publish only the
            # delta since this stream last looked, so multiple streams
            # on one timeline stay additive
            stalled = self.buffer.stall_seconds
            with self._stats_lock:
                delta = stalled - self._stall_seen
                self._stall_seen = stalled
            if delta > 0:
                tl.bump("exchange_producer_stall_s", delta)

    def producer_done(self) -> None:
        self.buffer.set_complete()

    def fail(self, exc: BaseException) -> None:
        if self._exc is None:
            self._exc = exc
        self.buffer.fail(f"{type(exc).__name__}: {exc}")

    def abort(self) -> bool:
        """Abort the stream; returns whether the underlying buffer
        actually aborted.  Idempotent and drain-safe (the buffer's
        abort is a no-op on a second call or after a full drain), so
        racing kill paths — deadline kill vs. memory kill vs. a
        consumer that already finished — never raise and never fail a
        query that delivered everything."""
        self.closed = True
        return self.buffer.abort()

    # -- consumer side -------------------------------------------------
    @property
    def buffered_bytes(self) -> int:
        return self.buffer.unacked_bytes

    @property
    def first_page_at(self) -> Optional[float]:
        return self.buffer.first_page_at

    @property
    def completed_at(self) -> Optional[float]:
        return self.buffer.completed_at

    def drain(self, batch_bytes: int = 8 << 20) -> Iterator:
        """Pull + ack until complete; re-raises a producer's error.
        Closing the generator early (LIMIT, a consumer-side error)
        aborts the buffer: a producer blocked on the byte cap would
        otherwise wait for acks that can never come — the deadlock the
        sanitizer's instrumented-lock runs flagged as unbounded
        producer stalls on dead consumers."""
        token = 0
        complete = False
        try:
            while True:
                items, nxt, done, err = self.buffer.get(
                    token, max_bytes=batch_bytes, timeout=10.0)
                if err is not None:
                    raise self._exc if self._exc is not None \
                        else StreamFailed(err)
                for it in items:
                    yield it
                if nxt > token:
                    self.buffer.acknowledge(nxt)
                    token = nxt
                if done:
                    complete = True
                    return
        finally:
            self.closed = True
            if not complete:
                self.buffer.abort()


class StreamingExchange:
    """One stage boundary: N producer streams feeding one consumer.
    ``kind`` names the exchange shape EXPLAIN prints (hash / gather /
    merge / union); ``streaming=False`` degrades every ``run``ed
    producer to inline (materialize-then-consume) execution — the A/B
    leg of the streamed-vs-materialized comparison."""

    def __init__(self, kind: str, name: str = "", streaming: bool = True,
                 max_bytes: Optional[int] = None):
        self.kind = kind
        self.name = name or kind
        self.streaming = streaming
        self.max_bytes = max_bytes
        self.streams: List[PageStream] = []
        self._threads: List[threading.Thread] = []

    def stream(self, producers: int = 1) -> PageStream:
        # materialized mode buffers the full intermediate by definition:
        # producers run inline BEFORE the consumer drains, so the byte
        # cap must not bind or an over-cap stage deadlocks in enqueue
        cap = (self.max_bytes or exchange_buffer_bytes_default()) \
            if self.streaming else (1 << 62)
        s = PageStream(max_bytes=cap, producers=producers,
                       name=f"{self.name}[{len(self.streams)}]")
        self.streams.append(s)
        return s

    def run(self, stream: PageStream, produce: Callable[[PageStream], None],
            ) -> None:
        """Run one producer into ``stream``: a daemon thread when
        streaming, inline (to completion, before the consumer pulls)
        when not.  The producer's error travels to the consumer through
        the stream; abort ends it quietly (kill path)."""

        def _run():
            try:
                produce(stream)
            except BufferAborted:
                pass
            except BaseException as e:
                stream.fail(e)
            finally:
                stream.producer_done()

        if not self.streaming:
            _run()
            return
        t = threading.Thread(target=_run, daemon=True,
                             name=f"exchange-{self.name}")
        t.start()
        self._threads.append(t)

    def abort(self) -> None:
        for s in self.streams:
            s.abort()

    def join(self) -> None:
        for t in self._threads:
            t.join()

    # -- overlap evidence (A/B harness + tests) ------------------------
    def stats(self) -> Dict[str, float]:
        firsts = [s.first_page_at for s in self.streams
                  if s.first_page_at is not None]
        dones = [s.completed_at for s in self.streams
                 if s.completed_at is not None]
        return {
            "streams": float(len(self.streams)),
            "pages": float(sum(s.pages_in for s in self.streams)),
            "bytes": float(sum(s.bytes_in for s in self.streams)),
            "peak_buffered_bytes": float(
                max((s.peak_bytes for s in self.streams), default=0)),
            "first_page_at": min(firsts) if firsts else 0.0,
            "producers_done_at": max(dones) if dones else 0.0,
        }


# ---------------------------------------------------------------------------
# query-scoped registry (the kill path) + process-wide occupancy gauges
# ---------------------------------------------------------------------------

_LIVE: "weakref.WeakSet[PageStream]" = weakref.WeakSet()
_TLS = threading.local()
_REGISTRY: Dict[str, "weakref.WeakSet[PageStream]"] = {}
_REG_LOCK = named_lock("streams._REG_LOCK")


def _register(stream: PageStream) -> None:
    qid = getattr(_TLS, "qid", None)
    if qid:
        with _REG_LOCK:
            _REGISTRY.setdefault(qid, weakref.WeakSet()).add(stream)


@contextlib.contextmanager
def query_scope(query_id: Optional[str]):
    """Tag streams created on this thread with ``query_id`` so
    ``abort_query`` (pool.kill_query) can reach them."""
    prev = getattr(_TLS, "qid", None)
    _TLS.qid = query_id
    try:
        yield
    finally:
        _TLS.qid = prev
        if query_id:
            with _REG_LOCK:
                _REGISTRY.pop(query_id, None)


def abort_query(query_id: str) -> int:
    """Abort every live stream of a killed query: producers blocked in
    ``enqueue`` raise BufferAborted and exit instead of leaking.

    Idempotent and drain-safe: calling it twice, or while (or after) a
    consumer drains the last page and acks it, is a no-op for the
    already-settled streams — never raises, and only streams this call
    actually tore down count toward ``exchange.streams_aborted`` (a
    deadline kill that loses the race with a successful drain must not
    report an abort that never happened).  Returns that count."""
    with _REG_LOCK:
        streams = list(_REGISTRY.pop(query_id, ()))
    aborted = sum(1 for s in streams if s.abort())
    if aborted:
        from presto_tpu.obs import METRICS

        METRICS.counter("exchange.streams_aborted").inc(aborted)
    return aborted


def _wire_gauges() -> None:
    from presto_tpu.obs import METRICS

    METRICS.gauge("exchange.buffered_bytes").set_fn(
        lambda: float(sum(s.buffered_bytes for s in list(_LIVE))))
    METRICS.gauge("exchange.open_streams").set_fn(
        lambda: float(sum(1 for s in list(_LIVE) if not s.closed)))


_wire_gauges()
