"""Result verifier: replay a query suite on two engines and diff.

Reference analog: ``presto-verifier`` (``verifier/Verifier.java``,
``Validator.java``) — replays production queries against a control and
a test cluster and compares checksummed results.  Here the two sides
are any pair of callables ``sql -> rows`` (two QueryRunners, a runner
vs the sqlite oracle, local vs distributed, two REST endpoints via
StatementClient).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Dict, List, Sequence, Tuple


@dataclasses.dataclass
class VerifierResult:
    name: str
    status: str  # MATCH | MISMATCH | CONTROL_FAILED | TEST_FAILED
    control_time: float = 0.0
    test_time: float = 0.0
    detail: str = ""


def _canonical(rows: Sequence[tuple], float_digits: int = 6) -> List[tuple]:
    def key(row):
        return tuple(
            round(v, float_digits) if isinstance(v, float) else v for v in row
        )

    return sorted((key(r) for r in rows))


def rows_match(a: Sequence[tuple], b: Sequence[tuple], rel_tol: float = 1e-9) -> bool:
    if len(a) != len(b):
        return False
    ca, cb = _canonical(a), _canonical(b)
    for ra, rb in zip(ca, cb):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                if va is None or vb is None:
                    if va is not vb:
                        return False
                elif not math.isclose(float(va), float(vb), rel_tol=rel_tol, abs_tol=1e-6):
                    return False
            elif va != vb:
                return False
    return True


class Verifier:
    def __init__(
        self,
        control: Callable[[str], Sequence[tuple]],
        test: Callable[[str], Sequence[tuple]],
    ):
        self.control = control
        self.test = test

    def verify(self, queries: Dict[str, str]) -> List[VerifierResult]:
        out: List[VerifierResult] = []
        for name, sql in queries.items():
            t0 = time.perf_counter()
            try:
                control_rows = self.control(sql)
            except Exception as e:
                out.append(VerifierResult(name, "CONTROL_FAILED", detail=str(e)))
                continue
            tc = time.perf_counter() - t0
            t0 = time.perf_counter()
            try:
                test_rows = self.test(sql)
            except Exception as e:
                out.append(VerifierResult(name, "TEST_FAILED", control_time=tc, detail=str(e)))
                continue
            tt = time.perf_counter() - t0
            if rows_match(control_rows, test_rows):
                out.append(VerifierResult(name, "MATCH", tc, tt))
            else:
                out.append(VerifierResult(
                    name, "MISMATCH", tc, tt,
                    detail=f"control {len(control_rows)} rows vs test {len(test_rows)} rows",
                ))
        return out


def main() -> int:  # pragma: no cover - CLI convenience
    """Verify the TPC-H corpus: engine vs sqlite oracle."""
    import sys

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner

    sys.path.insert(0, "tests")
    from oracle import load_oracle, run_oracle  # type: ignore
    from tpch_queries import QUERIES  # type: ignore

    tpch = Tpch(sf=0.01)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    runner = QueryRunner(catalog)
    oracle = load_oracle(tpch)

    v = Verifier(
        control=lambda sql: run_oracle(oracle, sql),
        test=lambda sql: runner.execute(sql).rows,
    )
    results = v.verify({f"q{k:02d}": sql for k, sql in sorted(QUERIES.items())})
    bad = 0
    for r in results:
        print(f"{r.name}: {r.status}  control={r.control_time:.2f}s test={r.test_time:.2f}s {r.detail}")
        bad += r.status != "MATCH"
    return 1 if bad else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
