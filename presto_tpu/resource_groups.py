"""Resource groups: hierarchical admission control.

Reference analog: ``execution/resourceGroups/InternalResourceGroup.java``
+ ``InternalResourceGroupManager`` and the spi/resourceGroups selector
contract — queries are admitted into a tree of groups with concurrency
and queue quotas; over-quota queries wait in FIFO order (the reference
also offers weighted/priority queues).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(Exception):
    pass


class ResourceGroup:
    """One node of the group tree: hard_concurrency + max_queued."""

    def __init__(self, name: str, hard_concurrency: int = 8, max_queued: int = 100,
                 parent: Optional["ResourceGroup"] = None):
        self.name = name
        self.hard_concurrency = hard_concurrency
        self.max_queued = max_queued
        self.parent = parent
        self.children: Dict[str, "ResourceGroup"] = {}
        self._lock = threading.Condition()
        self.running = 0
        self.queued = 0

    def subgroup(self, name: str, hard_concurrency: int = 8, max_queued: int = 100) -> "ResourceGroup":
        g = self.children.get(name)
        if g is None:
            g = ResourceGroup(f"{self.name}.{name}", hard_concurrency, max_queued, self)
            self.children[name] = g
        return g

    # ------------------------------------------------------------------
    def _can_run(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency:
                return False
            g = g.parent
        return True

    def _charge(self, delta: int) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += delta
            g = g.parent

    def acquire(self, timeout: Optional[float] = None) -> None:
        """Block until this query may run (FIFO within the group)."""
        with self._lock:
            if self.queued >= self.max_queued:
                raise QueryQueueFullError(
                    f"group {self.name}: {self.queued} queries queued (max {self.max_queued})"
                )
            self.queued += 1
            try:
                while not self._can_run():
                    if not self._lock.wait(timeout=timeout):
                        raise TimeoutError(f"group {self.name}: queue wait timed out")
                self._charge(1)
            finally:
                self.queued -= 1

    def release(self) -> None:
        with self._lock:
            self._charge(-1)
            self._lock.notify_all()

    def run(self, fn: Callable, timeout: Optional[float] = None):
        self.acquire(timeout=timeout)
        try:
            return fn()
        finally:
            self.release()


class ResourceGroupManager:
    """Selector: maps (user, source) to a group
    (spi/resourceGroups/ResourceGroupConfigurationManager analog)."""

    def __init__(self, root: Optional[ResourceGroup] = None):
        self.root = root or ResourceGroup("global", hard_concurrency=16, max_queued=1000)
        self._selectors: List[Callable[[str], Optional[ResourceGroup]]] = []

    def add_selector(self, fn: Callable[[str], Optional[ResourceGroup]]) -> None:
        self._selectors.append(fn)

    def group_for(self, user: str) -> ResourceGroup:
        for sel in self._selectors:
            g = sel(user)
            if g is not None:
                return g
        return self.root
