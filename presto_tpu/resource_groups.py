"""Resource groups: hierarchical admission control.

Reference analog: ``execution/resourceGroups/InternalResourceGroup.java``
+ ``InternalResourceGroupManager`` and the spi/resourceGroups selector
contract — queries are admitted into a tree of groups with concurrency
and queue quotas.  Scheduling policies mirror the reference's:

  fair            FIFO within the group (FifoQueue)
  weighted_fair   a freed slot goes to the contending sibling with the
                  lowest running/weight ratio (WeightedFairQueue.java)
  query_priority  highest submission priority first
                  (the reference's StochasticPriorityQueue/priority mode,
                  deterministic here)

All groups of a tree share one lock; eligibility walks the ancestor
chain so sibling fairness is enforced at every level.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu.sync import named_condition


class QueryQueueFullError(Exception):
    pass


_seq = itertools.count()


class ResourceGroup:
    """One node of the group tree."""

    def __init__(self, name: str, hard_concurrency: int = 8, max_queued: int = 100,
                 parent: Optional["ResourceGroup"] = None,
                 scheduling_weight: int = 1, scheduling_policy: str = "fair"):
        self.name = name
        self.hard_concurrency = hard_concurrency
        self.max_queued = max_queued
        self.parent = parent
        self.scheduling_weight = max(int(scheduling_weight), 1)
        self.scheduling_policy = scheduling_policy
        self.children: Dict[str, "ResourceGroup"] = {}
        # one condition per TREE: cross-group fairness needs a shared
        # monitor (the reference synchronizes on the root too,
        # InternalResourceGroup.root lock)
        self._lock = (parent._lock if parent is not None
                      else named_condition(
                          "resource_groups.ResourceGroup._lock"))
        self.running = 0
        self.queued = 0
        self.pending = 0  # waiters in this subtree (for sibling contention)
        self._wait_queue: List[Tuple[int, int]] = []  # (order_key, seq)
        # stride-scheduling virtual time: each admission costs 1/weight,
        # so long-run admissions converge to the weight ratio even when
        # instantaneous running counts tie (WeightedFairQueue's
        # utilization/share comparison, made history-aware)
        self._vtime = 0.0

    def subgroup(self, name: str, hard_concurrency: int = 8, max_queued: int = 100,
                 scheduling_weight: int = 1,
                 scheduling_policy: str = "fair") -> "ResourceGroup":
        g = self.children.get(name)
        if g is None:
            g = ResourceGroup(f"{self.name}.{name}", hard_concurrency, max_queued,
                              self, scheduling_weight, scheduling_policy)
            self.children[name] = g
        return g

    # ------------------------------------------------------------------
    def _can_run(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency:
                return False
            g = g.parent
        return True

    def _charge(self, delta: int) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += delta
            g = g.parent

    def _charge_pending(self, delta: int) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.pending += delta
            g = g.parent

    def _eligible(self, entry: Tuple[int, int]) -> bool:
        """entry may run: it heads its own queue AND every contended
        weighted-fair ancestor prefers this path."""
        if not self._wait_queue or min(self._wait_queue) != entry:
            return False
        g: ResourceGroup = self
        while g.parent is not None:
            parent = g.parent
            if parent.scheduling_policy == "weighted_fair":
                # only siblings that can actually admit contend — a
                # capacity-saturated preferred child must not idle the
                # parent's free slots (head-of-line starvation)
                contenders = [c for c in parent.children.values()
                              if c.pending > 0 and c.running < c.hard_concurrency]
                if len(contenders) > 1 and g in contenders:
                    preferred = min(contenders, key=lambda c: (c._vtime, c.name))
                    if preferred is not g:
                        return False
            g = parent
        return True

    def acquire(self, timeout: Optional[float] = None, priority: int = 0) -> None:
        """Block until this query may run under the group's policy."""
        import time as _time

        order_key = -priority if self.scheduling_policy == "query_priority" else 0
        entry = (order_key, next(_seq))
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            if self.queued >= self.max_queued:
                raise QueryQueueFullError(
                    f"group {self.name}: {self.queued} queries queued (max {self.max_queued})"
                )
            self.queued += 1
            self._wait_queue.append(entry)
            self._charge_pending(1)
            try:
                while not (self._can_run() and self._eligible(entry)):
                    # absolute deadline: notify_all wakeups must not
                    # restart the timeout window
                    remaining = None if deadline is None \
                        else deadline - _time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(f"group {self.name}: queue wait timed out")
                    if not self._lock.wait(timeout=remaining):
                        raise TimeoutError(f"group {self.name}: queue wait timed out")
                self._charge(1)
                g: Optional[ResourceGroup] = self
                while g is not None:
                    g._vtime += 1.0 / g.scheduling_weight
                    g = g.parent
            finally:
                self.queued -= 1
                self._wait_queue.remove(entry)
                self._charge_pending(-1)
                # a state change may unblock a different sibling
                self._lock.notify_all()

    def release(self) -> None:
        with self._lock:
            self._charge(-1)
            self._lock.notify_all()

    def run(self, fn: Callable, timeout: Optional[float] = None, priority: int = 0):
        self.acquire(timeout=timeout, priority=priority)
        try:
            return fn()
        finally:
            self.release()


class ResourceGroupManager:
    """Selector: maps (user, source) to a group
    (spi/resourceGroups/ResourceGroupConfigurationManager analog)."""

    def __init__(self, root: Optional[ResourceGroup] = None):
        self.root = root or ResourceGroup("global", hard_concurrency=16, max_queued=1000)
        self._selectors: List[Callable[[str], Optional[ResourceGroup]]] = []

    def add_selector(self, fn: Callable[[str], Optional[ResourceGroup]]) -> None:
        self._selectors.append(fn)

    def group_for(self, user: str) -> ResourceGroup:
        for sel in self._selectors:
            g = sel(user)
            if g is not None:
                return g
        return self.root


class DbResourceGroupManager(ResourceGroupManager):
    """sqlite-backed resource-group configuration with live reload
    (resource-group-managers/.../db/DbResourceGroupConfigurationManager
    .java: groups + selectors live in DB tables and the manager polls
    for changes, so admins retune concurrency without a restart).

    Schema (created on first use):
      resource_groups(name PK, parent, hard_concurrency, max_queued,
                      scheduling_policy, scheduling_weight)
      selectors(user_regex, group_name, priority)

    Reload: every ``poll_interval`` seconds the config tables are
    re-read when sqlite's data_version pragma moved.  Rebuilt groups
    REPLACE the tree for new queries; queries already queued keep their
    admission slot in the old tree (the reference migrates running
    queries the same lazily)."""

    def __init__(self, path: str, poll_interval: float = 1.0):
        import sqlite3

        self.path = path
        self.poll_interval = poll_interval
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS resource_groups ("
            " name TEXT PRIMARY KEY, parent TEXT,"
            " hard_concurrency INTEGER NOT NULL DEFAULT 8,"
            " max_queued INTEGER NOT NULL DEFAULT 100,"
            " scheduling_policy TEXT NOT NULL DEFAULT 'fair',"
            " scheduling_weight INTEGER NOT NULL DEFAULT 1)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS selectors ("
            " user_regex TEXT NOT NULL, group_name TEXT NOT NULL,"
            " priority INTEGER NOT NULL DEFAULT 0)")
        self._db.commit()
        self._version = -1
        self._last_poll = 0.0
        super().__init__()
        self._reload()

    # -- admin helpers (tests + operational tooling) -------------------
    def upsert_group(self, name: str, parent: Optional[str] = None,
                     hard_concurrency: int = 8, max_queued: int = 100,
                     scheduling_policy: str = "fair",
                     scheduling_weight: int = 1) -> None:
        self._db.execute(
            "INSERT INTO resource_groups VALUES (?,?,?,?,?,?) "
            "ON CONFLICT(name) DO UPDATE SET parent=excluded.parent,"
            " hard_concurrency=excluded.hard_concurrency,"
            " max_queued=excluded.max_queued,"
            " scheduling_policy=excluded.scheduling_policy,"
            " scheduling_weight=excluded.scheduling_weight",
            (name, parent, hard_concurrency, max_queued,
             scheduling_policy, scheduling_weight))
        self._db.commit()
        # data_version only moves for OTHER connections' writes — a
        # manager that edits its own config reloads itself directly
        self._reload()

    def add_db_selector(self, user_regex: str, group_name: str,
                        priority: int = 0) -> None:
        self._db.execute("INSERT INTO selectors VALUES (?,?,?)",
                         (user_regex, group_name, priority))
        self._db.commit()
        self._reload()

    # -- reload --------------------------------------------------------
    def _data_version(self) -> int:
        return self._db.execute("PRAGMA data_version").fetchone()[0]

    def _maybe_reload(self) -> None:
        import time as _time

        now = _time.monotonic()
        if now - self._last_poll < self.poll_interval:
            return
        self._last_poll = now
        v = self._data_version()
        if v != self._version:
            self._reload()

    def _reload(self) -> None:
        import re

        self._version = self._data_version()
        rows = self._db.execute(
            "SELECT name, parent, hard_concurrency, max_queued,"
            " scheduling_policy, scheduling_weight "
            "FROM resource_groups").fetchall()
        groups: Dict[str, ResourceGroup] = {}
        root_row = next((r for r in rows if r[1] is None), None)
        if root_row is None:
            root = ResourceGroup("global", hard_concurrency=16,
                                 max_queued=1000)
        else:
            root = ResourceGroup(root_row[0], root_row[2], root_row[3],
                                 scheduling_policy=root_row[4],
                                 scheduling_weight=root_row[5])
            groups[root_row[0]] = root
        pending = [r for r in rows if r[1] is not None]
        # attach children breadth-first so parents exist
        while pending:
            progressed = False
            for r in list(pending):
                parent = groups.get(r[1])
                if parent is None:
                    continue
                groups[r[0]] = parent.subgroup(
                    r[0], r[2], r[3], scheduling_policy=r[4],
                    scheduling_weight=r[5])
                pending.remove(r)
                progressed = True
            if not progressed:  # orphan rows: ignore (bad parent name)
                break
        sel_rows = self._db.execute(
            "SELECT user_regex, group_name, priority FROM selectors "
            "ORDER BY priority DESC").fetchall()
        selectors: List[Callable[[str], Optional[ResourceGroup]]] = []
        for user_regex, group_name, _prio in sel_rows:
            target = groups.get(group_name)
            if target is None:
                continue
            pat = re.compile(user_regex)

            def sel(user: str, pat=pat, target=target):
                return target if pat.fullmatch(user) else None

            selectors.append(sel)
        self.root = root
        self._selectors = selectors

    def group_for(self, user: str) -> ResourceGroup:
        self._maybe_reload()
        return super().group_for(user)
