"""Resource groups: hierarchical admission control.

Reference analog: ``execution/resourceGroups/InternalResourceGroup.java``
+ ``InternalResourceGroupManager`` and the spi/resourceGroups selector
contract — queries are admitted into a tree of groups with concurrency
and queue quotas.  Scheduling policies mirror the reference's:

  fair            FIFO within the group (FifoQueue)
  weighted_fair   a freed slot goes to the contending sibling with the
                  lowest running/weight ratio (WeightedFairQueue.java)
  query_priority  highest submission priority first
                  (the reference's StochasticPriorityQueue/priority mode,
                  deterministic here)

All groups of a tree share one lock; eligibility walks the ancestor
chain so sibling fairness is enforced at every level.
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Tuple


class QueryQueueFullError(Exception):
    pass


_seq = itertools.count()


class ResourceGroup:
    """One node of the group tree."""

    def __init__(self, name: str, hard_concurrency: int = 8, max_queued: int = 100,
                 parent: Optional["ResourceGroup"] = None,
                 scheduling_weight: int = 1, scheduling_policy: str = "fair"):
        self.name = name
        self.hard_concurrency = hard_concurrency
        self.max_queued = max_queued
        self.parent = parent
        self.scheduling_weight = max(int(scheduling_weight), 1)
        self.scheduling_policy = scheduling_policy
        self.children: Dict[str, "ResourceGroup"] = {}
        # one condition per TREE: cross-group fairness needs a shared
        # monitor (the reference synchronizes on the root too,
        # InternalResourceGroup.root lock)
        self._lock = parent._lock if parent is not None else threading.Condition()
        self.running = 0
        self.queued = 0
        self.pending = 0  # waiters in this subtree (for sibling contention)
        self._wait_queue: List[Tuple[int, int]] = []  # (order_key, seq)
        # stride-scheduling virtual time: each admission costs 1/weight,
        # so long-run admissions converge to the weight ratio even when
        # instantaneous running counts tie (WeightedFairQueue's
        # utilization/share comparison, made history-aware)
        self._vtime = 0.0

    def subgroup(self, name: str, hard_concurrency: int = 8, max_queued: int = 100,
                 scheduling_weight: int = 1,
                 scheduling_policy: str = "fair") -> "ResourceGroup":
        g = self.children.get(name)
        if g is None:
            g = ResourceGroup(f"{self.name}.{name}", hard_concurrency, max_queued,
                              self, scheduling_weight, scheduling_policy)
            self.children[name] = g
        return g

    # ------------------------------------------------------------------
    def _can_run(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency:
                return False
            g = g.parent
        return True

    def _charge(self, delta: int) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += delta
            g = g.parent

    def _charge_pending(self, delta: int) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.pending += delta
            g = g.parent

    def _eligible(self, entry: Tuple[int, int]) -> bool:
        """entry may run: it heads its own queue AND every contended
        weighted-fair ancestor prefers this path."""
        if not self._wait_queue or min(self._wait_queue) != entry:
            return False
        g: ResourceGroup = self
        while g.parent is not None:
            parent = g.parent
            if parent.scheduling_policy == "weighted_fair":
                # only siblings that can actually admit contend — a
                # capacity-saturated preferred child must not idle the
                # parent's free slots (head-of-line starvation)
                contenders = [c for c in parent.children.values()
                              if c.pending > 0 and c.running < c.hard_concurrency]
                if len(contenders) > 1 and g in contenders:
                    preferred = min(contenders, key=lambda c: (c._vtime, c.name))
                    if preferred is not g:
                        return False
            g = parent
        return True

    def acquire(self, timeout: Optional[float] = None, priority: int = 0) -> None:
        """Block until this query may run under the group's policy."""
        import time as _time

        order_key = -priority if self.scheduling_policy == "query_priority" else 0
        entry = (order_key, next(_seq))
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            if self.queued >= self.max_queued:
                raise QueryQueueFullError(
                    f"group {self.name}: {self.queued} queries queued (max {self.max_queued})"
                )
            self.queued += 1
            self._wait_queue.append(entry)
            self._charge_pending(1)
            try:
                while not (self._can_run() and self._eligible(entry)):
                    # absolute deadline: notify_all wakeups must not
                    # restart the timeout window
                    remaining = None if deadline is None \
                        else deadline - _time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(f"group {self.name}: queue wait timed out")
                    if not self._lock.wait(timeout=remaining):
                        raise TimeoutError(f"group {self.name}: queue wait timed out")
                self._charge(1)
                g: Optional[ResourceGroup] = self
                while g is not None:
                    g._vtime += 1.0 / g.scheduling_weight
                    g = g.parent
            finally:
                self.queued -= 1
                self._wait_queue.remove(entry)
                self._charge_pending(-1)
                # a state change may unblock a different sibling
                self._lock.notify_all()

    def release(self) -> None:
        with self._lock:
            self._charge(-1)
            self._lock.notify_all()

    def run(self, fn: Callable, timeout: Optional[float] = None, priority: int = 0):
        self.acquire(timeout=timeout, priority=priority)
        try:
            return fn()
        finally:
            self.release()


class ResourceGroupManager:
    """Selector: maps (user, source) to a group
    (spi/resourceGroups/ResourceGroupConfigurationManager analog)."""

    def __init__(self, root: Optional[ResourceGroup] = None):
        self.root = root or ResourceGroup("global", hard_concurrency=16, max_queued=1000)
        self._selectors: List[Callable[[str], Optional[ResourceGroup]]] = []

    def add_selector(self, fn: Callable[[str], Optional[ResourceGroup]]) -> None:
        self._selectors.append(fn)

    def group_for(self, user: str) -> ResourceGroup:
        for sel in self._selectors:
            g = sel(user)
            if g is not None:
                return g
        return self.root
