"""Raw (non-dictionary) VARCHAR kernels over fixed-width byte matrices.

Reference analog: ``spi/block/VariableWidthBlock.java`` ((offsets, bytes)
slices) and the byte-level comparisons of ``type/VarcharOperators.java``.
TPU redesign: a raw varchar column is a zero-padded ``(capacity, W)``
uint8 matrix (W static from the declared VARCHAR(n) length), so
equality/order/substr/concat are static-shape vector ops on the VPU;
only genuinely irregular ops (LIKE, regex) fall back to a host callback
per page (``jax.pure_callback`` — the host-side fallback eval the
variable-width representation was specced with).

Semantics note: device fast paths (substr positions, upper/lower) are
BYTE-oriented and exact for ASCII; multi-byte UTF-8 routes through the
host transforms for code-point-correct results (length does so
unconditionally to match the dictionary path's code-point counts)."""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def encode_strings(values, width: int) -> np.ndarray:
    """List of str/None -> (n, width) uint8, zero-padded/truncated."""
    out = np.zeros((len(values), width), dtype=np.uint8)
    for i, v in enumerate(values):
        if v is None:
            continue
        b = str(v).encode("utf-8")[:width]
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


def decode_strings(data: np.ndarray):
    """(n, W) uint8 -> list of str (padding stripped)."""
    out = []
    for row in np.asarray(data, dtype=np.uint8):
        b = row.tobytes().rstrip(b"\x00")
        out.append(b.decode("utf-8", errors="replace"))
    return out


def encode_literal(s: str, width: int) -> jnp.ndarray:
    return jnp.asarray(encode_strings([s], width)[0])


def lengths(data: jax.Array) -> jax.Array:
    """Byte length per row (padding is the only NUL source)."""
    return jnp.sum((data != 0).astype(jnp.int64), axis=-1)


def _pad_to(data: jax.Array, width: int) -> jax.Array:
    w = data.shape[-1]
    if w == width:
        return data
    if w > width:
        return data[..., :width]
    pad = jnp.zeros(data.shape[:-1] + (width - w,), dtype=data.dtype)
    return jnp.concatenate([data, pad], axis=-1)


def compare(a: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(lt, eq) lexicographic over rows; zero padding sorts shortest
    first ('' < 'a'), matching SQL byte collation."""
    w = max(a.shape[-1], b.shape[-1])
    a = _pad_to(a, w)
    b = _pad_to(b, w)
    diff = a != b
    any_diff = jnp.any(diff, axis=-1)
    first = jnp.argmax(diff, axis=-1)
    av = jnp.take_along_axis(a, first[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(b, first[..., None], axis=-1)[..., 0]
    lt = any_diff & (av < bv)
    return lt, ~any_diff


def substr(data: jax.Array, start: int, length=None) -> jax.Array:
    """1-based static BYTE slice, re-padded to the column width (the
    type's declared width is preserved; only the live bytes change).
    Internal helper — SQL substr routes through :func:`substr_chars`,
    which counts UTF-8 characters."""
    w = data.shape[-1]
    s = max(start - 1, 0)
    end = w if length is None else min(s + length, w)
    return _pad_to(data[..., s:end], w)


def substr_chars(data: jax.Array, start: int, length=None) -> jax.Array:
    """1-based substring by UTF-8 CHARACTER count, on device (SQL
    semantics: a multi-byte code point is one position; byte slicing
    would cut sequences mid-codepoint).  Char starts are the bytes that
    are neither padding NULs nor continuations ((b & 0xC0) == 0x80); a
    stable argsort compacts the kept bytes to the row prefix — O(W log
    W) per row at the static column width, no scalar loops."""
    is_byte = data != 0
    is_start = is_byte & ((data & 0xC0) != 0x80)
    char_idx = jnp.cumsum(is_start.astype(jnp.int32), axis=-1) - 1
    s = max(start - 1, 0)  # same clamp as the byte path / SQL 1-based
    keep = is_byte & (char_idx >= s)
    if length is not None:
        keep = keep & (char_idx < s + length)
    order = jnp.argsort(~keep, axis=-1, stable=True)
    vals = jnp.take_along_axis(data, order, axis=-1)
    kept = jnp.take_along_axis(keep, order, axis=-1)
    return jnp.where(kept, vals, 0)


def change_case(data: jax.Array, upper: bool) -> jax.Array:
    """ASCII + Latin-1 case mapping on device.  Bytes >= 0x80 outside
    the UTF-8 0xC3 page pass through unchanged (never corrupting a
    multi-byte sequence, since only letter bytes are remapped); the
    Latin-1 letters À..Þ/à..þ live on the 0xC3 continuation byte and
    map with a fixed ±0x20 like ASCII.  ÿ→Ÿ (prefix change) and full
    Unicode case folding stay host-side (documented deviation)."""
    prev = jnp.pad(data[..., :-1], [(0, 0)] * (data.ndim - 1) + [(1, 0)])
    after_c3 = prev == 0xC3
    if upper:
        ascii_hit = (data >= ord("a")) & (data <= ord("z"))
        # à (0xC3 0xA0) .. þ (0xC3 0xBE), excluding ÷ (0xC3 0xB7)
        lat_hit = after_c3 & (data >= 0xA0) & (data <= 0xBE) & (data != 0xB7)
        return jnp.where(ascii_hit | lat_hit, data - 32, data)
    ascii_hit = (data >= ord("A")) & (data <= ord("Z"))
    # À (0xC3 0x80) .. Þ (0xC3 0x9E), excluding × (0xC3 0x97)
    lat_hit = after_c3 & (data >= 0x80) & (data <= 0x9E) & (data != 0x97)
    return jnp.where(ascii_hit | lat_hit, data + 32, data)


def concat(a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-wise concatenation: output width Wa+Wb; b's bytes land right
    after a's length via a gathered shift (static shapes throughout)."""
    wa, wb = a.shape[-1], b.shape[-1]
    w = wa + wb
    la = lengths(a)
    out_idx = jnp.arange(w)
    # for each output byte j: a[j] if j < la else b[j - la]
    from_b = out_idx[None, :] >= la[:, None]
    a_pad = _pad_to(a, w)
    bj = jnp.clip(out_idx[None, :] - la[:, None], 0, wb - 1)
    b_vals = jnp.take_along_axis(b, bj.astype(jnp.int32), axis=-1)
    in_b = from_b & (out_idx[None, :] - la[:, None] < lengths(b)[:, None])
    return jnp.where(in_b, b_vals, jnp.where(from_b, 0, a_pad))


def hash_bytes(data: jax.Array) -> jax.Array:
    """Fold a (n, W) byte matrix into one int64 hash lane per row
    (FNV-1a over the static width; the pack_or_hash fallback lane for
    raw-string keys)."""
    h = jnp.full(data.shape[:-1], 0xCBF29CE484222325, dtype=jnp.uint64)
    for j in range(data.shape[-1]):  # static W: unrolled, fuses on VPU
        h = (h ^ data[..., j].astype(jnp.uint64)) * jnp.uint64(0x100000001B3)
    return h.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF)


def pack_lanes(data: jax.Array) -> jax.Array:
    """(n, W) bytes -> (n, ceil(W/8)) int64 lanes whose lexicographic
    lane order equals byte order: big-endian 8-byte chunks, sign bit of
    the leading byte flipped so signed int64 comparison matches
    unsigned byte comparison.  Enables min/max over raw strings as a
    k-phase lexicographic segment reduction (the PagesIndex comparator
    role for VARCHAR, without scalar loops)."""
    w = data.shape[-1]
    k = -(-w // 8)
    padded = _pad_to(data, k * 8).astype(jnp.uint64)
    lanes = []
    for c in range(k):
        lane = jnp.zeros(data.shape[:-1], dtype=jnp.uint64)
        for j in range(8):  # static: unrolls and fuses
            lane = (lane << jnp.uint64(8)) | padded[..., c * 8 + j]
        # flip the sign bit: unsigned order -> signed int64 order
        lanes.append((lane ^ jnp.uint64(1 << 63)).astype(jnp.int64))
    return jnp.stack(lanes, axis=-1)


def unpack_lanes(lanes: jax.Array, width: int) -> jax.Array:
    """Inverse of pack_lanes -> (n, width) uint8."""
    k = lanes.shape[-1]
    u = (lanes.astype(jnp.uint64) ^ jnp.uint64(1 << 63))
    cols = []
    for c in range(k):
        for j in range(8):
            shift = jnp.uint64(8 * (7 - j))
            cols.append(((u[..., c] >> shift) & jnp.uint64(0xFF)).astype(jnp.uint8))
    return jnp.stack(cols, axis=-1)[..., :width]


def host_predicate(pred: Callable[[str], bool]):
    """Wrap a python str predicate as a page-level device op via host
    callback (LIKE/regex on raw strings — the irregular tail)."""

    def run(data: jax.Array) -> jax.Array:
        def cb(arr):
            return np.asarray([bool(pred(s)) for s in decode_strings(arr)],
                              dtype=np.bool_)

        return jax.pure_callback(
            cb, jax.ShapeDtypeStruct(data.shape[:-1], jnp.bool_), data,
            vmap_method="sequential",
        )

    return run
