"""Hash-join kernels: build + probe via sorted lookup.

Reference analog: HashBuilderOperator (operator/HashBuilderOperator.java:51)
building PagesIndex/PagesHash (operator/PagesHash.java:34 — open
addressing over build rows with synthetic addresses) probed by
LookupJoinOperator (operator/LookupJoinOperator.java:53) through
JoinProbe. Random-probe hash tables serialize on TPU, so the build side
is instead *sorted by join key* and probes are vectorized
``searchsorted`` binary searches — every probe row resolves its match
range [lo, hi) in parallel on the VPU.

Match semantics: keys are packed exactly (domains from table metadata;
TPC-H keys always fit 63 bits) so equality is exact, or hash-mixed as a
fallback. NULL join keys never match (SQL semantics) — they pack to the
reserved 0 code which is excluded, or sort to the +inf sentinel.

Shapes: probe_join aligned outputs (unique build keys, or first-match)
keep the probe page's capacity. probe_expand emits up to out_capacity
rows for many-to-many joins, with an overflow flag the driver checks
(it re-probes in smaller chunks on overflow — the analog of the
reference's yielding LookupJoinPageBuilder)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.ops.aggregate import pack_or_hash_keys
from presto_tpu.page import Block, Page

_I64_MAX = jnp.iinfo(jnp.int64).max

# Direct-address lookup table cap: when the packed-key domain is dense
# enough, the build also materializes CSR-style ``starts`` offsets over
# the FULL key domain so every probe resolves its match range with two
# int32 gathers instead of ~log2(build) serialized binary-search rounds
# (the TPU answer to PagesHash.java:152's O(1) open-addressing probe).
# Bounded in absolute size (HBM) and relative to the build (so a tiny
# build over a huge sparse domain doesn't pay a domain-sized sort).
DIRECT_DOMAIN_MAX = 1 << 26
DIRECT_DOMAIN_PER_ROW = 64


# Direct-table / unique-direct selection resolves ONCE per process
# (first runner construction warms it) instead of re-reading the
# environment inside every build_join call — the per-build hot path.
# The explicit override hooks exist for the A/B harness
# (tools/tpu_ab_direct_join.py) and tests, which flip legs in-process.
_DIRECT_JOIN_RESOLVED: "Optional[bool]" = None
_UNIQUE_DIRECT_RESOLVED: "Optional[bool]" = None


def set_direct_join_override(value: "Optional[bool]") -> None:
    """Force the direct-address join table on/off (None re-resolves
    from the environment/backend on next use)."""
    global _DIRECT_JOIN_RESOLVED
    _DIRECT_JOIN_RESOLVED = None if value is None else bool(value)


def set_unique_direct_override(value: "Optional[bool]") -> None:
    """Force the sort-free unique-build path on/off (None re-resolves
    from the environment on next use)."""
    global _UNIQUE_DIRECT_RESOLVED
    _UNIQUE_DIRECT_RESOLVED = None if value is None else bool(value)


def resolve_direct_join() -> bool:
    """The direct table pays a domain-sized fused sort at build time to
    make probes O(1) gathers.  That trade wins on TPU (binary-search
    probes serialize ~log2(build) gather rounds; measured CPU-vs-TPU in
    PERF.md) but LOSES on XLA:CPU, whose searchsorted is already cheap
    and whose domain-sized sort is not (TPC-H Q3 SF1 measured 1.7x
    slower with the table).  Env override PRESTO_TPU_DIRECT_JOIN=0/1
    forces it off/on for A/B runs; resolved once per process."""
    global _DIRECT_JOIN_RESOLVED
    if _DIRECT_JOIN_RESOLVED is None:
        import os as _os

        force = _os.environ.get("PRESTO_TPU_DIRECT_JOIN")
        if force is not None:
            _DIRECT_JOIN_RESOLVED = force not in ("0", "false", "")
        else:
            import jax as _jax

            _DIRECT_JOIN_RESOLVED = _jax.default_backend() != "cpu"
    return _DIRECT_JOIN_RESOLVED


def _direct_table_profitable() -> bool:
    return resolve_direct_join()


def _unique_direct_enabled() -> bool:
    global _UNIQUE_DIRECT_RESOLVED
    if _UNIQUE_DIRECT_RESOLVED is None:
        import os

        _UNIQUE_DIRECT_RESOLVED = os.environ.get(
            "PRESTO_TPU_UNIQUE_DIRECT", "1") not in ("0", "false", "")
    return _UNIQUE_DIRECT_RESOLVED


def _direct_budget(page: Page) -> int:
    """Largest key domain worth a direct-address table for this build
    size (shared by the sorted and unique paths so they agree)."""
    return min(DIRECT_DOMAIN_MAX,
               max(1 << 20, DIRECT_DOMAIN_PER_ROW * page.capacity))


def packed_domain_size(domains) -> Optional[int]:
    """Size of the packed-key code space [0, prod) when every key
    column has a known domain (mirrors pack_or_hash_keys' exact path:
    per-column cardinality hi-lo+2 with code 0 reserved for NULL)."""
    if not domains or any(d is None for d in domains):
        return None
    prod = 1
    for lo, hi in domains:
        prod *= int(hi - lo + 2)
    return prod if prod < (1 << 62) else None


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class JoinBuild:
    """Sorted build-side index (LookupSource analog)."""

    sorted_keys: jax.Array  # packed keys (cap,), max-sentinel padded
    perm: jax.Array  # int32 (cap,): sorted pos -> build row
    page: Page  # original build page (payload source)
    # optional direct-address table: starts[k] = first sorted position
    # with key >= k, for k in [0, domain_size]; int32 (domain_size+1,)
    starts: Optional[jax.Array] = None
    # sort-free unique-build path: False iff the planner's uniqueness
    # promise was violated at runtime (caller rebuilds via the sort)
    unique_ok: Optional[jax.Array] = None
    # three-valued IN/NOT IN support (HashSemiJoinOperator.java:32):
    # whether any live build row had a NULL key, and whether the build
    # had any live row at all — device bool scalars
    has_null_key: Optional[jax.Array] = None
    nonempty: Optional[jax.Array] = None

    def tree_flatten(self):
        return (self.sorted_keys, self.perm, self.page, self.starts,
                self.unique_ok, self.has_null_key, self.nonempty), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.sorted_keys.shape[0]


def build_join(
    page: Page,
    key_exprs: Sequence[Expr],
    key_domains: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
    null_safe: bool = False,
    unique: bool = False,
) -> JoinBuild:
    """``null_safe``: NULL keys match each other (IS NOT DISTINCT FROM
    — the INTERSECT/EXCEPT comparison; default SQL joins drop them).
    ``unique``: the planner promises distinct build keys (primary-key
    joins) — with a dense exact domain the build then skips the sort
    entirely: ranks come from a prefix count over the domain, the
    direct-address table from its cumulative sum (PagesHash's
    addressing rebuilt as two scatters + one scan; a violated promise
    is detected and reported through ``unique_ok`` for the caller to
    rebuild via the sort path)."""
    c = ExprCompiler.for_page(page)
    kd = [c.compile(e)(page) for e in key_exprs]
    from presto_tpu.ops.aggregate import canonicalize_codes, expr_key_dicts

    datas = canonicalize_codes([d for d, _ in kd],
                               expr_key_dicts(page, key_exprs))
    valids = [v for _, v in kd]
    key, exact = pack_or_hash_keys(datas, valids, key_domains)
    live = page.row_mask
    if not null_safe:
        # NULL keys never participate: exclude rows with any null key
        for v in valids:
            live = live & v
    key = jnp.where(live, key, jnp.iinfo(key.dtype).max)

    # three-valued IN/NOT IN metadata (cheap reductions; only the
    # null-aware semi/anti/mark probes read them)
    nonempty = jnp.any(page.row_mask)
    all_valid = valids[0]
    for v in valids[1:]:
        all_valid = all_valid & v
    has_null = jnp.any(page.row_mask & jnp.logical_not(all_valid))

    prod_u = (packed_domain_size(key_domains)
              if unique and exact and _unique_direct_enabled() else None)
    if prod_u is not None and prod_u <= _direct_budget(page):
        cap = page.capacity
        key_c = jnp.clip(key, 0, prod_u - 1)
        slot = jnp.where(live, key_c, prod_u)
        counts = jnp.zeros(prod_u + 1, jnp.int32).at[slot].add(
            jnp.where(live, 1, 0))
        present = jnp.minimum(counts[:prod_u], 1)
        starts_u = jnp.concatenate([
            jnp.zeros(1, jnp.int32), jnp.cumsum(present).astype(jnp.int32)])
        rank = starts_u[key_c.astype(jnp.int64)]
        tgt = jnp.where(live, rank.astype(jnp.int64), cap)
        sorted_keys = jnp.full((cap,), jnp.iinfo(key.dtype).max,
                               dtype=key.dtype).at[tgt].set(key, mode="drop")
        order_u = jnp.zeros((cap,), jnp.int32).at[tgt].set(
            jnp.arange(cap, dtype=jnp.int32), mode="drop")
        collision = jnp.any(counts[:prod_u] > 1)
        return JoinBuild(sorted_keys, order_u, page, starts_u,
                         unique_ok=jnp.logical_not(collision),
                         has_null_key=has_null, nonempty=nonempty)

    order = jnp.argsort(key)
    sorted_keys = key[order]

    starts = None
    prod = (packed_domain_size(key_domains)
            if exact and _direct_table_profitable() else None)
    if prod is not None and prod <= _direct_budget(page):
        # one fused sort at build time buys O(1)-gather probes forever:
        # dead/sentinel rows sort past prod-1 so they never enter a range
        queries = jnp.arange(prod + 1, dtype=sorted_keys.dtype)
        starts = jnp.searchsorted(
            sorted_keys, queries, method="sort").astype(jnp.int32)
    return JoinBuild(sorted_keys, order.astype(jnp.int32), page, starts,
                     has_null_key=has_null, nonempty=nonempty)


def build_null_flags(page: Page, key_exprs: Sequence[Expr]):
    """(has_null_key, nonempty) of a build-side page WITHOUT building
    the sorted index — used by partitioned joins to compute the GLOBAL
    three-valued-IN flags across partitions (a build NULL in one
    partition makes every unmatched probe everywhere UNKNOWN)."""
    c = ExprCompiler.for_page(page)
    valids = [c.compile(e)(page)[1] for e in key_exprs]
    all_valid = valids[0]
    for v in valids[1:]:
        all_valid = all_valid & v
    return (jnp.any(page.row_mask & jnp.logical_not(all_valid)),
            jnp.any(page.row_mask))


def _lookup_first(build: JoinBuild, key: jax.Array):
    """(candidate sorted position, key-match mask) per probe row."""
    if build.starts is not None:
        d = build.starts.shape[0] - 1
        kk = jnp.clip(key, 0, d - 1)
        lo = build.starts[kk]
        hi = build.starts[kk + 1]
        in_dom = (key >= 0) & (key < d)
        return jnp.clip(lo, 0, build.capacity - 1), (hi > lo) & in_dom
    pos = jnp.searchsorted(build.sorted_keys, key)
    pos_c = jnp.clip(pos, 0, build.capacity - 1)
    return pos_c, build.sorted_keys[pos_c] == key


def _lookup_range(build: JoinBuild, key: jax.Array):
    """[lo, hi) sorted-position match range per probe row."""
    if build.starts is not None:
        d = build.starts.shape[0] - 1
        kk = jnp.clip(key, 0, d - 1)
        lo = build.starts[kk]
        hi = build.starts[kk + 1]
        in_dom = (key >= 0) & (key < d)
        zero = jnp.zeros((), dtype=lo.dtype)
        return jnp.where(in_dom, lo, zero), jnp.where(in_dom, hi, zero)
    lo = jnp.searchsorted(build.sorted_keys, key, side="left")
    hi = jnp.searchsorted(build.sorted_keys, key, side="right")
    return lo, hi


def _probe_keys(page: Page, key_exprs: Sequence[Expr], key_domains,
                null_safe: bool = False):
    c = ExprCompiler.for_page(page)
    kd = [c.compile(e)(page) for e in key_exprs]
    from presto_tpu.ops.aggregate import canonicalize_codes, expr_key_dicts

    datas = canonicalize_codes([d for d, _ in kd],
                               expr_key_dicts(page, key_exprs))
    valids = [v for _, v in kd]
    key, _ = pack_or_hash_keys(datas, valids, key_domains)
    ok = page.row_mask
    if not null_safe:
        for v in valids:
            ok = ok & v
    # distinct sentinel from the build's (max): never matches build keys
    return jnp.where(ok, key, jnp.iinfo(key.dtype).max - 1), ok


def probe_join(
    build: JoinBuild,
    probe: Page,
    probe_key_exprs: Sequence[Expr],
    key_domains: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
    kind: str = "inner",
    build_output: Optional[Sequence[int]] = None,
    null_safe: bool = False,
    null_aware: bool = False,
) -> Page:
    """Probe-aligned join for unique (or first-match) build keys.

    kind: inner | left | semi | anti.
    Output: probe blocks followed by the selected build blocks
    (build_output indexes into build.page.blocks; default all).
    semi/anti emit probe blocks only, with the row mask filtered.

    ``null_aware`` selects ANSI three-valued IN/NOT IN semantics
    (HashSemiJoinOperator.java:32): an unmatched probe whose key is
    NULL — or any unmatched probe when the build holds a NULL key —
    is UNKNOWN, which filters as FALSE (semi/anti) and surfaces as a
    NULL mark.  IN over an empty subquery stays FALSE for every probe,
    NULL keys included.
    """
    key, ok = _probe_keys(probe, probe_key_exprs, key_domains, null_safe)
    pos_c, found = _lookup_first(build, key)
    match = found & probe.row_mask
    build_row = build.perm[pos_c]

    if null_aware and kind in ("semi", "anti", "mark") \
            and build.has_null_key is not None:
        has_null = build.has_null_key
        nonempty = build.nonempty
        # UNKNOWN rows: unmatched with a NULL somewhere in the
        # comparison (probe key NULL against a nonempty build, or any
        # build-side NULL key); empty build is decidedly FALSE
        unknown = jnp.logical_not(match) & nonempty & (
            jnp.logical_not(ok) | has_null)
        if kind == "semi":
            return Page(probe.blocks, probe.row_mask & match)
        if kind == "anti":
            keep = jnp.logical_not(match) & jnp.logical_not(unknown)
            return Page(probe.blocks, probe.row_mask & keep)
        from presto_tpu.types import BOOLEAN

        mark = Block(match, jnp.logical_not(unknown), BOOLEAN)
        return Page(tuple(probe.blocks) + (mark,), probe.row_mask)

    if kind == "semi":
        return Page(probe.blocks, probe.row_mask & match)
    if kind == "anti":
        return Page(probe.blocks, probe.row_mask & jnp.logical_not(match))
    if kind == "mark":
        # mark join: emit the presence test as a BOOLEAN column instead
        # of filtering — EXISTS/IN under OR (the reference's mark
        # semijoin, MarkDistinct/SemiJoinRewriter role)
        from presto_tpu.types import BOOLEAN

        mark = Block(match, jnp.ones_like(probe.row_mask), BOOLEAN)
        return Page(tuple(probe.blocks) + (mark,), probe.row_mask)

    if build_output is None:
        build_output = range(len(build.page.blocks))
    out_blocks: List[Block] = list(probe.blocks)
    for i in build_output:
        b = build.page.blocks[i]
        data = b.data[build_row]
        valid = b.valid[build_row] & match
        out_blocks.append(Block(data, valid, b.type, b.dictionary))
    if kind == "inner":
        mask = probe.row_mask & match
    elif kind == "left":
        mask = probe.row_mask
    else:
        raise ValueError(kind)
    return Page(tuple(out_blocks), mask)


def probe_expand(
    build: JoinBuild,
    probe: Page,
    probe_key_exprs: Sequence[Expr],
    out_capacity: int,
    key_domains: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
    kind: str = "inner",
    build_output: Optional[Sequence[int]] = None,
    return_matched: bool = False,
    null_safe: bool = False,
) -> Tuple[Page, jax.Array]:
    """Many-to-many join: each probe row emits one output row per
    matching build row. Returns (page, total_matches); if
    total_matches > out_capacity the page is truncated and the driver
    must re-probe in chunks.

    kind: inner | left (left emits one null-extended row for probes
    with no match).

    return_matched: additionally return a bool (build_capacity,) mask of
    build rows touched by a match — the driver ORs these across probe
    pages to emit the FULL OUTER tail (reference:
    operator/LookupOuterOperator.java, which streams unvisited build
    positions after all probes finish)."""
    key, _ = _probe_keys(probe, probe_key_exprs, key_domains, null_safe)
    lo, hi = _lookup_range(build, key)
    counts = jnp.where(probe.row_mask, hi - lo, 0)
    if kind == "left":
        counts = jnp.where(probe.row_mask & (counts == 0), 1, counts)
    offsets = jnp.cumsum(counts) - counts
    total = jnp.sum(counts)

    out_idx = jnp.arange(out_capacity)
    # probe row for each output slot
    p_row = jnp.searchsorted(offsets, out_idx, side="right") - 1
    p_row = jnp.clip(p_row, 0, probe.capacity - 1).astype(jnp.int32)
    j = out_idx - offsets[p_row]
    live_out = out_idx < total
    b_pos = jnp.clip(lo[p_row] + j, 0, build.capacity - 1)
    matched = j < (hi[p_row] - lo[p_row])  # false only for left-join null rows
    b_row = build.perm[b_pos]

    out_blocks: List[Block] = []
    for b in probe.blocks:
        out_blocks.append(
            Block(b.data[p_row], b.valid[p_row] & live_out, b.type, b.dictionary)
        )
    if build_output is None:
        build_output = range(len(build.page.blocks))
    for i in build_output:
        b = build.page.blocks[i]
        out_blocks.append(
            Block(b.data[b_row], b.valid[b_row] & matched & live_out, b.type, b.dictionary)
        )
    out_page = Page(tuple(out_blocks), live_out)
    if return_matched:
        b_matched = jnp.zeros((build.page.capacity,), dtype=jnp.bool_)
        b_matched = b_matched.at[b_row].max(matched & live_out, mode="drop")
        return out_page, total, b_matched
    return out_page, total


def outer_build_tail(
    build: JoinBuild,
    matched: jax.Array,
    probe_types_dicts: Sequence[Tuple],
    build_output: Optional[Sequence[int]] = None,
) -> Page:
    """FULL OUTER tail: build rows never matched by any probe page,
    null-extended on the probe columns. ``probe_types_dicts`` is
    [(Type, Dictionary|None)] for the probe side's output layout."""
    cap = build.page.capacity
    blocks: List[Block] = []
    for t, d in probe_types_dicts:
        blocks.append(
            Block(jnp.zeros((cap,) + t.value_shape, dtype=t.np_dtype),
                  jnp.zeros(cap, dtype=jnp.bool_), t, d)
        )
    if build_output is None:
        build_output = range(len(build.page.blocks))
    for i in build_output:
        blocks.append(build.page.blocks[i])
    return Page(tuple(blocks), build.page.row_mask & jnp.logical_not(matched))
