"""Sort and TopN kernels.

Reference analog: OrderByOperator (operator/OrderByOperator.java:30)
over PagesIndex with JIT'd comparators (sql/gen/OrderingCompiler.java),
and TopNOperator's bounded heap (operator/TopNOperator.java:35). Row
heaps don't vectorize; both become whole-array XLA sorts: multi-key
ORDER BY is a sequence of stable argsorts from the least-significant
key up (radix-style composition), and TopN is the same sort with the
consumer reading only the first n live rows via the row mask.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.page import Block, Page

def _dict_rank(page: Page, e: Expr, d: jax.Array) -> jax.Array:
    """Dictionary-encoded varchar sort keys order by VALUE, not code:
    codes map through the dictionary's cached collation-rank LUT
    (ops/aggregate._collation_luts — e.g. cd_gender's dictionary is
    ['M','F'], where code order would sort M before F)."""
    from presto_tpu.expr.ir import ColumnRef
    from presto_tpu.ops.aggregate import _collation_luts

    if not isinstance(e, ColumnRef) or not getattr(e.type, "is_string", False):
        return d
    if e.index >= len(page.blocks):
        return d
    dic = page.blocks[e.index].dictionary
    if dic is None:
        return d
    rank_lut, _ = _collation_luts(dic)
    codes = jnp.clip(d, 0, rank_lut.shape[0] - 1)
    return rank_lut[codes]


def _value_key(data: jax.Array, ascending: bool) -> jax.Array:
    """Exact sortable form of one key's values. Integers stay integral
    (no float64 round-trip — BIGINT/DECIMAL beyond 2^53 must order
    exactly); descending integers use bitwise complement (~x = -x-1,
    overflow-free), descending floats negate.  Long-decimal limb
    matrices go through the multi-pass path in sort_perm, not here."""
    if data.ndim > 1:
        raise ValueError(
            "limb sort keys take the per-limb radix path (sort_perm)")
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int32)
    if jnp.issubdtype(data.dtype, jnp.floating):
        return -data if not ascending else data
    return jnp.invert(data) if not ascending else data


def sort_perm(
    page: Page,
    sort_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    nulls_first: Optional[Sequence[bool]] = None,
) -> jax.Array:
    """Permutation ordering live rows by the sort keys; dead rows go
    last. Stable composition from the least-significant key up; each
    key is two stable passes (values, then a null-rank pass) so NULL
    ordering is exact without sentinel values colliding with real
    data."""
    c = ExprCompiler.for_page(page)
    if nulls_first is None:
        nulls_first = [False] * len(sort_exprs)
    perm = jnp.arange(page.capacity)
    for e, asc, nf in list(zip(sort_exprs, ascending, nulls_first))[::-1]:
        d, v = c.compile(e)(page)
        d = _dict_rank(page, e, d)
        if e.type.is_raw_string and d.ndim > 1:
            # lexicographic byte order = stable radix passes from the
            # last byte column to the first (static width unrolls)
            for j in range(d.shape[-1] - 1, -1, -1):
                kb = _value_key(d[:, j].astype(jnp.int32), asc)
                perm = perm[jnp.argsort(kb[perm], stable=True)]
        elif e.type.is_long_decimal and d.ndim > 1:
            # long decimals (widened sums, p>18 columns): the canonical
            # limb form IS value order (msb-first digits, limbs[1:]
            # non-negative — ops/decimal128.compare), so the same
            # stable radix composition as raw strings works limb-wise;
            # ~x on each int64 limb inverts the order exactly
            for j in range(d.shape[-1] - 1, -1, -1):
                kb = _value_key(d[:, j], asc)
                perm = perm[jnp.argsort(kb[perm], stable=True)]
        else:
            k = _value_key(d, asc)
            perm = perm[jnp.argsort(k[perm], stable=True)]
        null_rank = jnp.where(v, 1, 0) if nf else jnp.where(v, 0, 1)
        perm = perm[jnp.argsort(null_rank[perm], stable=True)]
    # dead rows to the end, preserving key order among live rows
    dead = jnp.logical_not(page.row_mask)[perm]
    perm = perm[jnp.argsort(dead, stable=True)]
    return perm


def gather_page(page: Page, perm: jax.Array, live: Optional[jax.Array] = None) -> Page:
    blocks: List[Block] = []
    for b in page.blocks:
        blocks.append(Block(b.data[perm], b.valid[perm], b.type, b.dictionary))
    mask = page.row_mask[perm] if live is None else live
    return Page(tuple(blocks), mask)


def sort_page(
    page: Page,
    sort_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    nulls_first: Optional[Sequence[bool]] = None,
) -> Page:
    perm = sort_perm(page, sort_exprs, ascending, nulls_first)
    return gather_page(page, perm)


def topn_page(
    page: Page,
    sort_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    n: int,
    nulls_first: Optional[Sequence[bool]] = None,
) -> Page:
    """Sorted page keeping only the first n live rows."""
    out = sort_page(page, sort_exprs, ascending, nulls_first)
    keep = jnp.arange(page.capacity) < n
    return Page(out.blocks, out.row_mask & keep)


def topn_compact_page(
    page: Page,
    sort_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    n: int,
    nulls_first: Optional[Sequence[bool]] = None,
) -> Page:
    """Top-n rows COMPACTED to an n-capacity page: the per-shard bound
    of a distributed TopN (CreatePartialTopN.java role) — each shard
    ships n rows across the mesh gather instead of its whole output.
    Dead rows sort last, so the first n rows of the sorted page are
    exactly the live top n."""
    if n >= page.capacity:
        return sort_page(page, sort_exprs, ascending, nulls_first)
    out = sort_page(page, sort_exprs, ascending, nulls_first)
    blocks = tuple(
        Block(b.data[:n], b.valid[:n], b.type, b.dictionary)
        for b in out.blocks)
    return Page(blocks, out.row_mask[:n])


def limit_compact_page(page: Page, n: int) -> Page:
    """First n live rows compacted to an n-capacity page (the
    per-shard bound of a distributed Limit)."""
    if n >= page.capacity:
        return limit_page(page, n)
    live = limit_page(page, n)
    order = jnp.argsort(~live.row_mask, stable=True)[:n]
    blocks = tuple(
        Block(jnp.take(b.data, order, axis=0), jnp.take(b.valid, order),
              b.type, b.dictionary)
        for b in live.blocks)
    return Page(blocks, jnp.take(live.row_mask, order))


def limit_page(page: Page, n: int) -> Page:
    """First n live rows in current order (LimitOperator analog).
    int32 running count: int64 scans are emulated (and observed
    pathological) on TPU."""
    seen = jnp.cumsum(page.row_mask.astype(jnp.int32))
    return Page(page.blocks, page.row_mask & (seen <= n))
