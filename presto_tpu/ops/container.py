"""ARRAY / MAP container kernels.

Reference analog: spi/type/ArrayType.java + spi/block/ArrayBlock.java
(offset-indexed variable-length element runs) and MapType/MapBlock, plus
the scalar array/map functions in presto-main operator/scalar/
(ArrayFunctions, CardinalityFunction, ArrayContains, ArrayMinMax,
MapKeys, MapValues, ElementAt...).

TPU-first re-design: a container column is a dense
``(capacity, 1 + slots)`` matrix in one storage dtype.  Slot 0 holds
the length (entry count for maps), the remaining slots hold elements
padded with a null sentinel (INT_MIN / NaN).  Every function below is a
masked reduction or gather over the trailing axis — static shapes, no
per-row interpretation, everything fuses in XLA.

Layout:
  array:  [len, e1..emax]
  map:    [len, k1..kmax, v1..vmax]
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.types import Type, null_sentinel

_I64_MIN = np.iinfo(np.int64).min


# ---------------------------------------------------------------------------
# host encode / decode (page construction and result materialization)
# ---------------------------------------------------------------------------

def encode_arrays(values: Sequence[Optional[list]], t: Type,
                  capacity: int) -> np.ndarray:
    """Encode python lists into the (capacity, 1+max) matrix.  ``None``
    rows encode as length 0 (row NULL-ness lives in Block.valid);
    ``None`` elements encode as the storage sentinel."""
    max_elems = t.max_elems
    storage = t.np_dtype
    sent = null_sentinel(storage)
    out = np.full((capacity, 1 + max_elems), sent, dtype=storage)
    out[:, 0] = 0
    elem = t.element
    for i, v in enumerate(values):
        if v is None:
            continue
        n = min(len(v), max_elems)
        if len(v) > max_elems:
            raise ValueError(
                f"array literal of {len(v)} elements exceeds the column's "
                f"static capacity {max_elems} (declare array(T, N) wider)")
        out[i, 0] = n
        for j, e in enumerate(v[:n]):
            out[i, 1 + j] = sent if e is None else _encode_scalar(e, elem)
    return out


def encode_maps(values: Sequence[Optional[dict]], t: Type,
                capacity: int) -> np.ndarray:
    max_elems = t.max_elems
    storage = t.np_dtype
    sent = null_sentinel(storage)
    out = np.full((capacity, 1 + 2 * max_elems), sent, dtype=storage)
    out[:, 0] = 0
    for i, v in enumerate(values):
        if v is None:
            continue
        items = list(v.items())
        if len(items) > max_elems:
            raise ValueError(
                f"map of {len(items)} entries exceeds static capacity {max_elems}")
        out[i, 0] = len(items)
        for j, (k, val) in enumerate(items):
            out[i, 1 + j] = _encode_scalar(k, t.key_element)
            out[i, 1 + max_elems + j] = sent if val is None else _encode_scalar(val, t.element)
    return out


def _encode_scalar(v, t: Type):
    if t.is_string:
        raise ValueError(
            "string container elements must be pre-coded to dictionary "
            "codes before encode (binder resolves literals)")
    if t.is_decimal:
        return int(round(float(v) * 10 ** (t.scale or 0)))
    if t.name == "boolean":
        return int(bool(v))
    return v


def _decode_scalar(v, t: Type, dictionary=None):
    if t.is_string:
        code = int(v)
        if dictionary is not None and 0 <= code < len(dictionary):
            return dictionary.values[code]
        return None
    if t.name in ("double", "real"):
        return float(v)
    if t.is_decimal:
        return float(v) / 10 ** (t.scale or 0)
    if t.name == "boolean":
        return bool(v)
    return int(v)


def _is_null_slot(x, storage: np.dtype) -> bool:
    if storage.kind == "f":
        return bool(np.isnan(x))
    return int(x) == np.iinfo(storage).min


def decode_arrays(data: np.ndarray, t: Type, dictionary=None) -> List[list]:
    """(n, 1+max) matrix -> python lists (row validity handled by caller)."""
    out = []
    storage = t.np_dtype
    for row in data:
        n = int(row[0]) if not _is_null_slot(row[0], storage) else 0
        out.append([
            None if _is_null_slot(x, storage) else _decode_scalar(x, t.element, dictionary)
            for x in row[1 : 1 + n]
        ])
    return out


def decode_maps(data: np.ndarray, t: Type, dictionary=None) -> List[dict]:
    out = []
    storage = t.np_dtype
    m = t.max_elems
    if t.element is not None and t.element.is_array:
        # multimap layout: [count, keys(m), value-arrays(m x (1+av))]
        av = 1 + t.element.max_elems
        for row in data:
            n = int(row[0]) if not _is_null_slot(row[0], storage) else 0
            d = {}
            for j in range(n):
                k = _decode_scalar(row[1 + j], t.key_element, dictionary)
                vrow = row[1 + m + j * av: 1 + m + (j + 1) * av]
                d[k] = decode_arrays(vrow[None, :], t.element)[0]
            out.append(d)
        return out
    for row in data:
        n = int(row[0]) if not _is_null_slot(row[0], storage) else 0
        d = {}
        for j in range(n):
            k = _decode_scalar(row[1 + j], t.key_element, dictionary)
            v = row[1 + m + j]
            d[k] = None if _is_null_slot(v, storage) else _decode_scalar(v, t.element)
        out.append(d)
    return out


def decode_rows(data: np.ndarray, t: Type) -> List[tuple]:
    """(n, nfields) row matrix -> python tuples (row validity is the
    caller's; NULL fields decode as None)."""
    out = []
    storage = t.np_dtype
    for r in data:
        out.append(tuple(
            None if _is_null_slot(x, storage) else _decode_scalar(x, ft)
            for x, ft in zip(r, t.fields)))
    return out


def construct_row(field_datas, field_valids, t: Type) -> jax.Array:
    """row(e1..en): stack per-row scalars into the (n, nfields) matrix
    with NULL fields as the storage sentinel."""
    storage = t.np_dtype
    sent = _null_const(storage)
    cols = [jnp.where(v, d.astype(storage), sent)
            for d, v in zip(field_datas, field_valids)]
    return jnp.stack(cols, axis=1)


def row_field(data: jax.Array, t: Type, i: int):
    """1-based field access: (values, non-null mask)."""
    ft = t.fields[i - 1]
    col = data[:, i - 1]
    nn = ~elem_null_mask(col)
    if ft.is_decimal:
        out = col.astype(jnp.int64)
    else:
        out = col.astype(ft.np_dtype)
    return out, nn


# ---------------------------------------------------------------------------
# device kernels (used by the expression compiler)
# ---------------------------------------------------------------------------

def _null_const(storage) -> jax.Array:
    if jnp.issubdtype(storage, jnp.floating):
        return jnp.asarray(jnp.nan, dtype=storage)
    return jnp.asarray(jnp.iinfo(storage).min, dtype=storage)


def slot_mask(data: jax.Array, nslots: int) -> jax.Array:
    """(n, slots) bool: slot j live iff j < len (slot 0 excluded)."""
    length = lengths(data)
    return jnp.arange(nslots)[None, :] < length[:, None]


def lengths(data: jax.Array) -> jax.Array:
    l0 = data[:, 0]
    if jnp.issubdtype(data.dtype, jnp.floating):
        l0 = jnp.where(jnp.isnan(l0), 0.0, l0)
    return jnp.maximum(l0.astype(jnp.int64), 0)


def elem_slots(data: jax.Array, t: Type) -> jax.Array:
    """Element slots of an array value: (n, max)."""
    return data[:, 1 : 1 + t.max_elems]


def map_key_slots(data: jax.Array, t: Type) -> jax.Array:
    return data[:, 1 : 1 + t.max_elems]


def map_value_slots(data: jax.Array, t: Type) -> jax.Array:
    m = t.max_elems
    return data[:, 1 + m : 1 + 2 * m]


def elem_null_mask(slots: jax.Array) -> jax.Array:
    """True where an element slot holds the null sentinel."""
    if jnp.issubdtype(slots.dtype, jnp.floating):
        return jnp.isnan(slots)
    return slots == jnp.iinfo(slots.dtype).min


def construct_array(elem_datas: Sequence[jax.Array],
                    elem_valids: Sequence[jax.Array], t: Type) -> jax.Array:
    """ARRAY[e1..en] constructor: stack per-row scalars into the matrix."""
    n = elem_datas[0].shape[0] if elem_datas else 0
    storage = t.np_dtype
    sent = _null_const(storage)
    cols = [jnp.full((n,), float(len(elem_datas)), dtype=storage)
            if storage.kind == "f"
            else jnp.full((n,), len(elem_datas), dtype=storage)]
    for d, v in zip(elem_datas, elem_valids):
        cols.append(jnp.where(v, d.astype(storage), sent))
    pad = t.max_elems - len(elem_datas)
    for _ in range(pad):
        cols.append(jnp.full((n,), sent, dtype=storage))
    return jnp.stack(cols, axis=1)


def subscript(data: jax.Array, t: Type, idx: jax.Array, idx_valid: jax.Array):
    """arr[i] (1-based) / map[k]: returns (value, valid).  Out-of-range
    or missing-key access yields NULL (reference element_at semantics;
    the subscript form raises there — deviation noted)."""
    if t.is_map:
        return map_get(data, t, idx, idx_valid)
    length = lengths(data)
    i0 = idx.astype(jnp.int64) - 1
    ok = idx_valid & (i0 >= 0) & (i0 < length)
    gathered = jnp.take_along_axis(
        elem_slots(data, t), jnp.clip(i0, 0, t.max_elems - 1)[:, None], axis=1
    )[:, 0]
    valid = ok & ~elem_null_mask(gathered)
    return gathered, valid


def map_get(data: jax.Array, t: Type, key: jax.Array, key_valid: jax.Array):
    keys = map_key_slots(data, t)
    vals = map_value_slots(data, t)
    live = slot_mask(data, t.max_elems)
    hit = live & (keys == key.astype(keys.dtype)[:, None]) & key_valid[:, None]
    any_hit = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1)
    v = jnp.take_along_axis(vals, first[:, None], axis=1)[:, 0]
    return v, any_hit & ~elem_null_mask(v)


def cardinality(data: jax.Array) -> jax.Array:
    return lengths(data)


def contains(data: jax.Array, t: Type, x: jax.Array, x_valid: jax.Array):
    slots = elem_slots(data, t)
    live = slot_mask(data, t.max_elems) & ~elem_null_mask(slots)
    hit = live & (slots == x.astype(slots.dtype)[:, None])
    return jnp.any(hit, axis=1), x_valid


def array_position(data: jax.Array, t: Type, x: jax.Array, x_valid: jax.Array):
    slots = elem_slots(data, t)
    live = slot_mask(data, t.max_elems) & ~elem_null_mask(slots)
    hit = live & (slots == x.astype(slots.dtype)[:, None])
    any_hit = jnp.any(hit, axis=1)
    pos = jnp.where(any_hit, jnp.argmax(hit, axis=1) + 1, 0)
    return pos.astype(jnp.int64), x_valid


def array_reduce(data: jax.Array, t: Type, fn: str):
    """array_min / array_max / array_sum / array_average over the slots."""
    slots = elem_slots(data, t)
    live = slot_mask(data, t.max_elems) & ~elem_null_mask(slots)
    n = jnp.sum(live.astype(jnp.int64), axis=1)
    storage = slots.dtype
    if fn in ("array_min", "array_max"):
        if jnp.issubdtype(storage, jnp.floating):
            fill = jnp.asarray(jnp.inf if fn == "array_min" else -jnp.inf, storage)
        else:
            info = jnp.iinfo(storage)
            fill = jnp.asarray(info.max if fn == "array_min" else info.min + 1, storage)
        red = jnp.min if fn == "array_min" else jnp.max
        out = red(jnp.where(live, slots, fill), axis=1)
        return out, n > 0
    s = jnp.sum(jnp.where(live, slots, jnp.zeros_like(slots)), axis=1)
    if fn == "array_sum":
        return s, n > 0
    return s.astype(jnp.float64) / jnp.maximum(n, 1).astype(jnp.float64), n > 0


def array_sort(data: jax.Array, t: Type) -> jax.Array:
    """Sort elements ascending, NULL elements last (reference
    ArraySortFunction null-last semantics)."""
    slots = elem_slots(data, t)
    live = slot_mask(data, t.max_elems)
    isnull = elem_null_mask(slots)
    storage = slots.dtype
    if jnp.issubdtype(storage, jnp.floating):
        # values sort to the front (nan keys last for nulls AND dead
        # slots alike); the non-null count nn is the boundary between
        # sorted values and trailing nulls — real +/-inf values sort as
        # ordinary values this way
        sort_key = jnp.where(live & ~isnull, slots, jnp.asarray(jnp.nan, storage))
        sorted_ = jnp.sort(sort_key, axis=1)
        j = jnp.arange(t.max_elems)[None, :]
        nn = jnp.sum((live & ~isnull).astype(jnp.int64), axis=1)[:, None]
        back = jnp.where(j < nn, sorted_, jnp.asarray(jnp.nan, storage))
    else:
        info = jnp.iinfo(storage)
        sort_key = jnp.where(live & ~isnull, slots.astype(jnp.int64),
                             jnp.int64(info.max))
        # null elements sort between values and dead slots
        sort_key = jnp.where(live & isnull, jnp.int64(info.max) - 1, sort_key)
        sorted_ = jnp.sort(sort_key, axis=1)
        n_live = lengths(data)
        j = jnp.arange(t.max_elems)[None, :]
        nn = jnp.sum((live & ~isnull).astype(jnp.int64), axis=1)[:, None]
        back = jnp.where(j < nn, sorted_, jnp.int64(info.min)).astype(storage)
        back = jnp.where(j < n_live[:, None], back, jnp.int64(info.min).astype(storage))
    return jnp.concatenate([data[:, :1], back], axis=1)


def array_distinct(data: jax.Array, t: Type) -> jax.Array:
    """Distinct elements, first-occurrence order dropped in favor of
    sorted order (deviation: reference keeps first occurrence; sorted
    is the shape-static TPU formulation).  Pads are separated from real
    extreme values (INT64_MAX / +inf) by position against the non-null
    count, never by value comparison."""
    slots = elem_slots(data, t)
    live = slot_mask(data, t.max_elems)
    isnull = elem_null_mask(slots)
    storage = slots.dtype
    j = jnp.arange(t.max_elems)[None, :]
    nn = jnp.sum((live & ~isnull).astype(jnp.int64), axis=1)
    had_null = jnp.any(live & isnull, axis=1)
    floating = jnp.issubdtype(storage, jnp.floating)
    if floating:
        pad = jnp.asarray(jnp.nan, storage)  # nan sorts last
        s = jnp.sort(jnp.where(live & ~isnull, slots, pad), axis=1)
        sent = jnp.asarray(jnp.nan, storage)
    else:
        info = jnp.iinfo(storage)
        pad = jnp.asarray(info.max, jnp.int64)
        s = jnp.sort(jnp.where(live & ~isnull, slots.astype(jnp.int64), pad), axis=1)
        sent = jnp.int64(info.min)
    # first occurrence among the leading nn sorted values
    keep = jnp.concatenate(
        [jnp.ones_like(s[:, :1], jnp.bool_), s[:, 1:] != s[:, :-1]], axis=1
    ) & (j < nn[:, None])
    # compact kept values to a prefix: stable argsort on the drop flag
    # preserves ascending value order among the kept slots
    order = jnp.argsort(~keep, axis=1, stable=True)
    comp = jnp.take_along_axis(s, order, axis=1)
    nkeep = jnp.sum(keep.astype(jnp.int64), axis=1)
    out = jnp.where(j < nkeep[:, None], comp, sent)
    total = nkeep + had_null.astype(jnp.int64)
    if floating:
        return jnp.concatenate([total[:, None].astype(storage), out], axis=1)
    return jnp.concatenate([total[:, None], out], axis=1).astype(storage)


def slice_array(data: jax.Array, t: Type, start: int, length: int) -> jax.Array:
    """slice(arr, start, length) — 1-based; negative start counts from
    the array end (ArraySliceFunction semantics); static offsets keep
    shapes fixed.  start==0 / negative length reject at bind time."""
    n = lengths(data)
    slots = elem_slots(data, t)
    if start > 0:
        base = jnp.full_like(n, start - 1)
    else:
        base = jnp.maximum(n + start, 0)
    avail = jnp.clip(jnp.minimum(n - base, length), 0, None)
    M = t.max_elems
    j = jnp.arange(M)[None, :]
    src = jnp.clip(j + base[:, None], 0, M - 1)
    gathered = jnp.take_along_axis(slots, src, axis=1)
    sent = _null_const(slots.dtype)
    out = jnp.where(j < avail[:, None], gathered, sent)
    return jnp.concatenate([avail[:, None].astype(data.dtype), out], axis=1)


def coerce_slots(slots: jax.Array, from_t: Type, to_t: Type,
                 storage) -> jax.Array:
    """Element-wise conversion of container slots between scalar types,
    preserving NULL sentinels across storage dtypes (the container
    analog of the expression compiler's _coerce)."""
    isnull = elem_null_mask(slots)
    vals = slots
    if from_t.is_decimal or to_t.is_decimal:
        fs = from_t.scale or 0 if from_t.is_decimal else 0
        tscale = to_t.scale or 0 if to_t.is_decimal else 0
        if to_t.name == "double":
            vals = vals.astype(jnp.float64) / (10.0 ** fs)
        elif to_t.is_decimal:
            if from_t.name == "double":
                vals = jnp.round(vals * (10.0 ** tscale))
            elif tscale >= fs:
                vals = vals.astype(jnp.int64) * (10 ** (tscale - fs))
            else:
                vals = vals.astype(jnp.int64) // (10 ** (fs - tscale))
    vals = vals.astype(storage)
    sent = _null_const(storage)
    return jnp.where(isnull, sent, vals)


def concat_arrays(a: jax.Array, ta: Type, b: jax.Array, tb: Type,
                  out_t: Type) -> jax.Array:
    """a || b element concatenation (ArrayConcatFunction analog)."""
    la, lb = lengths(a), lengths(b)
    M = out_t.max_elems
    storage = out_t.np_dtype
    sent = _null_const(storage)
    elem_t = out_t.element
    sa = coerce_slots(elem_slots(a, ta), ta.element, elem_t, storage)
    sb = coerce_slots(elem_slots(b, tb), tb.element, elem_t, storage)
    wa = sa.shape[1]
    j = jnp.arange(M)[None, :]
    from_a = j < la[:, None]
    # position j takes a[j] when j < la, else b[j - la]
    bj = jnp.clip(j - la[:, None], 0, sb.shape[1] - 1)
    b_vals = jnp.take_along_axis(sb, bj, axis=1)
    a_pad = jnp.concatenate(
        [sa, jnp.full((sa.shape[0], M - wa), sent, dtype=storage)], axis=1)
    out = jnp.where(from_a, a_pad, b_vals)
    total = la + lb
    out = jnp.where(j < total[:, None], out, sent)
    return jnp.concatenate([total[:, None].astype(storage), out], axis=1)


def map_keys_array(data: jax.Array, t: Type, out_t: Type) -> jax.Array:
    """map_keys(m) -> array of keys (order = insertion order)."""
    n = lengths(data)
    keys = map_key_slots(data, t).astype(out_t.np_dtype)
    return jnp.concatenate([n[:, None].astype(out_t.np_dtype), keys], axis=1)


def map_values_array(data: jax.Array, t: Type, out_t: Type) -> jax.Array:
    n = lengths(data)
    vals = map_value_slots(data, t).astype(out_t.np_dtype)
    return jnp.concatenate([n[:, None].astype(out_t.np_dtype), vals], axis=1)


def unnest_expand(page, unnest_exprs, ordinality: bool, out_types):
    """Expand container columns to one row per element (UnnestOperator
    analog).  Output capacity = capacity * M where M is the widest
    static slot count; row r, slot j maps to output position r*M+j,
    live iff the source row is live and j < max(len over args) — rows
    whose containers are all empty/NULL produce nothing, shorter args
    NULL-pad (reference UNNEST multi-argument semantics)."""
    from presto_tpu.expr.compile import ExprCompiler
    from presto_tpu.page import Block, Page

    c = ExprCompiler.for_page(page)
    cap = page.capacity
    M = max(e.type.max_elems for e in unnest_exprs)
    rep = lambda a: jnp.repeat(a, M, axis=0)
    slot_j = jnp.tile(jnp.arange(M, dtype=jnp.int64), cap)

    evaluated = [(c.compile(e)(page), e.type) for e in unnest_exprs]
    total_len = jnp.zeros(cap, dtype=jnp.int64)
    for (d, v), t in evaluated:
        total_len = jnp.maximum(total_len, jnp.where(v, lengths(d), 0))
    live = rep(page.row_mask) & (slot_j < rep(total_len))

    out_blocks = []
    ti = 0
    for b in page.blocks:
        out_blocks.append(Block(rep(b.data), rep(b.valid) & live, b.type, b.dictionary))
        ti += 1

    def elem_block(slots, n_slots, t_elem, dictionary, v_container):
        pad = M - slots.shape[1]
        if pad:
            sent = _null_const(slots.dtype)
            slots = jnp.concatenate(
                [slots, jnp.full((cap, pad), sent, dtype=slots.dtype)], axis=1)
        flat = slots.reshape(cap * M)
        ev = (rep(v_container) & live & (slot_j < rep(n_slots))
              & ~elem_null_mask(flat))
        return Block(flat.astype(t_elem.np_dtype), ev, t_elem, dictionary)

    for (d, v), t in evaluated:
        n = jnp.where(v, lengths(d), 0)
        elem_dict = out_types[ti].dictionary if hasattr(out_types[ti], "dictionary") else None
        if t.is_map:
            key_dict = elem_dict
            out_blocks.append(elem_block(map_key_slots(d, t), n, t.key_element, key_dict, v))
            ti += 1
            val_dict = out_types[ti].dictionary if hasattr(out_types[ti], "dictionary") else None
            out_blocks.append(elem_block(map_value_slots(d, t), n, t.element, val_dict, v))
            ti += 1
        else:
            out_blocks.append(elem_block(elem_slots(d, t), n, t.element, elem_dict, v))
            ti += 1

    if ordinality:
        from presto_tpu.types import BIGINT

        out_blocks.append(Block(slot_j + 1, live, BIGINT))

    return Page(tuple(out_blocks), live)


def construct_map(keys: jax.Array, key_t: Type, values: jax.Array,
                  val_t: Type, out_t: Type) -> jax.Array:
    """map(array_k, array_v) constructor: zip two array columns."""
    n = jnp.minimum(lengths(keys), lengths(values))
    m = out_t.max_elems
    storage = out_t.np_dtype
    k = elem_slots(keys, key_t)[:, :m].astype(storage)
    v = elem_slots(values, val_t)[:, :m].astype(storage)
    sent = _null_const(storage)
    live = jnp.arange(m)[None, :] < n[:, None]
    k = jnp.where(live, k, sent)
    v = jnp.where(live, v, sent)
    return jnp.concatenate([n[:, None].astype(storage), k, v], axis=1)


# ---------------------------------------------------------------------------
# set algebra (ArrayIntersect/Union/Except/ArraysOverlap/ArrayRemove,
# MapConcatFunction) — membership is one (rows, Ma, Mb) broadcast
# compare; compaction is the array_filter argsort pattern.  No scalar
# loops; shapes stay static for XLA.
# ---------------------------------------------------------------------------

def _row_compact(slots, keep, cap_out, storage):
    """Order-preserving per-row compaction of kept slots into a
    [len, vals..] array matrix with cap_out value lanes."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    comp = jnp.take_along_axis(slots, order, axis=1).astype(storage)
    n = jnp.sum(keep.astype(jnp.int64), axis=1)
    M = slots.shape[1]
    sent = _null_const(storage)
    if cap_out > M:
        comp = jnp.concatenate(
            [comp, jnp.full((comp.shape[0], cap_out - M), sent, storage)],
            axis=1)
    elif cap_out < M:
        comp = comp[:, :cap_out]
        n = jnp.minimum(n, cap_out)
    j = jnp.arange(cap_out)[None, :]
    vals = jnp.where(j < n[:, None], comp, sent)
    return jnp.concatenate([n[:, None].astype(storage), vals], axis=1)


def _membership(a, ta, b, tb):
    """Per-slot masks for the set ops: a's slots (ORIGINAL storage, for
    compaction into the left-typed output), live/null masks, whether
    each a-slot's value appears among b's non-null live slots, and
    whether b holds any null element.  Values compare in the common
    super type via coerce_slots (decimal rescaling included) — a raw
    astype would truncate 2.5 to 2 and call it a match."""
    from presto_tpu.types import common_super_type

    sa = elem_slots(a, ta)
    sb = elem_slots(b, tb)
    la, na = slot_mask(a, ta.max_elems), elem_null_mask(sa)
    lb, nb = slot_mask(b, tb.max_elems), elem_null_mask(sb)
    cmp_t = common_super_type(ta.element, tb.element)
    sa_c = coerce_slots(sa, ta.element, cmp_t, cmp_t.np_dtype)
    sb_c = coerce_slots(sb, tb.element, cmp_t, cmp_t.np_dtype)
    b_live_nn = lb & ~nb
    member = jnp.any(
        (sa_c[:, :, None] == sb_c[:, None, :]) & b_live_nn[:, None, :],
        axis=2)
    b_has_null = jnp.any(lb & nb, axis=1)
    return sa, la, na, member, b_has_null


def array_intersect(a: jax.Array, ta: Type, b: jax.Array, tb: Type,
                    out_t: Type) -> jax.Array:
    """Deduplicated intersection; NULL intersects when both sides hold
    a NULL element (sorted output order — the array_distinct
    deviation)."""
    storage = out_t.np_dtype
    sa, la, na, member, b_null = _membership(a, ta, b, tb)
    keep = la & ((~na & member) | (na & b_null[:, None]))
    return array_distinct(_row_compact(sa, keep, out_t.max_elems, storage),
                          out_t)


def array_except(a: jax.Array, ta: Type, b: jax.Array, tb: Type,
                 out_t: Type) -> jax.Array:
    storage = out_t.np_dtype
    sa, la, na, member, b_null = _membership(a, ta, b, tb)
    keep = la & ((~na & ~member) | (na & ~b_null[:, None]))
    return array_distinct(_row_compact(sa, keep, out_t.max_elems, storage),
                          out_t)


def array_union(a: jax.Array, ta: Type, b: jax.Array, tb: Type,
                out_t: Type) -> jax.Array:
    return array_distinct(concat_arrays(a, ta, b, tb, out_t), out_t)


def arrays_overlap(a: jax.Array, ta: Type, b: jax.Array, tb: Type):
    """(bool, valid): TRUE on a shared non-null element; NULL when no
    match but either side holds a NULL element (ANSI three-valued)."""
    sa, la, na, member, b_null = _membership(a, ta, b, tb)
    match = jnp.any(la & ~na & member, axis=1)
    a_null = jnp.any(la & na, axis=1)
    return match, match | ~(a_null | b_null)


def array_remove(a: jax.Array, ta: Type, x: jax.Array) -> jax.Array:
    """Drop elements equal to x; NULL elements stay
    (ArrayRemoveFunction — a NULL x nulls the result, handled by the
    caller's validity)."""
    storage = ta.np_dtype
    sa = elem_slots(a, ta)
    la, na = slot_mask(a, ta.max_elems), elem_null_mask(sa)
    keep = la & (na | (sa != x.astype(storage)[:, None]))
    return _row_compact(sa, keep, ta.max_elems, storage)


def map_concat(m1: jax.Array, t1: Type, m2: jax.Array, t2: Type,
               out_t: Type) -> jax.Array:
    """Key union with the LAST map's value winning on duplicates
    (MapConcatFunction) — m1 entries shadowed by an m2 key DROP, so
    device lookups, host decodes and the reference all agree."""
    storage = out_t.np_dtype
    cap = out_t.max_elems
    k1 = coerce_slots(map_key_slots(m1, t1), t1.key_element,
                      out_t.key_element, storage)
    k2 = coerce_slots(map_key_slots(m2, t2), t2.key_element,
                      out_t.key_element, storage)
    v1 = coerce_slots(map_value_slots(m1, t1), t1.element,
                      out_t.element, storage)
    v2 = coerce_slots(map_value_slots(m2, t2), t2.element,
                      out_t.element, storage)
    live1 = slot_mask(m1, t1.max_elems)
    live2 = slot_mask(m2, t2.max_elems)
    shadowed = jnp.any(
        (k1[:, :, None] == k2[:, None, :]) & live2[:, None, :], axis=2)
    k = jnp.concatenate([k1, k2], axis=1)
    v = jnp.concatenate([v1, v2], axis=1)
    keep = jnp.concatenate([live1 & ~shadowed, live2], axis=1)
    return compact_entry_pairs(k, v, keep, cap, storage)


def compact_entry_pairs(ks: jax.Array, vs: jax.Array, keep: jax.Array,
                        cap: int, storage) -> jax.Array:
    """Order-preserving compaction of kept (key, value) entry pairs
    into a [len, keys.., vals..] map matrix with cap entry lanes —
    shared by map_filter / transform_keys / map_concat."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    kc = jnp.take_along_axis(ks, order, axis=1).astype(storage)
    vc = jnp.take_along_axis(vs, order, axis=1).astype(storage)
    n = jnp.minimum(jnp.sum(keep.astype(jnp.int64), axis=1), cap)
    sent = _null_const(storage)
    j = jnp.arange(kc.shape[1])[None, :]
    kc = jnp.where(j < n[:, None], kc, sent)[:, :cap]
    vc = jnp.where(j < n[:, None], vc, sent)[:, :cap]
    if cap > kc.shape[1]:
        pad = jnp.full((kc.shape[0], cap - kc.shape[1]), sent, storage)
        kc = jnp.concatenate([kc, pad], axis=1)
        vc = jnp.concatenate([vc, pad], axis=1)
    return jnp.concatenate([n[:, None].astype(storage), kc, vc], axis=1)
