"""Order-preserving merge of pre-sorted pages.

Reference analog: ``operator/MergeOperator.java:45`` +
``operator/MergeHashSort.java`` — the consumer-side k-way merge that
keeps distributed sort distributed: each producer sorts its partition,
the consumer merges without re-sorting.

TPU re-design: no scalar heap walk.  Each row gets one int64
total-order key (floats map through the IEEE-754 order-isomorphic
bit trick; multi-key specs pack lanes by their observed ranges); two
sorted runs then merge with two ``searchsorted`` rank computations and
one scatter — an element's output position is its own rank plus its
rank in the other run.  k runs fold pairwise (log k rounds); ties
break toward the earlier run, so the fold is stable across producers.
Specs that cannot form a single exact key (e.g. several float lanes)
fall back to concatenate+sort, which is still correct.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.page import Block, Page

_I64_MAX = jnp.iinfo(jnp.int64).max


def _float_order_bits(x: jax.Array) -> jax.Array:
    """IEEE-754 total-order map: float64 -> int64 with the same <."""
    i = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)
    return jnp.where(i < 0, jnp.int64(-1) ^ (i & _I64_MAX), i)


class _NoScalarKey(Exception):
    pass


def _raw_lane(page: Page, e: Expr, asc: bool):
    """Order-isomorphic int64 lane + validity, NULL/dead garbage NOT yet
    masked."""
    c = ExprCompiler.for_page(page)
    d, v = c.compile(e)(page)
    if d.ndim > 1:
        raise _NoScalarKey()
    from presto_tpu.ops.sort import _dict_rank

    d = _dict_rank(page, e, d)
    lane = (_float_order_bits(d)
            if jnp.issubdtype(d.dtype, jnp.floating)
            else d.astype(jnp.int64))
    if not asc:
        lane = ~lane
    return lane, v


def merge_keys_for_pages(pages: Sequence[Page], sort_exprs: Sequence[Expr],
                         ascending: Sequence[bool],
                         nulls_first: Optional[Sequence[bool]] = None):
    """One int64 total-order key per row for EVERY page jointly — lane
    ranges are global, so keys compare across producers.  Dead rows get
    INT64_MAX (the tail of each sorted page).  Raises _NoScalarKey when
    the combined lanes cannot pack into 62 bits.  Eager-only (ranges
    are read from device data to detect packing overflow)."""
    if nulls_first is None:
        nulls_first = [False] * len(sort_exprs)
    if len(sort_exprs) == 1:
        # single lane: the raw order-isomorphic lane is globally
        # comparable without packing; NULLs pin to the extremes
        # (collision with actual INT64_MIN+1/MAX-1 values is the
        # documented edge)
        e, a, nf = sort_exprs[0], ascending[0], nulls_first[0]
        null_key = jnp.iinfo(jnp.int64).min + 1 if nf else _I64_MAX - 1
        keys = []
        for p in pages:
            lane, v = _raw_lane(p, e, a)
            keys.append(jnp.where(p.row_mask,
                                  jnp.where(v, lane, null_key), _I64_MAX))
        return keys

    per_page_lanes = []  # [page][lane] = (masked_lane, valid)
    cards = []
    for li, (e, a, nf) in enumerate(zip(sort_exprs, ascending, nulls_first)):
        lanes = []
        lo, hi = None, None
        for p in pages:
            lane, v = _raw_lane(p, e, a)
            present = v & p.row_mask
            neutral = jnp.where(jnp.any(present), lane[jnp.argmax(present)], 0)
            lane = jnp.where(present, lane, neutral)
            lanes.append((lane, v))
            # one stacked transfer, not two blocking scalar pulls per
            # lane per page (engine_lint device-sync rule)
            lo_hi = jax.device_get(jnp.stack([jnp.min(lane), jnp.max(lane)]))
            plo, phi = int(lo_hi[0]), int(lo_hi[1])
            lo = plo if lo is None else min(lo, plo)
            hi = phi if hi is None else max(hi, phi)
        width = hi - lo + 1
        cards.append(width + 2)
        per_page_lanes.append([(lane - lo, v) for lane, v in lanes])
        null_key = -1 if nulls_first[li] else width
        per_page_lanes[-1] = [
            (jnp.where(v, lk, null_key) + 1, v) for lk, v in per_page_lanes[-1]
        ]
    total = 1
    for c in cards:
        total *= c
        if total >= (1 << 62):
            raise _NoScalarKey()
    keys = []
    for pi, p in enumerate(pages):
        key = jnp.zeros(p.capacity, dtype=jnp.int64)
        for li, card in enumerate(cards):
            key = key * card + per_page_lanes[li][pi][0]
        keys.append(jnp.where(p.row_mask, key, _I64_MAX))
    return keys


def merge_two_sorted(a: Page, b: Page, key_a: jax.Array,
                     key_b: jax.Array) -> Tuple[Page, jax.Array]:
    """Merge two sorted pages by per-row keys (dead rows at the tail
    with INT64_MAX keys)."""
    na, nb = a.capacity, b.capacity
    pos_a = jnp.arange(na) + jnp.searchsorted(key_b, key_a, side="left")
    pos_b = jnp.arange(nb) + jnp.searchsorted(key_a, key_b, side="right")
    n = na + nb
    blocks = []
    for ba, bb in zip(a.blocks, b.blocks):
        data = jnp.zeros((n,) + ba.data.shape[1:], dtype=ba.data.dtype)
        data = data.at[pos_a].set(ba.data).at[pos_b].set(bb.data)
        valid = jnp.zeros(n, dtype=jnp.bool_)
        valid = valid.at[pos_a].set(ba.valid).at[pos_b].set(bb.valid)
        blocks.append(Block(data, valid, ba.type, ba.dictionary or bb.dictionary))
    mask = jnp.zeros(n, dtype=jnp.bool_)
    mask = mask.at[pos_a].set(a.row_mask).at[pos_b].set(b.row_mask)
    key = jnp.full(n, _I64_MAX, dtype=jnp.int64)
    key = key.at[pos_a].set(key_a).at[pos_b].set(key_b)
    return Page(tuple(blocks), mask), key


def merge_sorted_pages(pages: Sequence[Page], sort_exprs: Sequence[Expr],
                       ascending: Sequence[bool],
                       nulls_first: Optional[Sequence[bool]] = None) -> Page:
    """k-way order-preserving merge of per-producer sorted pages;
    falls back to concatenate+sort when no exact scalar key exists."""
    from presto_tpu.exec.local import concat_pages_device
    from presto_tpu.ops.sort import sort_page

    if len(pages) == 1:
        return pages[0]
    try:
        keys = merge_keys_for_pages(pages, sort_exprs, ascending, nulls_first)
        items = list(zip(pages, keys))
    except _NoScalarKey:
        return sort_page(concat_pages_device(list(pages)), list(sort_exprs),
                         list(ascending), nulls_first)
    while len(items) > 1:
        nxt = []
        for i in range(0, len(items) - 1, 2):
            (pa, ka), (pb, kb) = items[i], items[i + 1]
            nxt.append(merge_two_sorted(pa, pb, ka, kb))
        if len(items) % 2:
            nxt.append(items[-1])
        items = nxt
    return items[0][0]
