"""Filter and projection over Pages.

Reference analog: FilterAndProjectOperator
(operator/FilterAndProjectOperator.java:31) + the JIT'd PageProcessor
(operator/project/PageProcessor.java:77-102). The reference evaluates a
compiled PageFilter into SelectedPositions then materializes projections
position-by-position; here the filter just ANDs into the row mask (no
compaction — selection is free on TPU and shapes stay static) and
projections are whole-column jnp computations that XLA fuses.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from presto_tpu.expr.compile import ExprCompiler, compile_filter
from presto_tpu.expr.ir import Expr
from presto_tpu.page import Block, Page


def filter_page(page: Page, predicate: Expr) -> Page:
    """Rows where predicate is not TRUE (false or NULL) are masked out."""
    return Page(page.blocks, compile_filter(predicate, page)(page))


def project_page(page: Page, projections: Sequence[Expr]) -> Page:
    """Produce a new Page with one block per projection expression.

    Dictionary provenance: a projection that is a bare ColumnRef keeps
    the source block's dictionary (dictionary-aware projection,
    DictionaryAwarePageProjection.java analog).
    """
    from presto_tpu.expr.compile import expr_dictionary

    c = ExprCompiler.for_page(page)
    dicts = [b.dictionary for b in page.blocks]
    blocks: List[Block] = []
    for e in projections:
        data, valid = c.compile(e)(page)
        wants_dict = e.type.is_string or (
            e.type.is_array and e.type.element is not None
            and e.type.element.is_string)
        dictionary = expr_dictionary(e, dicts) if wants_dict else None
        if data.dtype != e.type.np_dtype:
            data = data.astype(e.type.np_dtype)
        blocks.append(Block(data, valid, e.type, dictionary))
    return Page(tuple(blocks), page.row_mask)
