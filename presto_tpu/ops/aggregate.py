"""Grouped aggregation kernels.

Reference analog: HashAggregationOperator
(operator/HashAggregationOperator.java:46) with GroupByHash
(operator/MultiChannelGroupByHash.java:54 — open-addressing row hash)
and the JIT-compiled accumulators (operator/aggregation/,
AccumulatorCompiler.java). Open-addressing probes are scalar-serial and
hostile to the TPU's vector units, so group resolution is re-designed:

* **Packed-direct path**: when every group key has a known small domain
  (dictionary codes, flags, small ints), the packed key IS the group id
  — no sort, one `segment_sum` per aggregate. This is the TPC-H Q1
  shape (6 groups) and the analog of the reference's
  BigintGroupByHash specialization.

* **Sort path**: general case. Pack (exact, when domains fit in 63
  bits) or hash-mix the key columns into one int64, argsort once,
  derive group ids from sorted-run boundaries, then segment-reduce.
  Deterministic output order (sorted by packed/hashed key).

Aggregates are expressed as (state columns, merge, finalize) triples so
the same kernel serves single-node, partial (pre-exchange) and final
(post-exchange) aggregation — the PARTIAL/FINAL split of
iterative/rule/PushPartialAggregationThroughExchange.java.

Exact sums: DECIMAL aggregates accumulate in scaled int64 when the
argument precision is at most SUM_SHORT_SAFE_PRECISION (15); higher
short precisions — every decimal arithmetic product types as p=18 —
accumulate in two-limb decimal128 state instead, because an int64
accumulator wraps silently once |addend| * rows crosses 2^63 (the
SF100 Q1 sum_charge class: ~6e9 rows x 10^16-scale addends; the
reference's checked accumulators raise ARITHMETIC_OVERFLOW there).
The limb fold (decimal128.to_sum_limbs) is exact to ~9.2e9 addends;
the kernel-soundness analyzer (analysis/kernel_soundness.py) flags any
accumulator whose folded interval still escapes its state width.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import AggCall, Expr
from presto_tpu.page import Block, Page
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, DecimalType, Type

_I64_MAX = jnp.iinfo(jnp.int64).max

AggSpec = AggCall  # public alias

DIRECT_GROUP_LIMIT = 1 << 14

# HyperLogLog bucket count (2^p, p=12 — the reference's default
# approx_distinct standard error 2.3%/sqrt-law class); must match
# ExprCompiler.HLL_P in expr/compile.py
HLL_M = 1 << 12

# static per-group element capacity of array_agg (the reference's
# ArrayAggregationFunction is unbounded; a fixed slot count keeps the
# state a dense (groups, cap) matrix — results past the cap truncate)
ARRAY_AGG_CAP = 64

# class-count cap of learn_classifier (labels must be ints in [0, C));
# reference presto-ml trains libsvm models — here Gaussian naive Bayes,
# whose sufficient statistics are plain segment sums (TPU-native)
ML_MAX_CLASSES = 8


# ---------------------------------------------------------------------------
# agg state machinery
# ---------------------------------------------------------------------------

# max short-decimal argument precision whose sum may accumulate in a
# plain int64 lane: 10^15 * ~9.2e3 max rows-per-... — conservatively,
# |addend| <= 10^15 leaves four orders of magnitude of headroom below
# 2^63 (~9.2e18), i.e. the fold stays exact past 9000x the largest
# tier-1 table; p=16..18 addends (every decimal arith product types as
# p=18) can cross 2^63 at realistic SF100 row counts and widen to
# two-limb decimal128 accumulation instead
SUM_SHORT_SAFE_PRECISION = 15


def _sum_type(t: Type) -> Type:
    if t.is_decimal:
        if (t.precision or 0) > 36:
            return DecimalType(38, t.scale)
        if t.is_long_decimal or (t.precision or 0) > SUM_SHORT_SAFE_PRECISION:
            return DecimalType(36, t.scale)
        return DecimalType(18, t.scale)
    if t.name.startswith("interval"):
        return t  # interval sums stay interval (Interval*SumAggregation)
    if t.name in ("double", "real"):
        return DOUBLE  # REAL accumulates in double (reference parity)
    return BIGINT  # tinyint/smallint/integer/bigint widen to bigint


VARIANCE_FNS = ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop")
# higher central moments (CentralMomentsAggregation: skewness/kurtosis)
MOMENT_FNS = ("skewness", "kurtosis")
# bitwise folds (BitwiseAndAggregation / BitwiseOrAggregation)
BITWISE_FNS = ("bitwise_and_agg", "bitwise_or_agg")

# two-argument moment statistics (AggregationUtils covariance/corr/
# regression states): fn(y, x) with state (sx, sy, sxy, sxx, syy, n)
COVAR_FNS = ("covar_pop", "covar_samp", "corr", "regr_slope", "regr_intercept")


def state_types(agg: AggCall) -> List[Type]:
    """Column types of this aggregate's partial state."""
    if agg.fn == "count_star" or agg.fn == "count":
        return [BIGINT]
    t = agg.arg.type
    if agg.fn in ("sum", "sum0"):
        return [_sum_type(t), BIGINT]
    if agg.fn == "avg":
        return [_sum_type(t), BIGINT]
    if agg.fn in ("min", "max"):
        return [t, BIGINT]
    if agg.fn in VARIANCE_FNS:
        return [DOUBLE, DOUBLE, BIGINT]  # sum, M2 (Σ(x-mean)²), count
    if agg.fn in MOMENT_FNS:
        return [DOUBLE, DOUBLE, DOUBLE, DOUBLE, BIGINT]  # s, M2, M3, M4, n
    if agg.fn in BITWISE_FNS:
        return [BIGINT, BIGINT]  # folded value, count of non-null
    if agg.fn in ("bool_and", "bool_or", "every"):
        return [BIGINT, BIGINT]  # count of true, count of non-null
    if agg.fn in COVAR_FNS:
        return [DOUBLE, DOUBLE, DOUBLE, DOUBLE, DOUBLE, BIGINT]
    if agg.fn == "checksum":
        return [BIGINT]
    if agg.fn in ("min_by", "max_by"):
        # x-at-extreme, x-non-null flag, extreme key, count of valid keys
        return [t, BIGINT, agg.arg2.type, BIGINT]
    if agg.fn == "hll_merge":
        # HyperLogLog register fold: Σ 2^-M over present buckets, count
        # of present buckets (input rows are one-per-(group, bucket))
        return [DOUBLE, BIGINT]
    if agg.fn == "array_agg":
        from presto_tpu.types import ArrayType

        return [ArrayType(t, ARRAY_AGG_CAP), BIGINT]
    if agg.fn in ("map_agg", "multimap_agg"):
        from presto_tpu.types import MapType

        return [MapType(t, agg.arg2.type, ARRAY_AGG_CAP), BIGINT]
    if agg.fn == "map_union":
        from presto_tpu.types import MapType

        return [MapType(t.key_element, t.element, ARRAY_AGG_CAP), BIGINT]
    if agg.fn in ("max_n", "min_n"):
        from presto_tpu.types import ArrayType

        return [ArrayType(t, int(agg.arg2.value)), BIGINT]
    if agg.fn in ("max_by_n", "min_by_n"):
        # two value halves sharing one storage dtype: the map state
        # geometry [len, xs.., ys..] with ys = the ordering keys, so
        # partial states merge exactly (top-n is a semilattice)
        from presto_tpu.types import MapType

        return [MapType(t, agg.arg2.type, int(agg.arg3.value)), BIGINT]
    if agg.fn == "hll_sketch":
        from presto_tpu.types import HllType

        return [HllType(), BIGINT]
    if agg.fn in ("make_set_digest", "merge_set_digest"):
        from presto_tpu.types import SetDigestType

        return [SetDigestType(), BIGINT]
    if agg.fn == "learn_regressor":
        # normal-equation sufficient statistics: flattened upper
        # triangle-free full XtX (dim*dim) + Xty (dim), dim = k+1 bias
        from presto_tpu.types import ArrayType

        dim = agg.arg2.type.max_elems + 1
        return [ArrayType(DOUBLE, dim * dim + dim), BIGINT]
    if agg.fn == "learn_classifier":
        # per class: count, sum x_j, sum x_j^2  (Gaussian NB stats)
        from presto_tpu.types import ArrayType

        k = agg.arg2.type.max_elems
        return [ArrayType(DOUBLE, ML_MAX_CLASSES * (1 + 2 * k)), BIGINT]
    if agg.fn == "evaluate_classifier_predictions":
        # per class: [tp, fp, fn] counts (presto-ml
        # EvaluateClassifierPredictionsAggregation state maps)
        from presto_tpu.types import ArrayType

        return [ArrayType(BIGINT, 3 * ML_MAX_CLASSES), BIGINT]
    raise KeyError(f"unknown aggregate {agg.fn}")


def output_type(agg: AggCall) -> Type:
    if agg.fn in ("count", "count_star", "hll_merge", "approx_distinct"):
        return BIGINT
    if agg.fn == "approx_set":
        from presto_tpu.types import HllType

        return HllType()  # rewritten to the two-level sketch pipeline
    if agg.fn == "merge":
        return agg.arg.type  # hll in, hll out (rewritten before exec)
    if agg.fn == "array_agg":
        from presto_tpu.types import ArrayType

        return ArrayType(agg.arg.type, ARRAY_AGG_CAP)
    if agg.fn == "map_agg":
        from presto_tpu.types import MapType

        return MapType(agg.arg.type, agg.arg2.type, ARRAY_AGG_CAP)
    if agg.fn == "hll_sketch":
        from presto_tpu.types import HllType

        return HllType()
    if agg.fn in ("make_set_digest", "merge_set_digest"):
        from presto_tpu.types import SetDigestType

        return SetDigestType()
    if agg.fn == "evaluate_classifier_predictions":
        # VARCHAR summary; the numeric state travels through the jitted
        # pipeline and LocalRunner formats it host-side at the end
        return VARCHAR
    if agg.fn == "multimap_agg":
        from presto_tpu.types import ArrayType, MapType

        vt = agg.arg2.type
        if not vt.is_array:  # pre-rewrite: second arg is the scalar v
            vt = ArrayType(vt, ARRAY_AGG_CAP)
        return MapType(agg.arg.type, vt, ARRAY_AGG_CAP)
    if agg.fn == "map_union":
        from presto_tpu.types import MapType

        t = agg.arg.type
        return MapType(t.key_element, t.element, ARRAY_AGG_CAP)
    if agg.fn in ("max_n", "min_n"):
        from presto_tpu.types import ArrayType

        return ArrayType(agg.arg.type, int(agg.arg2.value))
    if agg.fn in ("max_by_n", "min_by_n"):
        from presto_tpu.types import ArrayType

        return ArrayType(agg.arg.type, int(agg.arg3.value))
    if agg.fn == "histogram":
        # rewritten to inner count + outer map_agg before execution
        from presto_tpu.types import MapType

        return MapType(agg.arg.type, BIGINT, ARRAY_AGG_CAP)
    if agg.fn == "numeric_histogram":
        # rewritten to window-span bins + map_agg before execution;
        # the map width is the shared container cap so the rewrite's
        # map_agg state layout and this declared type agree
        from presto_tpu.types import MapType

        return MapType(DOUBLE, DOUBLE, ARRAY_AGG_CAP)
    if agg.fn == "learn_regressor":
        from presto_tpu.types import ArrayType

        return ArrayType(DOUBLE, agg.arg2.type.max_elems + 1)
    if agg.fn == "learn_classifier":
        from presto_tpu.types import ArrayType

        k = agg.arg2.type.max_elems
        return ArrayType(DOUBLE, 1 + ML_MAX_CLASSES * (1 + 2 * k))
    if agg.fn in ("sum", "sum0"):
        return _sum_type(agg.arg.type)
    if agg.fn == "avg":
        if agg.arg.type.is_decimal:
            # reference parity: avg(decimal(p,s)) keeps the input type,
            # rounded HALF_UP at scale s (DecimalAverageAggregation)
            return agg.arg.type
        if agg.arg.type.name.startswith("interval"):
            return agg.arg.type  # Interval*AverageAggregation
        return DOUBLE
    if agg.fn in VARIANCE_FNS or agg.fn in COVAR_FNS or agg.fn in MOMENT_FNS:
        return DOUBLE
    if agg.fn in BITWISE_FNS:
        return BIGINT
    if agg.fn == "checksum":
        return BIGINT
    if agg.fn in ("bool_and", "bool_or", "every"):
        from presto_tpu.types import BOOLEAN

        return BOOLEAN
    return agg.arg.type  # min/max/min_by/max_by/approx_percentile: x's type


# Below this segment count, segment reductions lower to a fused masked
# broadcast-reduce instead of XLA's scatter-add — scatter serializes on
# the TPU (measured 583ms vs ~0ms extra for a 6M-row f64 page), while
# the masked form fuses into one memory pass per call.  XLA:CPU does
# NOT fuse the broadcast (it materializes the (G, rows) intermediate,
# measured 10x slower on TPC-H Q1) and its scatter-add is fine, so the
# masked form is TPU-only.
SMALL_SEG_LIMIT = 128


def _masked_segments_profitable() -> bool:
    import jax as _jax

    return _jax.default_backend() != "cpu"


def _seg_sum(vals, gid, n):
    if n <= SMALL_SEG_LIMIT and _masked_segments_profitable():
        seg = jnp.arange(n, dtype=gid.dtype)
        hit = gid[None, :] == seg[:, None]
        if vals.ndim == 1:
            return jnp.sum(jnp.where(hit, vals[None, :], jnp.zeros_like(vals)[None, :]), axis=1)
        # leading-axis segmentation of (rows, k) limb arrays
        return jnp.sum(
            jnp.where(hit[:, :, None], vals[None, :, :], jnp.zeros_like(vals)[None, :, :]),
            axis=1,
        )
    return jax.ops.segment_sum(vals, gid, num_segments=n)


def _gsum(ctx, vals, gid, n):
    """Per-group sums for groups 0..n-1 (rows with gid == n are dead):
    cumsum-over-sorted-runs when a _SortCtx is available and the group
    count is past the masked-reduce limit, else _seg_sum."""
    if ctx is not None and n + 1 > SMALL_SEG_LIMIT:
        return ctx.sum(vals, gid, n)
    return _seg_sum(vals, gid, n + 1)[:n]


def _ident_max(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).max
    if dtype == jnp.bool_:
        return True
    return jnp.iinfo(dtype).max


def _ident_min(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).min
    if dtype == jnp.bool_:
        return False
    return jnp.iinfo(dtype).min


def _seg_min(vals, gid, n):
    if n <= SMALL_SEG_LIMIT and _masked_segments_profitable():
        seg = jnp.arange(n, dtype=gid.dtype)
        hit = gid[None, :] == seg[:, None]
        fill = jnp.asarray(_ident_max(vals.dtype), vals.dtype)
        return jnp.min(jnp.where(hit, vals[None, :], fill), axis=1)
    return jax.ops.segment_min(vals, gid, num_segments=n)


def _seg_max(vals, gid, n):
    if n <= SMALL_SEG_LIMIT and _masked_segments_profitable():
        seg = jnp.arange(n, dtype=gid.dtype)
        hit = gid[None, :] == seg[:, None]
        fill = jnp.asarray(_ident_min(vals.dtype), vals.dtype)
        return jnp.max(jnp.where(hit, vals[None, :], fill), axis=1)
    return jax.ops.segment_max(vals, gid, num_segments=n)


def _seg_assoc(op, identity, vals, gid, n):
    """Segmented reduction under ANY associative op (bitwise and/or
    here): argsort rows by group, run one segmented
    ``associative_scan`` (scan state = (segment-start flag, value); a
    start flag resets the accumulation), then gather each group's last
    scan position via searchsorted — no scatter, TPU-friendly.  Rows
    with gid == n are dead and land in the trailing run."""
    order = jnp.argsort(gid)
    g = gid[order]
    v = vals[order]
    starts = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), g[1:] != g[:-1]])

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    _, scanned = jax.lax.associative_scan(combine, (starts, v))
    # g is sorted: each group's last row index via right-edge search;
    # a group is present exactly when the row at its right edge still
    # carries its id
    ends = jnp.clip(jnp.searchsorted(g, jnp.arange(n, dtype=g.dtype),
                                     side="right") - 1, 0, g.shape[0] - 1)
    present = g[ends] == jnp.arange(n, dtype=g.dtype)
    return jnp.where(present, scanned[ends], identity)


def _partial_states(page: Page, aggs: Sequence[AggCall], gid: jax.Array, n: int,
                    ctx: "Optional[_SortCtx]" = None):
    """Compute per-group state columns for each aggregate.

    gid must already be ``n`` for dead rows (dropped by segment ops via
    an extra slot)."""
    c = ExprCompiler.for_page(page)
    out: List[List[jax.Array]] = []
    live = page.row_mask
    for agg in aggs:
        if agg.filter is not None:
            fd, fv = c.compile(agg.filter)(page)
            rowsel = live & fd & fv
        else:
            rowsel = live
        gid_a = jnp.where(rowsel, gid, n)
        if agg.fn == "count_star":
            cnt = _gsum(ctx, jnp.ones_like(gid_a, dtype=jnp.int64), gid_a, n)
            out.append([cnt])
            continue
        data, valid = c.compile(agg.arg)(page)
        if agg.fn in ("min", "max") and agg.arg.type.is_raw_string:
            # raw varchar: k-phase lexicographic reduction over
            # order-preserving int64 lanes (PagesIndex VARCHAR
            # comparator role, no scalar loops)
            from presto_tpu.ops import rawstring as rs

            nonnull = rowsel & valid
            gid_nn = jnp.where(nonnull, gid, n)
            cnt = _gsum(ctx, nonnull.astype(jnp.int64), gid_nn, n)
            lanes = rs.pack_lanes(data)
            best = _minmax_lanes(agg.fn, lanes, nonnull, gid_nn, n)
            out.append([rs.unpack_lanes(best, data.shape[-1]), cnt])
            continue
        if agg.fn in ("min", "max") and agg.arg.type.is_string:
            # reduce over collation ranks, not assignment-ordered codes
            adict = _agg_dict(agg, [b.dictionary for b in page.blocks])
            if adict is not None:
                rank_lut, _ = _collation_luts(adict)
                data = rank_lut[jnp.clip(data, 0, rank_lut.shape[0] - 1)]
        nonnull = rowsel & valid
        gid_nn = jnp.where(nonnull, gid, n)
        cnt = _gsum(ctx, nonnull.astype(jnp.int64), gid_nn, n)
        if agg.fn == "count":
            out.append([cnt])
        elif agg.fn in ("sum", "sum0", "avg") \
                and _sum_type(agg.arg.type).is_long_decimal:
            from presto_tpu.ops import decimal128 as d128

            # covers short p>15 args too: their scaled-int64 lanes lift
            # to two-limb rows first, then the same base-1e9 digit fold
            if not agg.arg.type.is_long_decimal:
                data = d128.from_int64(data.astype(jnp.int64))
            limbs = d128.to_sum_limbs(data)
            limbs = jnp.where(nonnull[:, None], limbs, 0)
            s = d128.from_sum_limbs(_gsum(ctx, limbs, gid_nn, n))
            out.append([s, cnt])
        elif agg.fn in ("sum", "sum0", "avg"):
            st = _sum_type(agg.arg.type)
            vals = data.astype(st.np_dtype)
            vals = jnp.where(nonnull, vals, jnp.zeros_like(vals))
            s = _gsum(ctx, vals, gid_nn, n)
            out.append([s, cnt])
        elif agg.fn in ("min", "max") and agg.arg.type.is_long_decimal:
            out.append(_minmax_long(agg.fn, data, nonnull, gid_nn, n) + [cnt])
        elif agg.fn in ("min", "max"):
            if agg.fn == "min":
                fill = _type_max(agg.arg.type)
                m = _seg_min(
                    jnp.where(nonnull, data, fill), gid_nn, n + 1
                )[:n]
            else:
                fill = _type_min(agg.arg.type)
                m = _seg_max(
                    jnp.where(nonnull, data, fill), gid_nn, n + 1
                )[:n]
            out.append([m, cnt])
        elif agg.fn in VARIANCE_FNS:
            from presto_tpu.expr.compile import _to_double

            # Welford-style state (count, mean, M2) per the reference's
            # AggregationUtils.updateVarianceState — s2/n - mean² loses
            # all precision when |mean| >> stddev.  Two passes: segment
            # mean first, then mean-relative second moment.
            x = jnp.where(nonnull, _to_double(data, agg.arg.type), 0.0)
            s = _gsum(ctx, x, gid_nn, n)
            mu = s / jnp.maximum(cnt, 1).astype(jnp.float64)
            mu_row = mu[jnp.clip(gid_nn, 0, n - 1)]
            dx = jnp.where(nonnull, x - mu_row, 0.0)
            m2 = _gsum(ctx, dx * dx, gid_nn, n)
            out.append([s, m2, cnt])
        elif agg.fn in MOMENT_FNS:
            from presto_tpu.expr.compile import _to_double

            # two-pass central moments, like the variance state
            x = jnp.where(nonnull, _to_double(data, agg.arg.type), 0.0)
            s = _gsum(ctx, x, gid_nn, n)
            mu = s / jnp.maximum(cnt, 1).astype(jnp.float64)
            dx = jnp.where(nonnull, x - mu[jnp.clip(gid_nn, 0, n - 1)], 0.0)
            dx2 = dx * dx
            out.append([s, _gsum(ctx, dx2, gid_nn, n),
                        _gsum(ctx, dx2 * dx, gid_nn, n),
                        _gsum(ctx, dx2 * dx2, gid_nn, n), cnt])
        elif agg.fn in BITWISE_FNS:
            is_and = agg.fn == "bitwise_and_agg"
            ident = jnp.int64(-1) if is_and else jnp.int64(0)
            v = jnp.where(nonnull, data.astype(jnp.int64), ident)
            op = jnp.bitwise_and if is_and else jnp.bitwise_or
            out.append([_seg_assoc(op, ident, v, gid_nn, n), cnt])
        elif agg.fn in ("bool_and", "bool_or", "every"):
            t = _seg_sum((nonnull & data.astype(jnp.bool_)).astype(jnp.int64),
                         gid_nn, n + 1)[:n]
            out.append([t, cnt])
        elif agg.fn in COVAR_FNS:
            from presto_tpu.expr.compile import _to_double

            x_data, x_valid = c.compile(agg.arg2)(page)
            sel = rowsel & valid & x_valid
            gid_s = jnp.where(sel, gid, n)
            y = jnp.where(sel, _to_double(data, agg.arg.type), 0.0)
            x = jnp.where(sel, _to_double(x_data, agg.arg2.type), 0.0)
            out.append([
                _gsum(ctx, x, gid_s, n),
                _gsum(ctx, y, gid_s, n),
                _gsum(ctx, x * y, gid_s, n),
                _gsum(ctx, x * x, gid_s, n),
                _gsum(ctx, y * y, gid_s, n),
                _gsum(ctx, sel.astype(jnp.int64), gid_s, n),
            ])
        elif agg.fn == "checksum":
            # order-independent wrapping sum of per-value hashes
            # (CheckSumAggregation — the verifier's result digest)
            if jnp.issubdtype(data.dtype, jnp.floating):
                lane = jax.lax.bitcast_convert_type(
                    data.astype(jnp.float64), jnp.int64)
            elif data.ndim > 1:
                from presto_tpu.ops.rawstring import hash_bytes

                lane = (hash_bytes(data.astype(jnp.uint8))
                        if data.dtype == jnp.uint8
                        else data[..., 0] * jnp.int64(1000003) + data[..., 1])
            else:
                lane = data.astype(jnp.int64)
            h = _mix64(lane.astype(jnp.uint64)).astype(jnp.int64)
            h = jnp.where(valid, h, jnp.int64(0x9E3779B97F4A7C15 - 2 ** 64))
            h = jnp.where(rowsel, h, 0)
            out.append([_gsum(ctx, h, jnp.where(rowsel, gid, n), n)])
        elif agg.fn in ("min_by", "max_by"):
            # two-phase coupled reduction: per-group extreme of the key,
            # then (any) x among the rows achieving it (reference:
            # operator/aggregation/minmaxby/ MinMaxByStateFactory)
            if agg.arg.type.value_shape or agg.arg2.type.value_shape:
                raise ValueError(
                    f"{agg.fn} over raw varchar / long decimal unsupported")
            y_data, y_valid = c.compile(agg.arg2)(page)
            if agg.arg2.type.is_string:
                from presto_tpu.expr.compile import expr_dictionary

                ydict = expr_dictionary(agg.arg2, [b.dictionary for b in page.blocks])
                if ydict is not None:
                    y_rank, _ = _collation_luts(ydict)
                    y_data = y_rank[jnp.clip(y_data, 0, y_rank.shape[0] - 1)]
            sel = rowsel & y_valid
            gid_y = jnp.where(sel, gid, n)
            ycnt = _gsum(ctx, sel.astype(jnp.int64), gid_y, n)
            if agg.fn == "min_by":
                yfill = _type_max(agg.arg2.type)
                y_best = _seg_min(
                    jnp.where(sel, y_data, yfill), gid_y, n + 1)[:n]
            else:
                yfill = _type_min(agg.arg2.type)
                y_best = _seg_max(
                    jnp.where(sel, y_data, yfill), gid_y, n + 1)[:n]
            tie = sel & (y_data == y_best[jnp.clip(gid_y, 0, n - 1)])
            xv = tie & valid
            x_best = _seg_max(
                jnp.where(xv, data, _type_min(agg.arg.type)),
                jnp.where(xv, gid, n), n + 1)[:n]
            xv_cnt = _gsum(ctx, xv.astype(jnp.int64), jnp.where(xv, gid, n), n)
            out.append([x_best, (xv_cnt > 0).astype(jnp.int64), y_best, ycnt])
        elif agg.fn == "hll_merge":
            # fold rho rows (one per (group, bucket)) into the sketch sum
            rho = jnp.where(nonnull, data.astype(jnp.float64), 0.0)
            s = _seg_sum(jnp.where(nonnull, jnp.exp2(-rho), 0.0), gid_nn, n + 1)[:n]
            out.append([s, cnt])
        elif agg.fn == "evaluate_classifier_predictions":
            # per-class tp/fp/fn lanes summed per group (presto-ml
            # EvaluateClassifierPredictionsAggregation input/combine;
            # labels are class ids in [0, ML_MAX_CLASSES))
            C = ML_MAX_CLASSES
            p_data, p_valid = c.compile(agg.arg2)(page)
            t64 = data.astype(jnp.int64)
            p64 = p_data.astype(jnp.int64)
            sel = (rowsel & valid & p_valid
                   & (t64 >= 0) & (t64 < C) & (p64 >= 0) & (p64 < C))
            cls = jnp.arange(C, dtype=jnp.int64)[None, :]
            t_oh = t64[:, None] == cls
            p_oh = p64[:, None] == cls
            eq = (t64 == p64)[:, None]
            lanes = jnp.concatenate(
                [jnp.where(t_oh & eq, 1, 0),          # tp at truth cls
                 jnp.where(p_oh & ~eq, 1, 0),         # fp at pred cls
                 jnp.where(t_oh & ~eq, 1, 0)],        # fn at truth cls
                axis=1).astype(jnp.int64)
            lanes = jnp.where(sel[:, None], lanes, 0)
            gid_s = jnp.where(sel, gid, n)
            scnt = _gsum(ctx, sel.astype(jnp.int64), gid_s, n)
            sums = _gsum(ctx, lanes, gid_s, n)
            state = jnp.concatenate(
                [jnp.full((n, 1), 3 * C, dtype=jnp.int64), sums], axis=1)
            out.append([state, scnt])
        elif agg.fn in ("learn_regressor", "learn_classifier"):
            # sufficient statistics are segment sums (TPU-native
            # training): normal equations for the regressor, Gaussian
            # NB class stats for the classifier (presto-ml analog)
            from presto_tpu.ops import container as ct

            ft = agg.arg2.type
            f_data, f_valid = c.compile(agg.arg2)(page)
            k = ft.max_elems
            slots = ct.elem_slots(f_data, ft)
            feats = jnp.where(ct.elem_null_mask(slots), 0.0,
                              slots.astype(jnp.float64))
            sel = rowsel & valid & f_valid
            gid_s = jnp.where(sel, gid, n)
            scnt = _gsum(ctx, sel.astype(jnp.int64), gid_s, n)
            if agg.fn == "learn_regressor":
                from presto_tpu.expr.compile import _to_double

                y = jnp.where(sel, _to_double(data, agg.arg.type), 0.0)
                x_aug = jnp.concatenate(
                    [feats, jnp.ones((feats.shape[0], 1))], axis=1)
                dim = k + 1
                outer = (x_aug[:, :, None] * x_aug[:, None, :]).reshape(
                    feats.shape[0], dim * dim)
                lanes = jnp.concatenate([outer, x_aug * y[:, None]], axis=1)
            else:
                cls = jnp.clip(data.astype(jnp.int64), 0, ML_MAX_CLASSES - 1)
                onehot = (cls[:, None] == jnp.arange(ML_MAX_CLASSES)[None, :]
                          ).astype(jnp.float64)
                sumx = (onehot[:, :, None] * feats[:, None, :]).reshape(
                    feats.shape[0], ML_MAX_CLASSES * k)
                sumx2 = (onehot[:, :, None] * (feats ** 2)[:, None, :]).reshape(
                    feats.shape[0], ML_MAX_CLASSES * k)
                lanes = jnp.concatenate([onehot, sumx, sumx2], axis=1)
            lanes = jnp.where(sel[:, None], lanes, 0.0)
            s = _gsum(ctx, lanes, gid_s, n)
            m = lanes.shape[1]
            state = jnp.concatenate(
                [jnp.full((n, 1), float(m)), s], axis=1)
            out.append([state, scnt])
        elif agg.fn == "array_agg":
            # scatter (group, within-group-rank) -> slot; NULL inputs
            # keep their position as sentinel slots (reference
            # ArrayAggregationFunction keeps nulls)
            at = state_types(agg)[0]
            cap_e = at.max_elems
            storage = at.np_dtype
            sent = _container_sent(storage)
            sel = rowsel
            gid_sel = jnp.where(sel, gid, n)
            rcnt = _gsum(ctx, sel.astype(jnp.int64), gid_sel, n)
            rank = _within_group_rank(gid_sel)
            vals = jnp.where(valid, data.astype(storage), sent)
            ok = sel & (rank < cap_e) & (gid_sel < n)
            tgt = jnp.where(ok, gid_sel.astype(jnp.int64) * cap_e + rank, n * cap_e)
            flat = jnp.full((n * cap_e,), sent, dtype=storage)
            flat = flat.at[tgt].set(vals, mode="drop")
            arr = flat.reshape(n, cap_e)
            length = jnp.minimum(rcnt, cap_e).astype(storage)
            out.append([jnp.concatenate([length[:, None], arr], axis=1), rcnt])
        elif agg.fn in ("map_agg", "hll_sketch"):
            # two scatters, same (group, rank) geometry: keys then
            # values (MapAggregationFunction analog); NULL-key rows drop
            mt = state_types(agg)[0]
            cap_e = mt.max_elems
            storage = mt.np_dtype
            sent = _container_sent(storage)
            v_data, v_valid = c.compile(agg.arg2)(page)
            sel = rowsel & valid  # keys must be non-null
            gid_sel = jnp.where(sel, gid, n)
            rcnt = _gsum(ctx, sel.astype(jnp.int64), gid_sel, n)
            rank = _within_group_rank(gid_sel)
            ok = sel & (rank < cap_e) & (gid_sel < n)
            tgt = jnp.where(ok, gid_sel.astype(jnp.int64) * cap_e + rank, n * cap_e)
            kflat = jnp.full((n * cap_e,), sent, dtype=storage)
            kflat = kflat.at[tgt].set(data.astype(storage), mode="drop")
            vflat = jnp.full((n * cap_e,), sent, dtype=storage)
            vflat = vflat.at[tgt].set(
                jnp.where(v_valid, v_data.astype(storage), sent), mode="drop")
            length = jnp.minimum(rcnt, cap_e).astype(storage)
            state = jnp.concatenate(
                [length[:, None], kflat.reshape(n, cap_e),
                 vflat.reshape(n, cap_e)], axis=1)
            out.append([state, rcnt])
        elif agg.fn == "multimap_agg":
            # map_agg geometry with ARRAY-valued lanes: the value half
            # is a (cap_e, 1+av) matrix per group, scattered row-wise
            mt = state_types(agg)[0]
            cap_e = mt.max_elems
            av = 1 + mt.element.max_elems
            storage = mt.np_dtype
            sent = _container_sent(storage)
            v_data, v_valid = c.compile(agg.arg2)(page)
            sel = rowsel & valid
            gid_sel = jnp.where(sel, gid, n)
            rcnt = _gsum(ctx, sel.astype(jnp.int64), gid_sel, n)
            rank = _within_group_rank(gid_sel)
            ok = sel & (rank < cap_e) & (gid_sel < n)
            tgt = jnp.where(ok, gid_sel.astype(jnp.int64) * cap_e + rank, n * cap_e)
            kflat = jnp.full((n * cap_e,), sent, dtype=storage)
            kflat = kflat.at[tgt].set(data.astype(storage), mode="drop")
            vrows = jnp.where(v_valid[:, None], v_data.astype(storage), sent)
            vflat = jnp.full((n * cap_e, av), sent, dtype=storage)
            vflat = vflat.at[tgt].set(vrows, mode="drop")
            length = jnp.minimum(rcnt, cap_e).astype(storage)
            state = jnp.concatenate(
                [length[:, None], kflat.reshape(n, cap_e),
                 vflat.reshape(n, cap_e * av)], axis=1)
            out.append([state, rcnt])
        elif agg.fn == "map_union":
            # union the entries of map-valued rows per group: flatten
            # each row's [len, keys.., vals..] into per-entry virtual
            # rows, then the map_agg (group, entry-rank) scatter
            # (MapUnionAggregation.java).  Deviation (engine-wide map
            # convention, see PARITY.md): duplicate keys keep every
            # occurrence — lookups take the first, but cardinality
            # counts entries, where the reference dedupes keys
            st = state_types(agg)[0]
            cap_e = st.max_elems
            storage = st.np_dtype
            sent = _container_sent(storage)
            cap_in = agg.arg.type.max_elems
            l0 = data[:, 0]
            if jnp.issubdtype(data.dtype, jnp.floating):
                l0 = jnp.where(jnp.isnan(l0), 0.0, l0)
            lens_in = jnp.maximum(l0.astype(jnp.int64), 0)
            sel = rowsel & valid
            j = jnp.arange(cap_in, dtype=jnp.int64)[None, :]
            entry_ok = sel[:, None] & (j < lens_in[:, None])
            egid = jnp.where(entry_ok, gid[:, None], n).reshape(-1)
            ecnt = _gsum(ctx, entry_ok.astype(jnp.int64).sum(axis=1),
                         gid_a, n)
            # the COUNT column tracks rows with non-null maps (empty
            # maps still make the group's result an empty map, not
            # NULL); the length lane tracks entries
            rows_cnt = _gsum(ctx, sel.astype(jnp.int64), gid_a, n)
            rank = _within_group_rank(egid)
            ok = entry_ok.reshape(-1) & (rank < cap_e) & (egid < n)
            tgt = jnp.where(ok, egid.astype(jnp.int64) * cap_e + rank,
                            n * cap_e)
            kflat = jnp.full((n * cap_e,), sent, dtype=storage)
            kflat = kflat.at[tgt].set(
                data[:, 1:1 + cap_in].reshape(-1).astype(storage),
                mode="drop")
            vflat = jnp.full((n * cap_e,), sent, dtype=storage)
            vflat = vflat.at[tgt].set(
                data[:, 1 + cap_in:1 + 2 * cap_in].reshape(-1).astype(storage),
                mode="drop")
            length = jnp.minimum(ecnt, cap_e).astype(storage)
            state = jnp.concatenate(
                [length[:, None], kflat.reshape(n, cap_e),
                 vflat.reshape(n, cap_e)], axis=1)
            out.append([state, rows_cnt])
        elif agg.fn == "make_set_digest":
            # KMV sketch build: hash the value, dedup per group summing
            # multiplicities, keep the K smallest hashes
            st = state_types(agg)[0]
            cap_e = st.max_elems
            storage = st.np_dtype
            sel = rowsel & valid
            if jnp.issubdtype(data.dtype, jnp.floating):
                v64 = jax.lax.bitcast_convert_type(
                    data.astype(jnp.float64), jnp.int64)
            else:
                v64 = data.astype(jnp.int64)
            h = mix64(v64)
            ones = jnp.ones_like(h)
            state, distinct = _kmv_lanes(gid, h, ones, sel, n, cap_e,
                                         storage)
            out.append([state, distinct])
        elif agg.fn == "merge_set_digest":
            # union of digest-valued rows: flatten their lanes and
            # re-lane (counts sum on shared hashes)
            st = state_types(agg)[0]
            cap_e = st.max_elems
            storage = st.np_dtype
            sel = rowsel & valid
            rows = jnp.where(sel[:, None], data.astype(storage),
                             jnp.zeros((), storage))
            egid, hs, cs, lane_ok = _digest_entries(
                rows, jnp.where(sel, gid, n), n, cap_e)
            state, distinct = _kmv_lanes(egid, hs, cs, lane_ok, n, cap_e,
                                         storage)
            out.append([state, distinct])
        elif agg.fn in ("max_n", "min_n", "max_by_n", "min_by_n"):
            # top-n per group via one value-ordered lexsort + scatter
            # (Max/MinNAggregationFunction's TypedHeap,
            # Max/MinByNAggregationFunction's TypedKeyValueHeap)
            st = state_types(agg)[0]
            cap_e = st.max_elems
            storage = st.np_dtype
            sent = _container_sent(storage)
            by = agg.fn in ("max_by_n", "min_by_n")
            if by:
                k_data, k_valid = c.compile(agg.arg2)(page)
                sel = rowsel & k_valid  # key must order; NULL x allowed
                keys = k_data
                vals = jnp.where(valid, data.astype(storage), sent)
            else:
                sel = rowsel & valid
                keys = data
                vals = data
            halves, gcnt = _topn_halves(
                ctx, gid, keys, vals, sel, n, cap_e, storage,
                descending=agg.fn in ("max_n", "max_by_n"), with_keys=by)
            length = jnp.minimum(gcnt, cap_e).astype(storage)
            state = jnp.concatenate([length[:, None]] + halves, axis=1)
            out.append([state, gcnt])
        else:
            raise KeyError(agg.fn)
    return out


#: aggregate fns whose packed-direct states combine POSITIONALLY —
#: slot i of one partial merges with slot i of another by pure
#: elementwise math (no sort, no scatter)
_POSITIONAL_FNS = frozenset({
    "count", "count_star", "sum", "sum0", "avg", "min", "max",
    "bitwise_and_agg", "bitwise_or_agg",
}) | set(VARIANCE_FNS)


def packed_direct_layout(group_exprs, key_domains, max_groups: int) -> bool:
    """THE packed-direct branch predicate (grouped_aggregate's own
    condition, exported so runners never hand-mirror it): exact scalar
    key domains whose product fits the direct-address budget.  Raw
    byte-matrix and multi-dim keys pack inexactly (pack_or_hash_keys
    returns exact=False for them), so they are excluded here too."""
    if not group_exprs or not key_domains             or any(d is None for d in key_domains):
        return False
    for e in group_exprs:
        t = getattr(e, "type", None)
        if t is None or t.is_raw_string or t.is_binary                 or t.value_shape != ():
            return False
    prod = 1
    for lo, hi in key_domains:
        prod *= hi - lo + 2
    return prod <= min(max_groups, DIRECT_GROUP_LIMIT)


def packed_fold_supported(aggs: Sequence[AggCall]) -> bool:
    """True when every aggregate's packed-direct state merges
    elementwise (raw-string min/max lane matrices excluded)."""
    for a in aggs:
        if a.fn not in _POSITIONAL_FNS:
            return False
        if a.fn in ("min", "max") and a.arg is not None \
                and (a.arg.type.is_raw_string
                     or a.arg.type.is_long_decimal):
            # lane matrices / limb vectors need lexicographic combines,
            # not per-component minimum
            return False
    return True


def _slice_state_cols(page: Page, num_keys: int, aggs):
    """(state columns, first-state dictionaries) per aggregate — ONE
    linear walk of the state layout (shared by the positional fold and
    finalize so the layout logic lives in one place)."""
    cols: List[List[jax.Array]] = []
    dicts: List[Optional[object]] = []
    pos = num_keys
    for agg in aggs:
        k = len(state_types(agg))
        cols.append([page.blocks[pos + j].data for j in range(k)])
        dicts.append(page.blocks[pos].dictionary)
        pos += k
    return cols, dicts


def combine_packed_states(a: Page, b: Page, num_keys: int,
                          aggs: Sequence[AggCall]) -> Page:
    """Fold two packed-direct partial pages ELEMENTWISE: group id ==
    packed key == slot position, so merging is vector adds/mins/maxes
    over aligned slots — the no-sort fast path the direct-address
    layout buys (dead slots hold the combine identities: 0 for sums,
    type extremes for min/max).  Variance states combine via Chan's
    pairwise formula, also elementwise."""
    ca, _ = _slice_state_cols(a, num_keys, aggs)
    cb, _ = _slice_state_cols(b, num_keys, aggs)
    out_blocks = list(a.blocks[:num_keys])
    pos = num_keys
    for agg, sa, sb in zip(aggs, ca, cb):
        sts = state_types(agg)
        if agg.fn in ("count", "count_star"):
            merged = [sa[0] + sb[0]]
        elif agg.fn in ("sum", "sum0", "avg") and agg.arg is not None \
                and sts[0].is_long_decimal:  # incl. widened short p>15 args
            from presto_tpu.ops import decimal128 as d128

            merged = [d128.add(sa[0], sb[0]), sa[1] + sb[1]]
        elif agg.fn in ("sum", "sum0", "avg"):
            merged = [sa[0] + sb[0], sa[1] + sb[1]]
        elif agg.fn == "min":
            merged = [jnp.minimum(sa[0], sb[0]), sa[1] + sb[1]]
        elif agg.fn == "max":
            merged = [jnp.maximum(sa[0], sb[0]), sa[1] + sb[1]]
        elif agg.fn == "bitwise_and_agg":
            merged = [sa[0] & sb[0], sa[1] + sb[1]]
        elif agg.fn == "bitwise_or_agg":
            merged = [sa[0] | sb[0], sa[1] + sb[1]]
        else:  # VARIANCE_FNS: (s, m2, cnt) via Chan's pairwise update
            s_a, m2a, n_a = sa
            s_b, m2b, n_b = sb
            naf = n_a.astype(jnp.float64)
            nbf = n_b.astype(jnp.float64)
            nf = jnp.maximum(naf + nbf, 1.0)
            mean_a = s_a / jnp.maximum(naf, 1.0)
            mean_b = s_b / jnp.maximum(nbf, 1.0)
            delta = mean_b - mean_a
            chan = m2a + m2b + delta * delta * naf * nbf / nf
            m2 = jnp.where(n_a == 0, m2b, jnp.where(n_b == 0, m2a, chan))
            merged = [s_a + s_b, m2, n_a + n_b]
        for st, col in zip(sts, merged):
            blk = a.blocks[pos]
            out_blocks.append(Block(col.astype(st.np_dtype),
                                    a.blocks[pos].valid | b.blocks[pos].valid,
                                    st, blk.dictionary))
            pos += 1
    mask = a.row_mask | b.row_mask
    return Page(tuple(out_blocks), mask)


def finalize_packed(acc: Page, num_keys: int,
                    aggs: Sequence[AggCall]) -> Page:
    """mode='single' finalize of a packed-direct accumulator WITHOUT
    re-grouping: slots already hold one group each."""
    states, agg_dicts = _slice_state_cols(acc, num_keys, aggs)
    agg_blocks = _finalize(states, aggs, agg_dicts)
    mask = acc.row_mask
    agg_blocks = [Block(b.data, b.valid & mask, b.type, b.dictionary)
                  for b in agg_blocks]
    return Page(tuple(acc.blocks[:num_keys]) + tuple(agg_blocks), mask)


def mix64(v: jax.Array) -> jax.Array:
    """splitmix64 (golden-ratio increment + the _mix64 finalizer below):
    int64 value -> well-mixed int64 hash — the hash behind
    make_set_digest's KMV slots (the reference's XxHash64 role for
    SetDigest.add)."""
    z = v.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    return _mix64(z).astype(jnp.int64)


def _kmv_lanes(egid, hashes, counts, sel, n, cap_e, storage):
    """Per-group KMV digest state from entry rows: dedup (group, hash)
    runs summing their counts, keep each group's cap_e SMALLEST hashes
    in ascending lanes.  Returns (state [len, hashes.., counts..],
    distinct_total) — the sketch construction AND the sketch union are
    this one kernel (SetDigest.mergeWith collapses to re-laning)."""
    sent = _container_sent(storage)
    m = hashes.shape[0]
    egid = jnp.where(sel, egid, n)
    order = jnp.lexsort((hashes, egid))
    gs, hs, cs, sl = egid[order], hashes[order], counts[order], sel[order]
    newrun = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), (gs[1:] != gs[:-1]) | (hs[1:] != hs[:-1])])
    first = sl & newrun
    rid = jnp.cumsum(first.astype(jnp.int64)) - 1
    rsum = jnp.zeros((m + 1,), jnp.int64).at[
        jnp.where(sl, rid, m)].add(cs.astype(jnp.int64))
    # distinct rank within group (ascending hash): run id offset by the
    # group's first run id (rid is nondecreasing over sorted rows)
    gfirst = jnp.concatenate([jnp.ones(1, jnp.bool_), gs[1:] != gs[:-1]])
    gstart = jax.lax.cummax(jnp.where(gfirst & sl, rid, 0))
    rank_d = rid - gstart
    ok = first & (rank_d < cap_e) & (gs < n)
    tgt = jnp.where(ok, gs.astype(jnp.int64) * cap_e + rank_d, n * cap_e)
    hflat = jnp.full((n * cap_e,), sent, dtype=storage)
    hflat = hflat.at[tgt].set(hs.astype(storage), mode="drop")
    cflat = jnp.full((n * cap_e,), sent, dtype=storage)
    cflat = cflat.at[tgt].set(
        rsum[jnp.clip(rid, 0, m)].astype(storage), mode="drop")
    distinct = jnp.zeros((n + 1,), jnp.int64).at[
        jnp.where(first, gs, n)].add(1)[:n]
    length = jnp.minimum(distinct, cap_e).astype(storage)
    state = jnp.concatenate(
        [length[:, None], hflat.reshape(n, cap_e), cflat.reshape(n, cap_e)],
        axis=1)
    return state, distinct


def _digest_entries(arr_col, gid, n, cap_e):
    """Flatten digest-state rows into per-entry (egid, hash, count,
    sel) vectors for re-laning."""
    l0 = arr_col[:, 0]
    lens = jnp.where(gid < n, jnp.maximum(l0.astype(jnp.int64), 0), 0)
    j = jnp.arange(cap_e, dtype=jnp.int64)[None, :]
    lane_ok = j < jnp.minimum(lens, cap_e)[:, None]
    hashes = arr_col[:, 1:1 + cap_e]
    counts = arr_col[:, 1 + cap_e:1 + 2 * cap_e]
    egid = jnp.where(lane_ok, gid[:, None], n)
    return (egid.reshape(-1), hashes.reshape(-1), counts.reshape(-1),
            lane_ok.reshape(-1))


def _container_sent(storage):
    if jnp.issubdtype(storage, jnp.floating):
        return jnp.asarray(jnp.nan, dtype=storage)
    return jnp.asarray(jnp.iinfo(storage).min, dtype=storage)


def _ordered_rank(gid: jax.Array, order: jax.Array) -> jax.Array:
    """0-based position of each element within its gid class when the
    elements are visited in ``order`` (a permutation that clusters equal
    gids together)."""
    gs = gid[order]
    idx = jnp.arange(gs.shape[0], dtype=jnp.int64)
    first = jnp.concatenate([jnp.ones(1, jnp.bool_), gs[1:] != gs[:-1]])
    start = jax.lax.cummax(jnp.where(first, idx, 0))
    rank_sorted = idx - start
    return jnp.zeros_like(rank_sorted).at[order].set(rank_sorted)


def _within_group_rank(gid: jax.Array) -> jax.Array:
    """0-based occurrence index of each row within its gid class
    (stable: earlier rows get lower ranks)."""
    return _ordered_rank(gid, jnp.argsort(gid, stable=True))


def _topn_halves(ctx, egid, keys, vals, sel, n, cap_e, storage,
                 descending, with_keys):
    """Scatter each group's cap_e extreme elements (ordered by ``keys``)
    into dense (n, cap_e) lanes, vals sorted by key — descending for
    max-forms, ascending for min-forms.

    The descending lane index is (group size - 1 - ascending rank), so
    no key negation is needed (int64 min would overflow under negation).
    Returns ([vals_lanes] or [vals_lanes, keys_lanes], live_count).
    TypedHeap.java analog: the heap becomes one lexsort + one scatter.
    """
    sent = _container_sent(storage)
    egid = jnp.where(sel, egid, n)
    gcnt = _gsum(ctx, sel.astype(jnp.int64), egid, n)
    order = jnp.lexsort((keys, egid))
    rank = _ordered_rank(egid, order)
    if descending:
        size_e = jnp.where(sel, gcnt[jnp.clip(egid, 0, n - 1)], 0)
        lane = size_e - 1 - rank
    else:
        lane = rank
    ok = sel & (lane >= 0) & (lane < cap_e) & (egid < n)
    tgt = jnp.where(ok, egid.astype(jnp.int64) * cap_e + lane, n * cap_e)
    vflat = jnp.full((n * cap_e,), sent, dtype=storage)
    vflat = vflat.at[tgt].set(vals.astype(storage), mode="drop")
    halves = [vflat.reshape(n, cap_e)]
    if with_keys:
        kflat = jnp.full((n * cap_e,), sent, dtype=storage)
        kflat = kflat.at[tgt].set(keys.astype(storage), mode="drop")
        halves.append(kflat.reshape(n, cap_e))
    return halves, gcnt


def _merge_states(state_cols: List[List[jax.Array]], aggs, gid, n,
                  ctx: "Optional[_SortCtx]" = None):
    """Merge partial-state rows (one row per upstream group) into final
    groups: sums/counts add, mins/maxes reduce."""
    out: List[List[jax.Array]] = []
    for agg, cols in zip(aggs, state_cols):
        if agg.fn in ("count", "count_star"):
            out.append([_gsum(ctx, cols[0], gid, n)])
        elif agg.fn in ("sum", "sum0", "avg") and agg.arg is not None \
                and state_types(agg)[0].is_long_decimal:
            from presto_tpu.ops import decimal128 as d128

            live_rows = cols[1] > 0
            limbs = jnp.where(live_rows[:, None], d128.to_sum_limbs(cols[0]), 0)
            out.append([
                d128.from_sum_limbs(_gsum(ctx, limbs, gid, n)),
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn in ("sum", "sum0", "avg"):
            out.append([
                _gsum(ctx, cols[0], gid, n),
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn in ("min", "max") and agg.arg is not None \
                and agg.arg.type.is_long_decimal:
            nonnull = cols[1] > 0
            gid_nn = jnp.where(nonnull, gid, n)
            out.append(
                _minmax_long(agg.fn, cols[0], nonnull, gid_nn, n)
                + [_gsum(ctx, cols[1], gid, n)]
            )
        elif agg.fn in ("min", "max") and agg.arg is not None \
                and agg.arg.type.is_raw_string:
            from presto_tpu.ops import rawstring as rs

            nonnull = cols[1] > 0
            gid_nn = jnp.where(nonnull, gid, n)
            lanes = rs.pack_lanes(cols[0])
            best = _minmax_lanes(agg.fn, lanes, nonnull, gid_nn, n)
            out.append([
                rs.unpack_lanes(best, cols[0].shape[-1]),
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn == "min":
            out.append([
                _seg_min(cols[0], gid, n + 1)[:n],
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn == "max":
            out.append([
                _seg_max(cols[0], gid, n + 1)[:n],
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn in VARIANCE_FNS:
            # Chan's pairwise combination generalized to k partials:
            # M2 = Σ M2ᵢ + Σ cᵢ·(μᵢ − μ)²  with μ the combined mean.
            s_i, m2_i, c_i = cols
            s = _gsum(ctx, s_i, gid, n)
            cnt = _gsum(ctx, c_i, gid, n)
            mu = s / jnp.maximum(cnt, 1).astype(jnp.float64)
            cf_i = c_i.astype(jnp.float64)
            mu_i = s_i / jnp.maximum(cf_i, 1.0)
            dev = jnp.where(c_i > 0, mu_i - mu[jnp.clip(gid, 0, n - 1)], 0.0)
            m2 = _gsum(ctx, m2_i + cf_i * dev * dev, gid, n)
            out.append([s, m2, cnt])
        elif agg.fn in MOMENT_FNS:
            # Chan's pairwise combination generalized to M3/M4 with
            # δi = μi − μ (Σ ci δi = 0):
            #   M3 += 3 M2i δi + ci δi³
            #   M4 += 4 M3i δi + 6 M2i δi² + ci δi⁴
            s_i, m2_i, m3_i, m4_i, c_i = cols
            s = _gsum(ctx, s_i, gid, n)
            cnt = _gsum(ctx, c_i, gid, n)
            mu = s / jnp.maximum(cnt, 1).astype(jnp.float64)
            cf = c_i.astype(jnp.float64)
            mu_i = s_i / jnp.maximum(cf, 1.0)
            d = jnp.where(c_i > 0, mu_i - mu[jnp.clip(gid, 0, n - 1)], 0.0)
            d2 = d * d
            m2 = _gsum(ctx, m2_i + cf * d2, gid, n)
            m3 = _gsum(ctx, m3_i + 3.0 * m2_i * d + cf * d2 * d, gid, n)
            m4 = _gsum(ctx, m4_i + 4.0 * m3_i * d + 6.0 * m2_i * d2
                       + cf * d2 * d2, gid, n)
            out.append([s, m2, m3, m4, cnt])
        elif agg.fn in BITWISE_FNS:
            is_and = agg.fn == "bitwise_and_agg"
            ident = jnp.int64(-1) if is_and else jnp.int64(0)
            op = jnp.bitwise_and if is_and else jnp.bitwise_or
            has = cols[1] > 0
            v = jnp.where(has, cols[0], ident)
            acc = _seg_assoc(op, ident, v, jnp.where(gid < n, gid, n), n)
            out.append([acc, _gsum(ctx, cols[1], gid, n)])
        elif agg.fn in ("bool_and", "bool_or", "every"):
            out.append([_gsum(ctx, c, gid, n) for c in cols])
        elif agg.fn in COVAR_FNS:
            zero = [jnp.where(gid < n, c, jnp.zeros_like(c)) for c in cols]
            out.append([_gsum(ctx, c, gid, n) for c in zero])
        elif agg.fn == "checksum":
            out.append([_gsum(ctx, jnp.where(gid < n, cols[0], 0), gid, n)])
        elif agg.fn in ("min_by", "max_by"):
            x_i, xv_i, y_i, c_i = cols
            sel = c_i > 0
            gid_y = jnp.where(sel, gid, n)
            ycnt = _gsum(ctx, c_i, gid_y, n)
            if agg.fn == "min_by":
                yfill = _type_max(agg.arg2.type)
                y_best = _seg_min(
                    jnp.where(sel, y_i, yfill), gid_y, n + 1)[:n]
            else:
                yfill = _type_min(agg.arg2.type)
                y_best = _seg_max(
                    jnp.where(sel, y_i, yfill), gid_y, n + 1)[:n]
            tie = sel & (y_i == y_best[jnp.clip(gid_y, 0, n - 1)])
            xv_in = tie & (xv_i > 0)
            x_best = _seg_max(
                jnp.where(xv_in, x_i, _type_min(agg.arg.type)),
                jnp.where(xv_in, gid, n), n + 1)[:n]
            xv_cnt = _gsum(ctx, xv_in.astype(jnp.int64), jnp.where(xv_in, gid, n), n)
            out.append([x_best, (xv_cnt > 0).astype(jnp.int64), y_best, ycnt])
        elif agg.fn == "hll_merge":
            out.append([
                _gsum(ctx, cols[0], gid, n),
                _gsum(ctx, cols[1], gid, n),
            ])
        elif agg.fn in ("learn_regressor", "learn_classifier",
                        "evaluate_classifier_predictions"):
            arr, cnt = cols
            zero_dead = jnp.where((gid < n)[:, None], arr,
                                  jnp.zeros((), arr.dtype))
            out.append([
                _gsum(ctx, zero_dead, gid, n),
                _gsum(ctx, cnt, gid, n),
            ])
        elif agg.fn in ("array_agg", "map_agg", "hll_sketch",
                        "multimap_agg", "map_union"):
            # concatenate partial containers per group: each partial
            # row's elements land at the group's running offset (stable
            # order).  Halves: arrays have one value lane per rank; maps
            # add a key half; multimaps' value half is an (av)-wide
            # matrix per rank — all three share the offset geometry.
            arr_col, cnt_col = cols
            at = state_types(agg)[0]
            cap_e = at.max_elems
            storage = arr_col.dtype
            sent = _container_sent(storage)
            l0 = arr_col[:, 0]
            if jnp.issubdtype(storage, jnp.floating):
                l0 = jnp.where(jnp.isnan(l0), 0.0, l0)
            lens = jnp.where(gid < n, jnp.maximum(l0.astype(jnp.int64), 0), 0)
            order = jnp.argsort(gid, stable=True)
            gs = gid[order]
            lens_s = lens[order]
            cum = jnp.cumsum(lens_s) - lens_s  # global exclusive prefix
            first = jnp.concatenate([jnp.ones(1, jnp.bool_), gs[1:] != gs[:-1]])
            base = jax.lax.cummax(jnp.where(first, cum, 0))
            off_s = cum - base
            off = jnp.zeros_like(off_s).at[order].set(off_s)
            j = jnp.arange(cap_e, dtype=jnp.int64)[None, :]
            ok = (j < lens[:, None]) & ((off[:, None] + j) < cap_e) & (gid < n)[:, None]
            tgt = jnp.where(
                ok, gid.astype(jnp.int64)[:, None] * cap_e + off[:, None] + j,
                n * cap_e,
            )
            total = _gsum(ctx, lens, gid, n)
            length = jnp.minimum(total, cap_e).astype(storage)
            if agg.fn == "array_agg":
                widths = [1]
            elif agg.fn == "multimap_agg":
                widths = [1, 1 + at.element.max_elems]
            else:
                widths = [1, 1]
            halves = []
            o = 1
            for w in widths:
                seg = arr_col[:, o: o + cap_e * w].reshape(-1, w)
                flat = jnp.full((n * cap_e, w), sent, dtype=storage)
                flat = flat.at[tgt.reshape(-1)].set(seg, mode="drop")
                halves.append(flat.reshape(n, cap_e * w))
                o += cap_e * w
            out.append([
                jnp.concatenate([length[:, None]] + halves, axis=1),
                _gsum(ctx, cnt_col, gid, n),
            ])
        elif agg.fn in ("make_set_digest", "merge_set_digest"):
            # KMV union: the K smallest of the union of per-partial
            # K-smallest lanes IS the union's K smallest (semilattice),
            # with counts summing on shared hashes
            arr_col, cnt_col = cols
            cap_e = state_types(agg)[0].max_elems
            storage = arr_col.dtype
            egid, hs, cs, lane_ok = _digest_entries(arr_col, gid, n, cap_e)
            state, _ = _kmv_lanes(egid, hs, cs, lane_ok, n, cap_e, storage)
            # distinct totals OVERCOUNT across partials (shared hashes);
            # the estimator only reads them below cap_e, where the lane
            # union is exact — recompute from the merged lanes
            merged_distinct = state[:, 0].astype(jnp.int64)
            total = _gsum(ctx, cnt_col, gid, n)
            distinct = jnp.where(merged_distinct < cap_e, merged_distinct,
                                 jnp.maximum(total, merged_distinct))
            state = state.at[:, 0].set(
                jnp.minimum(distinct, cap_e).astype(storage))
            out.append([state, distinct])
        elif agg.fn in ("max_n", "min_n", "max_by_n", "min_by_n"):
            # top-n of the union of per-partial top-n lanes IS the
            # global top-n (semilattice), so merging re-runs the same
            # ordered scatter over the flattened lanes
            arr_col, cnt_col = cols
            cap_e = state_types(agg)[0].max_elems
            storage = arr_col.dtype
            by = agg.fn in ("max_by_n", "min_by_n")
            l0 = arr_col[:, 0]
            if jnp.issubdtype(storage, jnp.floating):
                l0 = jnp.where(jnp.isnan(l0), 0.0, l0)
            lens = jnp.where(gid < n, jnp.maximum(l0.astype(jnp.int64), 0), 0)
            j = jnp.arange(cap_e, dtype=jnp.int64)[None, :]
            lane_ok = j < jnp.minimum(lens, cap_e)[:, None]
            vals = arr_col[:, 1:1 + cap_e]
            keys = arr_col[:, 1 + cap_e:1 + 2 * cap_e] if by else vals
            egid = jnp.where(lane_ok, gid[:, None], n)
            # ctx=None: the sort ctx's gather order covers row-length
            # arrays, not the rows*cap_e flattened lanes
            halves, _ = _topn_halves(
                None, egid.reshape(-1), keys.reshape(-1), vals.reshape(-1),
                lane_ok.reshape(-1), n, cap_e, storage,
                descending=agg.fn in ("max_n", "max_by_n"), with_keys=by)
            total = _gsum(ctx, cnt_col, gid, n)
            length = jnp.minimum(total, cap_e).astype(storage)
            out.append([
                jnp.concatenate([length[:, None]] + halves, axis=1), total,
            ])
        else:
            raise KeyError(agg.fn)
    return out


def _agg_dict(agg: AggCall, dictionaries) -> Optional[object]:
    """Dictionary carried through value-preserving aggregates
    (min/max/min_by/max_by of a VARCHAR argument)."""
    if agg.fn not in ("min", "max", "min_by", "max_by", "array_agg"):
        return None
    if agg.arg is None or not agg.arg.type.is_string:
        return None
    from presto_tpu.expr.compile import expr_dictionary

    return expr_dictionary(agg.arg, dictionaries)


# (id(dict)) -> (dict ref, rank list, inv list); host lists so nothing
# device-resident leaks across traces (cached: the sort is O(n log n)
# per dictionary and the eager spill path calls kernels per page)
_COLLATION_CACHE: dict = {}

# (id(dict)) -> (dict ref, has_duplicate_values) — derived dictionaries
# (substr, date_format, day_name...) may map MANY codes to one value
_DUP_CACHE: dict = {}


def _dict_has_duplicates(d) -> bool:
    got = _DUP_CACHE.get(id(d))
    if got is not None:
        return got[1]
    dup = len(set(d.values)) < len(d.values)
    _DUP_CACHE[id(d)] = (d, dup)
    return dup


def canonicalize_codes(datas, dicts):
    """Replace each dictionary-coded key column's codes with the
    representative code of their VALUE class when the dictionary holds
    duplicate values — grouping, DISTINCT, joins, window partitions and
    exchange routing must follow value equality, not code identity.
    Non-string columns and injective dictionaries pass through
    untouched (the common case: zero cost)."""
    out = []
    for d, dic in zip(datas, dicts):
        if dic is None or not _dict_has_duplicates(dic):
            out.append(d)
            continue
        rank, inv = _collation_luts(dic)
        c = jnp.clip(d, 0, rank.shape[0] - 1)
        out.append(inv[rank[c]].astype(d.dtype))
    return out


def expr_key_dicts(page: Page, exprs) -> list:
    """Dictionary provenance per key expression (None for non-string)."""
    from presto_tpu.expr.compile import expr_dictionary

    dicts = [b.dictionary for b in page.blocks]
    return [expr_dictionary(e, dicts) if e.type.is_string else None
            for e in exprs]


def _collation_luts(d) -> Tuple[jax.Array, jax.Array]:
    """(code -> collation rank, rank -> representative code) LUTs.
    Dictionary codes are assignment-ordered, not collation-ordered, so
    string min/max must reduce over ranks (duplicate values share a
    rank; the inverse picks a representative code)."""
    cached = _COLLATION_CACHE.get(id(d))
    if cached is not None:
        _, rank, inv = cached
        return (jnp.asarray(rank, dtype=jnp.int32), jnp.asarray(inv, dtype=jnp.int32))
    values = d.values
    order = sorted(range(len(values)), key=lambda i: values[i])
    rank = [0] * len(values)
    inv = [0] * len(values)
    prev = None
    r = 0
    for pos, i in enumerate(order):
        if values[i] != prev:
            r = pos
            prev = values[i]
            inv[r] = i
        rank[i] = r
    _COLLATION_CACHE[id(d)] = (d, rank, inv)
    return (jnp.asarray(rank, dtype=jnp.int32), jnp.asarray(inv, dtype=jnp.int32))


def _finalize(states: List[List[jax.Array]], aggs, agg_dicts=None) -> List[Block]:
    blocks = []
    agg_dicts = agg_dicts or [None] * len(aggs)
    for agg, cols, adict in zip(aggs, states, agg_dicts):
        t = output_type(agg)
        if agg.fn in ("count", "count_star"):
            blocks.append(Block(cols[0].astype(jnp.int64), jnp.ones_like(cols[0], jnp.bool_), t))
        elif agg.fn in ("sum", "sum0"):
            # sum0 = sum with 0-on-empty: the outer half of a decomposed
            # plain count in the mixed-DISTINCT rewrite (never NULL)
            s, cnt = cols
            st = _sum_type(agg.arg.type) if agg.arg is not None else t
            if st.is_long_decimal and agg.type.is_decimal \
                    and not agg.type.is_long_decimal:
                # outer half of a decomposed sum (mixed-DISTINCT
                # rewrite): the fold runs in widened limbs because the
                # partial-sum argument types as p=18, but the plan keeps
                # the original short output type — collapse like avg
                s = s[..., 0] * jnp.int64(10 ** 18) + s[..., 1]
                t = agg.type
            valid = cnt > 0 if agg.fn == "sum" \
                else jnp.ones_like(cnt, jnp.bool_)
            blocks.append(Block(s.astype(t.np_dtype), valid, t))
        elif agg.fn == "avg":
            s, cnt = cols
            st = _sum_type(agg.arg.type)
            n = jnp.maximum(cnt, 1)
            if t.is_decimal and st.is_long_decimal:
                # exact unscaled-sum / count, HALF_UP, staying decimal
                q = _avg_decimal128(s, n)
                if not t.is_long_decimal:
                    # widened accumulator over a short p>15 argument:
                    # the per-group mean fits the argument type again
                    q = q[..., 0] * jnp.int64(10 ** 18) + q[..., 1]
                blocks.append(Block(q, cnt > 0, t))
            elif t.is_decimal:
                av = jnp.abs(s)
                sign = jnp.where(s < 0, -1, 1)
                # overflow-free HALF_UP away from zero (2*av could wrap
                # for sums near the decimal(18) accumulator ceiling)
                q = av // n
                q = q + (2 * (av - q * n) >= n).astype(q.dtype)
                blocks.append(Block((sign * q).astype(t.np_dtype), cnt > 0, t))
            elif t.name.startswith("interval"):
                # exact integer average with HALF-UP away from zero —
                # same machinery as the decimal branch (float division
                # would lose microseconds once the sum passes 2^53)
                av = jnp.abs(s)
                sign = jnp.where(s < 0, -1, 1)
                q = av // n
                q = q + (2 * (av - q * n) >= n).astype(q.dtype)
                blocks.append(Block((sign * q).astype(jnp.int64),
                                    cnt > 0, t))
            else:
                num = s.astype(jnp.float64)
                d = num / n.astype(jnp.float64)
                blocks.append(Block(d, cnt > 0, t))
        elif agg.fn in ("min", "max"):
            m, cnt = cols
            if adict is not None:
                # state holds collation ranks; map back to codes
                _, inv_lut = _collation_luts(adict)
                m = inv_lut[jnp.clip(m.astype(jnp.int32), 0, inv_lut.shape[0] - 1)]
            blocks.append(Block(m.astype(t.np_dtype), cnt > 0, t, adict))
        elif agg.fn in VARIANCE_FNS:
            s, m2, cnt = cols
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            pop_var = jnp.maximum(m2 / n, 0.0)
            sample = agg.fn in ("stddev", "stddev_samp", "variance", "var_samp")
            if sample:
                var = pop_var * n / jnp.maximum(n - 1, 1)
                valid = cnt > 1
            else:
                var = pop_var
                valid = cnt > 0
            out_v = jnp.sqrt(var) if agg.fn.startswith("stddev") else var
            blocks.append(Block(out_v, valid, t))
        elif agg.fn in COVAR_FNS:
            sx, sy, sxy, sxx, syy, cnt = cols
            nf = jnp.maximum(cnt, 1).astype(jnp.float64)
            cov = sxy / nf - (sx / nf) * (sy / nf)
            varx = jnp.maximum(sxx / nf - (sx / nf) ** 2, 0.0)
            vary = jnp.maximum(syy / nf - (sy / nf) ** 2, 0.0)
            if agg.fn == "covar_pop":
                v, ok = cov, cnt > 0
            elif agg.fn == "covar_samp":
                v = cov * nf / jnp.maximum(nf - 1, 1.0)
                ok = cnt > 1
            elif agg.fn == "corr":
                denom = jnp.sqrt(varx * vary)
                v = cov / jnp.where(denom == 0, 1.0, denom)
                ok = (cnt > 1) & (denom > 0)
            elif agg.fn == "regr_slope":
                v = cov / jnp.where(varx == 0, 1.0, varx)
                ok = (cnt > 1) & (varx > 0)
            else:  # regr_intercept
                slope = cov / jnp.where(varx == 0, 1.0, varx)
                v = sy / nf - slope * (sx / nf)
                ok = (cnt > 1) & (varx > 0)
            blocks.append(Block(v, ok, t))
        elif agg.fn == "checksum":
            blocks.append(Block(cols[0].astype(jnp.int64),
                                jnp.ones_like(cols[0], jnp.bool_), t))
        elif agg.fn in ("bool_and", "bool_or", "every"):
            trues, cnt = cols
            if agg.fn == "bool_or":
                v = trues > 0
            else:
                v = trues == cnt
            blocks.append(Block(v, cnt > 0, t))
        elif agg.fn in MOMENT_FNS:
            _s, m2, m3, m4, cnt = cols
            nf = jnp.maximum(cnt, 1).astype(jnp.float64)
            safe_m2 = jnp.where(m2 == 0, 1.0, m2)
            if agg.fn == "skewness":
                # sqrt(n) * M3 / M2^1.5 (CentralMomentsAggregation)
                v = jnp.sqrt(nf) * m3 / jnp.power(safe_m2, 1.5)
                ok = (cnt >= 3) & (m2 > 0)
            else:
                # unbiased sample excess kurtosis (Σd⁴/s⁴ with
                # s² = M2/(n−1)):
                #   n(n+1)(n−1)/((n−2)(n−3)) · M4/M2² −
                #   3(n−1)²/((n−2)(n−3))
                d1, d2, d3 = nf - 1.0, jnp.maximum(nf - 2.0, 1.0), \
                    jnp.maximum(nf - 3.0, 1.0)
                v = (nf * (nf + 1.0) * d1 / (d2 * d3)
                     * (m4 / (safe_m2 * safe_m2))
                     - 3.0 * d1 * d1 / (d2 * d3))
                ok = (cnt >= 4) & (m2 > 0)
            blocks.append(Block(v, ok, t))
        elif agg.fn in BITWISE_FNS:
            acc, cnt = cols
            blocks.append(Block(acc.astype(jnp.int64), cnt > 0, t))
        elif agg.fn in ("min_by", "max_by"):
            x, xv, _y, cnt = cols
            blocks.append(Block(x.astype(t.np_dtype), (cnt > 0) & (xv > 0), t, adict))
        elif agg.fn == "learn_regressor":
            s, cnt = cols
            dim = agg.arg2.type.max_elems + 1
            n = s.shape[0]
            xtx = s[:, 1 : 1 + dim * dim].reshape(n, dim, dim)
            xty = s[:, 1 + dim * dim : 1 + dim * dim + dim]
            # tiny ridge keeps rank-deficient groups solvable
            reg = 1e-8 * jnp.eye(dim)[None, :, :]
            w = jnp.linalg.solve(xtx + reg, xty[..., None])[..., 0]
            model = jnp.concatenate([jnp.full((n, 1), float(dim)), w], axis=1)
            blocks.append(Block(model.astype(t.np_dtype), cnt > 0, t))
        elif agg.fn == "learn_classifier":
            s, cnt = cols
            k = agg.arg2.type.max_elems
            C = ML_MAX_CLASSES
            n = s.shape[0]
            counts = s[:, 1 : 1 + C]
            sumx = s[:, 1 + C : 1 + C + C * k].reshape(n, C, k)
            sumx2 = s[:, 1 + C + C * k : 1 + C + 2 * C * k].reshape(n, C, k)
            total = jnp.maximum(jnp.sum(counts, axis=1, keepdims=True), 1.0)
            prior = counts / total
            cc = jnp.maximum(counts, 1.0)[:, :, None]
            mean = sumx / cc
            var = jnp.maximum(sumx2 / cc - mean ** 2, 1e-9)
            model = jnp.concatenate([
                jnp.full((n, 1), float(1 + C * (1 + 2 * k))),
                jnp.full((n, 1), float(C)),
                prior, mean.reshape(n, C * k), var.reshape(n, C * k),
            ], axis=1)
            blocks.append(Block(model.astype(t.np_dtype), cnt > 0, t))
        elif agg.fn in ("array_agg", "map_agg", "hll_sketch",
                        "multimap_agg", "map_union", "max_n", "min_n",
                        "make_set_digest", "merge_set_digest"):
            arr_state, cnt = cols
            blocks.append(Block(arr_state.astype(t.np_dtype), cnt > 0, t, adict))
        elif agg.fn == "evaluate_classifier_predictions":
            # transient: int64 count matrix under the VARCHAR type —
            # LocalRunner._host_finalize_aggs rewrites it to dictionary
            # codes immediately after the final merge (strings cannot
            # be built inside jit)
            arr_state, cnt = cols
            blocks.append(Block(arr_state.astype(jnp.int64), cnt > 0, t))
        elif agg.fn in ("max_by_n", "min_by_n"):
            # drop the ordering-key half of the state; convert the
            # shared-storage sentinel to the output array's own
            cap_e = state_types(agg)[0].max_elems
            arr_state, cnt = cols
            sub = arr_state[:, :1 + cap_e]
            if jnp.issubdtype(sub.dtype, jnp.floating) \
                    and not jnp.issubdtype(t.np_dtype, jnp.floating):
                osent = _container_sent(t.np_dtype)
                body = jnp.where(jnp.isnan(sub[:, 1:]),
                                 jnp.float64(osent), sub[:, 1:])
                l0 = jnp.where(jnp.isnan(sub[:, :1]), 0.0, sub[:, :1])
                sub = jnp.concatenate([l0, body], axis=1)
            blocks.append(Block(sub.astype(t.np_dtype), cnt > 0, t, adict))
        elif agg.fn == "hll_merge":
            # HLL estimator with linear-counting small-range correction
            # (airlift HyperLogLog / the original Flajolet et al. paper)
            s, present = cols
            m = float(HLL_M)
            alpha = 0.7213 / (1.0 + 1.079 / m)
            zeros = m - present.astype(jnp.float64)
            s_full = s + zeros  # absent buckets contribute 2^-0 = 1
            raw = alpha * m * m / jnp.maximum(s_full, 1e-12)
            lc = m * jnp.log(m / jnp.maximum(zeros, 1.0))
            est = jnp.where((raw <= 2.5 * m) & (zeros > 0), lc, raw)
            blocks.append(Block(jnp.round(est).astype(jnp.int64),
                                jnp.ones_like(present, jnp.bool_), t))
        else:
            raise KeyError(agg.fn)
    return blocks


def _avg_decimal128(s: jax.Array, n: jax.Array) -> jax.Array:
    """Exact limb-decimal sum divided by int64 count with HALF_UP
    rounding, keeping the unscaled representation — the finalize of
    avg(decimal) over a limb accumulator.  Long division over base-10^6
    (or, for wide 5-limb sums, base-10^9) digits so the running
    remainder times the base never overflows int64 (sound for counts <
    2^43 / 2^33 respectively — far above any page capacity)."""
    from presto_tpu.ops import decimal128 as d128

    neg = s[..., 0] < 0
    a = jnp.where(neg[..., None], d128.neg(s), s)
    if a.shape[-1] == d128.WIDE_LIMBS:
        r = jnp.zeros_like(n)
        qs = []
        for i in range(d128.WIDE_LIMBS):
            cur = r * jnp.int64(d128._B9) + a[..., i]
            qs.append(jnp.floor_divide(cur, n))
            r = cur - qs[-1] * n
        q = jnp.stack(qs, axis=-1)
        q = q.at[..., -1].add((2 * r >= n).astype(jnp.int64))
        q = d128._norm_wide(q)
        return jnp.where(neg[..., None], d128.neg(q), q)
    hi, lo = a[..., 0], a[..., 1]
    m = jnp.int64(1_000_000)
    digits = [hi // (m * m), (hi // m) % m, hi % m,
              lo // (m * m), (lo // m) % m, lo % m]
    r = jnp.zeros_like(n)
    qs = []
    for d in digits:
        cur = r * m + d
        qs.append(cur // n)
        r = cur % n
    q_hi = (qs[0] * m + qs[1]) * m + qs[2]
    q_lo = (qs[3] * m + qs[4]) * m + qs[5]
    q_lo = q_lo + (2 * r >= n).astype(jnp.int64)  # HALF_UP
    q = d128.normalize(q_hi, q_lo)
    return jnp.where(neg[..., None], d128.neg(q), q)


def _type_max(t: Type):
    return jnp.asarray(jnp.finfo(jnp.float64).max if t.name == "double" else _I64_MAX).astype(t.np_dtype)


def _type_min(t: Type):
    return jnp.asarray(jnp.finfo(jnp.float64).min if t.name == "double" else -_I64_MAX - 1).astype(t.np_dtype)


def _minmax_lanes(fn: str, lanes, nonnull, gid_nn, n):
    """k-phase lexicographic segment extreme over (rows, k) int64
    lanes: phase c reduces lane c among rows still tying on lanes
    < c (generalizes _minmax_long's two-limb walk)."""
    red = _seg_min if fn == "min" else _seg_max
    fill = _I64_MAX if fn == "min" else -_I64_MAX - 1
    tie = nonnull
    gid_cur = gid_nn
    best = []
    for c in range(lanes.shape[-1]):
        b = red(jnp.where(tie, lanes[..., c], fill), gid_cur, n + 1)[:n]
        best.append(b)
        tie = tie & (lanes[..., c] == b[jnp.clip(gid_cur, 0, n - 1)])
        gid_cur = jnp.where(tie, gid_nn, n)
    return jnp.stack(best, axis=-1)


def _minmax_long(fn: str, data, nonnull, gid_nn, n):
    """Phased lexicographic extreme over limb vectors, msb limb first —
    canonical limb order IS value order (limbs[1:] in [0, base)).
    Works for both the (.., 2) and the wide (.., 5) layouts."""
    L = int(data.shape[-1])
    if fn == "min":
        red, fill = _seg_min, _I64_MAX
    else:
        red, fill = _seg_max, -_I64_MAX - 1
    tie = nonnull
    bests = []
    for i in range(L):
        limb = data[..., i]
        gid_tie = jnp.where(tie, gid_nn, n)
        best = red(jnp.where(tie, limb, fill), gid_tie, n + 1)[:n]
        bests.append(best)
        tie = tie & (limb == best[jnp.clip(gid_nn, 0, n - 1)])
    return [jnp.stack(bests, axis=-1)]


# ---------------------------------------------------------------------------
# group id assignment
# ---------------------------------------------------------------------------

def _mix64(x: jax.Array) -> jax.Array:
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _key_codes(datas, valids, domains):
    """Per-column null-aware codes (0 = NULL), plus cardinalities."""
    codes, cards = [], []
    for (d, v), dom in zip(zip(datas, valids), domains):
        lo, hi = dom
        code = jnp.where(v, d.astype(jnp.int64) - lo + 1, 0)
        codes.append(code)
        cards.append(int(hi - lo + 2))
    return codes, cards


def pack_or_hash_keys(datas, valids, domains) -> Tuple[jax.Array, bool]:
    """Combine key columns into one integer key. Exact packing when
    domains fit 63 bits (always true for TPC-H keys); else 64-bit mix
    (collision odds ~ n^2/2^65 — the planner can demand exactness by
    supplying domains).

    TPU dtype note: packed keys narrow to int32 when the domain product
    fits 31 bits — int64 is emulated on TPU (v5e has no native 64-bit
    lanes), so narrow keys make the downstream sorts/searches/scatters
    run at native width."""
    if not datas:
        return None, True
    if any(d.ndim > 1 for d in datas):
        # raw-varchar keys fold through a byte hash lane; long-decimal
        # limbs have no safe hash-collision semantics for decimals
        from presto_tpu.ops.rawstring import hash_bytes

        lanes = []
        for d, v in zip(datas, valids):
            if d.ndim > 1 and d.dtype == jnp.uint8:
                lanes.append((hash_bytes(d), v))
            elif d.ndim > 1:
                raise ValueError(
                    "long-decimal grouping/join keys unsupported (cast to "
                    "a shorter decimal or double)")
            else:
                lanes.append((d, v))
        h = jnp.zeros(datas[0].shape[0], dtype=jnp.uint64)
        for d, v in lanes:
            lane = jnp.where(v, d.astype(jnp.int64), 0).astype(jnp.uint64)
            h = _mix64(h ^ _mix64(lane + jnp.uint64(0x9E37) * v.astype(jnp.uint64)))
        return h.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF), False
    if domains is not None and all(d is not None for d in domains):
        codes, cards = _key_codes(datas, valids, domains)
        prod = 1
        for c in cards:
            prod *= c
        if prod < (1 << 62):
            key = jnp.zeros_like(codes[0])
            for code, card in zip(codes, cards):
                key = key * card + code
            if prod < (1 << 31):
                key = key.astype(jnp.int32)
            return key, True
    h = jnp.zeros(datas[0].shape, dtype=jnp.uint64)
    for d, v in zip(datas, valids):
        # NULLs must hash identically regardless of residual data: zero
        # the data lane and fold the null flag in separately.
        lane = jnp.where(v, d.astype(jnp.int64), 0).astype(jnp.uint64)
        h = _mix64(h ^ _mix64(lane + jnp.uint64(0x9E37) * v.astype(jnp.uint64)))
    return h.astype(jnp.int64) & jnp.int64(0x7FFFFFFFFFFFFFFF), False


@dataclasses.dataclass(frozen=True)
class _SortCtx:
    """Sorted-run geometry from _sorted_group_ids, enabling large-G
    segment sums as gather+cumsum+boundary-difference instead of XLA
    scatter-add (scatter serializes on TPU and compiles pathologically
    slowly at big shapes; cumsum is one vector pass).

    order:  (rows,) row index per sorted position
    starts: (max_groups,) sorted position of each group's first row
    ends:   (max_groups,) sorted position of each group's last row
    group_live: (max_groups,) group index < num_groups
    """

    order: jax.Array
    starts: jax.Array
    ends: jax.Array
    group_live: jax.Array

    def sum(self, vals: jax.Array, gid: jax.Array, n: int) -> jax.Array:
        """Per-group sums for groups 0..n-1; rows with gid >= n (dead /
        filtered / null per this aggregate) contribute zero."""
        dead = gid >= n
        if vals.ndim > 1:
            dead = dead[:, None]
            glive = self.group_live[:, None]
        else:
            glive = self.group_live
        vals_z = jnp.where(dead, jnp.zeros_like(vals), vals)
        vs = jnp.take(vals_z, self.order, axis=0)
        cs = jnp.cumsum(vs, axis=0)
        ends = jnp.clip(self.ends, 0, vs.shape[0] - 1)
        starts = jnp.clip(self.starts, 0, vs.shape[0] - 1)
        seg = (jnp.take(cs, ends, axis=0) - jnp.take(cs, starts, axis=0)
               + jnp.take(vs, starts, axis=0))
        return jnp.where(glive, seg, jnp.zeros_like(seg))


def _sorted_group_ids(key: jax.Array, live: jax.Array, max_groups: int,
                      want_ctx: bool = False):
    """Shared sort-path grouping: returns per-row group ids (dead rows
    -> max_groups), the live group count, and a representative row per
    group (first sorted occurrence); with ``want_ctx`` also the
    _SortCtx for cumsum-based segment reductions."""
    sentinel = jnp.iinfo(key.dtype).max
    key_live = jnp.where(live, key, sentinel)
    order = jnp.argsort(key_live)
    sk = key_live[order]
    is_live_sorted = sk != sentinel
    first = jnp.concatenate([jnp.ones(1, jnp.bool_), sk[1:] != sk[:-1]]) & is_live_sorted
    gid_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    gid_sorted = jnp.where(is_live_sorted, jnp.minimum(gid_sorted, max_groups), max_groups)
    num_groups = jnp.sum(first.astype(jnp.int32))
    gid = jnp.zeros_like(gid_sorted).at[order].set(gid_sorted)
    gid = jnp.where(live, gid, max_groups).astype(jnp.int32)
    rep_slot = jnp.where(first, gid_sorted, max_groups)
    rep_rows = (
        jnp.zeros(max_groups + 1, dtype=jnp.int32)
        .at[rep_slot]
        .set(order.astype(jnp.int32), mode="drop")
    )[:max_groups]
    if not want_ctx:
        return gid, num_groups, rep_rows
    idx = jnp.arange(sk.shape[0], dtype=jnp.int32)
    starts = (
        jnp.zeros(max_groups + 1, dtype=jnp.int32)
        .at[rep_slot]
        .set(idx, mode="drop")
    )[:max_groups]
    live_count = jnp.sum(is_live_sorted.astype(jnp.int32))
    g = jnp.arange(max_groups, dtype=jnp.int32)
    next_start = jnp.where(g + 1 < num_groups,
                           jnp.concatenate([starts[1:], jnp.zeros(1, jnp.int32)]),
                           live_count)
    ctx = _SortCtx(order=order, starts=starts, ends=next_start - 1,
                   group_live=g < num_groups)
    return gid, num_groups, rep_rows, ctx


def _presorted_group_ids(key: jax.Array, live: jax.Array, max_groups: int):
    """Streaming-aggregation grouping (StreamingAggregationOperator.java:38
    analog): input rows arrive grouped (equal keys contiguous), so run
    boundaries come from comparing each live row with the previous LIVE
    row (cummax forward-fill skips filtered holes) — no sort at all.
    Returns the same (gid, num_groups, rep_rows, ctx) shape as
    _sorted_group_ids with an identity traversal order."""
    rows = key.shape[0]
    idx = jnp.arange(rows, dtype=jnp.int32)
    last_live = jax.lax.cummax(jnp.where(live, idx, -1))
    prev_live = jnp.concatenate([jnp.full(1, -1, jnp.int32), last_live[:-1]])
    prev_key = key[jnp.clip(prev_live, 0, rows - 1)]
    first = live & ((prev_live < 0) | (prev_key != key))
    gid_raw = jnp.cumsum(first.astype(jnp.int32)) - 1
    gid = jnp.where(live, jnp.minimum(gid_raw, max_groups), max_groups).astype(jnp.int32)
    num_groups = jnp.sum(first.astype(jnp.int32))
    rep_slot = jnp.where(first, gid_raw, max_groups)
    starts = (
        jnp.zeros(max_groups + 1, dtype=jnp.int32)
        .at[rep_slot]
        .set(idx, mode="drop")
    )[:max_groups]
    g = jnp.arange(max_groups, dtype=jnp.int32)
    next_start = jnp.where(g + 1 < num_groups,
                           jnp.concatenate([starts[1:], jnp.zeros(1, jnp.int32)]),
                           rows)
    ctx = _SortCtx(order=idx, starts=starts, ends=next_start - 1,
                   group_live=g < num_groups)
    return gid, num_groups, starts, ctx


# ---------------------------------------------------------------------------
# main kernels
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GroupLayout:
    """Static description of a grouped aggregation's output page:
    group-key blocks then one block per state column (partial) or per
    aggregate (final/single)."""

    num_keys: int
    aggs: Tuple[AggCall, ...]
    mode: str  # single | partial | final


def grouped_aggregate(
    page: Page,
    group_exprs: Sequence[Expr],
    aggs: Sequence[AggCall],
    max_groups: int,
    key_domains: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
    mode: str = "single",
    return_count: bool = False,
    presorted: bool = False,
) -> Page:
    """Aggregate ``page`` by ``group_exprs``.  With ``presorted=True``
    the input is promised to arrive with equal group keys contiguous
    (streaming aggregation) and grouping skips the argsort.

    mode='single' emits finalized values; 'partial' emits state columns
    (for exchange + merge_aggregate).

    Overflow: if the input has more than ``max_groups`` distinct keys
    the output is silently truncated to the first ``max_groups`` groups
    in key order — pass ``return_count=True`` to get (page, num_groups)
    so the driver can detect ``num_groups > max_groups`` and re-plan
    with a larger capacity (the reference instead rehashes:
    MultiChannelGroupByHash.java:138-145 tryRehash).
    """
    c = ExprCompiler.for_page(page)
    kd = [c.compile(e)(page) for e in group_exprs]
    key_dicts = expr_key_dicts(page, group_exprs)
    datas = canonicalize_codes([d for d, _ in kd], key_dicts)
    valids = [v for _, v in kd]
    kd = list(zip(datas, valids))  # rep rows must carry canonical codes
    agg_dicts = [_agg_dict(a, [b.dictionary for b in page.blocks])
                 for a in aggs]

    live = page.row_mask

    if not group_exprs:
        # global aggregation: one group
        gid = jnp.where(live, 0, 1)
        states = _partial_states(page, aggs, gid, 1)
        key_blocks: List[Block] = []
        out_mask = jnp.ones(1, dtype=jnp.bool_)
        out = _emit(key_blocks, states, aggs, out_mask, mode, group_exprs, key_dicts, agg_dicts)
        return (out, jnp.ones((), jnp.int32)) if return_count else out

    key, exact = pack_or_hash_keys(datas, valids, key_domains)

    if presorted:
        # streaming path: run boundaries from the input order itself
        gid, num_groups, rep_rows, ctx = _presorted_group_ids(key, live, max_groups)
        states = _partial_states(page, aggs, gid, max_groups, ctx=ctx)
        key_blocks = []
        for (d, v), e, dic in zip(kd, group_exprs, key_dicts):
            key_blocks.append(Block(d[rep_rows].astype(e.type.np_dtype),
                                    v[rep_rows], e.type, dic))
        out_mask = jnp.arange(max_groups) < num_groups
        out = _emit(key_blocks, states, aggs, out_mask, mode, group_exprs,
                    key_dicts, agg_dicts)
        return (out, num_groups) if return_count else out

    # packed-direct: group id == packed key, no sort; output capacity is
    # always max_groups (padded above prod) so downstream shapes match
    # the sort path.
    if exact and key_domains is not None and all(d is not None for d in key_domains):
        _, cards = _key_codes(datas, valids, key_domains)
        prod = 1
        for card in cards:
            prod *= card
        if prod <= min(max_groups, DIRECT_GROUP_LIMIT):
            gid = jnp.where(live, key, max_groups)
            states = _partial_states(page, aggs, gid, max_groups)
            present = _seg_sum(live.astype(jnp.int64), gid, max_groups + 1)[:max_groups] > 0
            key_blocks = _unpack_key_blocks(
                cards, key_domains, group_exprs, key_dicts, prod, max_groups
            )
            out = _emit(key_blocks, states, aggs, present, mode, group_exprs, key_dicts, agg_dicts)
            return (out, jnp.sum(present.astype(jnp.int32))) if return_count else out

    # sort path
    gid, num_groups, rep_rows, ctx = _sorted_group_ids(
        key, live, max_groups, want_ctx=True)
    states = _partial_states(page, aggs, gid, max_groups, ctx=ctx)
    key_blocks = []
    for (d, v), e, dic in zip(kd, group_exprs, key_dicts):
        kb_data = d[rep_rows].astype(e.type.np_dtype)
        kb_valid = v[rep_rows]
        key_blocks.append(Block(kb_data, kb_valid, e.type, dic))
    out_mask = jnp.arange(max_groups) < num_groups
    out = _emit(key_blocks, states, aggs, out_mask, mode, group_exprs, key_dicts, agg_dicts)
    return (out, num_groups) if return_count else out


def _unpack_key_blocks(cards, domains, group_exprs, key_dicts, prod, capacity) -> List[Block]:
    gids = jnp.arange(capacity, dtype=jnp.int64)
    in_range = gids < prod
    blocks = []
    stride = prod
    for card, (lo, _), e, dic in zip(cards, domains, group_exprs, key_dicts):
        stride //= card
        code = (gids // stride) % card
        data = (code - 1 + lo).astype(e.type.np_dtype)
        blocks.append(Block(data, (code > 0) & in_range, e.type, dic))
    return blocks


def _emit(key_blocks, states, aggs, out_mask, mode, group_exprs, key_dicts,
          agg_dicts=None) -> Page:
    agg_dicts = agg_dicts or [None] * len(aggs)
    if mode == "partial":
        blocks = list(key_blocks)
        for agg, cols, adict in zip(aggs, states, agg_dicts):
            for j, (t, colv) in enumerate(zip(state_types(agg), cols)):
                blocks.append(Block(colv.astype(t.np_dtype), out_mask, t,
                                    adict if j == 0 else None))
        return Page(tuple(blocks), out_mask)
    agg_blocks = _finalize(states, aggs, agg_dicts)
    # clamp validity to live groups
    agg_blocks = [Block(b.data, b.valid & out_mask, b.type, b.dictionary) for b in agg_blocks]
    return Page(tuple(key_blocks) + tuple(agg_blocks), out_mask)


def merge_aggregate(
    partial: Page,
    num_keys: int,
    aggs: Sequence[AggCall],
    max_groups: int,
    key_domains: Optional[Sequence[Optional[Tuple[int, int]]]] = None,
    mode: str = "single",
    return_count: bool = False,
) -> Page:
    """Final aggregation over a page of partial states (group keys in
    the first ``num_keys`` blocks, then state columns in
    ``state_types`` order).

    With ``return_count=True`` returns (page, num_groups) so callers can
    detect ``num_groups > max_groups`` truncation and retry larger —
    the distributed counterpart of LocalRunner._check_overflow."""
    live = partial.row_mask
    key_dicts = [partial.blocks[i].dictionary for i in range(num_keys)]
    datas = canonicalize_codes(
        [partial.blocks[i].data for i in range(num_keys)], key_dicts)
    valids = [partial.blocks[i].valid for i in range(num_keys)]
    key_types = [partial.blocks[i].type for i in range(num_keys)]

    # slice state columns per agg; the first state column carries the
    # dictionary for value-preserving aggregates (min/max/min_by/max_by)
    state_cols: List[List[jax.Array]] = []
    agg_dicts: List[Optional[object]] = []
    pos = num_keys
    for agg in aggs:
        ncols = len(state_types(agg))
        state_cols.append([partial.blocks[pos + j].data for j in range(ncols)])
        agg_dicts.append(partial.blocks[pos].dictionary)
        pos += ncols

    from presto_tpu.expr.ir import ColumnRef

    group_exprs = [
        ColumnRef(type=key_types[i], index=i) for i in range(num_keys)
    ]

    if num_keys == 0:
        gid = jnp.where(live, 0, 1).astype(jnp.int32)
        merged = _merge_states(state_cols, aggs, gid, 1)
        out = _emit([], merged, aggs, jnp.ones(1, jnp.bool_), mode, group_exprs, key_dicts, agg_dicts)
        return (out, jnp.ones((), jnp.int32)) if return_count else out

    key, exact = pack_or_hash_keys(datas, valids, key_domains)
    gid, num_groups, rep_rows, ctx = _sorted_group_ids(
        key, live, max_groups, want_ctx=True)
    merged = _merge_states(state_cols, aggs, gid, max_groups, ctx=ctx)
    key_blocks = []
    for d, v, t, dic in zip(datas, valids, key_types, key_dicts):
        key_blocks.append(Block(d[rep_rows].astype(t.np_dtype), v[rep_rows], t, dic))
    out_mask = jnp.arange(max_groups) < num_groups
    out = _emit(key_blocks, merged, aggs, out_mask, mode, group_exprs, key_dicts, agg_dicts)
    return (out, num_groups) if return_count else out
