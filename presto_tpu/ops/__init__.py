"""Vectorized relational operators over Pages.

Reference analog: presto-main/.../operator/ (the vectorized kernel tier:
FilterAndProjectOperator, HashAggregationOperator + GroupByHash,
HashBuilderOperator/LookupJoinOperator + PagesHash, OrderByOperator,
TopNOperator ...). Re-designed for TPU: no row loops and no
open-addressing hash probes — grouping and joins are sort/searchsorted
algorithms with static shapes, so everything compiles to fused XLA.
"""

from presto_tpu.ops.filter_project import filter_page, project_page  # noqa: F401
from presto_tpu.ops.aggregate import (  # noqa: F401
    AggSpec,
    grouped_aggregate,
    merge_aggregate,
)
from presto_tpu.ops.join import (  # noqa: F401
    JoinBuild,
    build_join,
    probe_expand,
    probe_join,
)
from presto_tpu.ops.sort import limit_page, sort_page, topn_page  # noqa: F401
