"""Window function kernels.

Reference analog: WindowOperator (operator/WindowOperator.java:47) and
the ``operator/window/`` machinery (WindowPartition.java walks rows of
a PagesIndex partition-by-partition, FramedWindowFunction per frame).
Row-at-a-time partition walks don't vectorize; the TPU design is:

  1. ONE multi-key stable sort of the whole page by (partition keys,
     order keys) — dead rows last;
  2. segment boundaries (partition firsts) + peer boundaries (order-key
     firsts) as boolean vectors;
  3. every window function becomes a *segmented scan* (associative_scan
     with a reset flag) or position arithmetic over those vectors —
     rank/dense_rank/row_number are index math, running aggregates are
     segmented prefix sums evaluated at the last peer (the default
     RANGE UNBOUNDED PRECEDING .. CURRENT ROW frame), whole-partition
     aggregates are a segment reduce + gather;
  4. scatter results back to the original row order.

Everything is O(n log n) in one fused XLA program, no per-partition
loop.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.ops.aggregate import pack_or_hash_keys
from presto_tpu.ops.sort import _value_key
from presto_tpu.page import Block, Page
from presto_tpu.types import BIGINT, DOUBLE, Type


@dataclasses.dataclass(frozen=True)
class WindowFunc:
    """One window function application.

    kind: row_number | rank | dense_rank | ntile | percent_rank |
          cume_dist | nth_value | sum | avg | min | max | count |
          count_star | lead | lag | first_value | last_value

    frame: None for the default frame (RANGE UNBOUNDED PRECEDING ..
    CURRENT ROW with ORDER BY, whole partition without — the same
    default as the reference, operator/window/WindowOperator.java);
    ("whole",) for the entire partition;
    ("rows", start, end) for a ROWS frame with signed row offsets
    relative to the current row (None = unbounded in that direction).
    """

    kind: str
    arg: Optional[Expr] = None
    offset: int = 1  # lead/lag offset; ntile buckets; nth_value n
    frame: Optional[tuple] = None
    # skip NULL argument values when stepping (lead/lag) or picking
    # (first/last/nth_value) — the reference's IGNORE NULLS treatment
    ignore_nulls: bool = False

    @property
    def type(self) -> Type:
        if self.kind in ("row_number", "rank", "dense_rank", "count", "count_star",
                         "ntile"):
            return BIGINT
        if self.kind in ("avg", "percent_rank", "cume_dist"):
            return DOUBLE
        if self.kind == "sum":
            return _window_sum_type(self.arg.type)
        return self.arg.type


def _window_sum_type(t: Type) -> Type:
    """Window-frame sum accumulator/output type.  Unlike the grouped
    aggregation tier (ops/aggregate._sum_type widens short p>15 args to
    limb state), frames accumulate via 1-D cumsum over at most one
    page of rows, so short decimals stay scaled int64 — the
    kernel-soundness analyzer treats window outputs as unbounded and
    the page-capacity row bound keeps the fold inside 2^63 for the
    corpus precisions."""
    if t.is_decimal and not t.is_long_decimal:
        from presto_tpu.types import DecimalType

        return DecimalType(18, t.scale)
    from presto_tpu.ops.aggregate import _sum_type

    return _sum_type(t)


def _segmented_scan(op, vals: jax.Array, seg_first: jax.Array) -> jax.Array:
    """Inclusive segmented scan: op-accumulate within segments, reset
    at seg_first."""

    def comb(a, b):
        av, af = a
        bv, bf = b
        return (jnp.where(bf, bv, op(av, bv)), af | bf)

    v, _ = jax.lax.associative_scan(comb, (vals, seg_first))
    return v


def window_page(
    page: Page,
    partition_exprs: Sequence[Expr],
    order_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    funcs: Sequence[WindowFunc],
    partition_domains=None,
) -> Page:
    """Append one Block per window function to ``page`` (original row
    order preserved)."""
    c = ExprCompiler.for_page(page)
    cap = page.capacity
    live = page.row_mask
    idx = jnp.arange(cap, dtype=jnp.int32)

    # ---- 1. sort by (partition, order), stable, dead rows last -------
    perm = jnp.arange(cap, dtype=jnp.int32)
    for e, asc in list(zip(order_exprs, ascending))[::-1]:
        d, v = c.compile(e)(page)
        from presto_tpu.ops.sort import _dict_rank

        d = _dict_rank(page, e, d)
        if d.ndim > 1:
            # limb matrices (widened long-decimal sums) and raw-string
            # lane keys: canonical form IS value order, so the same
            # stable radix composition sort_perm uses works here
            for j in range(d.shape[-1] - 1, -1, -1):
                col = d[:, j]
                if col.dtype != jnp.int64:
                    col = col.astype(jnp.int32)
                kb = _value_key(col, asc)
                perm = perm[jnp.argsort(kb[perm], stable=True)]
        else:
            k = _value_key(d, asc)
            perm = perm[jnp.argsort(k[perm], stable=True)]
        null_rank = jnp.where(v, 0, 1)  # nulls last (Presto default asc)
        perm = perm[jnp.argsort(null_rank[perm], stable=True)]
    if partition_exprs:
        kd = [c.compile(e)(page) for e in partition_exprs]
        from presto_tpu.ops.aggregate import canonicalize_codes, expr_key_dicts

        pkey, _ = pack_or_hash_keys(
            canonicalize_codes([d for d, _ in kd],
                               expr_key_dicts(page, partition_exprs)),
            [v for _, v in kd], partition_domains
        )
        perm = perm[jnp.argsort(pkey[perm], stable=True)]
    else:
        pkey = jnp.zeros(cap, dtype=jnp.int32)
    dead = jnp.logical_not(live)[perm]
    perm = perm[jnp.argsort(dead, stable=True)]

    live_s = live[perm]
    pkey_s = pkey[perm]

    # ---- 2. boundaries ----------------------------------------------
    seg_first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), pkey_s[1:] != pkey_s[:-1]]
    ) | jnp.concatenate([jnp.ones(1, jnp.bool_), live_s[1:] != live_s[:-1]])

    peer_first = seg_first
    for e, asc in zip(order_exprs, ascending):
        d, v = c.compile(e)(page)
        ds = d[perm]
        vs = v[perm]
        neq = ds[1:] != ds[:-1]
        if neq.ndim > 1:  # limb keys: rows differ if ANY limb differs
            neq = neq.any(axis=-1)
        changed = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), neq | (vs[1:] != vs[:-1])]
        )
        peer_first = peer_first | changed

    seg_start = jax.lax.associative_scan(jnp.maximum, jnp.where(seg_first, idx, 0))
    # last peer position for each row (for RANGE-frame running aggs):
    # reverse-scan the *next* peer boundary
    peer_next = jnp.concatenate([peer_first[1:], jnp.ones(1, jnp.bool_)])
    last_peer = jnp.flip(
        jax.lax.associative_scan(
            jnp.minimum, jnp.where(jnp.flip(peer_next), jnp.flip(idx), cap - 1)
        )
    )

    has_order = len(order_exprs) > 0
    seg_last = _segment_last(seg_first, cap)

    # ---- 3. per-function computation in sorted space -----------------
    out_blocks: List[Block] = list(page.blocks)
    for f in funcs:
        data_s, valid_s = _compute_sorted(
            f, c, page, perm, idx, cap, live_s, seg_first, peer_first,
            seg_start, last_peer, has_order, seg_last,
        )
        # ---- 4. scatter back to original order ----------------------
        data = jnp.zeros_like(data_s).at[perm].set(data_s)
        valid = jnp.zeros_like(valid_s).at[perm].set(valid_s & live_s)
        out_blocks.append(Block(data, valid, f.type))
    return Page(tuple(out_blocks), page.row_mask)


def _nonnull_rank_index(vs, live_s, idx, cap):
    """(nonnull mask, 1-based cumulative non-null rank, rank ->
    position scatter) — the IGNORE NULLS lookup scaffolding shared by
    lead/lag and first/last/nth_value."""
    nonnull = vs & live_s
    grank = jnp.cumsum(nonnull.astype(jnp.int64))
    pos_of = jnp.zeros(cap + 1, jnp.int64).at[
        jnp.where(nonnull, grank, 0)].set(idx, mode="drop")
    return nonnull, grank, pos_of


def _compute_sorted(f, c, page, perm, idx, cap, live_s, seg_first, peer_first,
                    seg_start, last_peer, has_order, seg_last):
    if f.kind == "row_number":
        rn = (idx - seg_start + 1).astype(jnp.int64)
        return rn, jnp.ones(cap, jnp.bool_)
    if f.kind == "rank":
        fp_pos = jax.lax.associative_scan(jnp.maximum, jnp.where(peer_first, idx, 0))
        return (fp_pos - seg_start + 1).astype(jnp.int64), jnp.ones(cap, jnp.bool_)
    if f.kind == "dense_rank":
        cum = jnp.cumsum(peer_first.astype(jnp.int32))
        cum_at_start = cum[seg_start]
        return (cum - cum_at_start + 1).astype(jnp.int64), jnp.ones(cap, jnp.bool_)
    if f.kind == "ntile":
        # presto semantics: first (count % n) buckets get one extra row
        n = f.offset
        rn0 = (idx - seg_start).astype(jnp.int64)
        count = (seg_last - seg_start + 1).astype(jnp.int64)
        q, r = count // n, count % n
        big = (q + 1) * r  # rows covered by the larger buckets
        bucket = jnp.where(
            rn0 < big,
            rn0 // jnp.maximum(q + 1, 1),
            r + (rn0 - big) // jnp.maximum(q, 1),
        )
        return bucket + 1, jnp.ones(cap, jnp.bool_)
    if f.kind == "percent_rank":
        fp_pos = jax.lax.associative_scan(jnp.maximum, jnp.where(peer_first, idx, 0))
        rank = (fp_pos - seg_start).astype(jnp.float64)
        count = (seg_last - seg_start).astype(jnp.float64)  # count-1
        out = jnp.where(count > 0, rank / jnp.maximum(count, 1.0), 0.0)
        return out, jnp.ones(cap, jnp.bool_)
    if f.kind == "cume_dist":
        covered = (last_peer - seg_start + 1).astype(jnp.float64)
        count = (seg_last - seg_start + 1).astype(jnp.float64)
        return covered / jnp.maximum(count, 1.0), jnp.ones(cap, jnp.bool_)

    if f.kind in ("lead", "lag"):
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm]
        if f.ignore_nulls and f.offset > 0:  # offset 0 IS the current row
            # step over NULLs: rank the non-null rows, look the k-th
            # non-null rank up through a rank->position scatter
            # (WindowOperator's IGNORE NULLS treatment, shape-static)
            nonnull, grank, pos_of = _nonnull_rank_index(vs, live_s, idx, cap)
            if f.kind == "lag":
                tgt_rank = grank - nonnull.astype(jnp.int64) - (f.offset - 1)
            else:
                tgt_rank = grank + f.offset
            exists = (tgt_rank >= 1) & (tgt_rank <= grank[-1])
            src_c = pos_of[jnp.clip(tgt_rank, 0, cap)]
            same_seg = seg_start[src_c] == seg_start
            ok = exists & same_seg
            return jnp.where(ok, ds[src_c], jnp.zeros_like(ds)), ok
        off = -f.offset if f.kind == "lag" else f.offset  # lag looks earlier
        src = idx + off
        in_range = (src >= 0) & (src < cap)
        src_c = jnp.clip(src, 0, cap - 1)
        same_seg = seg_start[jnp.clip(src_c, 0, cap - 1)] == seg_start
        ok = in_range & same_seg
        return jnp.where(ok, ds[src_c], jnp.zeros_like(ds)), ok & vs[src_c]

    # ---- frame resolution: each row's [f_start, f_end] in sorted space.
    # Default: RANGE UNBOUNDED PRECEDING .. CURRENT ROW (end = last
    # peer) with ORDER BY, whole partition without; ("whole",) forces
    # the partition; ("rows", s, e) clamps signed offsets to the
    # segment. empty marks frames that exclude every row.
    frame = f.frame
    if frame is not None and frame[0] == "rows":
        s_off, e_off = frame[1], frame[2]
        f_start = seg_start if s_off is None else jnp.maximum(seg_start, idx + s_off)
        f_end = seg_last if e_off is None else jnp.minimum(seg_last, idx + e_off)
    elif frame == ("whole",) or not has_order:
        f_start, f_end = seg_start, seg_last
    else:
        f_start, f_end = seg_start, last_peer
    empty = f_end < f_start
    s_c = jnp.clip(f_start, 0, cap - 1)
    e_c = jnp.clip(f_end, 0, cap - 1)

    if f.kind in ("first_value", "last_value", "nth_value"):
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm]
        if f.ignore_nulls:
            # pick by non-null RANK within the frame: frames stay
            # inside a segment, so global ranks + bounds checks suffice
            nonnull, grank, pos_of = _nonnull_rank_index(vs, live_s, idx, cap)
            before = grank[s_c] - nonnull[s_c].astype(jnp.int64)
            in_frame = grank[e_c] - before  # non-nulls inside the frame
            if f.kind == "first_value":
                want = before + 1
            elif f.kind == "last_value":
                want = grank[e_c]
            else:
                want = before + f.offset
            have = jnp.logical_not(empty) & (want > before) \
                & (want <= grank[e_c]) & (in_frame > 0)
            pos = pos_of[jnp.clip(want, 0, cap)]
            return jnp.where(have, ds[pos], jnp.zeros_like(ds)), have
        if f.kind == "first_value":
            pos = s_c
        elif f.kind == "last_value":
            pos = e_c
        else:
            pos = jnp.clip(f_start + (f.offset - 1), 0, cap - 1)
            empty = empty | (f_start + (f.offset - 1) > f_end)
        return ds[pos], vs[pos] & jnp.logical_not(empty)

    # aggregates over the frame: global prefix sums + frame-bound
    # differences (frames never span segments, so a segmented scan is
    # unnecessary); min/max use the running segmented scan and support
    # unbounded-start frames only.
    if f.kind == "count_star":
        vcount = live_s
    else:
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm] & live_s
        vcount = vs

    def frame_sum(vals):
        p = jnp.cumsum(vals, axis=0)
        out = p[e_c] - p[s_c] + vals[s_c]
        return jnp.where(empty, jnp.zeros_like(out), out)

    cnt = frame_sum(vcount.astype(jnp.int64))
    if f.kind in ("count", "count_star"):
        return cnt, jnp.ones(cap, jnp.bool_)
    if f.kind in ("sum", "avg"):
        st = _window_sum_type(f.arg.type)
        vals = jnp.where(vs, ds.astype(st.np_dtype), jnp.zeros((), st.np_dtype))
        s_out = frame_sum(vals)
        if f.kind == "sum":
            return s_out, cnt > 0
        num = s_out.astype(jnp.float64)
        if st.is_decimal:
            num = num / (10.0 ** st.scale)
        return num / jnp.maximum(cnt, 1).astype(jnp.float64), cnt > 0
    if f.kind in ("min", "max"):
        from presto_tpu.ops.aggregate import _type_max, _type_min

        fill = _type_max(f.arg.type) if f.kind == "min" else _type_min(f.arg.type)
        op = jnp.minimum if f.kind == "min" else jnp.maximum
        vals = jnp.where(vs, ds, fill)
        m = _segmented_scan(op, vals, seg_first)
        # running scan value at the frame end (start must be unbounded —
        # enforced at bind time, sql/binder.py _register_window)
        return m[e_c], cnt > 0
    raise KeyError(f.kind)


def _broadcast_total(scanned: jax.Array, seg_first: jax.Array, seg_start: jax.Array, cap: int):
    """Whole-partition value: the scan result at the segment's last row,
    broadcast to every row of the segment."""
    seg_last = _segment_last(seg_first, cap)
    return scanned[seg_last]


def _broadcast_total_op(scanned, seg_first, seg_start, cap):
    return scanned[_segment_last(seg_first, cap)]


def _segment_last(seg_first: jax.Array, cap: int) -> jax.Array:
    idx = jnp.arange(cap, dtype=jnp.int32)
    next_first = jnp.concatenate([seg_first[1:], jnp.ones(1, jnp.bool_)])
    return jnp.flip(
        jax.lax.associative_scan(
            jnp.minimum, jnp.where(jnp.flip(next_first), jnp.flip(idx), cap - 1)
        )
    )
