"""Window function kernels.

Reference analog: WindowOperator (operator/WindowOperator.java:47) and
the ``operator/window/`` machinery (WindowPartition.java walks rows of
a PagesIndex partition-by-partition, FramedWindowFunction per frame).
Row-at-a-time partition walks don't vectorize; the TPU design is:

  1. ONE multi-key stable sort of the whole page by (partition keys,
     order keys) — dead rows last;
  2. segment boundaries (partition firsts) + peer boundaries (order-key
     firsts) as boolean vectors;
  3. every window function becomes a *segmented scan* (associative_scan
     with a reset flag) or position arithmetic over those vectors —
     rank/dense_rank/row_number are index math, running aggregates are
     segmented prefix sums evaluated at the last peer (the default
     RANGE UNBOUNDED PRECEDING .. CURRENT ROW frame), whole-partition
     aggregates are a segment reduce + gather;
  4. scatter results back to the original row order.

Everything is O(n log n) in one fused XLA program, no per-partition
loop.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.ops.aggregate import pack_or_hash_keys
from presto_tpu.ops.sort import _value_key
from presto_tpu.page import Block, Page
from presto_tpu.types import BIGINT, DOUBLE, Type


@dataclasses.dataclass(frozen=True)
class WindowFunc:
    """One window function application.

    kind: row_number | rank | dense_rank | ntile? (later) |
          sum | avg | min | max | count | count_star |
          lead | lag | first_value | last_value
    """

    kind: str
    arg: Optional[Expr] = None
    offset: int = 1  # lead/lag

    @property
    def type(self) -> Type:
        if self.kind in ("row_number", "rank", "dense_rank", "count", "count_star"):
            return BIGINT
        if self.kind == "avg":
            return DOUBLE
        if self.kind == "sum":
            from presto_tpu.ops.aggregate import _sum_type

            return _sum_type(self.arg.type)
        return self.arg.type


def _segmented_scan(op, vals: jax.Array, seg_first: jax.Array) -> jax.Array:
    """Inclusive segmented scan: op-accumulate within segments, reset
    at seg_first."""

    def comb(a, b):
        av, af = a
        bv, bf = b
        return (jnp.where(bf, bv, op(av, bv)), af | bf)

    v, _ = jax.lax.associative_scan(comb, (vals, seg_first))
    return v


def window_page(
    page: Page,
    partition_exprs: Sequence[Expr],
    order_exprs: Sequence[Expr],
    ascending: Sequence[bool],
    funcs: Sequence[WindowFunc],
    partition_domains=None,
) -> Page:
    """Append one Block per window function to ``page`` (original row
    order preserved)."""
    c = ExprCompiler.for_page(page)
    cap = page.capacity
    live = page.row_mask
    idx = jnp.arange(cap, dtype=jnp.int32)

    # ---- 1. sort by (partition, order), stable, dead rows last -------
    perm = jnp.arange(cap, dtype=jnp.int32)
    for e, asc in list(zip(order_exprs, ascending))[::-1]:
        d, v = c.compile(e)(page)
        k = _value_key(d, asc)
        perm = perm[jnp.argsort(k[perm], stable=True)]
        null_rank = jnp.where(v, 0, 1)  # nulls last (Presto default asc)
        perm = perm[jnp.argsort(null_rank[perm], stable=True)]
    if partition_exprs:
        kd = [c.compile(e)(page) for e in partition_exprs]
        pkey, _ = pack_or_hash_keys(
            [d for d, _ in kd], [v for _, v in kd], partition_domains
        )
        perm = perm[jnp.argsort(pkey[perm], stable=True)]
    else:
        pkey = jnp.zeros(cap, dtype=jnp.int32)
    dead = jnp.logical_not(live)[perm]
    perm = perm[jnp.argsort(dead, stable=True)]

    live_s = live[perm]
    pkey_s = pkey[perm]

    # ---- 2. boundaries ----------------------------------------------
    seg_first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), pkey_s[1:] != pkey_s[:-1]]
    ) | jnp.concatenate([jnp.ones(1, jnp.bool_), live_s[1:] != live_s[:-1]])

    peer_first = seg_first
    for e, asc in zip(order_exprs, ascending):
        d, v = c.compile(e)(page)
        ds = d[perm]
        vs = v[perm]
        changed = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), (ds[1:] != ds[:-1]) | (vs[1:] != vs[:-1])]
        )
        peer_first = peer_first | changed

    seg_start = jax.lax.associative_scan(jnp.maximum, jnp.where(seg_first, idx, 0))
    # last peer position for each row (for RANGE-frame running aggs):
    # reverse-scan the *next* peer boundary
    peer_next = jnp.concatenate([peer_first[1:], jnp.ones(1, jnp.bool_)])
    last_peer = jnp.flip(
        jax.lax.associative_scan(
            jnp.minimum, jnp.where(jnp.flip(peer_next), jnp.flip(idx), cap - 1)
        )
    )

    has_order = len(order_exprs) > 0

    # ---- 3. per-function computation in sorted space -----------------
    out_blocks: List[Block] = list(page.blocks)
    for f in funcs:
        data_s, valid_s = _compute_sorted(
            f, c, page, perm, idx, cap, live_s, seg_first, peer_first,
            seg_start, last_peer, has_order,
        )
        # ---- 4. scatter back to original order ----------------------
        data = jnp.zeros_like(data_s).at[perm].set(data_s)
        valid = jnp.zeros_like(valid_s).at[perm].set(valid_s & live_s)
        out_blocks.append(Block(data, valid, f.type))
    return Page(tuple(out_blocks), page.row_mask)


def _compute_sorted(f, c, page, perm, idx, cap, live_s, seg_first, peer_first,
                    seg_start, last_peer, has_order):
    if f.kind == "row_number":
        rn = (idx - seg_start + 1).astype(jnp.int64)
        return rn, jnp.ones(cap, jnp.bool_)
    if f.kind == "rank":
        fp_pos = jax.lax.associative_scan(jnp.maximum, jnp.where(peer_first, idx, 0))
        return (fp_pos - seg_start + 1).astype(jnp.int64), jnp.ones(cap, jnp.bool_)
    if f.kind == "dense_rank":
        cum = jnp.cumsum(peer_first.astype(jnp.int32))
        cum_at_start = cum[seg_start]
        return (cum - cum_at_start + 1).astype(jnp.int64), jnp.ones(cap, jnp.bool_)

    if f.kind in ("lead", "lag"):
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm]
        off = -f.offset if f.kind == "lag" else f.offset  # lag looks earlier
        src = idx + off
        in_range = (src >= 0) & (src < cap)
        src_c = jnp.clip(src, 0, cap - 1)
        same_seg = seg_start[jnp.clip(src_c, 0, cap - 1)] == seg_start
        ok = in_range & same_seg
        return jnp.where(ok, ds[src_c], jnp.zeros_like(ds)), ok & vs[src_c]

    if f.kind == "first_value":
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm]
        return ds[seg_start], vs[seg_start]
    if f.kind == "last_value":
        d, v = c.compile(f.arg)(page)
        ds, vs = d[perm], v[perm]
        return ds[last_peer], vs[last_peer]  # default frame: up to last peer

    # aggregates
    if f.kind == "count_star":
        cnt = _segmented_scan(jnp.add, live_s.astype(jnp.int64), seg_first)
        out = cnt[last_peer] if has_order else _broadcast_total(cnt, seg_first, seg_start, cap)
        return out, jnp.ones(cap, jnp.bool_)

    d, v = c.compile(f.arg)(page)
    ds, vs = d[perm], v[perm] & live_s
    if f.kind == "count":
        cnt = _segmented_scan(jnp.add, vs.astype(jnp.int64), seg_first)
        out = cnt[last_peer] if has_order else _broadcast_total(cnt, seg_first, seg_start, cap)
        return out, jnp.ones(cap, jnp.bool_)
    if f.kind in ("sum", "avg"):
        from presto_tpu.ops.aggregate import _sum_type

        st = _sum_type(f.arg.type)
        vals = jnp.where(vs, ds.astype(st.np_dtype), jnp.zeros((), st.np_dtype))
        s = _segmented_scan(jnp.add, vals, seg_first)
        cnt = _segmented_scan(jnp.add, vs.astype(jnp.int64), seg_first)
        s_out = s[last_peer] if has_order else _broadcast_total(s, seg_first, seg_start, cap)
        c_out = cnt[last_peer] if has_order else _broadcast_total(cnt, seg_first, seg_start, cap)
        if f.kind == "sum":
            return s_out, c_out > 0
        num = s_out.astype(jnp.float64)
        if st.is_decimal:
            num = num / (10.0 ** st.scale)
        return num / jnp.maximum(c_out, 1).astype(jnp.float64), c_out > 0
    if f.kind in ("min", "max"):
        from presto_tpu.ops.aggregate import _type_max, _type_min

        fill = _type_max(f.arg.type) if f.kind == "min" else _type_min(f.arg.type)
        op = jnp.minimum if f.kind == "min" else jnp.maximum
        vals = jnp.where(vs, ds, fill)
        m = _segmented_scan(op, vals, seg_first)
        cnt = _segmented_scan(jnp.add, vs.astype(jnp.int64), seg_first)
        m_out = m[last_peer] if has_order else _broadcast_total_op(m, seg_first, seg_start, cap)
        c_out = cnt[last_peer] if has_order else _broadcast_total(cnt, seg_first, seg_start, cap)
        return m_out, c_out > 0
    raise KeyError(f.kind)


def _broadcast_total(scanned: jax.Array, seg_first: jax.Array, seg_start: jax.Array, cap: int):
    """Whole-partition value: the scan result at the segment's last row,
    broadcast to every row of the segment."""
    seg_last = _segment_last(seg_first, cap)
    return scanned[seg_last]


def _broadcast_total_op(scanned, seg_first, seg_start, cap):
    return scanned[_segment_last(seg_first, cap)]


def _segment_last(seg_first: jax.Array, cap: int) -> jax.Array:
    idx = jnp.arange(cap, dtype=jnp.int32)
    next_first = jnp.concatenate([seg_first[1:], jnp.ones(1, jnp.bool_)])
    return jnp.flip(
        jax.lax.associative_scan(
            jnp.minimum, jnp.where(jnp.flip(next_first), jnp.flip(idx), cap - 1)
        )
    )
