"""Long-decimal (p <= 36) limb arithmetic.

Reference analog: ``presto-spi/.../type/Decimals.java`` +
``UnscaledDecimal128Arithmetic.java`` — the reference packs 128-bit
unscaled values into two java longs and implements add/compare/rescale
over them.  TPU redesign: limbs are **base 10^18** signed int64 arrays
(`value = hi * 10^18 + lo`, invariant `0 <= lo < 10^18`), so every
carry/borrow is a native vector op — no 128-bit emulation, no byte
swizzles, and decimal rescaling by powers of ten stays exact.

Device layout: a long-decimal Block's data has shape (capacity, 2) with
[:, 0] = hi, [:, 1] = lo.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

BASE = 10 ** 18
_B9 = 10 ** 9


# -- host-side encode/decode --------------------------------------------------

def encode_py(values, capacity: int) -> np.ndarray:
    """Python ints (arbitrary precision) -> (capacity, 2) limbs."""
    out = np.zeros((capacity, 2), dtype=np.int64)
    for i, v in enumerate(values):
        if v is None:
            continue
        hi, lo = divmod(int(v), BASE)  # python divmod: 0 <= lo < BASE
        out[i, 0] = hi
        out[i, 1] = lo
    return out


def decode_py(limbs: np.ndarray):
    """(n, 2) limbs -> list of python ints."""
    return [int(h) * BASE + int(l) for h, l in np.asarray(limbs, dtype=np.int64)]


# -- normalization ------------------------------------------------------------

def normalize(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Restore the 0 <= lo < BASE invariant after add/sub; returns
    stacked (..., 2)."""
    carry = jnp.floor_divide(lo, BASE)
    lo = lo - carry * BASE
    hi = hi + carry
    return jnp.stack([hi, lo], axis=-1)


def split(d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return d[..., 0], d[..., 1]


# -- arithmetic ---------------------------------------------------------------

def add(a: jax.Array, b: jax.Array) -> jax.Array:
    ah, al = split(a)
    bh, bl = split(b)
    return normalize(ah + bh, al + bl)  # lo sums < 2*BASE: no int64 overflow


def neg(a: jax.Array) -> jax.Array:
    ah, al = split(a)
    return normalize(-ah, -al)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    ah, al = split(a)
    bh, bl = split(b)
    return normalize(ah - bh, al - bl)


def from_int64(x: jax.Array) -> jax.Array:
    """Short (int64) value -> limbs."""
    return normalize(jnp.zeros_like(x), x)


def mul_small(a: jax.Array, k: jax.Array) -> jax.Array:
    """Multiply limbs by a small int64 (|k| <= ~4*10^9, e.g. rescale
    powers of ten): split lo into base-10^9 halves so every partial
    product fits int64."""
    ah, al = split(a)
    l1, l0 = jnp.floor_divide(al, _B9), jnp.remainder(al, _B9)
    p0 = l0 * k  # < 10^9 * 4*10^9 < 9.2*10^18 OK
    p1 = l1 * k
    # p1 contributes at 10^9: fold its overflow beyond 10^9 into hi
    c1 = jnp.floor_divide(p1, _B9)
    r1 = p1 - c1 * _B9
    return normalize(ah * k + c1, r1 * _B9 + p0)


def mul_int64(a: jax.Array, b: jax.Array) -> jax.Array:
    """Full product of two int64 scaled values (|a|,|b| < 10^18) ->
    limbs. Schoolbook over base-10^9 halves; every partial < 10^18."""
    a1, a0 = jnp.floor_divide(a, _B9), jnp.remainder(a, _B9)
    b1, b0 = jnp.floor_divide(b, _B9), jnp.remainder(b, _B9)
    # value = a1*b1*10^18 + (a1*b0 + a0*b1)*10^9 + a0*b0
    cross = a1 * b0 + a0 * b1  # < 2*10^18 OK
    c_hi = jnp.floor_divide(cross, _B9)
    c_lo = cross - c_hi * _B9
    return normalize(a1 * b1 + c_hi, c_lo * _B9 + a0 * b0)


def mul_long_short(a: jax.Array, k: jax.Array) -> jax.Array:
    """Long limbs x int64 scaled value: (hi*B + lo)*k = (hi*k)*B + lo*k,
    with lo*k going through the full int64 multiplier. Exact whenever
    the result fits p<=36 (hi*k then < 10^18)."""
    ah, al = split(a)
    low = mul_int64(al, k)
    lh, ll = split(low)
    return normalize(ah * k + lh, ll)


def rescale(a: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    if to_scale > from_scale:
        k = to_scale - from_scale
        out = a
        while k > 0:  # static python loop: at most a few steps of 10^9
            step = min(k, 9)
            out = mul_small(out, jnp.asarray(10 ** step, jnp.int64))
            k -= step
        return out
    if to_scale < from_scale:
        k = from_scale - to_scale
        if k > 18:
            raise ValueError("long-decimal downscale beyond 18 digits unsupported")
        d = 10 ** k  # k <= 18: divides BASE exactly
        ah, al = split(a)
        # floor((hi*BASE + lo)/d) = hi*(BASE/d) + floor(lo/d): the first
        # term can exceed 10^18, so it goes through the limb multiplier
        m = jnp.broadcast_to(jnp.asarray(BASE // d, jnp.int64), ah.shape)
        return add(mul_int64(ah, m), from_int64(jnp.floor_divide(al, d)))
    return a


def compare(a: jax.Array, b: jax.Array):
    """(lt, eq, gt) boolean triples — limb order is value order since
    lo is canonical."""
    ah, al = split(a)
    bh, bl = split(b)
    lt = (ah < bh) | ((ah == bh) & (al < bl))
    eq = (ah == bh) & (al == bl)
    return lt, eq, ~(lt | eq)


def to_double(a: jax.Array, scale: int) -> jax.Array:
    ah, al = split(a)
    return (ah.astype(jnp.float64) * float(BASE) + al.astype(jnp.float64)) / (10.0 ** scale)


# -- aggregation support -------------------------------------------------------

def to_sum_limbs(a: jax.Array) -> jax.Array:
    """(n, 2) base-10^18 -> (n, 4) base-10^9 limbs, safe to segment_sum
    over ~9*10^9 rows without int64 overflow."""
    ah, al = split(a)
    return jnp.stack([
        jnp.floor_divide(ah, _B9), jnp.remainder(ah, _B9),
        jnp.floor_divide(al, _B9), jnp.remainder(al, _B9),
    ], axis=-1)


def from_sum_limbs(s: jax.Array) -> jax.Array:
    """(n, 4) summed base-10^9 limbs -> normalized (n, 2)."""
    h1, h0, l1, l0 = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    # fold base-10^9 carries upward
    c = jnp.floor_divide(l0, _B9)
    l0 = l0 - c * _B9
    l1 = l1 + c
    c = jnp.floor_divide(l1, _B9)
    l1 = l1 - c * _B9
    hi_extra = c
    lo = l1 * _B9 + l0
    c = jnp.floor_divide(h0, _B9)
    h0 = h0 - c * _B9
    h1 = h1 + c
    hi = h1 * _B9 + h0 + hi_extra
    return normalize(hi, lo)
