"""Long-decimal limb arithmetic (p <= 38).

Reference analog: ``presto-spi/.../type/Decimals.java`` +
``UnscaledDecimal128Arithmetic.java`` — the reference packs 128-bit
unscaled values into two java longs and implements add/compare/rescale
over them.  TPU redesign: limb vectors in native int64, so every
carry/borrow is a vector op — no 128-bit emulation, no byte swizzles,
and decimal rescaling by powers of ten stays exact.

Two layouts, selected by precision (dispatch is on the trailing array
dimension, so call sites stay layout-blind):
  p <= 36: (capacity, 2) base-10^18 limbs  (value = hi*10^18 + lo)
  p <= 38: (capacity, 5) base-10^9  limbs  (most-significant first) —
           the r5 extension for DecimalType.java's full 38 digits.
           add/sub/compare/sum/avg/rescale/casts are exact; products
           beyond 36 digits remain unsupported (the reference caps at
           38 TOTAL digits, so p38 x pN multiplication overflows there
           too).
The canonical form keeps limbs [1:] in [0, base); limb 0 carries the
sign, making lexicographic limb order the value order.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

BASE = 10 ** 18
_B9 = 10 ** 9
#: limb count of the wide (p in (36, 38]) layout
WIDE_LIMBS = 5


def width(d) -> int:
    """Limb count of a decimal array (2 = base-10^18, 5 = base-10^9)."""
    return int(d.shape[-1])


def _norm_wide(limbs: "jax.Array") -> "jax.Array":
    """Restore the canonical form of a (..., 5) base-10^9 vector."""
    outs = []
    carry = jnp.zeros_like(limbs[..., 0])
    for i in range(WIDE_LIMBS - 1, 0, -1):
        cur = limbs[..., i] + carry
        carry = jnp.floor_divide(cur, _B9)
        outs.append(cur - carry * _B9)
    outs.append(limbs[..., 0] + carry)
    return jnp.stack(outs[::-1], axis=-1)


# -- host-side encode/decode --------------------------------------------------

def encode_py(values, capacity: int, limbs: int = 2) -> np.ndarray:
    """Python ints (arbitrary precision) -> (capacity, limbs) limbs."""
    out = np.zeros((capacity, limbs), dtype=np.int64)
    base = BASE if limbs == 2 else _B9
    for i, v in enumerate(values):
        if v is None:
            continue
        rest = int(v)
        for j in range(limbs - 1, 0, -1):
            rest, lo = divmod(rest, base)
            out[i, j] = lo
        out[i, 0] = rest
    return out


def decode_py(limbs: np.ndarray):
    """(n, L) limbs -> list of python ints."""
    arr = np.asarray(limbs, dtype=np.int64)
    base = BASE if arr.shape[-1] == 2 else _B9
    out = []
    for row in arr:
        v = int(row[0])
        for x in row[1:]:
            v = v * base + int(x)
        out.append(v)
    return out


# -- normalization ------------------------------------------------------------

def normalize(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Restore the 0 <= lo < BASE invariant after add/sub; returns
    stacked (..., 2)."""
    carry = jnp.floor_divide(lo, BASE)
    lo = lo - carry * BASE
    hi = hi + carry
    return jnp.stack([hi, lo], axis=-1)


def split(d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return d[..., 0], d[..., 1]


# -- arithmetic ---------------------------------------------------------------

def add(a: jax.Array, b: jax.Array) -> jax.Array:
    if width(a) != 2:
        return _norm_wide(a + b)  # limb sums < 2*10^9: no overflow
    ah, al = split(a)
    bh, bl = split(b)
    return normalize(ah + bh, al + bl)  # lo sums < 2*BASE: no int64 overflow


def neg(a: jax.Array) -> jax.Array:
    if width(a) != 2:
        return _norm_wide(-a)
    ah, al = split(a)
    return normalize(-ah, -al)


def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    if width(a) != 2:
        return _norm_wide(a - b)
    ah, al = split(a)
    bh, bl = split(b)
    return normalize(ah - bh, al - bl)


def widen(a: jax.Array) -> jax.Array:
    """(n, 2) base-10^18 -> (n, 5) base-10^9 (decimal(38) coercion)."""
    ah, al = split(a)
    z = jnp.zeros_like(ah)
    h1, h0 = jnp.floor_divide(ah, _B9), jnp.remainder(ah, _B9)
    l1, l0 = jnp.floor_divide(al, _B9), jnp.remainder(al, _B9)
    return _norm_wide(jnp.stack([z, h1, h0, l1, l0], axis=-1))


def from_int64(x: jax.Array, limbs: int = 2) -> jax.Array:
    """Short (int64) value -> limbs."""
    if limbs != 2:
        cols = [jnp.zeros_like(x)] * (limbs - 1) + [x]
        return _norm_wide(jnp.stack(cols, axis=-1))
    return normalize(jnp.zeros_like(x), x)


def mul_small(a: jax.Array, k: jax.Array) -> jax.Array:
    """Multiply limbs by a small int64 (|k| <= ~4*10^9, e.g. rescale
    powers of ten): split lo into base-10^9 halves so every partial
    product fits int64."""
    if width(a) != 2:
        # wide limbs are base 10^9: limb*k <= 10^9 * 4*10^9 overflows,
        # so split k into <= 10^5-sized steps at the call sites; here
        # k must stay <= ~9*10^9 / 1 — enforce the per-limb bound via
        # base-10^5 halves of each limb
        k5h = jnp.floor_divide(k, 100_000)
        k5l = k - k5h * 100_000
        hi_part = _norm_wide(a * k5h)          # limb * k/1e5 < 9e18/1e5*1e9 ok? see below
        lo_part = _norm_wide(a * k5l)          # limb*1e5 < 1e14 ok
        return add(_shift_digits_wide(hi_part, 5), lo_part)
    ah, al = split(a)
    l1, l0 = jnp.floor_divide(al, _B9), jnp.remainder(al, _B9)
    p0 = l0 * k  # < 10^9 * 4*10^9 < 9.2*10^18 OK
    p1 = l1 * k
    # p1 contributes at 10^9: fold its overflow beyond 10^9 into hi
    c1 = jnp.floor_divide(p1, _B9)
    r1 = p1 - c1 * _B9
    return normalize(ah * k + c1, r1 * _B9 + p0)


def mul_int64(a: jax.Array, b: jax.Array) -> jax.Array:
    """Full product of two int64 scaled values (|a|,|b| < 10^18) ->
    limbs. Schoolbook over base-10^9 halves; every partial < 10^18."""
    a1, a0 = jnp.floor_divide(a, _B9), jnp.remainder(a, _B9)
    b1, b0 = jnp.floor_divide(b, _B9), jnp.remainder(b, _B9)
    # value = a1*b1*10^18 + (a1*b0 + a0*b1)*10^9 + a0*b0
    cross = a1 * b0 + a0 * b1  # < 2*10^18 OK
    c_hi = jnp.floor_divide(cross, _B9)
    c_lo = cross - c_hi * _B9
    return normalize(a1 * b1 + c_hi, c_lo * _B9 + a0 * b0)


def mul_long_short(a: jax.Array, k: jax.Array) -> jax.Array:
    """Long limbs x int64 scaled value: (hi*B + lo)*k = (hi*k)*B + lo*k,
    with lo*k going through the full int64 multiplier. Exact whenever
    the result fits p<=36 (hi*k then < 10^18); wide (p<=38) operands
    route through the base-10^9 schoolbook below."""
    if width(a) != 2:
        return mul_wide_small(a, k)
    ah, al = split(a)
    low = mul_int64(al, k)
    lh, ll = split(low)
    return normalize(ah * k + lh, ll)


def mul_wide_small(a: jax.Array, k: jax.Array) -> jax.Array:
    """Wide ((n, 5) base-10^9) limbs x int64 scaled value (|k| < 10^18)
    -> wide limbs.  k splits into base-10^9 halves so every partial
    limb product stays < 10^18; the k-high half's contribution shifts
    up one limb.  Exact whenever the product fits 38 digits (the
    reference's DecimalType cap); past 38 the most-significant carry
    drops — the same wrap deviation _shift_digits_wide documents
    (in-jit code cannot raise)."""
    # canonical negative wides carry the sign in the MSB limb; the
    # limb shift below drops that limb, so compute on magnitudes and
    # reapply the sign
    neg_a = a[..., 0] < 0
    a_abs = jnp.where(neg_a[..., None], _norm_wide(-a), a)
    neg_k = k < 0
    k_abs = jnp.where(neg_k, -k, k)
    k1 = jnp.floor_divide(k_abs, _B9)
    k0 = k_abs - k1 * _B9
    lo = _norm_wide(a_abs * k0)   # limb < 10^9, k0 < 10^9: fits int64
    hi = _norm_wide(a_abs * k1)
    hi_shift = jnp.concatenate(   # * 10^9 == shift limbs up one slot
        [hi[..., 1:], jnp.zeros_like(hi[..., :1])], axis=-1)
    res = add(hi_shift, lo)
    flip = neg_a ^ neg_k
    return jnp.where(flip[..., None], _norm_wide(-res), res)


def _shift_digits_wide(a: jax.Array, k: int) -> jax.Array:
    """Multiply a wide vector by 10^k for k in [0, 9) via limb-local
    shifts: each limb splits at 10^(9-k), the high part carries into
    the next limb.  The most-significant limb's carry-out is dropped:
    an upscale past 38 total digits wraps (documented deviation — the
    reference raises DECIMAL overflow; in-jit code cannot raise, and
    rescales the planner emits stay within the declared precision)."""
    if k == 0:
        return a
    m = 10 ** (9 - k)
    mul = 10 ** k
    high = jnp.floor_divide(a, m)      # carries up
    low = a - high * m
    shifted = low * mul
    carried = jnp.concatenate(
        [high[..., 1:], jnp.zeros_like(high[..., :1])], axis=-1)
    return _norm_wide(shifted + carried)


def _downscale_wide(a: jax.Array, k: int) -> jax.Array:
    """Floor-divide a wide vector by 10^k (k <= 9 per step): remainder
    chain over base-10^9 limbs, msb first (r < 10^k <= 10^9, so
    r*10^9 + limb < 10^18)."""
    d = 10 ** k
    outs = []
    r = jnp.zeros_like(a[..., 0])
    for i in range(WIDE_LIMBS):
        cur = r * _B9 + a[..., i]
        q = jnp.floor_divide(cur, d)
        r = cur - q * d
        outs.append(q)
    return _norm_wide(jnp.stack(outs, axis=-1))


def rescale(a: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    if to_scale > from_scale:
        k = to_scale - from_scale
        out = a
        if width(a) != 2:
            while k > 0:
                step = min(k, 8)
                out = _shift_digits_wide(out, step)
                k -= step
            return out
        while k > 0:  # static python loop: at most a few steps of 10^9
            step = min(k, 9)
            out = mul_small(out, jnp.asarray(10 ** step, jnp.int64))
            k -= step
        return out
    if to_scale < from_scale:
        k = from_scale - to_scale
        if width(a) != 2:
            out = a
            while k > 0:
                step = min(k, 9)
                out = _downscale_wide(out, step)
                k -= step
            return out
        if k > 18:
            raise ValueError("long-decimal downscale beyond 18 digits unsupported")
        d = 10 ** k  # k <= 18: divides BASE exactly
        ah, al = split(a)
        # floor((hi*BASE + lo)/d) = hi*(BASE/d) + floor(lo/d): the first
        # term can exceed 10^18, so it goes through the limb multiplier
        m = jnp.broadcast_to(jnp.asarray(BASE // d, jnp.int64), ah.shape)
        return add(mul_int64(ah, m), from_int64(jnp.floor_divide(al, d)))
    return a


def compare(a: jax.Array, b: jax.Array):
    """(lt, eq, gt) boolean triples — canonical limb order (msb-first,
    limbs[1:] non-negative) IS value order, any width."""
    L = width(a)
    lt = jnp.zeros(a.shape[:-1], jnp.bool_)
    eq = jnp.ones(a.shape[:-1], jnp.bool_)
    for i in range(L):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt, eq, ~(lt | eq)


def to_double(a: jax.Array, scale: int) -> jax.Array:
    if width(a) != 2:
        acc = a[..., 0].astype(jnp.float64)
        for i in range(1, WIDE_LIMBS):
            acc = acc * float(_B9) + a[..., i].astype(jnp.float64)
        return acc / (10.0 ** scale)
    ah, al = split(a)
    return (ah.astype(jnp.float64) * float(BASE) + al.astype(jnp.float64)) / (10.0 ** scale)


# -- aggregation support -------------------------------------------------------

def to_sum_limbs(a: jax.Array) -> jax.Array:
    """(n, 2) base-10^18 -> (n, 4) base-10^9 limbs, safe to segment_sum
    over ~9*10^9 rows without int64 overflow.  Wide (n, 5) vectors are
    already base-10^9: summed as-is under the same row bound."""
    if width(a) != 2:
        return a
    ah, al = split(a)
    return jnp.stack([
        jnp.floor_divide(ah, _B9), jnp.remainder(ah, _B9),
        jnp.floor_divide(al, _B9), jnp.remainder(al, _B9),
    ], axis=-1)


def from_sum_limbs(s: jax.Array) -> jax.Array:
    """Summed base-10^9 limbs -> normalized: (n, 4) -> (n, 2) for the
    classic layout, (n, 5) -> (n, 5) for the wide layout."""
    if s.shape[-1] == WIDE_LIMBS:
        return _norm_wide(s)
    h1, h0, l1, l0 = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    # fold base-10^9 carries upward
    c = jnp.floor_divide(l0, _B9)
    l0 = l0 - c * _B9
    l1 = l1 + c
    c = jnp.floor_divide(l1, _B9)
    l1 = l1 - c * _B9
    hi_extra = c
    lo = l1 * _B9 + l0
    c = jnp.floor_divide(h0, _B9)
    h0 = h0 - c * _B9
    h1 = h1 + c
    hi = h1 * _B9 + h0 + hi_extra
    return normalize(hi, lo)
