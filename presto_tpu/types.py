"""SQL type system mapped onto TPU-friendly device dtypes.

Reference analog: ``presto-spi/src/main/java/com/facebook/presto/spi/type/``
(BigintType.java, DoubleType.java, DateType.java, DecimalType.java,
VarcharType.java, BooleanType.java ...).  Unlike the reference's
object-per-value Java model, every type here defines a *device
representation*: one fixed-width ``jnp`` dtype per column, so whole
columns live in HBM as dense arrays and all ops compile onto the MXU/VPU.

Representation decisions (TPU-first):
  BIGINT / INTEGER  -> int64 / int32
  DOUBLE            -> float64 on host, float32 or float64 on device
                       (TPU float64 is emulated; aggregations keep exact
                       sums for DECIMAL-typed data via scaled int64)
  BOOLEAN           -> bool_
  DATE              -> int32 days since 1970-01-01 (same as reference
                       DateType.java which stores days-since-epoch)
  TIMESTAMP         -> int64 microseconds since 1970-01-01 00:00:00
                       (reference TimestampType.java stores epoch
                       millis; micros here so device datetime math
                       never loses sub-ms precision)
  DECIMAL(p<=18,s)  -> int64 scaled by 10**s ("short decimal")
  DECIMAL(p<=36,s)  -> (capacity, 2) int64 limbs: value = hi*10^18 + lo
                       with lo in [0, 10^18) ("long decimal"; reference
                       uses 2x64-bit UnscaledDecimal128 — base-10^18
                       limbs here keep every carry in native int64 ops)
  VARCHAR           -> int32 dictionary code per row + host-side
                       ``Dictionary`` of unique strings.  TPC-H string
                       columns are low-cardinality or only ever touched
                       by predicates, so predicates evaluate host-side on
                       the dictionary once and broadcast as boolean LUTs.
  VARCHAR(n) raw    -> (capacity, n) uint8 byte matrix, zero-padded
                       (VarcharType(n, raw=True)).  The non-dictionary
                       representation for unbounded-cardinality text:
                       comparisons/substr/concat/upper/lower run as
                       vector byte ops on device; LIKE/regex fall back
                       to a host callback per page (reference analog:
                       spi/block/VariableWidthBlock.java — offsets+bytes
                       there, fixed-width padded here so XLA keeps
                       static shapes).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """A SQL type with a fixed-width device representation."""

    name: str
    np_dtype: np.dtype
    # True for types whose device array holds dictionary codes, with the
    # actual values host-side (VARCHAR/CHAR).
    dictionary: bool = False
    # decimal scale (digits after the point) when this is a DECIMAL.
    scale: Optional[int] = None
    precision: Optional[int] = None
    # ARRAY element type / MAP value type (None otherwise); MAP key type.
    element: Optional["Type"] = None
    key_element: Optional["Type"] = None
    # ROW field types (None otherwise); optional field names for
    # named-row access (spi/type/RowType.java RowField names).  Names
    # are access metadata, not identity: eq/hash ignore them so a cast
    # that only names fields is a retype.
    fields: Optional[tuple] = None
    field_names: Optional[tuple] = None

    def __repr__(self) -> str:
        if self.name == "row":
            if self.field_names:
                inner = ", ".join(f"{n} {t!r}" for n, t in
                                  zip(self.field_names, self.fields))
                return f"row({inner})"
            return f"row({', '.join(map(repr, self.fields))})"
        if self.name == "array":
            return f"array({self.element!r})"
        if self.name == "map":
            return f"map({self.key_element!r},{self.element!r})"
        if self.scale is not None:
            return f"decimal({self.precision},{self.scale})"
        if self.name in ("char", "varbinary") and self.precision:
            return f"{self.name}({self.precision})"
        return self.name

    # -- classification helpers -------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.name in ("bigint", "integer", "smallint", "tinyint",
                             "double", "real", "decimal")

    @property
    def is_integerlike(self) -> bool:
        return self.name in ("bigint", "integer", "smallint", "tinyint",
                             "date", "timestamp", "time",
                             "interval day to second",
                             "interval year to month")

    @property
    def is_binary(self) -> bool:
        return self.name == "varbinary"

    @property
    def is_decimal(self) -> bool:
        return self.name == "decimal"

    @property
    def is_long_decimal(self) -> bool:
        return self.name == "decimal" and (self.precision or 0) > 18

    @property
    def value_shape(self) -> tuple:
        """Trailing per-value shape of the device array: (2,) for
        two-limb long decimals, (width,) for raw varchar byte matrices,
        (1+max,) for arrays (slot 0 = length), (1+2*max,) for maps
        (slot 0 = entry count, then keys, then values),
        () for everything else."""
        if self.is_long_decimal:
            return (5,) if (self.precision or 0) > 36 else (2,)
        if self.is_raw_string or self.is_binary:
            return (self.precision or 32,)
        if self.name == "array":
            return (1 + (self.precision or 8),)
        if self.name in ("map", "hll", "setdigest"):
            m = self.precision or 8
            if self.element is not None and self.element.is_array:
                # multimap: each value lane is itself a fixed array
                return (1 + m + m * (1 + self.element.max_elems),)
            return (1 + 2 * m,)
        if self.name == "row":
            return (len(self.fields),)
        return ()

    @property
    def is_string(self) -> bool:
        return self.name in ("varchar", "char")

    @property
    def is_raw_string(self) -> bool:
        return self.is_string and not self.dictionary

    @property
    def is_array(self) -> bool:
        return self.name == "array"

    @property
    def is_map(self) -> bool:
        # HYPERLOGLOG shares the map storage layout (bucket -> rho)
        return self.name in ("map", "hll", "setdigest")

    @property
    def is_hll(self) -> bool:
        return self.name == "hll"

    @property
    def max_elems(self) -> int:
        """Static element-slot capacity of an ARRAY/MAP value."""
        return self.precision or 8

    def __eq__(self, other) -> bool:
        if not isinstance(other, Type):
            return NotImplemented
        return (
            self.name == other.name
            and self.scale == other.scale
            and self.precision == other.precision
            and self.element == other.element
            and self.key_element == other.key_element
            # ROW identity includes field types (but not names, which
            # are access metadata) — eq ignoring fields made every two
            # row types "equal" and row(bigint) silently adopted
            # row(bigint, double)'s layout in coercion
            and self.fields == other.fields
        )

    def __hash__(self) -> int:
        return hash((self.name, self.scale, self.precision,
                     self.element, self.key_element, self.fields))


BIGINT = Type("bigint", np.dtype(np.int64))
INTEGER = Type("integer", np.dtype(np.int32))
SMALLINT = Type("smallint", np.dtype(np.int16))
TINYINT = Type("tinyint", np.dtype(np.int8))
DOUBLE = Type("double", np.dtype(np.float64))
REAL = Type("real", np.dtype(np.float32))
BOOLEAN = Type("boolean", np.dtype(np.bool_))
DATE = Type("date", np.dtype(np.int32))
TIMESTAMP = Type("timestamp", np.dtype(np.int64))
# TIME: microseconds since midnight (reference: spi/type/TimeType.java)
TIME = Type("time", np.dtype(np.int64))
# INTERVAL types (spi/type/IntervalDayTimeType.java / IntervalYearMonthType):
# day-to-second = int64 microseconds, year-to-month = int64 months —
# plain int64 columns on device, so interval sum/avg/min/max ride the
# integer aggregation kernels unchanged
INTERVAL_DAY_SECOND = Type("interval day to second", np.dtype(np.int64))
INTERVAL_YEAR_MONTH = Type("interval year to month", np.dtype(np.int64))
MICROS_PER_DAY = 86_400_000_000


def VarbinaryType(length: int = 32) -> Type:
    """VARBINARY as a fixed-capacity (capacity, length) uint8 byte
    matrix — the raw-varchar representation without string semantics
    (reference: spi/type/VarbinaryType.java)."""
    return Type("varbinary", np.dtype(np.uint8), precision=length)


VARBINARY = VarbinaryType()


def CharType(length: int = 32) -> Type:
    """CHAR(n): dictionary-coded like VARCHAR but typed distinctly so
    typeof() reports char(n) (reference: spi/type/CharType.java; the
    blank-padded comparison semantics are NOT emulated — values are
    compared as stored)."""
    return Type("char", np.dtype(np.int32), dictionary=True, precision=length)


def VarcharType(length: int = 32, raw: bool = False) -> Type:
    """Raw (non-dictionary) varchar: (capacity, length) uint8, padded.
    The dictionary-coded VARCHAR remains the default for low-cardinality
    columns; raw is the unbounded-cardinality representation."""
    if not raw:
        return VARCHAR
    return Type("varchar", np.dtype(np.uint8), dictionary=False,
                precision=int(length))
VARCHAR = Type("varchar", np.dtype(np.int32), dictionary=True)


LONG_DECIMAL_BASE = 10 ** 18

# pseudo-type of ST_Point(x, y): never materializes as a column — it
# exists only inside ST_Distance / ST_Contains argument positions
# (reference GeometryType is a real SliceType; point construction here
# stays two device lanes until a consuming kernel uses them)
GEOMETRY_POINT = Type("geometry_point", np.dtype(np.float64))


def _container_storage_dtype(*types: Type, _allow_array: bool = False) -> np.dtype:
    """Storage dtype for ARRAY/MAP slots: one fixed-width lane wide
    enough for every participating scalar type (booleans widen to int32,
    everything integer-like rides int64, doubles force float64).
    ``_allow_array``: a MAP value may itself be a one-level fixed array
    (multimap_agg's MAP(K, ARRAY(V)) — its lanes flatten into the same
    matrix); everywhere else nesting stays a bind-time error."""
    flat = []
    for t in types:
        if (_allow_array and t.is_array and t.element is not None
                and not t.element.value_shape):
            flat.append(t.element)
        elif t.value_shape:
            raise ValueError(f"nested container element type {t} unsupported")
        else:
            flat.append(t)
    if any(t.name in ("double", "real") for t in flat):
        # REAL rides a float64 lane too — an int64 lane would floor it
        return np.dtype(np.float64)
    if all(t.name == "boolean" for t in flat):
        return np.dtype(np.int32)
    return np.dtype(np.int64)


def ArrayType(element: Type, max_elems: int = 8) -> Type:
    """ARRAY(element) with a static per-value slot capacity.

    Reference: spi/type/ArrayType.java (variable-length element blocks
    with offsets).  TPU-first re-design: a (capacity, 1+max_elems)
    matrix per column — slot 0 holds the length, slots 1.. hold
    elements padded with the type's null sentinel — so every array op
    is a masked vector op over the trailing axis and shapes stay
    static for XLA."""
    return Type("array", _container_storage_dtype(element),
                precision=int(max_elems), element=element)


def RowType(*field_types: Type, names=None) -> Type:
    """ROW value: one slot per field in a shared storage dtype
    (reference: spi/type/RowType.java's variable per-field blocks —
    here a dense (capacity, nfields) matrix, TPU-first).  Fields must
    be fixed-width non-string scalars.  ``names`` makes the fields
    addressable (CAST(... AS ROW(x bigint, ...)).x)."""
    if not field_types:
        raise ValueError("ROW needs at least one field")
    for t in field_types:
        if t.is_string or t.is_array or t.is_map or t.is_long_decimal:
            raise ValueError(
                f"ROW fields must be fixed-width scalars (got {t})")
    if names is not None and len(names) != len(field_types):
        raise ValueError("ROW field names/types length mismatch")
    storage = _container_storage_dtype(*field_types)
    return Type(name="row", np_dtype=storage, fields=tuple(field_types),
                field_names=tuple(names) if names is not None else None)


def MapType(key: Type, value: Type, max_elems: int = 8) -> Type:
    """MAP(key, value): (capacity, 1+2*max) matrix — slot 0 = entry
    count, slots 1..max = keys, slots max+1..2*max = values, in one
    common storage dtype (reference: spi/type/MapType.java)."""
    return Type("map", _container_storage_dtype(key, value, _allow_array=True),
                precision=int(max_elems), element=value, key_element=key)


#: HLL sketch bucket count for approx_set/merge/cardinality: m = 2^9.
#: Smaller than approx_distinct's m=4096 (rel. error ~4.6% vs ~1.6%)
#: because the sketch is a first-class VALUE here — every populated
#: register occupies a slot in the column's (capacity, 1+2m) matrix.
HLL_SET_BUCKETS = 512


def HllType() -> Type:
    """HYPERLOGLOG approximate-set sketch (reference:
    spi/type/HyperLogLogType + io.airlift.stats HLL behind approx_set/
    merge/cardinality).  TPU-first re-design: a DENSE-capable sparse
    map bucket -> rho over the HLL_SET_BUCKETS register domain, sharing
    the map storage layout so sketch construction is the map_agg
    scatter and sketch union is a per-bucket max."""
    return Type("hll", _container_storage_dtype(BIGINT, BIGINT),
                precision=HLL_SET_BUCKETS, element=BIGINT, key_element=BIGINT)


#: KMV (k-minimum-values) slot count for make_set_digest/
#: merge_set_digest: the digest keeps the K smallest 64-bit hashes of
#: the distinct inputs with per-hash counts.
SET_DIGEST_HASHES = 64


def SetDigestType() -> Type:
    """SETDIGEST (reference: type/setdigest/SetDigestType.java — HLL +
    minhash behind make_set_digest/merge_set_digest/jaccard_index/
    intersection_cardinality/hash_counts).  TPU-first re-design: a KMV
    sketch — the K smallest hashes with counts in the map storage
    layout [len, hashes ascending.., counts..] — one structure serving
    both the cardinality estimator ((K-1)/fraction-of-hash-space) and
    the minhash role (jaccard from the K-smallest union sample)."""
    return Type("setdigest", _container_storage_dtype(BIGINT, BIGINT),
                precision=SET_DIGEST_HASHES, element=BIGINT,
                key_element=BIGINT)


def null_sentinel(storage: np.dtype):
    """In-slot NULL marker for container elements (int: INT64_MIN
    truncated to the lane dtype; float: NaN)."""
    if storage.kind == "f":
        return np.nan
    return np.iinfo(storage).min


def DecimalType(precision: int = 18, scale: int = 0) -> Type:
    """Scaled-integer decimal: int64 for p <= 18, two base-10^18 limbs
    for p <= 36, five base-10^9 limbs for the full 38 digits.

    Reference: spi/type/DecimalType.java + spi/type/Decimals.java
    (short = long java primitive, long = Slice-backed 128-bit).
    """
    if precision > 38:
        raise ValueError("decimal precision > 38 unsupported")
    return Type("decimal", np.dtype(np.int64), scale=scale, precision=precision)


def common_super_type(a: Type, b: Type) -> Type:
    """Implicit coercion for binary ops (reference: FunctionRegistry
    coercion matrix, metadata/FunctionRegistry.java:349)."""
    if a == b:
        return a
    if a.name == "array" and b.name == "array":
        # unify recursively; slot capacities (precision) widen to the
        # larger — identity equality alone rejected array(bigint) vs
        # array(bigint) whose widths differed (VERDICT r5 probe: the
        # repr hides precision, so the error looked self-contradictory)
        elem = common_super_type(a.element, b.element)
        return ArrayType(elem, max(a.max_elems, b.max_elems))
    if a.name == "map" and b.name == "map":
        key = common_super_type(a.key_element, b.key_element)
        val = common_super_type(a.element, b.element)
        return MapType(key, val, max(a.max_elems, b.max_elems))
    if (a.name == "row" and b.name == "row"
            and len(a.fields or ()) == len(b.fields or ())):
        fields = [common_super_type(x, y)
                  for x, y in zip(a.fields, b.fields)]
        names = a.field_names if a.field_names == b.field_names else None
        return RowType(*fields, names=names)
    if {a.name, b.name} == {"date", "timestamp"}:
        return TIMESTAMP
    if a.is_string and b.is_string:
        if a.is_raw_string and b.is_raw_string:
            return a if (a.precision or 0) >= (b.precision or 0) else b
        # raw wins over a dictionary-typed operand (string literals are
        # dictionary-typed until they meet a raw column)
        if a.is_raw_string:
            return a
        if b.is_raw_string:
            return b
    if a.name == "char" and b.name == "char":
        return a if (a.precision or 0) >= (b.precision or 0) else b
    if a.name == "char" and b.name == "varchar":
        return b
    if a.name == "varchar" and b.name == "char":
        return a
    # the ladder follows the reference's coercion matrix: fixed-width
    # integers widen upward, DECIMAL op REAL -> REAL, anything op
    # DOUBLE -> DOUBLE (metadata/FunctionRegistry.java:349)
    order = {"boolean": 0, "tinyint": 1, "smallint": 2, "integer": 3,
             "date": 3, "bigint": 4, "decimal": 5, "real": 6, "double": 7}
    if a.name in order and b.name in order:
        winner = a if order[a.name] >= order[b.name] else b
        loser = b if winner is a else a
        if winner.is_decimal and loser.is_decimal:
            scale = max(a.scale, b.scale)
            if (a.precision or 0) > 36 or (b.precision or 0) > 36:
                return DecimalType(38, scale)  # wide 5-limb layout
            long_ = a.is_long_decimal or b.is_long_decimal
            return DecimalType(36 if long_ else 18, scale)
        if winner.is_decimal and loser.name in (
                "bigint", "integer", "smallint", "tinyint"):
            return winner
        return winner
    raise TypeError(f"no common super type for {a} and {b}")


def _split_top_level(s: str) -> list:
    """Split 'a,b,c' on commas not nested inside parentheses."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur).strip())
    return out


def parse_type(s: str) -> Type:
    """Parse a SQL type name, e.g. 'bigint', 'decimal(12,2)', 'varchar(25)',
    'raw_varchar(24)' (the non-dictionary fixed-width representation)."""
    s = s.strip().lower()
    if s == "hyperloglog" or s == "hll":
        return HllType()
    if s == "setdigest":
        return SetDigestType()
    if s.startswith("array"):
        inner = s[s.index("(") + 1 : s.rindex(")")]
        parts = _split_top_level(inner)
        max_elems = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 8
        return ArrayType(parse_type(parts[0]), max_elems)
    if s.startswith("map"):
        inner = s[s.index("(") + 1 : s.rindex(")")]
        parts = _split_top_level(inner)
        max_elems = int(parts[2]) if len(parts) > 2 and parts[2].isdigit() else 8
        return MapType(parse_type(parts[0]), parse_type(parts[1]), max_elems)
    if s.startswith("raw_varchar"):
        width = int(s[s.index("(") + 1 : s.rindex(")")]) if "(" in s else 32
        return VarcharType(width, raw=True)
    if s.startswith("row(") or s.startswith("row ("):
        inner = s[s.index("(") + 1: s.rindex(")")]
        names, fts = [], []
        for part in _split_top_level(inner):
            part = part.strip()
            # "name type" (named field) vs bare "type"
            bits = part.split(None, 1)
            # a name candidate must be a bare identifier — 'decimal(10,'
            # from 'row(decimal(10, 2))' is type text, not a field name
            if len(bits) == 2 and "(" not in bits[0] \
                    and bits[0] not in ("double",):
                names.append(bits[0])
                fts.append(parse_type(bits[1]))
            else:
                names.append(None)
                fts.append(parse_type(part))
        named = [n for n in names if n is not None]
        return RowType(*fts, names=names if len(named) == len(fts) else None)
    if s.startswith("decimal"):
        if "(" in s:
            inner = s[s.index("(") + 1 : s.rindex(")")]
            parts = [p.strip() for p in inner.split(",")]
            p = int(parts[0])
            sc = int(parts[1]) if len(parts) > 1 else 0
            return DecimalType(p, sc)
        return DecimalType()
    if s.startswith("varbinary"):
        width = int(s[s.index("(") + 1 : s.rindex(")")]) if "(" in s else 32
        return VarbinaryType(width)
    if s.startswith("char"):
        width = int(s[s.index("(") + 1 : s.rindex(")")]) if "(" in s else 32
        return CharType(width)
    if s.startswith("varchar"):
        return VARCHAR
    m = {
        "bigint": BIGINT,
        "integer": INTEGER,
        "int": INTEGER,
        "smallint": SMALLINT,
        "tinyint": TINYINT,
        "double": DOUBLE,
        "double precision": DOUBLE,
        "real": REAL,
        "boolean": BOOLEAN,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "time": TIME,
    }
    if s in m:
        return m[s]
    raise ValueError(f"unknown type: {s}")
