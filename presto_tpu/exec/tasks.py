"""Morsel-driven local split scheduler: bounded worker pool + async
prefetch + ordered/unordered delivery.

Reference analogs: the ``TaskExecutor``/Driver tier's time-sliced split
concurrency (``task.concurrency``,
execution/executor/TaskExecutor.java:75) and morsel-driven parallelism
(Leis et al., SIGMOD 2014) — small self-contained work units (our
bucket-padded splits) dispatched to a bounded worker pool with
backpressure.

The executor's serial generator chain walked splits one at a time, so
host-side page prep (connector split generation + ladder padding),
device dispatch, and result pull never overlapped even though jitted
XLA programs release the GIL.  :class:`SplitScheduler` runs up to
``concurrency`` splits in flight on worker threads while a producer
thread prefetches the next splits' host pages, and delivers results to
the consumer either in source order (sequence-numbered reorder buffer
— the default: byte-identical to the serial path) or in completion
order (for commutative consumers such as exact aggregation folds).

Knobs resolve ONCE per process (the engine_lint env-read contract):

- ``PRESTO_TPU_TASK_CONCURRENCY`` / ``query.task-concurrency`` config /
  ``task_concurrency`` session property — splits in flight; ``1`` (the
  default) reproduces the serial path exactly and is the A/B leg.
- ``PRESTO_TPU_TASK_PREFETCH`` / ``task_prefetch`` session property —
  extra host pages prepared ahead of the worker pool.

Backpressure is structural: at most ``concurrency + prefetch`` splits
exist between the source and the consumer (produced, executing, or
completed-but-unconsumed), and an optional ``headroom`` probe defers
dispatch while the memory pool is tight — throttling, not OOM.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, Optional

from presto_tpu.envflag import EnvInt
from presto_tpu.sync import named_condition, named_lock

#: splits in flight per pipeline; 1 = today's serial path (A/B leg).
#: The pool width is config-derived by construction: env var, config
#: key and session property all funnel through here.
_TASK_CONCURRENCY = EnvInt("PRESTO_TPU_TASK_CONCURRENCY", default=1, floor=1)
#: host pages prepared AHEAD of the worker pool (double-buffering depth)
_TASK_PREFETCH = EnvInt("PRESTO_TPU_TASK_PREFETCH", default=2, floor=0)


def task_concurrency_default() -> int:
    return _TASK_CONCURRENCY()


def set_task_concurrency(value: Optional[int]) -> None:
    _TASK_CONCURRENCY.set(value)


def task_prefetch_default() -> int:
    return _TASK_PREFETCH()


def set_task_prefetch(value: Optional[int]) -> None:
    _TASK_PREFETCH.set(value)


# ---------------------------------------------------------------------------
# process-wide live gauges (task.splits_queued / task.splits_running)
# ---------------------------------------------------------------------------

_LIVE_LOCK = named_lock("tasks._LIVE_LOCK")
_LIVE = {"queued": 0, "running": 0}


def _live_add(key: str, n: int, enabled: bool = True) -> None:
    if not enabled:
        return  # metrics=False schedulers (wave prefetch) stay out of
        # the split gauges — their units are not morsel scan splits
    with _LIVE_LOCK:
        _LIVE[key] += n


def _wire_gauges() -> None:
    from presto_tpu.obs import METRICS

    METRICS.gauge("task.splits_queued").set_fn(lambda: _LIVE["queued"])
    METRICS.gauge("task.splits_running").set_fn(lambda: _LIVE["running"])


_wire_gauges()


class SchedulerStats:
    """Per-run counters, merged per query for EXPLAIN ANALYZE and the
    system_runtime_tasks row (GIL-atomic int/float adds; readers take
    a point-in-time copy)."""

    __slots__ = ("splits", "stall_s", "prefetch_hits", "prefetch_misses",
                 "concurrency", "backpressure_s")

    def __init__(self):
        self.splits = 0
        self.stall_s = 0.0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.concurrency = 1
        self.backpressure_s = 0.0

    def as_dict(self) -> Dict[str, float]:
        return {
            "splits": self.splits,
            "concurrency": self.concurrency,
            "stall_s": round(self.stall_s, 4),
            "prefetch_hits": self.prefetch_hits,
            "prefetch_misses": self.prefetch_misses,
            "backpressure_s": round(self.backpressure_s, 4),
        }


class _Cancelled(Exception):
    """Internal: the consumer closed the result generator early."""


class SplitScheduler:
    """Execute a stream of splits through ``fn`` with bounded
    concurrency and async prefetch.

    ``map(items, fn)`` returns an iterator of results.  With
    ``concurrency == 1`` it degrades to the bare serial generator —
    no threads, identical pull semantics to the legacy path.  Above 1:

    - a producer thread drains ``items`` (host page prep runs there,
      overlapping device execution) into a bounded queue;
    - ``concurrency`` worker threads call ``fn`` on queued splits
      (jitted XLA programs release the GIL, so they genuinely overlap);
    - the consumer receives results through a sequence-numbered reorder
      buffer (``ordered=True``, the default — delivery order equals
      source order, so results are byte-identical to the serial path)
      or in completion order (``ordered=False`` — for commutative
      consumers; lower latency to first result);
    - at most ``concurrency + prefetch`` splits are outstanding, and
      the optional ``headroom()`` probe defers further dispatch while
      it returns False (one split always proceeds — backpressure must
      never deadlock progress).

    Worker/producer exceptions propagate to the consumer: in ordered
    mode at the failing split's sequence position (exactly where the
    serial path would have raised), in unordered mode as soon as the
    failure is observed.  Closing the result iterator (LIMIT early
    exit) stops the producer and drains the workers without leaking
    threads.
    """

    def __init__(self, concurrency: Optional[int] = None,
                 prefetch: Optional[int] = None, ordered: bool = True,
                 headroom: Optional[Callable[[], bool]] = None,
                 name: str = "task", stats: Optional[SchedulerStats] = None,
                 drop: Optional[Callable] = None, metrics: bool = True):
        self.concurrency = max(1, int(concurrency
                                      if concurrency is not None
                                      else task_concurrency_default()))
        self.prefetch = max(0, int(prefetch if prefetch is not None
                                   else task_prefetch_default()))
        self.ordered = ordered
        self.headroom = headroom
        self.name = name
        self.stats = stats if stats is not None else SchedulerStats()
        self.stats.concurrency = max(self.stats.concurrency,
                                     self.concurrency)
        # called once per produced-but-never-executed item when the
        # consumer closes early — the owner's chance to release
        # per-item resources (scan_page memory reservations)
        self.drop = drop
        # False keeps the process-wide task.* counters untouched — for
        # reuse outside the morsel scan-split pipeline (mesh wave
        # prefetch), whose units would pollute the documented metrics
        self.metrics = metrics

    # ------------------------------------------------------------------
    def map(self, items: Iterable, fn: Callable) -> Iterator:
        if self.concurrency <= 1:
            return self._map_serial(items, fn)
        return self._map_threaded(items, fn)

    def _map_serial(self, items: Iterable, fn: Callable) -> Iterator:
        for item in items:
            self.stats.splits += 1
            yield fn(item)

    # ------------------------------------------------------------------
    def _map_threaded(self, items: Iterable, fn: Callable) -> Iterator:
        from presto_tpu.obs import (
            METRICS, current_progress, current_tracer, publishing, tracing,
        )

        # capture the caller thread's ambient context so producer/worker
        # threads publish to the same query's tracer and progress
        tracer = current_tracer()
        progress = current_progress()
        window = self.concurrency + self.prefetch

        lock = named_lock("tasks._map_threaded.lock")
        cond = named_condition("tasks._map_threaded.lock", lock)
        inq: collections.deque = collections.deque()  # (seq, item)
        results: Dict[int, tuple] = {}  # seq -> (ok, value)
        completion: collections.deque = collections.deque()
        state = {
            "inflight": 0,       # produced, result not yet consumed
            "produced": 0,
            "consumed": 0,
            "source_done": False,
            "source_error": None,  # (seq, exc)
            "stop": False,
        }

        def _produce():
            seq = 0
            try:
                with tracing(tracer), publishing(progress):
                    for item in items:
                        with cond:
                            t0 = time.perf_counter()
                            while not state["stop"] and (
                                    state["inflight"] >= window
                                    or (self.headroom is not None
                                        and state["inflight"] >= 1
                                        and not self._headroom_ok())):
                                # the timed wait exists ONLY to re-probe
                                # external headroom; window waits are
                                # notify-driven (every consumer pop
                                # notifies under the lock)
                                cond.wait(0.05 if self.headroom is not None
                                          else None)
                            waited = time.perf_counter() - t0
                            if waited > 1e-4:
                                self.stats.backpressure_s += waited
                            if state["stop"]:
                                self._drop(item)
                                return
                            state["inflight"] += 1
                            state["produced"] += 1
                            # gauge bump inside the lock: a worker can
                            # only pop (and decrement) after we release,
                            # so task.splits_queued never reads negative
                            _live_add("queued", 1, self.metrics)
                            inq.append((seq, item))
                            cond.notify_all()
                        seq += 1
            except BaseException as e:  # noqa: BLE001 — relayed below
                with cond:
                    state["source_error"] = (seq, e)
                    cond.notify_all()
            finally:
                with cond:
                    state["source_done"] = True
                    cond.notify_all()

        def _work():
            with tracing(tracer), publishing(progress):
                while True:
                    with cond:
                        # notify-driven: producer appends, consumer
                        # pops, and terminal transitions all notify
                        # under this lock
                        while not inq and not state["stop"] \
                                and not state["source_done"]:
                            cond.wait()
                        if state["stop"]:
                            return
                        if not inq:
                            if state["source_done"]:
                                return
                            continue
                        seq, item = inq.popleft()
                    _live_add("queued", -1, self.metrics)
                    _live_add("running", 1, self.metrics)
                    try:
                        from presto_tpu.obs import span

                        with span(f"{self.name}:split", cat="task"):
                            val = (True, fn(item))
                    except BaseException as e:  # noqa: BLE001 — relayed
                        val = (False, e)
                    finally:
                        _live_add("running", -1, self.metrics)
                    with cond:
                        if self.ordered:
                            results[seq] = val
                        else:
                            completion.append(val)
                        cond.notify_all()

        producer = threading.Thread(
            target=_produce, daemon=True, name=f"{self.name}-producer")
        # pool width is config-derived (task_concurrency); the lint
        # thread-pool rule pins that property repo-wide
        workers = [
            threading.Thread(target=_work, daemon=True,
                             name=f"{self.name}-worker-{i}")
            for i in range(self.concurrency)
        ]
        producer.start()
        for w in workers:
            w.start()

        def _next_result():
            """Block until the next deliverable result; raise worker or
            source exceptions at their ordered position."""
            t0 = time.perf_counter()
            with cond:
                while True:
                    if self.ordered:
                        nxt = state["consumed"]
                        if nxt in results:
                            val = results.pop(nxt)
                            state["consumed"] += 1
                            state["inflight"] -= 1
                            cond.notify_all()
                            break
                        err = state["source_error"]
                        if err is not None and err[0] == nxt:
                            raise err[1]
                    else:
                        if completion:
                            val = completion.popleft()
                            state["consumed"] += 1
                            state["inflight"] -= 1
                            cond.notify_all()
                            break
                        err = state["source_error"]
                        if err is not None and state["consumed"] >= err[0]:
                            raise err[1]
                    if state["source_done"] and state["inflight"] == 0 \
                            and state["source_error"] is None:
                        raise _Cancelled  # drained: normal exhaustion
                    cond.wait()
            stall = time.perf_counter() - t0
            # prefetch accounting: a result already buffered when the
            # consumer asked (no measurable wait) is a hit — the
            # pipeline stayed ahead of the consumer
            if stall > 1e-4:
                self.stats.stall_s += stall
                self.stats.prefetch_misses += 1
                if self.metrics:
                    METRICS.counter(
                        "task.scheduler_stall_seconds_total").inc(stall)
                    METRICS.counter("task.prefetch_misses").inc()
            else:
                self.stats.prefetch_hits += 1
                if self.metrics:
                    METRICS.counter("task.prefetch_hits").inc()
            ok, value = val
            if not ok:
                raise value
            return value

        def _gen():
            try:
                while True:
                    try:
                        value = _next_result()
                    except _Cancelled:
                        return
                    self.stats.splits += 1
                    if self.metrics:
                        METRICS.counter("task.splits_dispatched").inc()
                    yield value
            finally:
                with cond:
                    state["stop"] = True
                    dropped = list(inq)
                    inq.clear()
                    cond.notify_all()
                if dropped:
                    _live_add("queued", -len(dropped), self.metrics)
                    # produced-but-never-executed splits still hold
                    # per-item resources (scan_page reservations) —
                    # hand them back to the owner
                    for _, item in dropped:
                        self._drop(item)
                producer.join(timeout=5.0)
                for w in workers:
                    w.join(timeout=5.0)

        return _gen()

    def _drop(self, item) -> None:
        if self.drop is None:
            return
        try:
            self.drop(item)
        except Exception:
            pass  # cleanup must never mask the closing path

    def _headroom_ok(self) -> bool:
        try:
            return bool(self.headroom())
        except Exception:
            return True  # a broken probe must not stall the pipeline


def run_splits(items: Iterable, fn: Callable, *,
               concurrency: Optional[int] = None,
               prefetch: Optional[int] = None, ordered: bool = True,
               headroom: Optional[Callable[[], bool]] = None,
               name: str = "task",
               stats: Optional[SchedulerStats] = None) -> Iterator:
    """One-shot convenience over :class:`SplitScheduler`."""
    return SplitScheduler(concurrency=concurrency, prefetch=prefetch,
                          ordered=ordered, headroom=headroom, name=name,
                          stats=stats).map(items, fn)


def prefetch_iter(items: Iterable, *, depth: Optional[int] = None,
                  name: str = "prefetch",
                  stats: Optional[SchedulerStats] = None) -> Iterator:
    """Async prefetch WITHOUT re-ordering or a worker pool: a producer
    thread stays ``depth`` items ahead of the consumer.  The
    double-buffering primitive for strictly serial device pipelines
    (mesh wave execution: wave k runs on the devices while wave k+1's
    host pages are assembled)."""
    d = depth if depth is not None else max(1, task_prefetch_default())
    if d <= 0:
        return iter(items)
    # metrics=False: waves are not morsel scan splits; incrementing the
    # documented task.* counters here would corrupt their units
    sched = SplitScheduler(concurrency=1, prefetch=d, name=name,
                           stats=stats, metrics=False)

    def _identity(x):
        return x

    # concurrency=1 but routed through the threaded path explicitly:
    # plain map() would degrade to the serial loop and never overlap
    return sched._map_threaded(items, _identity)
