"""Single-process pipeline executor.

Reference analog: the worker execution tier — ``operator/Driver.java:262``
(processFor loop moving Pages between operators), pipelines from
``planner/LocalExecutionPlanner.java:271``, and the in-process harness
``testing/LocalQueryRunner.java:584``.

TPU-first redesign: instead of thread-per-driver pulling one Page at a
time through virtual operator calls, the executor fuses every *streaming
chain* of a plan (scan -> filter -> project -> join-probe -> partial-agg)
into ONE jitted function Page -> Page, so XLA compiles the whole chain
into a single fused TPU program per split.  Pipeline breakers
(aggregation finalization, join build, sort) materialize, mirroring the
reference's pipeline boundaries at LocalExchange/HashBuilder points.

Data-dependent sizes (the big CPU/TPU impedance mismatch, SURVEY.md §7)
are handled with static capacities + live masks; expanding joins and
group-by overflow use count-check-and-retry with doubled capacity
(the analog of MultiChannelGroupByHash.tryRehash and the yielding
LookupJoinPageBuilder).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.catalog import Catalog
from presto_tpu.ops.aggregate import grouped_aggregate, merge_aggregate
from presto_tpu.ops.filter_project import filter_page, project_page
from presto_tpu.ops.join import JoinBuild, build_join, probe_expand, probe_join
from presto_tpu.ops.sort import limit_page, sort_page, sort_perm, topn_page
from presto_tpu.page import Block, Page
from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    RemoteSourceNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    ValuesNode,
    WindowNode,
)
from presto_tpu.types import Type


@dataclasses.dataclass
class MaterializedResult:
    """Host-side query result (testing/MaterializedResult.java analog)."""

    names: List[str]
    types: List[Type]
    rows: List[tuple]

    def __len__(self):
        return len(self.rows)

    def __iter__(self):
        return iter(self.rows)


def concat_pages_device(pages: Sequence[Page]) -> Page:
    """Concatenate pages column-wise on device (capacities may differ)."""
    if len(pages) == 1:
        return pages[0]
    blocks = []
    for i in range(pages[0].num_blocks):
        data = jnp.concatenate([p.blocks[i].data for p in pages])
        valid = jnp.concatenate([p.blocks[i].valid for p in pages])
        b0 = pages[0].blocks[i]
        blocks.append(Block(data, valid, b0.type, b0.dictionary))
    mask = jnp.concatenate([p.row_mask for p in pages])
    return Page(tuple(blocks), mask)


def bucket_capacity(n: int) -> int:
    """Shape-bucketed page capacity: next multiple of 64K for large
    pages, next power of two below that.  Pow2 alone doubles pages
    sitting just past a boundary (TPC-H generator splits land at
    ~1048576 +- 1200 rows, so pow2 sent a third of them to 2M — a 33%
    compute tax); 64K granularity keeps the waste <= 6.5% while still
    collapsing the data-dependent capacities that each cost a full
    XLA compile of the chain program.

    The 2048-row slack absorbs boundary straddle: generator split
    sizes scatter within ~1200 rows of the nominal split, so a bare
    ceil parked siblings of one scan in TWO adjacent buckets (1048576
    vs 1114112 measured at SF1) — one extra chain program per scan for
    0 rows of useful capacity.  Counts within slack below a boundary
    round up with their just-past-the-boundary siblings; exact
    multiples stay put so the function is idempotent.  The slack makes
    the map non-monotonic in a 2048-row band below each boundary
    (bounded extra padding, never insufficient capacity); scans avoid
    even that via the uniform-capacity pass in ``_source_pages``, which
    keeps a tail from overshooting the bucket its full-size siblings
    occupy."""
    n = int(n)
    if n >= (1 << 16):
        g = 1 << 16
        if n % g == 0:
            return n
        return ((n + 2048) // g + 1) * g
    return 1 << max(0, n - 1).bit_length()


def pad_page_to(page: Page, tgt: int) -> Page:
    """Pad a page with dead rows up to capacity ``tgt`` (no-op when
    already at least that large)."""
    cap = page.capacity
    if tgt <= cap or cap == 0:
        return page
    arrs, pm = _pad_arrays(
        tuple(b.data for b in page.blocks) + tuple(b.valid for b in page.blocks),
        page.row_mask, tgt - cap)
    nb = len(page.blocks)
    blocks = tuple(
        Block(arrs[i], arrs[nb + i], b.type, b.dictionary)
        for i, b in enumerate(page.blocks))
    return Page(blocks, pm)


# A/B escape hatches, resolved ONCE per process (engine_lint env-read
# rule: pad_page_pow2 runs per page, _run_aggregation_impl per query —
# neither is a place for an environment lookup); the set_* hooks
# override for tests/tools without touching the environment.
from presto_tpu.envflag import EnvFlag

#: ``PRESTO_TPU_PAD_SCAN=0`` disables scan-page ladder padding
#: (uniform-capacity pass included) for A/B runs.
_PAD_SCAN = EnvFlag("PRESTO_TPU_PAD_SCAN", default=True)
#: ``PRESTO_TPU_AGG_TOWER=0`` reverts to the running-fold aggregation
#: path for A/B runs.
_AGG_TOWER = EnvFlag("PRESTO_TPU_AGG_TOWER", default=True)


def pad_scan_enabled() -> bool:
    return _PAD_SCAN()


def set_pad_scan(value: Optional[bool]) -> None:
    _PAD_SCAN.set(value)


def agg_tower_enabled() -> bool:
    return _AGG_TOWER()


def set_agg_tower(value: Optional[bool]) -> None:
    _AGG_TOWER.set(value)


def pad_page_pow2(page: Page) -> Page:
    """Pad a page with dead rows up to its bucketed capacity
    (bucket_capacity).  Scan splits otherwise carry data-dependent
    capacities (ragged last split, per-table row counts) and every
    distinct capacity costs a full XLA compile of the whole chain
    program — the dominant cold-start cost (19 of q3's 32 warmup
    compiles were one agg program re-traced per shape)."""
    if not pad_scan_enabled():
        return page
    return pad_page_to(page, bucket_capacity(page.capacity))


def _pad_arrays_impl(arrs, mask, pad):
    """One jitted program per (shapes, pad) signature — not one concat
    program per block — pads every column and the mask together."""
    out = tuple(
        jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]) for a in arrs)
    return out, jnp.concatenate([mask, jnp.zeros((pad,), jnp.bool_)])


_pad_arrays = jax.jit(_pad_arrays_impl, static_argnums=(2,))


def slice_page(page: Page, n: int) -> Page:
    """First n physical rows (static slice — used after sorts where live
    rows are compacted to the front)."""
    blocks = tuple(
        Block(b.data[:n], b.valid[:n], b.type, b.dictionary) for b in page.blocks
    )
    return Page(blocks, page.row_mask[:n])


def cross_append_single(q: Page, r: Page) -> Page:
    """Append a single-row page's columns to every row of ``q`` (the
    cross-join-with-scalar-subquery kernel, EnforceSingleRow +
    NestedLoopJoin's one-row case)."""
    blocks = list(q.blocks)
    for b in r.blocks:
        blocks.append(
            Block(
                jnp.broadcast_to(b.data[0], (q.capacity,) + b.data.shape[1:]),
                jnp.broadcast_to(b.valid[0] & r.row_mask[0], (q.capacity,)),
                b.type,
                b.dictionary,
            )
        )
    return Page(tuple(blocks), q.row_mask)


class QueryStats:
    """Per-plan-node execution stats (QueryStats/OperatorStats analog).
    Wall times are inclusive of upstream stages (chains are fused into
    one XLA program; exclusive per-operator timing would require
    breaking fusion).

    Keying: entries key on a STABLE structural node id — (structural
    signature, occurrence-within-plan) — not ``PlanNode`` object
    identity.  Keying by identity fragmented stats the moment a
    structurally identical node re-appeared (re-planned retries,
    rebuilt executors sharing registry programs): each object opened
    its own entry and EXPLAIN ANALYZE totals undercounted.  Twin nodes
    inside one plan (self-join scans) stay distinct through the
    occurrence index, assigned in deterministic walk order by
    :meth:`register_plan`."""

    def __init__(self):
        self.by_key: Dict[tuple, Dict[str, float]] = {}
        self._key_of: Dict[int, tuple] = {}
        # keyed nodes are pinned so their id() can never be recycled
        # onto a different node mid-lifetime
        self._pin: List[PlanNode] = []
        # record() runs on whichever thread iterates the page
        # generator; distributed roll-up merges from puller threads
        import threading

        self._lock = threading.Lock()

    @staticmethod
    def _sig(node: PlanNode):
        from presto_tpu.exec.programs import structural_digest

        return (type(node).__name__, structural_digest(node))

    def register_plan(self, root: PlanNode) -> None:
        """Assign keys for a whole tree in preorder walk order, so two
        structurally identical plans map node-for-node onto the SAME
        keys: stats recorded while executing a re-built plan land on
        the entries the original plan's annotations read."""
        for n, key in plan_node_keys(root):
            if id(n) not in self._key_of:
                self._key_of[id(n)] = key
                self._pin.append(n)

    def _key(self, node: PlanNode) -> tuple:
        k = self._key_of.get(id(node))
        if k is None:
            # lazily seen node (e.g. an injected partial-agg stage not
            # present in the registered tree): occurrence 0 of its
            # signature — structural twins merge, which is the point
            k = (self._sig(node), 0)
            self._key_of[id(node)] = k
            self._pin.append(node)
        return k

    def record(self, node: PlanNode, wall: float, rows: int,
               nbytes: int = 0) -> None:
        with self._lock:
            s = self.by_key.setdefault(
                self._key(node),
                {"invocations": 0, "rows": 0, "wall_s": 0.0, "bytes": 0})
            s["invocations"] += 1
            s["rows"] += rows
            s["wall_s"] += wall
            s["bytes"] += nbytes

    def annotation(self, node: PlanNode) -> str:
        s = self.by_key.get(self._key(node))
        if s is None or not s["invocations"]:
            return ""
        return (
            f"  [rows={s['rows']}, pages={s['invocations']}, "
            f"wall={s['wall_s'] * 1e3:.1f}ms]"
        )

    def actual_rows(self, node: PlanNode) -> Optional[int]:
        """Observed output rows for a node, or None when it never
        recorded (est-vs-actual rendering, history feed)."""
        s = self.by_key.get(self._key(node))
        if s is None or not s["invocations"]:
            return None
        return int(s["rows"])

    # -- distributed roll-up wire format ------------------------------------
    # Keys are stable across processes (structural_digest), so a
    # worker's by_key snapshot serializes as JSON and merges onto the
    # coordinator's entries by key alone — the OperatorStats →
    # TaskStats → QueryStats roll-up of the reference, flattened.
    def to_wire(self) -> list:
        with self._lock:
            return [
                {"node": sig[0], "digest": sig[1], "occ": occ,
                 "invocations": int(s["invocations"]),
                 "rows": int(s["rows"]), "wall_s": float(s["wall_s"]),
                 "bytes": int(s.get("bytes", 0))}
                for (sig, occ), s in self.by_key.items()
            ]

    def merge_wire(self, entries) -> None:
        with self._lock:
            for e in entries or ():
                key = ((str(e["node"]), str(e["digest"])), int(e["occ"]))
                s = self.by_key.setdefault(
                    key, {"invocations": 0, "rows": 0, "wall_s": 0.0,
                          "bytes": 0})
                s["invocations"] += int(e.get("invocations", 0))
                s["rows"] += int(e.get("rows", 0))
                s["wall_s"] += float(e.get("wall_s", 0.0))
                s["bytes"] += int(e.get("bytes", 0))


def plan_node_keys(root: PlanNode):
    """``[(node, ((type name, digest), occurrence))]`` for a whole plan
    tree in deterministic preorder — THE shared key walk: QueryStats
    registration, bind-time estimate capture, and the history provider
    all key through this one function, so estimates and actuals share a
    key space by construction."""
    counts: Dict[tuple, int] = {}
    out = []
    stack = [root]
    while stack:
        n = stack.pop()
        sig = QueryStats._sig(n)
        occ = counts.get(sig, 0)
        counts[sig] = occ + 1
        out.append((n, (sig, occ)))
        stack.extend(reversed(n.sources))
    return out


# Ceiling for capacity-doubling retries, shared by the local, mesh
# (parallel/dist.py) and multi-host (parallel/multihost.py) runners.
MAX_AGG_GROUPS = 1 << 26

# Capacity beyond which aggregation stops doubling in place and
# switches to host-RAM partitioned (spill) execution instead —
# the MemoryRevokingScheduler threshold analog.
SPILL_GROUP_THRESHOLD = 1 << 22


class GroupCapacityExceeded(Exception):
    """An aggregation saw more groups than its static capacity; the
    runner retries the query with a doubled max_groups (the analog of
    MultiChannelGroupByHash.java:138 tryRehash), or switches to the
    partitioned spill path past SPILL_GROUP_THRESHOLD."""

    def __init__(self, needed: int, node=None):
        self.needed = needed
        self.node = node


def _split_pruned(constraints, stats) -> bool:
    """True if split min/max stats prove no row can satisfy ALL the
    pushed-down conjuncts (ORC stripe-stats pruning role), via the
    TupleDomain pushdown language (spi/predicate/TupleDomain.java
    analog; closed-interval form is conservative for strict bounds)."""
    from presto_tpu.predicate import TupleDomain

    td = TupleDomain.from_constraints(constraints)
    return td.is_none or not td.overlaps_split_stats(stats)


@jax.jit
def _extent_live(mask):
    """(highest live index + 1, live count) of a row mask, as one
    2-element device array so the host pays a single transfer."""
    idx = jnp.arange(mask.shape[0], dtype=jnp.int32)
    extent = jnp.max(jnp.where(mask, idx, -1)) + 1
    return jnp.stack([extent, jnp.sum(mask.astype(jnp.int32))])


class _AggFoldTower:
    """Binary-counter (LSM-style) fold of partial aggregation pages.

    The round-4 running fold concatenated every partial page onto a
    full-capacity accumulator and re-sorted ~2*max_groups keys per
    split; at SF10 that made Q3's aggregation tail ~57x slower for 10x
    data.  Two fixes compose here:

    - each incoming partial page is sliced to the power-of-two bucket
      just above its live extent (sort-path partials arrive
      front-compacted, and extent-based slicing is safe even for the
      packed-direct layout), so merge sizes track the data rather than
      the planner's conservative ``max_groups``; and
    - pages merge in a binary-counter tower — one slot per capacity,
      a carry merges equal-capacity pages — so every group takes part
      in O(log splits) merges instead of one full-capacity re-sort per
      split.  This is the sorted-run analog of the reference's
      incremental hash builder, which pays O(1) hash updates per row
      (operator/aggregation/builder/InMemoryHashAggregationBuilder.java,
      MultiChannelGroupByHash.java:138-145).

    Truncation: tower merges are UNCLAMPED — capacities follow the live
    data past ``max_groups``, so the merged result is exact no matter
    how conservative the planner's capacity guess was.  The one place
    truncation can still happen is INSIDE the jitted chain's per-split
    partial aggregation (grouped_aggregate at static ``max_groups``);
    an input page arriving full (live >= max_groups) records
    ``suspect_truncation`` and the caller re-plans with a capacity
    jumped to the observed live total (one retry, not a doubling
    ladder — MultiChannelGroupByHash.java:138 rehashes incrementally;
    this is the static-shape analog).
    """

    # floor of the slice/merge capacity ladder.  4096 starts typical
    # per-split partials (a few thousand live groups) at ONE level, so
    # the binary counter compiles log2(splits)-1 merge programs instead
    # of one more; merging <=4096 rows is noise on the VPU either way
    MIN_CAP = 1 << 12

    def __init__(self, runner, node, num_keys, aggs, kd, mg, account=True):
        self.runner = runner
        self.node = node
        self.mg = mg
        self.account = account
        self.levels: Dict[int, tuple] = {}  # capacity -> (page, live, tag)
        # a full input page means the chain's static-capacity partial
        # may have dropped groups; the total live count sizes the retry
        self.suspect_truncation = False
        self.live_total = 0
        cache_key = (node, "tower")
        fns = runner._fold_cache.get(cache_key)
        if fns is None:
            def fold(pages, out_cap):
                return merge_aggregate(
                    concat_pages_device(list(pages)), num_keys, list(aggs),
                    out_cap, key_domains=kd, mode="partial",
                    return_count=True)

            def final(pages, out_cap):
                return merge_aggregate(
                    concat_pages_device(list(pages)), num_keys, list(aggs),
                    out_cap, key_domains=kd, mode="single")

            sig = (num_keys, tuple(aggs), tuple(kd or ()))
            fold_p = runner._program(
                "agg_tower_fold", sig,
                lambda f=fold: jax.jit(f, static_argnames=("out_cap",))
                if runner.jit else f,
                node=node)
            final_p = runner._program(
                "agg_tower_final", sig,
                lambda f=final: jax.jit(f, static_argnames=("out_cap",))
                if runner.jit else f,
                node=node)
            runner._fold_cache[cache_key] = (fold_p, final_p)
            fns = (fold_p, final_p)
        self.fold, self.final = fns

    def _cap(self, n: int) -> int:
        """Pow2 capacity bound — never clamped to max_groups: tower
        merges follow the live data, so results are exact past the
        planner's capacity guess."""
        return max(self.MIN_CAP, 1 << max(0, int(n) - 1).bit_length())

    _slice_cap = _cap

    def _reserve(self, page):
        if not self.account or self.runner._mem is None:
            return None
        from presto_tpu.memory import page_bytes

        return self.runner._mem.reserve(
            f"agg_accumulator@{id(self.node)}", page_bytes(page))

    def add(self, page: Page) -> None:
        el = np.asarray(_extent_live(page.row_mask))
        extent, live = int(el[0]), int(el[1])
        self.live_total += live
        if live >= self.mg:
            self.suspect_truncation = True
        cap = self._slice_cap(extent)
        if page.capacity > cap:
            page = slice_page(page, cap)
        mem = self.runner._mem if self.account else None
        tag = self._reserve(page)
        cap = page.capacity
        while cap in self.levels:
            o_page, o_live, o_tag = self.levels.pop(cap)
            # shape-determined merge capacity: the binary counter only
            # merges equal-capacity pages, so 2*cap always fits
            # live + o_live — a live-count-derived out_cap flip-flopped
            # between cap and 2*cap, compiling two programs per level
            out_cap = 2 * cap
            page, cnt = self.fold([o_page, page], out_cap=out_cap)
            live = min(int(np.asarray(cnt)), out_cap)
            if mem is not None:
                mem.free(tag)
                mem.free(o_tag)
            tag = self._reserve(page)
            cap = page.capacity
        self.levels[cap] = (page, live, tag)

    def finish_single(self) -> Optional[Page]:
        """One mode='single' merge over the surviving level pages,
        largest first (deterministic program signature)."""
        if not self.levels:
            return None
        entries = sorted(self.levels.values(), key=lambda e: -e[0].capacity)
        pages = [e[0] for e in entries]
        out_cap = self._cap(sum(e[1] for e in entries))
        return self.final(pages, out_cap=out_cap)


def _probe_with_retry(probe_fn, build, page):
    """One expanding probe with the bucketed capacity retry shared by
    the in-HBM and spilled join paths (yielding LookupJoinPageBuilder
    analog). probe_fn(build, page, out_capacity) -> (page, total, ...).
    Retry capacities ride the same pow2/64K ladder as scan pages
    (bucket_capacity) so expansions that land near each other share one
    compiled probe program instead of one per observed match count."""
    cap = max(int(page.capacity), 1024)
    res = probe_fn(build, page, cap)
    total = int(np.asarray(res[1]))
    if total > cap:
        res = probe_fn(build, page, bucket_capacity(total))
    return res


def _is_streaming_join(node: JoinNode) -> bool:
    """True when the probe is row-aligned (jittable in a chain):
    semi/anti (presence tests) or unique-key builds. FULL joins always
    take the materializing path — the unmatched-build tail needs
    cross-page match state."""
    if node.kind == "full":
        return False
    return node.kind in ("semi", "anti", "mark") or node.unique_build


class LocalRunner:
    """Executes a plan tree against registered connectors.

    ``jit=False`` runs chains eagerly for debugging.
    """

    def __init__(self, catalog: Catalog, jit: bool = True, split_capacity: Optional[int] = None,
                 memory_pool=None, spill_partitions: int = 8, programs=None,
                 task_concurrency: Optional[int] = None,
                 task_prefetch: Optional[int] = None):
        from presto_tpu.exec.programs import (
            default_registry, maybe_enable_persistent_cache,
            structural_sharing_enabled,
        )
        from presto_tpu.exec.tasks import (
            task_concurrency_default, task_prefetch_default,
        )
        from presto_tpu.ops.join import resolve_direct_join

        self.catalog = catalog
        self.jit = jit
        self.split_capacity = split_capacity
        # morsel-driven split scheduler knobs (exec/tasks.py): splits
        # in flight per pipeline (1 = the exact legacy serial path) and
        # prefetch depth.  None resolves the process default, which is
        # env/config-derived — resolved ONCE here, not per chain.
        self.task_concurrency = max(1, int(
            task_concurrency if task_concurrency
            else task_concurrency_default()))
        self.task_prefetch = max(0, int(
            task_prefetch if task_prefetch is not None and task_prefetch >= 0
            else task_prefetch_default()))
        # structural program registry (ExpressionCompiler-cache analog):
        # compiled callables keyed by kernel family + canonical IR +
        # baked-in parameters, shared process-wide unless injected
        self.programs = programs if programs is not None else default_registry()
        self._structural = structural_sharing_enabled()
        self._own_registry = None  # per-node keying when sharing is off
        maybe_enable_persistent_cache()
        # env-dependent kernel choices resolve ONCE at construction —
        # not per join build (satellite of the registry PR)
        resolve_direct_join()
        # per-THREAD stats sink (property below): worker task threads
        # and concurrent coordinator queries share one runner, and a
        # shared sink would interleave two queries' actuals
        import threading as _threading

        self._stats_tls = _threading.local()
        # HBM accounting (memory/MemoryPool.java analog); None = untracked
        self.memory_pool = memory_pool
        # per-THREAD last-query peaks (properties below): concurrent
        # queries on one runner must not swap memory footprints — the
        # coordinator records last_peak_bytes into the admission
        # projection history, and a cross-query swap would make a light
        # statement inherit a heavy one's 8GB projection (and vice
        # versa, defeating the memory gate)
        import threading as _threading

        self._peaks_tls = _threading.local()
        # host-RAM spill fan-out when state exceeds the pool/threshold
        self.spill_partitions = spill_partitions
        # multi-producer ORDER BY: per-page sorts + order-preserving
        # merge (distributed_sort session property analog)
        self.merge_sort = True
        # per-THREAD query memory context: concurrent queries share one
        # runner (the coordinator runs each on its own thread), so the
        # active context must not be clobbered across threads
        import threading as _threading

        self._mem_tls = _threading.local()
        self._chain_cache: Dict[PlanNode, Callable] = {}
        self._fold_cache: Dict[PlanNode, Callable] = {}
        self._agg_overrides: Dict[PlanNode, int] = {}
        self._partial_nodes: Dict[PlanNode, AggregationNode] = {}
        # per-THREAD materialized join builds: device-resident state
        # that concurrent queries (and worker task threads) must not
        # share or clobber; dies with the thread
        self._builds_tls = _threading.local()
        # joins demoted out of fused chains because their build spilled
        self._force_expanding: set = set()
        # per-query split-scheduler stats (consumer-thread-local: the
        # scheduler's worker threads report through the shared stats
        # object, but the accumulator is owned by the query thread) and
        # the completed-query snapshot EXPLAIN ANALYZE prints
        self._task_stats_tls = _threading.local()
        self.last_task_stats: Dict[str, float] = {}
        # consume-once unordered-delivery grant: an order-insensitive
        # consumer (exact commutative aggregation fold) sets it just
        # before pulling a chain; the TOP-level chain takes completion-
        # order delivery, nested chains (join builds) stay ordered
        self._unordered_tls = _threading.local()

    # ------------------------------------------------------------------
    def run(self, plan: PlanNode, query_id: Optional[str] = None) -> MaterializedResult:
        from presto_tpu.obs import METRICS, record_point, span

        page = self.run_to_page(plan, query_id=query_id)
        # the result transfer is THE device sync of a local query — a
        # span + counters so host-transfer time/bytes are attributable
        # (the device_get tax EXPLAIN could not see before)
        with span("device_get", cat="device"):
            out = page.compact_host()
            rows = out.to_pylist()
        METRICS.counter("device.get_calls").inc()
        record_point("device.get_calls", 1.0)
        try:
            from presto_tpu.memory import page_bytes

            METRICS.counter("device.get_bytes").inc(page_bytes(out))
        except Exception:
            pass  # byte accounting is best-effort on exotic pages
        return MaterializedResult(
            names=plan.output_names,
            types=plan.output_types,
            rows=rows,
        )

    def _query_mem(self, query_id: Optional[str]):
        """Per-query memory-context ceremony shared by run_to_page and
        stream_pages: pool reservations tagged by the COORDINATOR's
        query id so the cluster memory manager can attribute + kill."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from presto_tpu.exec.tasks import SchedulerStats

            self._task_stats_tls.stats = SchedulerStats()
            # per-query: predicted-interval memo keys on id(node), which
            # is only stable while this query's plan is alive
            self._range_pred_memo = {}
            if self.memory_pool is not None:
                from presto_tpu.memory import QueryMemoryContext
                import uuid

                self._mem = QueryMemoryContext(
                    self.memory_pool, query_id or uuid.uuid4().hex[:8])
            try:
                yield
            finally:
                self.last_task_stats = self._task_stats.as_dict()
                if self._mem is not None:
                    self.last_peak_bytes = self._mem.peak
                    # per-site peak reservations (site strings embed the
                    # plan-node id) survive the context so EXPLAIN
                    # ANALYZE can attribute peak bytes per operator
                    self.last_site_peaks = dict(self._mem.site_peak)
                    self._mem.release_all()
                    self._mem = None

        return ctx()

    def run_to_page(self, plan: PlanNode, query_id: Optional[str] = None) -> Page:
        with self._query_mem(query_id):
            while True:
                try:
                    self._builds.clear()
                    return self._execute_to_page(plan)
                except GroupCapacityExceeded:
                    continue  # _agg_overrides updated; re-execute

    def stream_pages(self, plan: PlanNode, query_id: Optional[str] = None) -> Iterator[Page]:
        """Stream output pages with run_to_page's memory-context
        ceremony but no internal retry: GroupCapacityExceeded
        propagates so a caller that consumed partial output can restart
        from scratch (the scaled-writer ingest path)."""
        with self._query_mem(query_id):
            self._builds.clear()
            yield from self._pages(plan)

    @property
    def stats(self) -> Optional[QueryStats]:
        """Per-THREAD QueryStats sink: pages record on the thread that
        iterates the generator, and worker task quanta rebind this per
        step — a plain attribute would let concurrent queries (or two
        worker tasks) interleave actuals."""
        return getattr(self._stats_tls, "stats", None)

    @stats.setter
    def stats(self, value: Optional["QueryStats"]) -> None:
        self._stats_tls.stats = value

    @property
    def _builds(self) -> Dict[JoinNode, JoinBuild]:
        got = getattr(self._builds_tls, "builds", None)
        if got is None:
            got = {}
            self._builds_tls.builds = got
        return got

    @property
    def last_peak_bytes(self) -> int:
        """Peak reserved bytes of the last query completed ON THIS
        THREAD (EXPLAIN headers and the coordinator's admission
        projection both read the footprint of the query they just
        ran, never a concurrent one's)."""
        return getattr(self._peaks_tls, "peak", 0)

    @last_peak_bytes.setter
    def last_peak_bytes(self, value: int) -> None:
        self._peaks_tls.peak = value

    @property
    def last_site_peaks(self) -> Dict[str, int]:
        """Per-site peaks of the last query completed on this thread
        (EXPLAIN ANALYZE's per-operator memory source)."""
        got = getattr(self._peaks_tls, "sites", None)
        return got if got is not None else {}

    @last_site_peaks.setter
    def last_site_peaks(self, value: Dict[str, int]) -> None:
        self._peaks_tls.sites = value

    @property
    def _mem(self):
        return getattr(self._mem_tls, "ctx", None)

    @_mem.setter
    def _mem(self, value):
        self._mem_tls.ctx = value

    @property
    def _task_stats(self):
        from presto_tpu.exec.tasks import SchedulerStats

        got = getattr(self._task_stats_tls, "stats", None)
        if got is None:
            got = SchedulerStats()
            self._task_stats_tls.stats = got
        return got

    def _take_unordered(self) -> bool:
        """Pop the consume-once unordered-delivery grant (see
        ``_unordered_tls``)."""
        got = getattr(self._unordered_tls, "ok", False)
        if got:
            self._unordered_tls.ok = False
        return bool(got)

    def _account(self, what: str, page, node=None) -> None:
        """Charge a materialized device intermediate against the pool
        (operator-level LocalMemoryContext.setBytes analog). ``node``
        tags the reservation so spill fallbacks can attribute failures
        to their own plan node."""
        if self._mem is not None:
            from presto_tpu.memory import page_bytes

            if node is not None:
                what = f"{what}@{id(node)}"
            self._mem.reserve(what, page_bytes(page))

    def explain(self, plan: PlanNode) -> str:
        from presto_tpu.planner.plan import plan_tree_str

        return plan_tree_str(plan)

    def explain_with_stats(self, plan: PlanNode, stats: "QueryStats",
                           misestimate_factor: float = 8.0) -> str:
        from presto_tpu.obs.history import worst_estimate
        from presto_tpu.planner.plan import plan_tree_str

        text = plan_tree_str(plan, stats=stats, mem=self._mem_by_node(),
                             misestimate_factor=misestimate_factor)
        worst = worst_estimate(stats, getattr(plan, "_estimates", None))
        if worst is not None and worst["ratio"] >= misestimate_factor:
            text = (f"worst estimate: {worst['node']} "
                    f"est {worst['est']:.0f} rows / actual "
                    f"{worst['actual']} rows (x{worst['ratio']:.1f})\n"
                    + text)
        peak = getattr(self, "last_peak_bytes", 0)
        if peak:
            text = f"peak reserved memory: {peak / 1e6:.1f}MB\n" + text
        sched = self._scheduler_line()
        if sched:
            text = sched + "\n" + text
        return text

    def _scheduler_line(self) -> str:
        """One-line split-scheduler summary for EXPLAIN ANALYZE (empty
        when the last query ran no splits through a scan pipeline)."""
        ts = getattr(self, "last_task_stats", None) or {}
        if not ts.get("splits"):
            return ""
        total = ts["prefetch_hits"] + ts["prefetch_misses"]
        return (f"task scheduler: {ts['splits']} splits, "
                f"concurrency {ts['concurrency']}, "
                f"stall {ts['stall_s']:.3f}s, "
                f"prefetch hits {ts['prefetch_hits']}/{total}")

    def _mem_by_node(self) -> Dict[int, int]:
        """id(plan node) -> peak reserved bytes, recovered from the last
        query's tagged reservation sites (``what@<id(node)>`` — the tag
        convention of :meth:`_account` and the agg tower).  Sites for
        different allocation kinds on the same node sum; sites without a
        node id (scan pages, sort input) stay in the query-level peak
        header only."""
        import re as _re

        out: Dict[int, int] = {}
        for site, nbytes in getattr(self, "last_site_peaks", {}).items():
            m = _re.search(r"@(\d+)$", site)
            if m:
                nid = int(m.group(1))
                out[nid] = out.get(nid, 0) + nbytes
        return out

    # ------------------------------------------------------------------
    # EXPLAIN ANALYZE VERBOSE: exclusive per-operator attribution
    # ------------------------------------------------------------------
    def explain_analyze_verbose(self, plan: PlanNode) -> str:
        """Fused chains make normal EXPLAIN ANALYZE times inclusive of
        everything upstream.  VERBOSE mode re-executes every chain
        prefix-by-prefix — scan alone, scan+filter, scan+filter+probe,
        … — and reports the DELTAS as exclusive per-operator device
        time (the reference's per-operator OperatorStats, recovered by
        deliberately breaking fusion; the numbers cost extra runs and
        differ slightly from the fused program's true schedule)."""
        from presto_tpu.planner.plan import plan_tree_str

        stats = QueryStats()
        stats.register_plan(plan)
        self.stats = stats
        try:
            self.run(plan)
        finally:
            self.stats = None
        exclusive = self._exclusive_times(plan)
        text = plan_tree_str(plan, stats=stats, exclusive=exclusive,
                             mem=self._mem_by_node())
        peak = getattr(self, "last_peak_bytes", 0)
        if peak:
            text = f"peak reserved memory: {peak / 1e6:.1f}MB\n" + text
        progs = self.compiled_program_count()
        if progs is not None:
            text = f"compiled XLA programs: {progs}\n" + text
        reg = (self._own_registry or self.programs).stats()
        line = (f"program registry: {reg['callables']} callables, "
                f"{reg['programs']} compiled programs, "
                f"{reg['hits']} hits / {reg['misses']} misses, "
                f"compile {reg['compile_s']:.1f}s")
        if reg.get("dir"):
            line += (f", persistent cache hits {reg['persistent_hits']}"
                     f" ({reg['dir']})")
        text = line + "\n" + text
        sched = self._scheduler_line()
        if sched:
            text = sched + "\n" + text
        report = getattr(plan, "_optimizer_report", None)
        if report is not None:
            # "optimizer: N iterations, rule hits: ..." — which rules
            # shaped this plan (binder attaches the OptimizerStats)
            text = report.summary() + "\n" + text
        return text

    def compiled_program_count(self) -> Optional[int]:
        """Distinct compiled XLA programs behind this runner's cached
        jitted callables (each shape signature of each callable is one
        program — the TPU cold-start cost driver; VERDICT r4 #9)."""
        total = 0
        seen = set()
        entries = list(self._chain_cache.values())
        for v in self._fold_cache.values():
            if isinstance(v, (tuple, list)):
                entries.extend(x for x in v if x is not None)
            else:
                entries.append(v)
        for fn in entries:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            try:
                total += fn._cache_size()
            except Exception:
                total += 1  # non-jitted (debug mode) counts as one
        return total

    def _program(self, kind: str, sig, factory, node=None):
        """Compiled callable for (kind, structural signature) from the
        shared registry — identical operator shapes in other plans,
        queries, and runners resolve to the same callable.  With
        structural sharing disabled (PRESTO_TPU_PROGRAM_REGISTRY=0,
        the A/B baseline) the key degrades to per-PlanNode identity in
        a runner-private registry, i.e. the pre-registry behavior."""
        if self._structural or node is None:
            return self.programs.get(kind, sig, factory, jit=self.jit)
        from presto_tpu.exec.programs import ProgramRegistry

        # A/B baseline: NO dedup — every request compiles fresh and the
        # per-runner memo dicts are the only cache (seed behavior), so
        # capacity-retry invalidation (memo deletion) fully retires a
        # stale program; a keyed per-node registry would hand the retry
        # the old max-groups capacity back.  The private registry holds
        # the programs solely for metrics (unique monotonic keys).
        if self._own_registry is None:
            self._own_registry = ProgramRegistry()
        self._ab_seq = getattr(self, "_ab_seq", 0) + 1
        return self._own_registry.get(kind, ("ab", self._ab_seq), factory,
                                      jit=self.jit)

    def _stage_signature(self, node: PlanNode):
        """Structural signature of the fused streaming chain rooted at
        ``node``.  Mirrors ``_build_stage`` member-for-member: every
        parameter a stage closure bakes in (expression IR, resolved
        capacities, key domains, join kind/flags, build arity) is part
        of the signature, so equal signatures guarantee the cached
        callable computes the same function.  Input-page schemas are
        NOT included — they ride as jit-static pytree aux data
        (types + dictionaries) and key jit's own trace cache."""
        if isinstance(node, FilterNode):
            return ("filter", node.predicate,
                    self._stage_signature(node.source))
        if isinstance(node, ProjectNode):
            return ("project", tuple(node.projections),
                    self._stage_signature(node.source))
        if isinstance(node, AggregationNode) and node.step == "partial":
            return ("agg_partial", tuple(node.group_exprs),
                    tuple(node.aggs), self._max_groups(node),
                    tuple(node.key_domains),
                    bool(getattr(node, "presorted", False)),
                    self._stage_signature(node.source))
        if isinstance(node, JoinNode) and self._streaming(node):
            return ("probe", tuple(node.left_keys),
                    tuple(node.key_domains or ()), node.kind,
                    node.null_safe_keys, getattr(node, "null_aware", False),
                    len(node.right.channels),
                    self._stage_signature(node.left))
        if isinstance(node, CrossSingleNode):
            return ("cross1", self._stage_signature(node.left))
        return ("leaf",)

    def _is_chain_member(self, n: PlanNode) -> bool:
        return (
            isinstance(n, (FilterNode, ProjectNode, CrossSingleNode))
            or (isinstance(n, AggregationNode) and n.step == "partial")
            or (isinstance(n, JoinNode) and not n.use_index and self._streaming(n))
        )

    def _exclusive_times(self, plan: PlanNode) -> Dict[PlanNode, float]:
        out: Dict[PlanNode, float] = {}

        def walk(n: PlanNode, parent_in_chain: bool) -> None:
            member = self._is_chain_member(n)
            if member and not parent_in_chain:
                try:
                    self._time_chain(n, out)
                except Exception as e:
                    # attribution is best-effort diagnostics, but a
                    # failure must not be invisible (VERDICT r3): the
                    # operator reading VERBOSE output needs to know the
                    # numbers are missing rather than zero
                    import logging

                    logging.getLogger("presto_tpu.explain").warning(
                        "EXPLAIN ANALYZE VERBOSE attribution failed for "
                        "%s chain: %s: %s", type(n).__name__,
                        type(e).__name__, e)
                    out.setdefault(n, float("nan"))
            if isinstance(n, (JoinNode, CrossSingleNode)):
                walk(n.sources[0], member)  # probe side continues chain
                walk(n.sources[1], False)  # build side is its own tree
            else:
                for s in n.sources:
                    walk(s, member)

        walk(plan, False)
        return out

    def _time_chain(self, root: PlanNode, out: Dict[PlanNode, float]) -> None:
        """Time prefix programs of the chain rooted at ``root`` and
        record per-member deltas (and the leaf's own source time)."""
        import time

        seq: List[PlanNode] = []
        n = root
        while self._is_chain_member(n):
            seq.append(n)
            n = n.sources[0] if isinstance(n, (JoinNode, CrossSingleNode)) else n.source
        leaf = n

        t0 = time.perf_counter()
        pages = list(self._source_pages(leaf))
        jax.block_until_ready(pages)
        if isinstance(leaf, (TableScanNode, ValuesNode, PrecomputedNode)):
            # breaker leaves (agg/sort/expanding join) keep inclusive
            # wall from QueryStats; an "excl" there would double-count
            out[leaf] = time.perf_counter() - t0
        if not pages:
            return

        prev = 0.0
        for prefix_root in reversed(seq):
            joins: List[JoinNode] = []
            stage = self._build_stage(prefix_root, joins)
            consts = {f"build_{i}": self._materialize_build(j)
                      for i, j in enumerate(joins)}
            fn = jax.jit(stage) if self.jit else stage
            jax.block_until_ready([fn(p, consts) for p in pages])  # compile
            t0 = time.perf_counter()
            jax.block_until_ready([fn(p, consts) for p in pages])
            t = time.perf_counter() - t0
            out[prefix_root] = max(t - prev, 0.0)
            prev = t

    # ------------------------------------------------------------------
    def _execute_to_page(self, node: PlanNode) -> Page:
        pages = list(self._pages(node))
        if not pages:
            return Page.empty(node.output_types, 1)
        return concat_pages_device(pages)

    def _pages(self, node: PlanNode) -> Iterator[Page]:
        """Stream output pages of ``node`` (pull model, Driver analog),
        recording per-stage stats when enabled (OperatorContext /
        OperatorStats analog, operator/OperatorStats.java:38 — times
        here are inclusive of the stage's inputs since chains fuse) and
        per-pull operator spans when the query traces.  Tracer-only
        runs skip the row-count device sync — tracing must not change
        the execution profile it measures."""
        from presto_tpu.analysis import range_sanitizer_enabled
        from presto_tpu.obs.trace import current_tracer

        tracer = current_tracer()
        sanitize = range_sanitizer_enabled()
        if self.stats is None and tracer is None and not sanitize:
            yield from self._pages_impl(node)
            return
        import time

        gen = self._pages_impl(node)
        name = type(node).__name__
        label = "op:" + (name[:-4] if name.endswith("Node") else name)
        cat = "exchange" if isinstance(node, RemoteSourceNode) else "operator"
        while True:
            t0 = time.perf_counter()
            try:
                if tracer is not None:
                    with tracer.span(label, cat):
                        p = next(gen)
                else:
                    p = next(gen)
            except StopIteration:
                return
            if self.stats is not None:
                wall = time.perf_counter() - t0
                rows = int(np.asarray(p.num_rows()))
                try:
                    from presto_tpu.memory import page_bytes

                    nb = page_bytes(p)
                except Exception:
                    nb = 0  # byte accounting is best-effort
                self.stats.record(node, wall, rows, nb)
            if sanitize:
                self._sanitize_page(node, p)
            yield p

    def _sanitize_page(self, node: PlanNode, page: Page) -> None:
        """PRESTO_TPU_RANGE_SANITIZER cross-check: every page crossing
        a stage boundary is tested against the abstract interpreter's
        predicted per-channel intervals (analysis/kernel_soundness.
        predicted_intervals).  An observed value outside its predicted
        interval means a transfer function under-approximates — that is
        a checker bug, and it fails LOUDLY here rather than silently
        missing real overflows forever."""
        from presto_tpu.analysis.kernel_soundness import predicted_intervals
        from presto_tpu.obs import METRICS

        memo = getattr(self, "_range_pred_memo", None)
        if memo is None:
            memo = self._range_pred_memo = {}
        if id(node) not in memo:
            # the root call fills the whole subtree in one analysis;
            # nodes the analyzer has no prediction for map to None
            memo.update(predicted_intervals(node))
            memo.setdefault(id(node), None)
        preds = memo[id(node)]
        if not preds:
            return
        for i, pred in enumerate(preds):
            if pred is None or i >= len(page.blocks):
                continue
            b = page.blocks[i]
            if getattr(b.data, "ndim", 0) != 1:
                continue
            live = np.asarray(page.row_mask & b.valid)
            if not live.any():
                continue
            vals = np.asarray(b.data)[live]
            lo, hi = pred
            mn, mx = int(vals.min()), int(vals.max())
            if mn < lo or mx > hi:
                METRICS.counter("kernel.sanitizer_escapes").inc()
                name = (node.output_names[i]
                        if i < len(node.output_names) else f"${i}")
                raise RuntimeError(
                    f"range sanitizer: {type(node).__name__} channel "
                    f"{i} ({name!r}) observed [{mn}, {mx}] outside the "
                    f"predicted interval [{lo}, {hi}] — an abstract "
                    "transfer under-approximates (analysis/ranges.py)")

    def _pages_impl(self, node: PlanNode) -> Iterator[Page]:
        if isinstance(node, OutputNode):
            yield from self._pages(node.source)
            return

        if isinstance(node, LimitNode):
            remaining = node.count
            for p in self._pages(node.source):
                if remaining <= 0:
                    return
                p = limit_page(p, remaining)
                remaining -= int(np.asarray(p.num_rows()))
                yield p
            return

        if isinstance(node, SortNode):
            sort_exprs = list(node.sort_exprs)
            ascending = list(node.ascending)
            nulls_first = node.nulls_first
            fn = self._fold_cache.get(node)
            if fn is None:

                def do_sort(p):
                    return sort_page(p, sort_exprs, ascending, nulls_first)

                fn = self._program(
                    "sort", (sort_exprs, ascending, nulls_first),
                    lambda: jax.jit(do_sort) if self.jit else do_sort,
                    node=node)
                self._fold_cache[node] = fn
            pages = list(self._pages(node.source))
            if len(pages) > 1 and self.merge_sort:
                # distributed-sort shape: sort each producer page, then
                # an order-preserving k-way merge (MergeOperator.java:45
                # + MergeHashSort) — no monolithic re-sort of the union
                from presto_tpu.ops.merge import merge_sorted_pages

                sorted_pages = [fn(p) for p in pages]
                for p in sorted_pages:
                    self._account("sort_input", p)
                yield merge_sorted_pages(sorted_pages, sort_exprs,
                                         ascending, nulls_first)
                return
            src = concat_pages_device(pages) if pages else Page.empty(
                node.output_types, 1)
            self._account("sort_input", src)
            yield fn(src)
            return

        if isinstance(node, TopNNode):
            yield self._run_topn(node)
            return

        if isinstance(node, AggregationNode) and node.step in ("single", "final"):
            yield self._run_aggregation(node)
            return

        if isinstance(node, ValuesNode):
            cols, valids = [], []
            for i, t in enumerate(node.types):
                raw = [r[i] for r in node.rows]
                valids.append(np.asarray([v is not None for v in raw], np.bool_))
                if t.is_array or t.is_map or t.is_long_decimal:
                    # Page encodes container lists / limb decimals
                    # (unscaled ints may exceed int64 at p > 18)
                    cols.append(raw)
                else:
                    cols.append(np.asarray([0 if v is None else v for v in raw],
                                           dtype=t.np_dtype))
            yield Page.from_arrays(cols, node.types, valids=valids,
                                   dictionaries=node.dictionaries)
            return

        if isinstance(node, PrecomputedNode):
            yield node.page
            return

        if isinstance(node, RemoteSourceNode):
            # worker-to-worker shuffle read: pull this stage's partition
            # from every upstream task's output buffer
            from presto_tpu.server.serde import deserialize_page
            from presto_tpu.server.shuffle_client import pull_pages

            dicts = [c.dictionary for c in node.channels]
            for uri, tid in node.tasks:
                for raw in pull_pages(uri, tid, node.buffer_id):
                    yield deserialize_page(raw, dicts)
            return

        if isinstance(node, UnionNode):
            from presto_tpu.parallel.fragment import remap_union_leg_page

            chans = node.channels
            for k, src in enumerate(node.inputs):
                offs = node.code_offsets[k]
                for p in self._pages(src):
                    yield remap_union_leg_page(p, offs, chans)
            return

        if isinstance(node, WindowNode):
            src = self._execute_to_page(node.source)
            fn = self._fold_cache.get(node)
            if fn is None:
                from presto_tpu.ops.window import window_page

                partition_exprs = list(node.partition_exprs)
                order_exprs = list(node.order_exprs)
                ascending = list(node.ascending)
                funcs = list(node.funcs)
                pd = node.partition_domains

                def do_window(p):
                    return window_page(
                        p, partition_exprs, order_exprs, ascending, funcs,
                        partition_domains=pd,
                    )

                fn = self._program(
                    "window",
                    (partition_exprs, order_exprs, ascending, funcs, pd),
                    lambda: jax.jit(do_window) if self.jit else do_window,
                    node=node)
                self._fold_cache[node] = fn
            yield fn(src)
            return

        if isinstance(node, GroupIdNode):
            yield from self._groupid_pages(node)
            return

        if isinstance(node, UnnestNode):
            fn = self._fold_cache.get(node)
            if fn is None:
                from presto_tpu.ops.container import unnest_expand

                exprs = list(node.unnest_exprs)
                ordinality = node.ordinality
                chans = node.channels

                def do_unnest(p: Page) -> Page:
                    return unnest_expand(p, exprs, ordinality, chans)

                fn = self._program(
                    "unnest",
                    (exprs, ordinality,
                     [(c.type, c.dictionary) for c in chans]),
                    lambda: jax.jit(do_unnest) if self.jit else do_unnest,
                    node=node)
                self._fold_cache[node] = fn
            for p in self._pages(node.source):
                yield fn(p)
            return

        if isinstance(node, JoinNode) and node.use_index:
            yield from self._index_join_pages(node)
            return

        if isinstance(node, JoinNode) and not self._streaming(node):
            yield from self._expanding_join_pages(node)
            return

        # streaming chain rooted at a scan or breaker
        yield from self._chain_pages(node)

    def _streaming(self, node: JoinNode) -> bool:
        # index joins must not fuse into chains: the chain builder would
        # materialize the full build scan instead of point lookups
        return (_is_streaming_join(node) and node not in self._force_expanding
                and not node.use_index)

    # ------------------------------------------------------------------
    # streaming-chain compilation
    # ------------------------------------------------------------------
    def _chain_pages(self, node: PlanNode) -> Iterator[Page]:
        from presto_tpu.memory import ExceededMemoryLimitError

        # pop the unordered grant FIRST: it applies to this chain only,
        # never to nested chains pulled while materializing builds
        unordered = self._take_unordered()
        leaf = self._chain_leaf(node)
        joins: List[JoinNode] = []
        stage = self._build_stage(node, joins)
        try:
            consts = {f"build_{i}": self._materialize_build(j) for i, j in enumerate(joins)}
        except ExceededMemoryLimitError as e:
            victim = next((j for j in joins if f"join_build@{id(j)}#" in e.tag), None)
            if victim is None:
                raise
            # demote the oversized build's join out of the fused chain;
            # it re-plans through the partitioned (spilled) join path
            self._force_expanding.add(victim)
            self._chain_cache.clear()
            self._fold_cache.clear()
            yield from self._pages_impl(node)
            return
        if node in self._chain_cache:
            fn = self._chain_cache[node]
        else:
            fn = self._program(
                "chain", self._stage_signature(node),
                lambda: jax.jit(stage) if self.jit else stage, node=node)
            self._chain_cache[node] = fn
        mem = self._mem
        # the scheduler takes SCAN pipelines (independent connector
        # splits — the morsel shape); breaker-leaf chains keep the
        # serial pull, since their "source" is a materialized upstream
        # whose own execution must stay on this thread (thread-local
        # memory context and build registries)
        if self.task_concurrency <= 1 or not isinstance(leaf, TableScanNode):
            # serial leg (task_concurrency=1): the exact legacy pull
            # loop — no threads, no reordering, the A/B baseline.
            # Split accounting covers SCAN pipelines only — breaker-leaf
            # chains pull materialized pages, not connector splits, and
            # counting them would make the splits surface meaningless
            count_splits = isinstance(leaf, TableScanNode)
            for page in self._source_pages(leaf):
                tag = None
                if mem is not None:
                    from presto_tpu.memory import page_bytes

                    # transient: the in-flight scan page is accountable
                    # while the chain program consumes it, but soft — a
                    # streaming input can't be spilled; it is bounded by
                    # split capacity, not by the pool
                    tag = mem.reserve("scan_page", page_bytes(page),
                                      enforce=False)
                if count_splits:
                    self._task_stats.splits += 1
                try:
                    yield fn(page, consts)
                finally:
                    # early generator exit (LIMIT) must not leak the tag
                    if tag is not None:
                        mem.free(tag)
            return
        yield from self._chain_pages_scheduled(leaf, fn, consts, mem,
                                               unordered)

    def _chain_pages_scheduled(self, leaf: PlanNode, fn, consts, mem,
                               unordered: bool) -> Iterator[Page]:
        """Morsel-driven chain execution: up to ``task_concurrency``
        splits in flight on the scheduler's worker pool, host page prep
        prefetched ahead, results delivered in source order (or
        completion order when the consumer granted it).  Backpressure:
        dispatch defers while the memory pool has no headroom, so
        concurrency throttles instead of OOMing."""
        from presto_tpu.exec.tasks import SplitScheduler

        def produced():
            for page in self._source_pages(leaf):
                tag = None
                if mem is not None:
                    from presto_tpu.memory import page_bytes

                    # soft reservation, exactly like the serial leg —
                    # tagged per split so in-flight pages are visible
                    # in the pool books while they await execution
                    tag = mem.reserve("scan_page", page_bytes(page),
                                      enforce=False)
                yield page, tag

        def run_split(item):
            page, tag = item
            try:
                return fn(page, consts)
            finally:
                if tag is not None:
                    mem.free(tag)

        def drop_split(item):
            # produced-but-never-executed split on early close (LIMIT):
            # its reservation must not linger until query end, where it
            # would skew headroom backpressure and spill decisions
            _, tag = item
            if tag is not None:
                mem.free(tag)

        headroom = None
        if mem is not None:
            headroom = lambda: mem.headroom() > 0  # noqa: E731
        sched = SplitScheduler(
            concurrency=self.task_concurrency, prefetch=self.task_prefetch,
            ordered=not unordered, headroom=headroom, name="chain",
            stats=self._task_stats,
            drop=drop_split if mem is not None else None)
        yield from sched.map(produced(), run_split)

    def _chain_leaf(self, node: PlanNode) -> PlanNode:
        if isinstance(node, (FilterNode, ProjectNode)):
            return self._chain_leaf(node.source)
        if isinstance(node, AggregationNode) and node.step == "partial":
            return self._chain_leaf(node.source)
        if isinstance(node, JoinNode) and self._streaming(node):
            return self._chain_leaf(node.left)  # probe side streams
        if isinstance(node, CrossSingleNode):
            return self._chain_leaf(node.left)
        return node

    def _build_stage(self, node: PlanNode, joins: List[JoinNode]):
        """Recursively build fn(page, consts)->page for the streaming
        prefix of ``node``; below the chain leaf, the identity.

        KEEP IN SYNC with ``_stage_signature``: every parameter a stage
        closure bakes in here must appear in the signature, or two
        different chains will share one compiled program (silent wrong
        results, not a crash).  test_cold_compile pins the current
        parameters' signature-sensitivity."""
        if isinstance(node, FilterNode):
            inner = self._build_stage(node.source, joins)
            pred = node.predicate
            return lambda p, c: filter_page(inner(p, c), pred)

        if isinstance(node, ProjectNode):
            inner = self._build_stage(node.source, joins)
            projections = list(node.projections)
            return lambda p, c: project_page(inner(p, c), projections)

        if isinstance(node, AggregationNode) and node.step == "partial":
            inner = self._build_stage(node.source, joins)
            group_exprs = list(node.group_exprs)
            aggs = list(node.aggs)
            mg = self._max_groups(node)
            kd = node.key_domains
            presorted = node.presorted

            def agg_stage(p, c):
                return grouped_aggregate(
                    inner(p, c), group_exprs, aggs, mg, key_domains=kd,
                    mode="partial", presorted=presorted,
                )

            return agg_stage

        if isinstance(node, JoinNode) and self._streaming(node):
            inner = self._build_stage(node.left, joins)
            key = f"build_{len(joins)}"
            joins.append(node)
            build_output = list(range(len(node.right.channels)))
            kd = node.key_domains
            left_keys = list(node.left_keys)
            kind = node.kind
            ns = node.null_safe_keys
            na = getattr(node, "null_aware", False)

            def probe_stage(p, c):
                return probe_join(
                    c[key], inner(p, c), left_keys, key_domains=kd,
                    kind=kind, build_output=build_output, null_safe=ns,
                    null_aware=na,
                )

            return probe_stage

        if isinstance(node, CrossSingleNode):
            inner = self._build_stage(node.left, joins)
            key = f"build_{len(joins)}"
            joins.append(node)

            def cross_stage(p, c):
                return cross_append_single(inner(p, c), c[key])

            return cross_stage

        # chain leaf (scan / breaker / expanding join): identity
        return lambda p, c: p

    def _source_pages(self, node: PlanNode) -> Iterator[Page]:
        if isinstance(node, TableScanNode):
            conn = self.catalog.connector(node.handle.connector_name)
            idx = list(node.columns)
            # split enumeration happens at EXECUTION time, not plan time
            # (DistributedExecutionPlanner opens SplitSources during
            # planDistribution, so cached plans see connector-side
            # changes — e.g. shardstore compaction/rebalance)
            if node.splits is not None:
                splits = node.splits
            else:
                splits = range(conn.num_splits(node.handle.table)
                               if hasattr(conn, "num_splits")
                               else node.handle.num_splits)
            td = None
            if node.constraints and hasattr(conn, "split_stats"):
                from presto_tpu.predicate import TupleDomain

                td = TupleDomain.from_constraints(node.constraints)
                if td.is_none:
                    return  # provably empty scan
            sample = node.sample
            produced = 0
            # live progress: one stage per scan invocation (self-join
            # twins and capacity retries each get their own entry; the
            # reported percentage is a running max, so re-runs never
            # regress it).  Rows are padded row SLOTS — counting live
            # rows would force a device sync per split.
            from presto_tpu.obs import current_progress, current_timeline

            prog = current_progress()
            tl = current_timeline()
            stage_name = None
            if prog is not None:
                stage_name = prog.new_stage_name(
                    f"scan:{node.handle.table}")
                try:
                    total = len(splits)
                except TypeError:
                    total = None
                prog.stage(stage_name, splits_total=total)

            def _split_mark(page=None):
                if tl is not None and stage_name is not None:
                    # one point per finished split, named by stage — the
                    # timeline's scan-progress track (value is always 1;
                    # consumers count points, not sum values)
                    tl.record(f"splits_done.{stage_name}", 1.0)
                if prog is None:
                    return
                if page is None:
                    prog.split_done(stage_name)
                    return
                from presto_tpu.memory import page_bytes

                prog.split_done(stage_name, rows=page.capacity,
                                nbytes=page_bytes(page))
            # scan-uniform capacity: a split that FITS a previously
            # established bucket of this scan (and is at least a third
            # of it) joins that bucket instead of opening its own, so the
            # whole scan runs ONE chain program — this catches both the
            # ragged tail and the boundary-straddle siblings without
            # consulting bucket_capacity's slack again (an exact-size
            # generator's just-short tail must NOT overshoot past the
            # full splits' bucket).  Much smaller splits keep their own
            # bucket: padding a sliver to full capacity would multiply
            # its compute, not add +6%.  PRESTO_TPU_PAD_SCAN=0 disables
            # all scan padding, uniform included.
            uniform = pad_scan_enabled()
            cap_hi = 0
            for split in splits:
                if node.limit is not None and produced >= node.limit:
                    break  # pushed-down LIMIT satisfied: skip the rest
                if sample is not None and sample[0] == "system":
                    # SYSTEM(p): keep whole splits by a deterministic
                    # split hash (SampleNode SYSTEM semantics); mixed so
                    # split 0 is not a fixed point
                    h = (((split + 1) * 2654435761) ^ 0x9E3779B9) % 10_000
                    if h >= sample[1] * 100:
                        _split_mark()
                        continue
                if td is not None:
                    stats = conn.split_stats(node.handle.table, split)
                    if not td.overlaps_split_stats(stats):
                        _split_mark()  # pruned splits still count as done
                        continue
                page = conn.page_for_split(
                    node.handle.table, split, capacity=self.split_capacity
                )
                if sample is not None and sample[0] == "bernoulli":
                    # BERNOULLI(p): deterministic per-(split, row) hash
                    # mask — every row kept with probability p%
                    r = jnp.arange(page.capacity, dtype=jnp.uint32)
                    h = (r + jnp.uint32(split) * jnp.uint32(0x9E3779B1))
                    h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
                    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
                    keep = (h % jnp.uint32(10_000)) < jnp.uint32(
                        int(sample[1] * 100))
                    page = Page(page.blocks, page.row_mask & keep)
                if node.limit is not None:
                    import numpy as _np

                    produced += int(_np.asarray(page.row_mask).sum())
                raw = Page(tuple(page.blocks[i] for i in idx), page.row_mask)
                if uniform and 0 < raw.capacity <= cap_hi \
                        and raw.capacity * 3 >= cap_hi:
                    out = pad_page_to(raw, cap_hi)
                else:
                    out = pad_page_pow2(raw)
                    if out.capacity > cap_hi:
                        cap_hi = out.capacity
                _split_mark(out)
                yield out
            if prog is not None:
                prog.finish_stage(stage_name)
        else:
            yield from self._pages(node)

    def _materialize_build(self, node):
        if node not in self._builds:
            if isinstance(node, CrossSingleNode):
                build_page = self._execute_to_page(node.right)
                self._builds[node] = slice_page(build_page.compact_host(), 1)
            else:
                pages = tuple(self._pages(node.right))
                if not pages:
                    pages = (Page.empty(node.right.output_types, 1),)
                def build_fn(uniq: bool):
                    fn = self._fold_cache.get((node, uniq))
                    if fn is None:
                        right_keys = list(node.right_keys)
                        kd = node.key_domains
                        ns = getattr(node, "null_safe_keys", False)

                        def make_build(ps, _u=uniq):
                            # bucket the build capacity (concat sums the
                            # producers' caps — a data-dependent shape
                            # every downstream probe program would bake
                            # in; padding dead rows restores the ladder)
                            return build_join(
                                pad_page_pow2(concat_pages_device(list(ps))),
                                right_keys,
                                key_domains=kd, null_safe=ns, unique=_u,
                            )

                        fn = self._program(
                            "join_build",
                            (right_keys, tuple(kd or ()), ns, uniq),
                            lambda: jax.jit(make_build) if self.jit
                            else make_build,
                            node=node)
                        self._fold_cache[(node, uniq)] = fn
                    return fn

                uniq = bool(getattr(node, "unique_build", False))
                build = build_fn(uniq)(pages)
                if build.unique_ok is not None and not bool(build.unique_ok):
                    # the planner's uniqueness promise failed at runtime
                    # (PagesHash would have chained): rebuild sorted
                    build = build_fn(False)(pages)
                self._account("join_build", build.page, node)
                self._builds[node] = build
        return self._builds[node]

    # ------------------------------------------------------------------
    def _expanding_join_pages(self, node: JoinNode) -> Iterator[Page]:
        """Many-to-many probe with capacity retry (the analog of the
        reference's yielding LookupJoinPageBuilder). A build side that
        exceeds the pool falls back to host-RAM partitioned join."""
        from presto_tpu.memory import ExceededMemoryLimitError

        if node in self._force_expanding:
            yield from self._partitioned_join_pages(node)
            return
        try:
            build = self._materialize_build(node)
        except ExceededMemoryLimitError as e:
            if f"join_build@{id(node)}#" not in e.tag:
                raise
            yield from self._partitioned_join_pages(node)
            return
        kd = node.key_domains
        left_keys = list(node.left_keys)
        build_output = list(range(len(node.right.channels)))
        is_full = node.kind == "full"
        kind = "left" if is_full else node.kind
        ns = node.null_safe_keys

        def probe(b, p, out_capacity):
            return probe_expand(
                b, p, left_keys, out_capacity, key_domains=kd,
                kind=kind, build_output=build_output, return_matched=is_full,
                null_safe=ns,
            )

        if node in self._chain_cache:
            fn = self._chain_cache[node]
        else:
            fn = self._program(
                "probe_expand",
                (left_keys, tuple(kd or ()), kind, tuple(build_output),
                 is_full, ns),
                lambda: jax.jit(probe, static_argnames=("out_capacity",))
                if self.jit else probe,
                node=node)
            self._chain_cache[node] = fn

        matched_acc = None
        for p in self._pages(node.left):
            res = _probe_with_retry(
                lambda b, pg, cap: fn(b, pg, out_capacity=cap), build, p)
            yield res[0]
            if is_full:
                matched_acc = res[2] if matched_acc is None else matched_acc | res[2]

        if is_full:
            from presto_tpu.ops.join import outer_build_tail

            if matched_acc is None:
                matched_acc = jnp.zeros((build.page.capacity,), dtype=jnp.bool_)
            probe_spec = [(c.type, c.dictionary) for c in node.left.channels]
            yield outer_build_tail(build, matched_acc, probe_spec, build_output)

    # ------------------------------------------------------------------
    def _groupid_pages(self, node: GroupIdNode) -> Iterator[Page]:
        """Emit each source page once per grouping set: source blocks +
        key blocks (inactive keys NULL-masked) + constant $group_id
        (GroupIdOperator.java analog; replication stays on device)."""
        fns = self._fold_cache.get(node)
        if fns is None:
            from presto_tpu.expr.compile import ExprCompiler

            key_exprs = list(node.key_exprs)
            nsrc = len(node.source.channels)
            key_chans = node.channels[nsrc:nsrc + len(key_exprs)]

            def make(mask, gid):
                def run(p: Page) -> Page:
                    comp = ExprCompiler.for_page(p)
                    blocks = list(p.blocks)
                    for e, live, ch in zip(key_exprs, mask, key_chans):
                        d, v = comp.compile(e)(p)
                        if not live:
                            v = jnp.zeros_like(v)
                        blocks.append(Block(d, v, e.type, ch.dictionary))
                    gid_data = jnp.full((p.capacity,), gid, dtype=jnp.int64)
                    blocks.append(
                        Block(gid_data, jnp.ones(p.capacity, dtype=jnp.bool_),
                              node.channels[-1].type)
                    )
                    return Page(tuple(blocks), p.row_mask)

                return run

            fns = [
                self._program(
                    "groupid",
                    (tuple(key_exprs),
                     [(c.type, c.dictionary) for c in key_chans],
                     tuple(bool(b) for b in mask), gid,
                     node.channels[-1].type),
                    lambda m=mask, g=gid: jax.jit(make(m, g)) if self.jit
                    else make(m, g),
                    node=node)
                for gid, mask in enumerate(node.set_masks)
            ]
            self._fold_cache[node] = fns
        for p in self._pages(node.source):
            for fn in fns:
                yield fn(p)

    # ------------------------------------------------------------------
    def _index_join_pages(self, node: JoinNode) -> Iterator[Page]:
        """Index join: fetch build rows per probe batch through the
        connector's point-lookup SPI (operator/index/IndexLoader.java +
        IndexSourceOperator.java).  Each probe page's distinct key
        tuples go to the connector; only matching build rows ever
        materialize."""
        from presto_tpu.expr.compile import ExprCompiler

        scan: TableScanNode = node.right
        conn = self.catalog.connector(scan.handle.connector_name)
        key_cols = [
            scan.handle.columns[scan.columns[k.index]].name
            for k in node.right_keys
        ]
        left_keys = list(node.left_keys)
        right_keys = list(node.right_keys)
        build_output = list(range(len(node.right.channels)))
        col_idx = list(scan.columns)

        for p in self._pages(node.left):
            ph = p.compact_host()
            c = ExprCompiler.for_page(ph)
            lanes = []
            sel = np.asarray(ph.row_mask)
            for e in left_keys:
                d, v = c.compile(e)(ph)
                lanes.append(np.asarray(d))
                sel = sel & np.asarray(v)
            keys = {tuple(int(lane[i]) for lane in lanes)
                    for i in np.nonzero(sel)[0]}
            fetched = conn.index_lookup(scan.handle.table, key_cols, sorted(keys))
            pruned = [Page(tuple(fp.blocks[i] for i in col_idx), fp.row_mask)
                      for fp in fetched]
            bpage = concat_pages_device(pruned) if pruned else Page.empty(
                node.right.output_types, 1)
            build = build_join(bpage, right_keys, key_domains=None)
            self._account("index_join_build", build.page, node)
            if node.kind in ("semi", "anti", "mark"):
                yield probe_join(build, p, left_keys, key_domains=None,
                                 kind=node.kind, build_output=build_output,
                                 null_aware=getattr(node, "null_aware", False))
            elif node.unique_build:
                yield probe_join(build, p, left_keys, key_domains=None,
                                 kind=node.kind, build_output=build_output)
            else:
                def probe_fn(b, pp, out_capacity):
                    return probe_expand(
                        b, pp, left_keys, out_capacity, key_domains=None,
                        kind=node.kind, build_output=build_output,
                    )

                res = _probe_with_retry(probe_fn, build, p)
                yield res[0]

    # ------------------------------------------------------------------
    def _partitioned_join_pages(self, node: JoinNode) -> Iterator[Page]:
        """Spilled hash join: both sides hash-partition by join key into
        host-RAM buckets, then each partition joins independently on
        device — build state is bounded by the largest partition
        (reference: spilled lookup joins,
        operator/SpilledLookupSourceHandle.java +
        GenericPartitioningSpiller)."""
        from presto_tpu.exec.spill import HostPage, make_bucket_fn, partition_to_host
        from presto_tpu.ops.join import outer_build_tail

        K = self.spill_partitions
        kd = node.key_domains
        left_keys = list(node.left_keys)
        right_keys = list(node.right_keys)
        build_output = list(range(len(node.right.channels)))
        is_full = node.kind == "full"
        kind = "left" if is_full else node.kind
        ns = node.null_safe_keys
        right_types = node.right.output_types

        bfn_r = self._program(
            "spill_bucket", (tuple(right_keys), tuple(kd or ()), K),
            lambda: make_bucket_fn(right_keys, kd, K, jit=self.jit),
            node=node)
        bfn_l = self._program(
            "spill_bucket", (tuple(left_keys), tuple(kd or ()), K),
            lambda: make_bucket_fn(left_keys, kd, K, jit=self.jit),
            node=node)

        bbuckets: List[List[HostPage]] = [[] for _ in range(K)]
        for p in self._pages(node.right):
            for k, hp in enumerate(partition_to_host(p, bfn_r(p), K)):
                if hp is not None:
                    bbuckets[k].append(hp)
        pbuckets: List[List[HostPage]] = [[] for _ in range(K)]
        for p in self._pages(node.left):
            for k, hp in enumerate(partition_to_host(p, bfn_l(p), K)):
                if hp is not None:
                    pbuckets[k].append(hp)

        # three-valued IN/NOT IN needs GLOBAL build flags: a NULL build
        # key in one partition makes unmatched probes in EVERY partition
        # UNKNOWN, and "build nonempty" is a whole-relation property
        na = getattr(node, "null_aware", False) and kind in ("semi", "anti",
                                                             "mark")
        g_has_null = g_nonempty = None
        if na:
            from presto_tpu.expr.ir import ColumnRef as _CR

            g_has_null = jnp.asarray(False)
            g_nonempty = jnp.asarray(False)
            plain = all(isinstance(k_, _CR) for k_ in right_keys)
            for k in range(K):
                for hp in bbuckets[k]:
                    if plain:
                        # host-side flags from the spilled numpy columns
                        # — no device rehydrate just for two booleans
                        av = np.ones(len(hp.mask), dtype=bool)
                        for k_ in right_keys:
                            av &= np.asarray(hp.columns[k_.index][1])
                        g_has_null = g_has_null | bool(
                            (hp.mask & ~av).any())
                        g_nonempty = g_nonempty | bool(hp.mask.any())
                    else:
                        from presto_tpu.ops.join import build_null_flags

                        h, ne = build_null_flags(hp.rehydrate(), right_keys)
                        g_has_null = g_has_null | h
                        g_nonempty = g_nonempty | ne

        probe_spec = [(c.type, c.dictionary) for c in node.left.channels]
        for k in range(K):
            if not pbuckets[k] and not (is_full and bbuckets[k]):
                continue
            if bbuckets[k]:
                bpage = concat_pages_device([hp.rehydrate() for hp in bbuckets[k]])
            else:
                bpage = Page.empty(right_types, 1)
            build = build_join(bpage, right_keys, key_domains=kd, null_safe=ns)
            if na:
                build = dataclasses.replace(
                    build, has_null_key=g_has_null, nonempty=g_nonempty)
            tag = None
            if self._mem is not None:
                from presto_tpu.memory import page_bytes

                tag = self._mem.reserve(f"join_build_partition@{id(node)}",
                                        page_bytes(build.page))
            def probe_fn(b, p, out_capacity):
                return probe_expand(
                    b, p, left_keys, out_capacity, key_domains=kd,
                    kind=kind, build_output=build_output, return_matched=is_full,
                    null_safe=ns,
                )

            matched_acc = None
            for hp in pbuckets[k]:
                p = hp.rehydrate()
                if kind in ("semi", "anti", "mark"):
                    yield probe_join(build, p, left_keys, key_domains=kd,
                                     kind=kind, build_output=build_output,
                                     null_safe=ns, null_aware=na)
                    continue
                res = _probe_with_retry(probe_fn, build, p)
                yield res[0]
                if is_full:
                    matched_acc = res[2] if matched_acc is None else matched_acc | res[2]
            if is_full:
                if matched_acc is None:
                    matched_acc = jnp.zeros((build.page.capacity,), dtype=jnp.bool_)
                yield outer_build_tail(build, matched_acc, probe_spec, build_output)
            if tag is not None:
                self._mem.free(tag)  # partition done; its build leaves HBM

    # ------------------------------------------------------------------
    def _run_topn(self, node: TopNNode) -> Page:
        """Fold: keep a device-resident accumulator of exactly ``count``
        rows; each input page is sorted together with the accumulator
        and truncated (TopNOperator.java bounded-heap analog)."""
        n = node.count
        sort_exprs = list(node.sort_exprs)
        ascending = list(node.ascending)
        nulls_first = node.nulls_first

        def fold(acc: Optional[Page], p: Page) -> Page:
            cand = p if acc is None else concat_pages_device([acc, p])
            s = sort_page(cand, sort_exprs, ascending, nulls_first)
            keep = jnp.arange(s.capacity) < n
            return slice_page(Page(s.blocks, s.row_mask & keep), n)

        fold_fn = self._fold_cache.get(node)
        if fold_fn is None:
            fold_fn = self._program(
                "topn", (n, sort_exprs, ascending, nulls_first),
                lambda: jax.jit(fold) if self.jit else fold, node=node)
            self._fold_cache[node] = fold_fn

        acc: Optional[Page] = None
        for p in self._pages(node.source):
            acc = fold_fn(acc, p)
        if acc is None:
            return Page.empty(node.output_types, max(n, 1))
        return acc

    # ------------------------------------------------------------------
    def _max_groups(self, node: AggregationNode) -> int:
        if node in self._agg_overrides:
            return self._agg_overrides[node]
        kd = node.key_domains
        if node.group_exprs and kd and all(d is not None for d in kd):
            prod = 1
            for lo, hi in kd:
                prod *= hi - lo + 2
            if prod <= node.max_groups:
                return prod
        return node.max_groups

    def _exact_capacity(self, node: AggregationNode, mg: int) -> bool:
        kd = node.key_domains
        if node.group_exprs and kd and all(d is not None for d in kd):
            prod = 1
            for lo, hi in kd:
                prod *= hi - lo + 2
            return prod <= mg
        return False

    def _packed_direct(self, node: AggregationNode, mg: int) -> bool:
        """True when the chain's partial aggregation takes the
        packed-direct layout (group id == slot position): exact domains
        AND within DIRECT_GROUP_LIMIT — mirrors grouped_aggregate's own
        branch condition.  Above the limit the sort path emits
        front-compacted pages instead, where position says nothing."""
        from presto_tpu.ops.aggregate import packed_direct_layout

        # presorted partials take grouped_aggregate's STREAMING branch
        # (front-compacted, first-appearance order) before packed-direct
        # is even considered — position says nothing there
        if getattr(node, "presorted", False):
            return False
        return packed_direct_layout(node.group_exprs, node.key_domains, mg)

    def _commutative_exact(self, node: AggregationNode) -> bool:
        """True when the aggregation's fold is order-insensitive in
        EXACT arithmetic: count/min/max always, sum only over integer
        representations (integer-like and short decimals — scaled
        int64s).  Float sums/avg stay ordered: float addition is
        non-associative, and concurrency must not change results."""
        for a in node.aggs:
            if a.distinct:
                return False
            if a.fn in ("count", "count_star", "min", "max"):
                continue
            if a.fn == "sum" and (a.type.is_integerlike or a.type.is_decimal):
                # all decimal sums are exact integer folds now — short
                # ones in scaled int64, widened/long ones in base-1e9
                # sum limbs (both associative and commutative)
                continue
            return False
        return True

    def _run_aggregation(self, node: AggregationNode) -> Page:
        """Breaker with spill fallback: the in-place path folds partial
        pages on device; past the pool limit or the capacity threshold
        it re-executes partitioned through host RAM (spiller analog)."""
        from presto_tpu.memory import ExceededMemoryLimitError

        try:
            return self._host_finalize_aggs(
                node, self._run_aggregation_impl(node))
        except ExceededMemoryLimitError as e:
            if f"agg_accumulator@{id(node)}#" not in e.tag:
                raise
        except GroupCapacityExceeded as e:
            if e.node is not node or e.needed <= SPILL_GROUP_THRESHOLD:
                raise
        return self._host_finalize_aggs(
            node, self._run_aggregation_spilled(node))

    def _host_finalize_aggs(self, node: AggregationNode, out: Page) -> Page:
        """Aggregates whose OUTPUT is a string cannot finalize inside
        jit; their jitted finalize emits the numeric state and this
        host pass formats it (evaluate_classifier_predictions — the
        presto-ml output function's role)."""
        if not any(a.fn == "evaluate_classifier_predictions"
                   for a in node.aggs):
            return out
        from presto_tpu.ops.aggregate import ML_MAX_CLASSES
        from presto_tpu.page import Dictionary
        from presto_tpu.types import VARCHAR

        C = ML_MAX_CLASSES
        nkeys = len(node.group_exprs)
        blocks = list(out.blocks)
        for i, agg in enumerate(node.aggs):
            if agg.fn != "evaluate_classifier_predictions":
                continue
            b = blocks[nkeys + i]
            data = np.asarray(b.data)
            valid = np.asarray(b.valid) & np.asarray(out.row_mask)
            live_rows = np.nonzero(valid)[0]
            texts = [""] * data.shape[0]
            for r in live_rows:  # dead padded slots skip formatting
                tp = data[r, 1:1 + C]
                fp = data[r, 1 + C:1 + 2 * C]
                fn = data[r, 1 + 2 * C:1 + 3 * C]
                correct = int(tp.sum())
                total = correct + int(fp.sum())
                pct = 100.0 * correct / total if total else 0.0
                parts = [f"Accuracy: {correct}/{total} ({pct:.2f}%)\n"]
                for cls in range(C):
                    t_, f_, n_ = int(tp[cls]), int(fp[cls]), int(fn[cls])
                    if t_ == 0 and f_ == 0 and n_ == 0:
                        continue
                    pp = 100.0 * t_ / (t_ + f_) if t_ + f_ else 0.0
                    rr = 100.0 * t_ / (t_ + n_) if t_ + n_ else 0.0
                    parts.append(f"Class '{cls}'\n")
                    parts.append(
                        f"Precision: {t_}/{t_ + f_} ({pp:.2f}%)\n")
                    parts.append(f"Recall: {t_}/{t_ + n_} ({rr:.2f}%)\n")
                texts[r] = "".join(parts)
            uniq = sorted({texts[r] for r in live_rows})
            dic = Dictionary(uniq)
            codes = np.zeros(data.shape[0], dtype=np.int32)
            for r in live_rows:
                codes[r] = dic.code_of(texts[r])  # memoized O(1) lookup
            blocks[nkeys + i] = Block(jnp.asarray(codes),
                                      jnp.asarray(valid), VARCHAR, dic)
        return Page(tuple(blocks), out.row_mask)

    def _run_aggregation_spilled(self, node: AggregationNode) -> Page:
        """Lifespan-style partitioned aggregation: hash-partition the
        pre-aggregation rows into host-RAM buckets, then aggregate each
        bucket to completion on device (grouped execution + partitioning
        spiller, execution/Lifespan.java:26 +
        spiller/GenericPartitioningSpiller.java)."""
        from presto_tpu.exec.spill import HostPage, make_bucket_fn, partition_to_host
        from presto_tpu.ops.aggregate import grouped_aggregate

        K = self.spill_partitions
        group_exprs = list(node.group_exprs)
        aggs = list(node.aggs)
        kd = node.key_domains
        num_keys = len(group_exprs)
        partial_input = node.step == "final"
        if partial_input:
            # source emits partial-state pages: keys are the first
            # num_keys channels
            from presto_tpu.expr.ir import ColumnRef

            src_ch = node.source.channels
            bucket_exprs = [ColumnRef(type=src_ch[i].type, index=i)
                            for i in range(num_keys)]
        else:
            bucket_exprs = group_exprs
        bucket_fn = self._program(
            "spill_bucket", (tuple(bucket_exprs), tuple(kd or ()), K),
            lambda: make_bucket_fn(bucket_exprs, kd, K, jit=self.jit),
            node=node)

        buckets: List[List[HostPage]] = [[] for _ in range(K)]
        for p in self._pages(node.source):
            for k, hp in enumerate(partition_to_host(p, bucket_fn(p), K)):
                if hp is not None:
                    buckets[k].append(hp)

        # per-bucket capacity ~ total/K (keys hash-spread); per-bucket
        # doubling below recovers skewed buckets
        cap0 = max(1 << 10, min(self._max_groups(node), SPILL_GROUP_THRESHOLD) // K)

        def fold_bucket(pages: List[HostPage], cap: int) -> "_AggFoldTower":
            # tower fold with live-extent compaction (same machinery as
            # the in-memory path; account=False — spill state must not
            # re-trip the pool it is relieving)
            tower = _AggFoldTower(self, node, num_keys, aggs, kd, cap,
                                  account=False)
            for hp in pages:
                p = hp.rehydrate()
                if partial_input:
                    pp = p
                else:
                    pp = grouped_aggregate(p, group_exprs, aggs, cap,
                                           key_domains=kd, mode="partial")
                tower.add(pp)
            return tower

        outs: List[Page] = []
        for k in range(K):
            if not buckets[k]:
                continue
            cap = cap0
            while True:
                tower = fold_bucket(buckets[k], cap)
                # tower merges are unclamped; only the per-page
                # grouped_aggregate at static ``cap`` can truncate, and
                # a full page is the tell (partial_input pages are
                # already states — nothing truncates)
                if (partial_input or not tower.suspect_truncation
                        or cap >= MAX_AGG_GROUPS):
                    out = tower.finish_single()
                    break
                cap = min(MAX_AGG_GROUPS,
                          max(cap * 2,
                              1 << max(1,
                                       2 * tower.live_total - 1).bit_length()))
            if out is None:  # every page in the bucket was all-dead
                continue
            # bucket outputs are result stream, not operator state — not
            # charged against the pool (the whole point of the spill)
            outs.append(out)
        if not outs:
            out = Page.empty(node.output_types, max(cap0, 1))
            return self._groupid_empty_fixup(node, out)
        if not node.group_exprs:
            # global agg never spills (one group); defensive
            return outs[0]
        return concat_pages_device(outs)

    def _run_aggregation_impl(self, node: AggregationNode) -> Page:
        """Breaker: stream partial pages and fold-merge with a bounded
        accumulator (2*max_groups concat each step, static shapes)."""
        mg = self._max_groups(node)
        aggs = list(node.aggs)
        num_keys = len(node.group_exprs)
        kd = node.key_domains

        if node.step == "final":
            source: PlanNode = node.source
        else:
            # step == 'single': inject a per-page partial step
            partial = self._partial_nodes.get(node)
            if partial is None:
                partial = AggregationNode(
                    source=node.source,
                    group_exprs=node.group_exprs,
                    group_names=node.group_names,
                    aggs=node.aggs,
                    agg_names=node.agg_names,
                    step="partial",
                    max_groups=node.max_groups,
                    presorted=node.presorted,
                )
                self._partial_nodes[node] = partial
            self._agg_overrides[partial] = mg
            source = partial

        if agg_tower_enabled() and node.group_exprs \
                and not self._packed_direct(node, mg):
            # sort-path partials: live-extent compaction + tower merge.
            # Tower capacities are unclamped, so the merge itself never
            # truncates; the one remaining hazard is the chain's
            # static-capacity per-split partial (only when THIS runner
            # injected it, i.e. step single) — a full partial page
            # triggers ONE retry with the capacity jumped to the
            # observed live total instead of a doubling ladder.
            tower = _AggFoldTower(self, node, num_keys, aggs, kd, mg)
            # exact commutative folds (count/min/max, integer sums) may
            # take chain pages in COMPLETION order: the tower's merged
            # values are order-independent in exact arithmetic, so the
            # scheduler skips the reorder buffer (grant is consume-once
            # and cleared below even if no chain ever claimed it)
            if self.task_concurrency > 1 and self._commutative_exact(node):
                self._unordered_tls.ok = True
            try:
                for p in self._pages(source):
                    tower.add(p)
            finally:
                self._unordered_tls.ok = False
            if node.step == "single" and tower.suspect_truncation \
                    and not self._exact_capacity(node, mg) \
                    and mg < MAX_AGG_GROUPS:
                needed = min(
                    MAX_AGG_GROUPS,
                    max(mg * 2,
                        1 << max(1, 2 * tower.live_total - 1).bit_length()))
                self._agg_overrides[node] = needed
                self._invalidate_agg_caches(node)
                raise GroupCapacityExceeded(needed, node)
            out = tower.finish_single()
            if out is None:
                return self._groupid_empty_fixup(
                    node, Page.empty(node.output_types, max(mg, 1)))
            return self._groupid_empty_fixup(node, out)

        # exact-capacity (packed-direct) partials: slot position IS the
        # group key, so the fold is pure ELEMENTWISE state combination —
        # no sort, no scatter, no concat (the direct-address layout's
        # payoff; the classic sort-merge fold re-sorted 2*capacity keys
        # per split)
        from presto_tpu.ops.aggregate import (
            combine_packed_states, finalize_packed, packed_fold_supported,
        )

        # positional fold requires the pages to BE packed-direct, which
        # only this runner's own injected partial guarantees — step
        # 'final' inputs arrive through exchange serde, which compacts
        # live rows and destroys the slot layout
        if node.group_exprs and node.step == "single" \
                and self._packed_direct(node, mg) \
                and packed_fold_supported(aggs):
            def fold_pk(acc: Optional[Page], p: Page) -> Page:
                if acc is None:
                    return p
                return combine_packed_states(acc, p, num_keys, aggs)

            def final_pk(acc: Page) -> Page:
                return finalize_packed(acc, num_keys, aggs)

            fold_fn, final_fn = self._fold_cache.get(node, (None, None))
            if fold_fn is None:
                sig = (num_keys, tuple(aggs))
                fold_fn = self._program(
                    "agg_packed_fold", sig,
                    lambda: jax.jit(fold_pk) if self.jit else fold_pk,
                    node=node)
                final_fn = self._program(
                    "agg_packed_final", sig,
                    lambda: jax.jit(final_pk) if self.jit else final_pk,
                    node=node)
                self._fold_cache[node] = (fold_fn, final_fn)
            acc = None
            for p in self._pages(source):
                if acc is None:
                    acc = p
                    self._account("agg_accumulator", acc, node)
                else:
                    acc = fold_fn(acc, p)
            if acc is None:
                return self._groupid_empty_fixup(
                    node, Page.empty(node.output_types, max(mg, 1)))
            out = final_fn(acc)
            return self._groupid_empty_fixup(node, out)

        # global aggregation and remaining exact-capacity shapes:
        # fixed-capacity running fold — pages are already as tight as the
        # key domain allows, so compaction buys nothing
        def fold(acc: Optional[Page], p: Page) -> Page:
            cand = p if acc is None else concat_pages_device([acc, p])
            return merge_aggregate(cand, num_keys, aggs, mg, key_domains=kd, mode="partial")

        def final(acc: Page) -> Page:
            return merge_aggregate(acc, num_keys, aggs, mg, key_domains=kd, mode="single")

        fold_fn, final_fn = self._fold_cache.get(node, (None, None))
        if fold_fn is None:
            sig = (num_keys, tuple(aggs), mg, tuple(kd or ()))
            fold_fn = self._program(
                "agg_fold", sig,
                lambda: jax.jit(fold) if self.jit else fold, node=node)
            final_fn = self._program(
                "agg_final", sig,
                lambda: jax.jit(final) if self.jit else final, node=node)
            self._fold_cache[node] = (fold_fn, final_fn)

        # seed the first fold with a dead-rows accumulator so EVERY
        # call has the steady-state (acc, page) shape — a bare first
        # call traced a second program (fold of the page alone) per
        # aggregation.  Dictionary-carrying states keep the unseeded
        # start: an empty block's dictionary is None and concat would
        # adopt it.
        seedable = all(c.dictionary is None for c in source.channels)
        acc: Optional[Page] = None
        for p in self._pages(source):
            if acc is None:
                seed = Page.empty(source.output_types, mg) if seedable \
                    else None
                acc = fold_fn(seed, p)
                self._account("agg_accumulator", acc, node)
            else:
                acc = fold_fn(acc, p)
        if acc is None:
            if not node.group_exprs:
                # global aggregation over zero input pages still emits
                # its one row (count 0, other aggregates NULL) — the
                # SQL empty-input contract
                empty = Page.empty(node.source.output_types, 1)
                return grouped_aggregate(empty, [], list(node.aggs), 1,
                                         mode="single")
            return self._groupid_empty_fixup(node, Page.empty(node.output_types, max(mg, 1)))
        out = final_fn(acc)
        self._check_overflow(node, out, mg)
        return self._groupid_empty_fixup(node, out)

    def _groupid_empty_fixup(self, node: AggregationNode, out: Page) -> Page:
        """GROUPING SETS over empty input: sets with no keys (the ()
        set of ROLLUP/CUBE) must still emit their one global-aggregate
        row (count=0, other aggregates NULL) — grouped hashing alone
        produces nothing from nothing."""
        src = node.source
        if not isinstance(src, GroupIdNode):
            return out
        empty_gids = [gid for gid, m in enumerate(src.set_masks) if not any(m)]
        if not empty_gids:
            return out
        if int(np.asarray(jnp.sum(out.row_mask.astype(jnp.int32)))) > 0:
            return out
        nkeys = len(node.group_exprs) - 1  # last group expr is $group_id
        types = node.output_types
        k = len(empty_gids)
        cols, valids = [], []
        for i, t in enumerate(types):
            if i < nkeys:
                cols.append(np.zeros((k,) + t.value_shape, t.np_dtype))
                valids.append(np.zeros(k, np.bool_))
            elif i == nkeys:
                cols.append(np.asarray(empty_gids, t.np_dtype))
                valids.append(np.ones(k, np.bool_))
            else:
                agg = node.aggs[i - nkeys - 1]
                cols.append(np.zeros((k,) + t.value_shape, t.np_dtype))
                valids.append(
                    np.full(k, agg.fn in ("count", "count_star"), np.bool_)
                )
        dicts = [c.dictionary for c in node.channels]
        return Page.from_arrays(cols, types, valids=valids, dictionaries=dicts)

    def _invalidate_agg_caches(self, node: AggregationNode) -> None:
        """Drop only the compiled programs the retried aggregation's
        capacity is baked into — the rest of the query's chains, builds
        and folds stay compiled across the retry (a full clear re-paid
        every compile per capacity step)."""
        targets = {id(node)}
        partial = self._partial_nodes.get(node)
        if partial is not None:
            targets.add(id(partial))

        def contains(root) -> bool:
            stack = [root]
            while stack:
                n = stack.pop()
                if id(n) in targets:
                    return True
                stack.extend(getattr(n, "sources", []) or [])
            return False

        for key in list(self._chain_cache):
            if isinstance(key, PlanNode) and contains(key):
                del self._chain_cache[key]
        for key in list(self._fold_cache):
            base = key[0] if isinstance(key, tuple) else key
            if isinstance(base, PlanNode) and id(base) in targets:
                del self._fold_cache[key]

    def _check_overflow(self, node: AggregationNode, out: Page, mg: int) -> None:
        if not node.group_exprs or self._exact_capacity(node, mg):
            return
        live = int(np.asarray(jnp.sum(out.row_mask.astype(jnp.int32))))
        if live >= mg and mg < MAX_AGG_GROUPS:
            self._agg_overrides[node] = mg * 2
            self._invalidate_agg_caches(node)
            raise GroupCapacityExceeded(mg * 2, node)
