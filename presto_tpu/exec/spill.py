"""Host-RAM spill: lifespan-style partitioned fallback for oversized
aggregation/join state.

Reference analog: the revocable-memory + spill tier —
``execution/MemoryRevokingScheduler.java:46`` triggers revocation,
``spiller/FileSingleStreamSpiller.java`` / ``GenericPartitioningSpiller``
write pages to local disk, and grouped execution
(``execution/Lifespan.java:26``) bounds hash state by processing
bucketed keyspaces one at a time.

A TPU chip has no local disk; the offload target is host RAM (pages
leave HBM as numpy arrays). The mechanism is the partitioning spiller's:
rows hash-partition by key into K buckets held host-side, then each
bucket is processed to completion on device with per-bucket capacity —
state never exceeds pool_limit/K-ish instead of the whole keyspace.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import Expr
from presto_tpu.ops.aggregate import pack_or_hash_keys
from presto_tpu.page import Block, Page


@dataclasses.dataclass
class HostPage:
    """A Page offloaded to host RAM (numpy-backed; the spill file
    analog — nothing device-resident)."""

    columns: List[Tuple[np.ndarray, np.ndarray, object, object]]  # data, valid, type, dict
    mask: np.ndarray

    @property
    def num_rows(self) -> int:
        return int(self.mask.sum())

    def rehydrate(self, capacity: Optional[int] = None) -> Page:
        n = len(self.mask)
        # pow2 padding by default: bucket sizes are data-dependent, and
        # raw row counts would give every rehydrated page a distinct
        # XLA shape — one full program compile per page (measured as
        # the dominant cost of the r4 spill cliff, not the sorts)
        cap = capacity if capacity is not None \
            else max(1024, 1 << max(0, n - 1).bit_length())
        blocks = []
        for data, valid, t, d in self.columns:
            dd = np.zeros((cap,) + data.shape[1:], dtype=data.dtype)
            dd[:n] = data
            vv = np.zeros(cap, dtype=np.bool_)
            vv[:n] = valid
            blocks.append(Block(jnp.asarray(dd), jnp.asarray(vv), t, d))
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = self.mask
        return Page(tuple(blocks), jnp.asarray(mask))


def make_bucket_fn(key_exprs: Sequence[Expr], key_domains, num_buckets: int,
                   jit: bool = True):
    """Compile page -> int32 bucket-id-per-row (hash of the group/join
    key, the GenericPartitioningSpiller partition function)."""

    def bucket_ids(page: Page) -> jax.Array:
        c = ExprCompiler.for_page(page)
        kd = [c.compile(e)(page) for e in key_exprs]
        from presto_tpu.ops.aggregate import canonicalize_codes, expr_key_dicts

        key, _ = pack_or_hash_keys(
            canonicalize_codes([d for d, _ in kd],
                               expr_key_dicts(page, key_exprs)),
            [v for _, v in kd], key_domains)
        if key is None:
            return jnp.zeros(page.capacity, dtype=jnp.int32)
        # re-mix so packed (non-hashed) keys spread across buckets
        h = key.astype(jnp.uint64)
        h = (h ^ (h >> jnp.uint64(33))) * jnp.uint64(0xFF51AFD7ED558CCD)
        h = h ^ (h >> jnp.uint64(33))
        return (h % jnp.uint64(num_buckets)).astype(jnp.int32)

    return jax.jit(bucket_ids) if jit else bucket_ids


def partition_to_host(page: Page, bids: jax.Array, num_buckets: int) -> List[Optional[HostPage]]:
    """Split one device page into per-bucket host pages (the spill
    write). Returns None for empty buckets."""
    bids_np = np.asarray(bids)
    mask_np = np.asarray(page.row_mask)
    out: List[Optional[HostPage]] = []
    datas = [np.asarray(b.data) for b in page.blocks]
    valids = [np.asarray(b.valid) for b in page.blocks]
    for k in range(num_buckets):
        idx = np.nonzero(mask_np & (bids_np == k))[0]
        if len(idx) == 0:
            out.append(None)
            continue
        cols = [(d[idx], v[idx], b.type, b.dictionary)
                for d, v, b in zip(datas, valids, page.blocks)]
        out.append(HostPage(cols, np.ones(len(idx), dtype=np.bool_)))
    spilled = sum(
        sum(d.nbytes + v.nbytes for d, v, _t, _dic in hp.columns)
        for hp in out if hp is not None)
    if spilled:
        from presto_tpu.obs import METRICS, current_timeline

        METRICS.counter("spill.bytes").inc(spilled)
        tl = current_timeline()
        if tl is not None:
            # per-query spill evidence for the doctor's spill-bound rule
            tl.record("spill.bytes", float(spilled))
            tl.bump("spill_bytes", spilled)
    return out
