"""Shape-canonicalizing program registry + persistent XLA cache.

Reference analog: ``sql/gen/ExpressionCompiler.java:53`` — the
reference keys generated operator bytecode by a *structural* cache key
(RowExpression + compiler flags), so two queries whose filters compile
to the same bytecode share one class.  This repo's executor instead
cached one jitted callable per ``PlanNode`` *object*
(``exec/local.py``), so two structurally identical aggregations in
different queries — or the same query re-planned after a write —
compiled twice, and every process started from zero.  Cold-start
compiles are the dominant latency tax of the XLA execution tier
(VERDICT checklist #1: q3 spent 30s of warmup in compiles at r5).

Two layers collapse that cost:

- :class:`ProgramRegistry` keys compiled executables by a structural
  signature — kernel family + the canonicalized expression IR + every
  parameter the closure bakes in (capacities, key domains, join kind,
  dictionaries) — so identical operator shapes share one traced
  callable across queries, plans, and runner rebuilds.  XLA program
  identity *within* a callable is then jit's own cache: input pytree
  statics (types, dictionaries) + shapes, which the pow2/64K shape
  ladder (``exec/local.py bucket_capacity``) keeps small.

- The JAX persistent compilation cache
  (``jax_compilation_cache_dir``) serializes compiled XLA binaries to
  disk so a *fresh process* — bench children, worker restarts, test
  runs — rehydrates executables instead of recompiling.  Wired through
  ``PRESTO_TPU_PROGRAM_CACHE_DIR`` / the ``query.program-cache-dir``
  config key (default under the warehouse root when one is
  configured).

Both layers export counters (distinct programs, registry hits/misses,
cumulative compile seconds, persistent hits) surfaced by ``EXPLAIN
ANALYZE VERBOSE`` and dumped by ``tools/benchmark_driver.py
--cold-compile-report``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from presto_tpu.sync import named_lock

# ---------------------------------------------------------------------------
# structural signatures
# ---------------------------------------------------------------------------

# Dictionary objects are identity-hashed (page.py).  Signatures need a
# token that is stable for the object's lifetime AND never aliases a
# dead dictionary's id — so the token table holds a strong reference.
# Table-metadata dictionaries are few, but derived ones (per-literal
# string arrays) scale with query diversity, so the table is a bounded
# LRU with MONOTONIC token numbers: evicting an entry only means a
# re-appearing dictionary gets a FRESH token (a recompile, never a
# collision — the id-vs-object check below catches reused ids too).
# (identity-keyed fallback signatures share this table: an evicted or
# dead object's id re-emerging maps to a fresh monotonic token, so a
# stale registry entry goes unused instead of colliding)
_DICT_TOKENS_MAX = 4096
_DICT_TOKENS: "Dict[int, Tuple[object, int]]" = {}
_DICT_SEQ = [0]
_DICT_LOCK = named_lock("programs._DICT_LOCK")


def _dict_token(d) -> int:
    with _DICT_LOCK:
        ent = _DICT_TOKENS.get(id(d))
        if ent is None or ent[0] is not d:
            _DICT_SEQ[0] += 1
            ent = (d, _DICT_SEQ[0])
            _DICT_TOKENS[id(d)] = ent
            while len(_DICT_TOKENS) > _DICT_TOKENS_MAX:
                _DICT_TOKENS.pop(next(iter(_DICT_TOKENS)))
        return ent[1]


def type_signature(t) -> tuple:
    """Full structural identity of a Type.  ``Type.__repr__`` is lossy
    (it hides the dictionary flag and raw-varchar width), and raw vs
    dictionary VARCHAR compile to different kernels — so signatures
    use every identity-bearing field."""
    if t is None:
        return ()
    return (
        t.name, str(t.np_dtype), t.dictionary, t.scale, t.precision,
        type_signature(t.element), type_signature(t.key_element),
        tuple(type_signature(f) for f in t.fields) if t.fields else None,
        t.field_names,
    )


def ir_signature(obj) -> Any:
    """Hashable structural signature of expression IR / plan parameters.

    Walks dataclasses field-by-field (Expr, AggCall, WindowFunc, ...),
    expands Types fully, tokens Dictionaries by identity, and converts
    sequences to tuples.  Anything unrecognized is keyed by object
    identity and pinned so the id can never alias — identity keys
    merely forgo sharing, they never produce a wrong hit."""
    from presto_tpu.page import Dictionary
    from presto_tpu.types import Type

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, Type):
        return ("T",) + type_signature(obj)
    if isinstance(obj, Dictionary):
        return ("D", _dict_token(obj))
    if isinstance(obj, (list, tuple)):
        return tuple(ir_signature(x) for x in obj)
    if isinstance(obj, (set, frozenset)):
        return ("S",) + tuple(sorted(map(ir_signature, obj), key=repr))
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return (type(obj).__name__,) + tuple(
            ir_signature(getattr(obj, f.name))
            for f in dataclasses.fields(obj))
    return ("I", type(obj).__name__, _dict_token(obj))


# Plan-node fields excluded from the CROSS-PROCESS structural signature:
# they vary between the coordinator's plan and the fragment a worker
# executes (the coordinator assigns `splits` per worker; Precomputed
# stage results carry a materialized `page`) without changing what the
# operator *is* — including them would make worker actuals unmergeable
# with coordinator estimates.
_VOLATILE_FIELDS = {
    "TableScanNode": {"splits"},
    "PrecomputedNode": {"page"},
}


def stable_signature(obj) -> Any:
    """``ir_signature`` minus every per-process identity source: a
    signature that is equal for structurally equal plans ACROSS
    processes, so a worker's per-node stats can be merged onto the
    coordinator's entries by key alone (estimate-vs-actual roll-up,
    plan-history store).

    Differences from :func:`ir_signature` (which must stay
    identity-precise for program-cache correctness): Dictionaries
    collapse to a bare marker instead of an identity token, unknown
    objects key by type name only, and per-dispatch volatile plan
    fields (``splits``, materialized stage pages) are skipped.  That
    trades some precision for portability — exactly right for stats
    keys, where structural twins merging is the point, and exactly
    wrong for compiled-program keys, where it would alias kernels."""
    from presto_tpu.page import Dictionary
    from presto_tpu.types import Type

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, Type):
        return ("T",) + type_signature(obj)
    if isinstance(obj, Dictionary):
        return "D"
    if isinstance(obj, (list, tuple)):
        return tuple(stable_signature(x) for x in obj)
    if isinstance(obj, (set, frozenset)):
        return ("S",) + tuple(sorted(map(stable_signature, obj), key=repr))
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        skip = _VOLATILE_FIELDS.get(name, ())
        return (name,) + tuple(
            stable_signature(getattr(obj, f.name))
            for f in dataclasses.fields(obj) if f.name not in skip)
    return ("I", type(obj).__name__)


def structural_digest(node) -> str:
    """16-hex-char digest of a plan node's stable structural signature
    — the JSON-safe half of the ``(signature, occurrence)`` stats key
    shared by the coordinator, every worker, and the persisted
    plan-history store.  sha1 over the signature's repr: ``hash()`` is
    salted per process and identity tokens are per-process counters,
    so neither survives serialization; this does."""
    import hashlib

    return hashlib.sha1(
        repr(stable_signature(node)).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

_PERSISTENT = {"dir": None, "hits": 0, "requests": 0, "listener": False}
_PERSISTENT_LOCK = named_lock("programs._PERSISTENT_LOCK")


def _cache_event_listener(event: str, **kwargs) -> None:
    # jax 0.4.x records cache_hits and compile_requests_use_cache but
    # NO miss event — misses are derived as requests - hits
    if event == "/jax/compilation_cache/cache_hits":
        _PERSISTENT["hits"] += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _PERSISTENT["requests"] += 1


def enable_persistent_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` so
    compiled XLA binaries survive the process: a fresh coordinator,
    worker, bench child, or test run rehydrates executables serialized
    by prior runs instead of recompiling (the make-or-break of the
    1200s bench-child budget when the TPU tunnel is cold)."""
    import jax

    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    if _PERSISTENT["dir"] == cache_dir:
        return cache_dir  # already wired (runner construction is hot)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default thresholds skip small/fast programs — exactly the chain
    # programs a SQL workload compiles hundreds of; cache everything
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    with _PERSISTENT_LOCK:
        _PERSISTENT["dir"] = cache_dir
        if not _PERSISTENT["listener"]:
            jax.monitoring.register_event_listener(_cache_event_listener)
            _PERSISTENT["listener"] = True
    return cache_dir


def maybe_enable_persistent_cache(config=None) -> Optional[str]:
    """Resolve + enable the persistent cache if configured.

    Precedence: ``PRESTO_TPU_PROGRAM_CACHE_DIR`` env (``0``/``false``/
    empty disables) > ``query.program-cache-dir`` config key > a
    ``.xla-program-cache`` directory under the configured warehouse
    root.  Returns the enabled directory or None."""
    env = os.environ.get("PRESTO_TPU_PROGRAM_CACHE_DIR")
    if env is not None:
        if env.strip() in ("", "0", "false"):
            return None
        return enable_persistent_cache(env)
    if config is not None:
        d = config.program_cache_dir()
        if d:
            return enable_persistent_cache(d)
    return None


def disable_persistent_cache() -> None:
    """Detach the persistent cache (tests: a tmpdir cache must not
    outlive its fixture)."""
    import jax

    with _PERSISTENT_LOCK:
        if _PERSISTENT["dir"] is None:
            return
        jax.config.update("jax_compilation_cache_dir", None)
        _PERSISTENT["dir"] = None


def persistent_cache_stats() -> Dict[str, Any]:
    return {
        "dir": _PERSISTENT["dir"],
        "persistent_hits": _PERSISTENT["hits"],
        "persistent_misses": max(
            _PERSISTENT["requests"] - _PERSISTENT["hits"], 0),
    }


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class Program:
    """A registered callable + its compile accounting.

    Wraps the (usually jitted) function; every call samples the jit
    trace-cache size, so a growing cache marks a compile event and the
    call's wall time is attributed to ``compile_s`` (trace+compile
    dominate a cold first call; steady-state calls add two cheap
    counter reads)."""

    __slots__ = ("fn", "kind", "jitted", "calls", "compile_s", "_registry")

    def __init__(self, fn: Callable, kind: str, jitted: bool, registry):
        self.fn = fn
        self.kind = kind
        self.jitted = jitted
        self.calls = 0
        self.compile_s = 0.0
        self._registry = registry

    def _cache_size(self) -> int:
        if not self.jitted:
            return 1
        try:
            return self.fn._cache_size()
        except Exception:
            return 1

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if not self.jitted:
            return self.fn(*args, **kwargs)
        n0 = self._cache_size()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        n1 = self._cache_size()
        if n1 > n0:
            dt = time.perf_counter() - t0
            self.compile_s += dt
            reg = self._registry
            if reg is not None:
                with reg._lock:
                    reg.compile_s += dt
                    reg.trace_events += 1
            # the compile becomes a span in the active query's trace
            # (retroactive: detected only after the call returned) and
            # feeds the process-wide XLA counters — "how much of this
            # query was XLA compile" is the headline TPU question
            from presto_tpu.obs import METRICS, current_tracer

            METRICS.counter("xla.programs_compiled").inc(n1 - n0)
            METRICS.counter("xla.compile_seconds_total").inc(dt)
            METRICS.histogram("xla.compile_ms").observe(dt * 1e3)
            tr = current_tracer()
            if tr is not None:
                tr.add_complete("xla_compile", "compile", t0, dt,
                                kind=self.kind, programs=n1 - n0)
        return out


class ProgramRegistry:
    """Structural-signature -> compiled-callable map shared by every
    runner in the process (coordinator executor, worker task runners,
    EXPLAIN re-executions, rebuilt executors after SET SESSION).

    Bounded LRU: the registry would otherwise keep every jitted
    callable — and through it every compiled XLA executable — alive
    for the process lifetime, and XLA:CPU segfaults deterministically
    once the live-executable arena grows past a few thousand programs
    (the r5 TPC-DS finding; reproduced by the tier-1 suite the moment
    the registry went process-global).  Eviction only drops the
    registry's reference: runners holding an evicted Program keep
    using it; a future structural twin recompiles."""

    DEFAULT_MAX_CALLABLES = 256

    def __init__(self, max_callables: Optional[int] = None):
        import collections

        if max_callables is None:
            max_callables = int(os.environ.get(
                "PRESTO_TPU_PROGRAM_REGISTRY_CAP",
                self.DEFAULT_MAX_CALLABLES))
        self.max_callables = max_callables
        self._programs: "collections.OrderedDict[tuple, Program]" = \
            collections.OrderedDict()
        self._lock = named_lock("programs.ProgramRegistry._lock")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compile_s = 0.0
        self.trace_events = 0

    def get(self, kind: str, sig, factory: Callable[[], Callable],
            jit: bool = True) -> Program:
        """The callable registered under (kind, signature), creating it
        via ``factory`` on first request.  ``jit`` is part of the key
        (a debug runner's eager callable must not shadow the compiled
        one)."""
        from presto_tpu.obs import METRICS

        key = (kind, bool(jit), ir_signature(sig))
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self.hits += 1
                METRICS.counter("xla.registry_hits").inc()
                self._programs.move_to_end(key)
                return prog
            self.misses += 1
            METRICS.counter("xla.registry_misses").inc()
            prog = Program(factory(), kind, jit, self)
            self._programs[key] = prog
            while len(self._programs) > self.max_callables:
                self._programs.popitem(last=False)
                self.evictions += 1
            return prog

    # -- metrics ------------------------------------------------------------
    def callable_count(self) -> int:
        with self._lock:
            return len(self._programs)

    def program_count(self) -> int:
        """Distinct compiled XLA programs across all registered
        callables (each shape signature of each callable is one)."""
        with self._lock:
            progs = list(self._programs.values())
        return sum(p._cache_size() for p in progs)

    def stats(self) -> Dict[str, Any]:
        out = {
            "callables": self.callable_count(),
            "programs": self.program_count(),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "compile_s": round(self.compile_s, 3),
            "trace_events": self.trace_events,
        }
        out.update(persistent_cache_stats())
        return out

    def clear(self) -> None:
        """Drop every registered callable (tests / executable-arena
        bounding; compiled executables additionally need
        ``jax.clear_caches()``)."""
        with self._lock:
            self._programs.clear()


_DEFAULT: Optional[ProgramRegistry] = None
_DEFAULT_LOCK = named_lock("programs._DEFAULT_LOCK")


def default_registry() -> ProgramRegistry:
    """The process-wide registry: every LocalRunner that isn't handed
    an explicit one shares it, so coordinator + worker runners + every
    rebuilt executor reuse one program space."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = ProgramRegistry()
        return _DEFAULT


def structural_sharing_enabled() -> bool:
    """A/B escape hatch: ``PRESTO_TPU_PROGRAM_REGISTRY=0`` reverts to
    per-PlanNode program identity (the pre-registry behavior) so the
    cold-compile win is measurable in one process."""
    return os.environ.get("PRESTO_TPU_PROGRAM_REGISTRY", "1") \
        not in ("0", "false")
