from presto_tpu.exec.local import LocalRunner, MaterializedResult  # noqa: F401
