"""File-based configuration.

Reference analog: airlift ``@Config`` bean binding from
``etc/config.properties`` (server/PrestoServer.java bootstraps from the
etc/ directory: config.properties, node.properties, plus per-catalog
``etc/catalog/*.properties``).  Java-properties syntax: ``key=value``
lines, ``#``/``!`` comments, no sections.

Recognized keys (the engine's subset of the reference's config space):
  coordinator                 true/false (role selection)
  http-server.http.port       REST port
  node.id                     stable node identifier
  query.max-memory-per-node   bytes for the local MemoryPool
  query.validate-plans        run the static plan/IR validator on every
                              bound plan (docs/static-analysis.md)
  query.validate-rewrites     gate every optimizer rule application
                              with the rewrite-soundness checker
                              (docs/static-analysis.md)
  query.trace-dir             write one Chrome-trace JSON per query
                              (docs/observability.md; enables tracing)
  query.log-path              JSONL query log (one line per completed
                              query via the EventListener sink)
  query.task-concurrency      splits in flight per scan pipeline
                              (morsel split scheduler; docs/tuning.md)
  query.task-prefetch         host pages prepared ahead of the split
                              worker pool (double-buffering depth)
  query.max-execution-time    duration (e.g. ``600s``, ``10m``) a query
                              may RUN before the coordinator kills it
                              (EXCEEDED_TIME_LIMIT; default 0 = no
                              deadline; docs/fault-tolerance.md)
  query.max-queued-time       duration a query may wait for resource-
                              group admission before failing
  coordinator.worker-uris     comma-separated worker base URIs the
                              coordinator heartbeats, polls and
                              schedules (failure detector, cluster
                              memory manager, system tables)
  query.result-cache-enabled  serve repeated read-only queries from the
                              structural result cache (docs/serving.md)
  query.result-cache-bytes    byte budget for that cache (0 = default)
  query.subplan-cache-enabled reuse warm stage intermediates at
                              exchange boundaries (docs/serving.md)
  query.admission-memory-fraction
                              dispatch only while pool reserved +
                              projected bytes <= fraction * limit
  query.admission-reserve-bytes
                              memory projection for statements with no
                              observed peak history
  task.buffer-bytes           worker output-buffer cap
  session.<property>          default for any system session property

Catalog files (``etc/catalog/<name>.properties``) declare
``connector.name=<tpch|tpcds|memory|blackhole|...>`` plus
connector-specific keys (e.g. ``tpch.scale-factor=1.0``), mirroring the
reference's per-catalog property files.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple


def parse_properties(text: str) -> Dict[str, str]:
    """Java-properties subset: key=value, # or ! comments, blank lines."""
    out: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        if "=" not in line:
            raise ValueError(f"malformed property line: {raw!r}")
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def load_properties(path: str) -> Dict[str, str]:
    with open(path) as f:
        return parse_properties(f.read())


def parse_duration(text: str, default: float = 0.0) -> float:
    """airlift ``Duration`` subset -> seconds: ``600``/``600s``,
    ``500ms``, ``10m``, ``2h``, ``1d``.  Empty/None/unparseable ->
    ``default`` (never raises: this runs on the coordinator's
    query-execution path, where a garbage config value must degrade
    to the default, not leak a resource-group slot — session values
    are additionally validated at SET time, session.py).  ``0`` (any
    unit) means disabled by the callers' convention."""
    if text is None:
        return default
    s = str(text).strip().lower()
    if not s:
        return default
    try:
        for suffix, scale in (("ms", 1e-3), ("s", 1.0), ("m", 60.0),
                              ("h", 3600.0), ("d", 86400.0)):
            if s.endswith(suffix):
                return float(s[: -len(suffix)]) * scale
        return float(s)
    except ValueError:
        return default


class EngineConfig:
    """Parsed etc/ directory (PrestoServer bootstrap analog)."""

    def __init__(self, props: Optional[Dict[str, str]] = None,
                 catalogs: Optional[Dict[str, Dict[str, str]]] = None):
        self.props = dict(props or {})
        self.catalogs = dict(catalogs or {})

    # -- typed accessors ----------------------------------------------------
    def bool(self, key: str, default: bool = False) -> bool:
        v = self.props.get(key)
        return default if v is None else v.lower() in ("true", "1", "yes")

    def int(self, key: str, default: int = 0) -> int:
        v = self.props.get(key)
        return default if v is None else int(v)

    def str(self, key: str, default: str = "") -> str:
        return self.props.get(key, default)

    def session_defaults(self) -> Dict[str, str]:
        """``session.<name>`` keys become session-property defaults."""
        return {
            k[len("session."):]: v
            for k, v in self.props.items()
            if k.startswith("session.")
        }

    def max_execution_time(self, default: float = 0.0) -> float:
        """``query.max-execution-time`` in seconds.  Default 0 = no
        deadline: a kill policy must be OPTED INTO — an unchanged
        config keeps the legacy behavior where long queries run to
        completion (the old 600s was only a long-poll bound, and
        silently turning it into a kill would fail every >10min query
        on upgrade)."""
        return parse_duration(self.props.get("query.max-execution-time"),
                              default)

    def max_queued_time(self, default: float = 600.0) -> float:
        """``query.max-queued-time`` in seconds: the resource-group
        admission wait bound (expiry = a FAILED statement, not a
        hang)."""
        return parse_duration(self.props.get("query.max-queued-time"),
                              default)

    def query_log_path(self) -> Optional[str]:
        """Path for the JSONL query log (``query.log-path``); None
        disables the sink."""
        v = self.props.get("query.log-path")
        return v if v and v.strip() not in ("0", "false") else None

    def program_cache_dir(self) -> Optional[str]:
        """Directory for the persistent XLA program cache
        (``query.program-cache-dir``; ``0``/``false`` disables).  With
        no explicit key, defaults under the warehouse root when a
        warehouse catalog is configured — compiled query programs are
        engine state and live with the data they serve."""
        v = self.props.get("query.program-cache-dir")
        if v is not None:
            return None if v.strip() in ("", "0", "false") else v
        for props in self.catalogs.values():
            if (props.get("connector.name") == "warehouse"
                    and props.get("warehouse.root")):
                return os.path.join(props["warehouse.root"],
                                    ".xla-program-cache")
        return None

    # -- loading ------------------------------------------------------------
    @classmethod
    def from_etc(cls, etc_dir: str) -> "EngineConfig":
        props = {}
        cfg = os.path.join(etc_dir, "config.properties")
        if os.path.exists(cfg):
            props.update(load_properties(cfg))
        node = os.path.join(etc_dir, "node.properties")
        if os.path.exists(node):
            props.update(load_properties(node))
        catalogs = {}
        catdir = os.path.join(etc_dir, "catalog")
        if os.path.isdir(catdir):
            for fn in sorted(os.listdir(catdir)):
                if fn.endswith(".properties"):
                    catalogs[fn[:-len(".properties")]] = load_properties(
                        os.path.join(catdir, fn))
        return cls(props, catalogs)

    # -- materialization ----------------------------------------------------
    def build_catalog(self, plugin_manager=None):
        """Instantiate connectors from the catalog property files
        (PluginManager + ConnectorFactory analog, keyed by
        ``connector.name``).  Unknown kinds resolve through the plugin
        manager (``plugin.dir`` in config.properties loads one)."""
        from presto_tpu.catalog import Catalog

        if plugin_manager is None and self.props.get("plugin.dir"):
            from presto_tpu.plugin import PluginManager

            plugin_manager = PluginManager()
            plugin_manager.load_directory(self.props["plugin.dir"])
        catalog = Catalog()
        for name, props in self.catalogs.items():
            kind = props.get("connector.name")
            if kind in _BUILTIN_CONNECTORS:
                conn = _make_connector(kind, props)
            elif plugin_manager is not None and kind in plugin_manager.connector_factories:
                conn = plugin_manager.make_connector(kind, props)
            else:
                raise ValueError(f"unknown connector.name: {kind!r}")
            catalog.register(name, conn)
        return catalog

    def build_session(self):
        from presto_tpu.session import Session

        props = self.session_defaults()
        # query.validate-plans: always-on static plan validation (the
        # dotted key mirrors the reference's config namespace; it is
        # sugar for session.validate_plans)
        v = self.props.get("query.validate-plans")
        if v is not None and "validate_plans" not in props:
            props["validate_plans"] = v
        # query.validate-rewrites: per-rewrite soundness gating in the
        # iterative optimizer (same sugar shape as validate-plans)
        v = self.props.get("query.validate-rewrites")
        if v is not None and "validate_rewrites" not in props:
            props["validate_rewrites"] = v
        # query.validate-kernels: expression-tier kernel-soundness
        # gating (same sugar shape as validate-plans)
        v = self.props.get("query.validate-kernels")
        if v is not None and "validate_kernels" not in props:
            props["validate_kernels"] = v
        # query.task-concurrency / query.task-prefetch: morsel split
        # scheduler defaults (dotted keys mirror the reference's
        # task.concurrency config; sugar for session.task_*)
        v = self.props.get("query.task-concurrency")
        if v is not None and "task_concurrency" not in props:
            props["task_concurrency"] = v
        v = self.props.get("query.task-prefetch")
        if v is not None and "task_prefetch" not in props:
            props["task_prefetch"] = v
        # query.result-cache-enabled / query.subplan-cache-enabled:
        # serving-tier cache defaults (docs/serving.md; sugar for
        # session.result_cache_enabled / session.subplan_cache_enabled)
        v = self.props.get("query.result-cache-enabled")
        if v is not None and "result_cache_enabled" not in props:
            props["result_cache_enabled"] = v
        v = self.props.get("query.subplan-cache-enabled")
        if v is not None and "subplan_cache_enabled" not in props:
            props["subplan_cache_enabled"] = v
        return Session(properties=props)

    # -- serving tier (admission + caches; docs/serving.md) -----------------
    def result_cache_bytes(self, default: int = 0) -> int:
        """``query.result-cache-bytes``: byte budget for the structural
        result cache (0 = the process default, 64 MiB or
        PRESTO_TPU_RESULT_CACHE_BYTES)."""
        return self.int("query.result-cache-bytes", default)

    def admission_memory_fraction(self, default: float = 0.9) -> float:
        """``query.admission-memory-fraction``: a query dispatches only
        while pool reserved + its projected bytes stay under this
        fraction of the pool limit (<= 0 disables the memory gate)."""
        v = self.props.get("query.admission-memory-fraction")
        if v is None:
            return default
        try:
            return float(v)
        except ValueError:
            return default

    def admission_reserve_bytes(self, default: int = 0) -> int:
        """``query.admission-reserve-bytes``: the memory projection for
        a statement with no observed history (0 = admit on the
        fraction gate alone)."""
        return self.int("query.admission-reserve-bytes", default)


_BUILTIN_CONNECTORS = ("tpch", "tpcds", "memory", "blackhole", "jdbc",
                       "localfile", "pcf", "rgf", "warehouse", "shardstore",
                       "remote", "stream", "kv", "metrics", "http")


def _make_connector(kind: Optional[str], props: Dict[str, str]):
    if kind == "tpch":
        from presto_tpu.connectors.tpch import Tpch

        return Tpch(
            sf=float(props.get("tpch.scale-factor", "0.01")),
            split_rows=int(props.get("tpch.split-rows", str(1 << 20))),
        )
    if kind == "tpcds":
        from presto_tpu.connectors.tpcds import Tpcds

        return Tpcds(sf=float(props.get("tpcds.scale-factor", "0.01")))
    if kind == "memory":
        from presto_tpu.connectors.memory import MemoryConnector

        return MemoryConnector()
    if kind == "blackhole":
        from presto_tpu.connectors.blackhole import BlackholeConnector

        return BlackholeConnector()
    if kind == "jdbc":
        from presto_tpu.connectors.jdbc import JdbcConnector

        return JdbcConnector.sqlite(props["jdbc.path"])
    if kind == "localfile":
        import json as _json

        from presto_tpu.connectors.localfile import LocalFileConnector

        conn = LocalFileConnector()
        with open(props["localfile.catalog"]) as f:
            for t in _json.load(f):  # [{name, path, format, schema}, ...]
                conn.add_table(t["name"], t["path"], t["format"],
                               [tuple(cs) for cs in t["schema"]])
        return conn
    if kind == "pcf":
        from presto_tpu.storage.pcf import PcfConnector

        return PcfConnector(props["pcf.root"])
    if kind == "rgf":
        from presto_tpu.storage.rgf import RgfConnector

        return RgfConnector(
            props["rgf.root"],
            split_bytes=int(props.get("rgf.split-bytes", str(1 << 22))))
    if kind == "warehouse":
        from presto_tpu.storage.warehouse import WarehouseConnector

        return WarehouseConnector(props["warehouse.root"])
    if kind == "shardstore":
        from presto_tpu.storage.shardstore import ShardStoreConnector

        nodes = [n.strip() for n in
                 props.get("shardstore.nodes", "node0").split(",")]
        return ShardStoreConnector(
            props["shardstore.root"], nodes=nodes,
            max_shard_rows=int(props.get("shardstore.max-shard-rows",
                                         str(1 << 20))),
            backup_root=props.get("shardstore.backup-root"))
    if kind == "remote":
        from presto_tpu.connectors.remote import RemoteConnector

        return RemoteConnector(props["remote.uri"])
    if kind == "stream":
        import json as _json

        from presto_tpu.connectors.stream import LogBroker, StreamConnector

        with open(props["stream.table-descriptions"]) as f:
            desc = _json.load(f)
        return StreamConnector(LogBroker(props["stream.root"]), desc)
    if kind == "kv":
        import json as _json

        from presto_tpu.connectors.stream import KvConnector

        with open(props["kv.table-descriptions"]) as f:
            desc = _json.load(f)
        return KvConnector(props["kv.path"], desc)
    if kind == "metrics":
        from presto_tpu.connectors.metrics import MetricsConnector

        return MetricsConnector()
    if kind == "http":
        from presto_tpu.connectors.http import HttpConnector

        return HttpConnector(catalog_uri=props["http.catalog-uri"])
    raise ValueError(f"unknown connector.name: {kind!r}")
