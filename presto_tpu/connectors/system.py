"""System tables: engine state queryable as SQL.

Reference analog: the system connector in
``presto-main/.../connector/system/`` — system.runtime.queries /
system.runtime.nodes fed by the coordinator's live state.  Tables here
are flat-named (``system_runtime_queries``...) and draw from a query
history recorded via the event-listener pipeline.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.events import EventListener, QueryCompletedEvent
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, Type


class QueryHistory(EventListener):
    """Accumulates completed-query summaries (QueryMonitor sink)."""

    def __init__(self, limit: int = 1000):
        self.completed: List[QueryCompletedEvent] = []
        self.limit = limit

    def query_completed(self, e: QueryCompletedEvent) -> None:
        self.completed.append(e)
        if len(self.completed) > self.limit:
            self.completed.pop(0)


class SystemConnector:
    """system_runtime_queries + system_runtime_nodes."""

    def __init__(self, history: QueryHistory, nodes: Optional[Callable[[], List[dict]]] = None):
        self.history = history
        self.nodes = nodes or (lambda: [{"node_id": "local", "state": "ACTIVE"}])

    SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
        "system_runtime_queries": [
            ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
            ("rows", BIGINT), ("wall_seconds", DOUBLE), ("query", VARCHAR),
        ],
        "system_runtime_nodes": [
            ("node_id", VARCHAR), ("state", VARCHAR),
        ],
    }

    def table_names(self) -> List[str]:
        return list(self.SCHEMAS.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self.SCHEMAS[table]

    def num_splits(self, table: str) -> int:
        return 1

    def row_count(self, table: str) -> int:
        if table == "system_runtime_queries":
            return len(self.history.completed)
        return len(self.nodes())

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        if table == "system_runtime_queries":
            evs = list(self.history.completed)
            cols: List[List] = [
                [e.query_id for e in evs],
                [e.state for e in evs],
                [e.user for e in evs],
                [e.rows for e in evs],
                [e.end_time - e.create_time for e in evs],
                [e.sql.strip()[:200] for e in evs],
            ]
        else:
            ns = self.nodes()
            cols = [[n["node_id"] for n in ns], [n["state"] for n in ns]]
        schema = self.SCHEMAS[table]
        arrays, dicts = [], []
        for vals, (_, t) in zip(cols, schema):
            if t.is_string:
                d = Dictionary(sorted(set(vals)))
                arrays.append(np.asarray([d.code_of(v) for v in vals], dtype=np.int32))
                dicts.append(d)
            else:
                arrays.append(np.asarray(vals, dtype=t.np_dtype))
                dicts.append(None)
        n = len(cols[0])
        return Page.from_arrays(
            arrays, [t for _, t in schema], dictionaries=dicts, capacity=max(n, 1)
        )
