"""System tables: engine state queryable as SQL.

Reference analog: the system connector in
``presto-main/.../connector/system/`` — system.runtime.queries /
system.runtime.nodes fed by the coordinator's live state.  Tables here
are flat-named (``system_runtime_queries``...) and draw from a query
history recorded via the event-listener pipeline.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.events import EventListener, QueryCompletedEvent
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, Type


class QueryHistory(EventListener):
    """Accumulates completed-query summaries (QueryMonitor sink)."""

    def __init__(self, limit: int = 1000):
        self.completed: List[QueryCompletedEvent] = []
        self.limit = limit

    def query_completed(self, e: QueryCompletedEvent) -> None:
        self.completed.append(e)
        if len(self.completed) > self.limit:
            self.completed.pop(0)


class SystemConnector:
    """system_runtime_queries + system_runtime_nodes +
    system_runtime_tasks + system_metrics — the engine observing
    itself in SQL (the reference's system connector + jmx tables)."""

    def __init__(self, history: QueryHistory,
                 nodes: Optional[Callable[[], List[dict]]] = None,
                 metrics=None, tasks=None):
        from presto_tpu.obs import METRICS, TASKS

        self.history = history
        self.nodes = nodes or (lambda: [{"node_id": "local", "state": "ACTIVE"}])
        # default to the process-wide registries (obs/metrics.py) —
        # injectable for tests
        self.metrics = metrics if metrics is not None else METRICS
        self.tasks = tasks if tasks is not None else TASKS

    SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
        "system_runtime_queries": [
            ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
            ("rows", BIGINT), ("wall_seconds", DOUBLE), ("query", VARCHAR),
            # distributed-tier observability: stage count of the mesh /
            # multi-host run and the fallback reason when the query
            # silently ran locally instead (VERDICT weak #8 — silent
            # MultiHostUnsupported fallbacks must be countable:
            # SELECT count(*) FROM system_runtime_queries WHERE
            # dist_fallback IS NOT NULL)
            ("dist_stages", BIGINT), ("dist_fallback", VARCHAR),
            # lifecycle stage times from the obs span spine (NULL-safe:
            # compile_ms is NULL when the query did not trace)
            ("planning_ms", DOUBLE), ("compile_ms", DOUBLE),
            ("execution_ms", DOUBLE),
        ],
        "system_runtime_nodes": [
            ("node_id", VARCHAR), ("state", VARCHAR),
        ],
        "system_runtime_tasks": [
            ("task_id", VARCHAR), ("source", VARCHAR), ("state", VARCHAR),
            ("trace_token", VARCHAR), ("elapsed_ms", DOUBLE),
            ("rows", BIGINT),
        ],
        "system_metrics": [
            ("name", VARCHAR), ("value", DOUBLE),
        ],
    }

    def table_names(self) -> List[str]:
        return list(self.SCHEMAS.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self.SCHEMAS[table]

    def num_splits(self, table: str) -> int:
        return 1

    def row_count(self, table: str) -> int:
        if table == "system_runtime_queries":
            return len(self.history.completed)
        if table == "system_runtime_tasks":
            return len(self.tasks.entries())
        if table == "system_metrics":
            return len(self.metrics.snapshot())
        return len(self.nodes())

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        if table == "system_runtime_queries":
            evs = list(self.history.completed)
            cols: List[List] = [
                [e.query_id for e in evs],
                [e.state for e in evs],
                [e.user for e in evs],
                [e.rows for e in evs],
                [e.end_time - e.create_time for e in evs],
                [e.sql.strip()[:200] for e in evs],
                [e.dist_stages for e in evs],
                [e.dist_fallback for e in evs],
                [getattr(e, "planning_ms", None) for e in evs],
                [getattr(e, "compile_ms", None) for e in evs],
                [getattr(e, "execution_ms", None) for e in evs],
            ]
        elif table == "system_runtime_tasks":
            ts = self.tasks.entries()
            cols = [
                [t.task_id for t in ts],
                [t.source for t in ts],
                [t.state for t in ts],
                [t.trace_token for t in ts],
                [t.elapsed_ms for t in ts],
                [t.rows for t in ts],
            ]
        elif table == "system_metrics":
            snap = self.metrics.snapshot()
            cols = [[n for n, _ in snap], [float(v) for _, v in snap]]
        else:
            ns = self.nodes()
            cols = [[n["node_id"] for n in ns], [n["state"] for n in ns]]
        schema = self.SCHEMAS[table]
        arrays, dicts, valids = [], [], []
        for vals, (_, t) in zip(cols, schema):
            valid = np.asarray([v is not None for v in vals], dtype=np.bool_)
            valids.append(valid)
            if t.is_string:
                # never an empty dictionary: an all-NULL column (every
                # query distributed fine) still needs a value for code 0
                d = Dictionary(sorted({v for v in vals if v is not None})
                               or [""])
                arrays.append(np.asarray(
                    [d.code_of(v) if v is not None else 0 for v in vals],
                    dtype=np.int32))
                dicts.append(d)
            else:
                arrays.append(np.asarray(
                    [v if v is not None else 0 for v in vals],
                    dtype=t.np_dtype))
                dicts.append(None)
        n = len(cols[0])
        # ladder capacity: the history length grows per query, and a
        # raw capacity here would bake one fresh XLA program per
        # history size (engine_lint raw-capacity rule)
        from presto_tpu.exec.local import bucket_capacity

        return Page.from_arrays(
            arrays, [t for _, t in schema], valids=valids,
            dictionaries=dicts, capacity=bucket_capacity(max(n, 1))
        )
