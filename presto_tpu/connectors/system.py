"""System tables: engine state queryable as SQL.

Reference analog: the system connector in
``presto-main/.../connector/system/`` — system.runtime.queries /
system.runtime.nodes fed by the coordinator's live state.  Tables here
are flat-named (``system_runtime_queries``...) and draw from a query
history recorded via the event-listener pipeline.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.events import EventListener, QueryCompletedEvent
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, Type


class QueryHistory(EventListener):
    """Accumulates completed-query summaries (QueryMonitor sink)."""

    def __init__(self, limit: int = 1000):
        self.completed: List[QueryCompletedEvent] = []
        self.limit = limit

    def query_completed(self, e: QueryCompletedEvent) -> None:
        self.completed.append(e)
        if len(self.completed) > self.limit:
            self.completed.pop(0)


def pool_row(node: str, pool) -> dict:
    """One system_memory_pools row for a live MemoryPool."""
    tags = pool.tags()
    return {
        "node": node,
        "reserved": int(pool.reserved),
        "peak": int(pool.peak),
        "limit": int(pool.limit),
        "queries": len({t.split("/", 1)[0] for t in tags}),
    }


class SystemConnector:
    """system_runtime_queries + system_runtime_nodes +
    system_runtime_tasks + system_metrics + system_memory_pools — the
    engine observing itself in SQL (the reference's system connector +
    jmx tables)."""

    def __init__(self, history: QueryHistory,
                 nodes: Optional[Callable[[], List[dict]]] = None,
                 metrics=None, tasks=None, remote_metrics=None,
                 remote_history=None,
                 pools: Optional[Callable[[], List[dict]]] = None,
                 workers: Optional[Callable[[], List[dict]]] = None,
                 node_id: str = "local"):
        from presto_tpu.obs import METRICS, TASKS

        self.history = history
        self.nodes = nodes or (lambda: [{"node_id": "local", "state": "ACTIVE"}])
        # default to the process-wide registries (obs/metrics.py) —
        # injectable for tests
        self.metrics = metrics if metrics is not None else METRICS
        self.tasks = tasks if tasks is not None else TASKS
        self.node_id = node_id
        # cluster fan-in: () -> {node: [(name, value), ...]} — the
        # coordinator wires CoordinatorServer.remote_metrics here so
        # system_metrics carries every worker's registry plus a
        # 'cluster' rollup row per metric (single-node processes skip
        # the rollup: it would just duplicate the local rows)
        self.remote_metrics = remote_metrics
        # cluster fan-in for the history ring:
        # () -> {node: [(ts_ms, name, value), ...]} — the coordinator
        # wires CoordinatorServer.remote_history here so
        # system_metrics_history carries every worker's ring
        self.remote_history = remote_history
        # () -> [{node, reserved, peak, limit, queries}] — defaults to
        # the process pool (memory.default_memory_pool)
        self.pools = pools
        # () -> failure-detector rows (parallel/failure.py snapshot):
        # the coordinator wires CoordinatorServer.worker_rows here so
        # system_runtime_workers shows detector state per worker
        self.workers = workers
        # one cluster poll per scan, not one per metadata call:
        # row_count (bind time) and page_for_split (execution) both
        # need the rows, and polling twice doubles the HTTP fan-out
        # AND risks the page disagreeing with the planned row count
        self._metrics_cache: Optional[Tuple[float, List]] = None
        self._history_cache: Optional[Tuple[float, List]] = None

    SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
        "system_runtime_queries": [
            ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
            ("rows", BIGINT), ("wall_seconds", DOUBLE), ("query", VARCHAR),
            # distributed-tier observability: stage count of the mesh /
            # multi-host run and the fallback reason when the query
            # silently ran locally instead (VERDICT weak #8 — silent
            # MultiHostUnsupported fallbacks must be countable:
            # SELECT count(*) FROM system_runtime_queries WHERE
            # dist_fallback IS NOT NULL)
            ("dist_stages", BIGINT), ("dist_fallback", VARCHAR),
            # lifecycle stage times from the obs span spine (NULL-safe:
            # compile_ms is NULL when the query did not trace)
            ("planning_ms", DOUBLE), ("compile_ms", DOUBLE),
            ("execution_ms", DOUBLE),
            # serving tier: 1 when the result came from the structural
            # result cache, 0 when executed, NULL where the cache does
            # not apply (writes, DDL, uncacheable plans)
            ("cache_hit", BIGINT),
            # admission-plane waits (serving/admission.py via the query
            # timeline): NULL when the query bypassed admission or
            # never blocked on memory headroom
            ("queued_ms", DOUBLE), ("memory_blocked_ms", DOUBLE),
        ],
        "system_runtime_nodes": [
            ("node_id", VARCHAR), ("state", VARCHAR),
        ],
        # worker fleet through the failure detector's eyes
        # (parallel/failure.py): detector state, failure streak, and
        # ms since the last successful heartbeat (NULL before the
        # first one — NULL-safe like every obs column)
        "system_runtime_workers": [
            ("node_id", VARCHAR), ("uri", VARCHAR), ("state", VARCHAR),
            ("consecutive_failures", BIGINT),
            ("last_heartbeat_ms", DOUBLE), ("last_error", VARCHAR),
        ],
        "system_runtime_tasks": [
            ("task_id", VARCHAR), ("source", VARCHAR), ("state", VARCHAR),
            ("trace_token", VARCHAR), ("elapsed_ms", DOUBLE),
            ("rows", BIGINT),
            # morsel split-scheduler footprint (exec/tasks.py; NULL for
            # tasks that never ran splits through it)
            ("splits", BIGINT), ("task_concurrency", BIGINT),
            ("scheduler_stall_ms", DOUBLE), ("prefetch_hits", BIGINT),
        ],
        "system_metrics": [
            ("node", VARCHAR), ("name", VARCHAR), ("value", DOUBLE),
        ],
        # the in-process metrics-history ring (obs/timeseries.py): one
        # row per (tick, metric) — gauges raw, counters as rates/s,
        # histograms as count-rates + p50/p95/p99.  ts_ms is epoch
        # milliseconds of the tick; the ring is bounded, so this table
        # is a sliding window, not an archive (docs/observability.md)
        "system_metrics_history": [
            ("node", VARCHAR), ("ts_ms", DOUBLE),
            ("name", VARCHAR), ("value", DOUBLE),
        ],
        # HBM pool accounting per node (memory/ClusterMemoryManager's
        # RemoteNodeMemory view as a table): reserved/peak/limit bytes
        # and the count of queries holding reservations ("limit" is a
        # parser keyword, hence the _bytes suffixes)
        "system_memory_pools": [
            ("node", VARCHAR), ("reserved_bytes", BIGINT),
            ("peak_bytes", BIGINT), ("limit_bytes", BIGINT),
            ("queries", BIGINT),
        ],
        # the plan-history store (obs/history.py): observed per-operator
        # actuals retained ACROSS queries, keyed by the stable
        # structural node signature.  ratio_last is the last run's
        # estimate-vs-actual factor (>= 1.0, NULL before any estimate
        # was comparable); a warehouse-backed store survives restarts
        "system_plan_history": [
            ("node_type", VARCHAR), ("digest", VARCHAR),
            ("observations", BIGINT), ("rows_mean", DOUBLE),
            ("rows_last", BIGINT), ("est_last", DOUBLE),
            ("ratio_last", DOUBLE), ("peak_bytes_max", BIGINT),
        ],
    }

    def table_names(self) -> List[str]:
        return list(self.SCHEMAS.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self.SCHEMAS[table]

    def num_splits(self, table: str) -> int:
        return 1

    def row_count(self, table: str) -> int:
        if table == "system_runtime_queries":
            return len(self.history.completed)
        if table == "system_runtime_tasks":
            return len(self.tasks.entries())
        if table == "system_metrics":
            return len(self._metrics_rows())
        if table == "system_metrics_history":
            return len(self._history_rows())
        if table == "system_memory_pools":
            return len(self._pool_rows())
        if table == "system_runtime_workers":
            return len(self._worker_rows())
        if table == "system_plan_history":
            return len(self._plan_history_rows())
        return len(self.nodes())

    @staticmethod
    def _plan_history_rows() -> List[dict]:
        from presto_tpu.obs.history import default_history

        # stable order: a bind-time row count and the executed page
        # must agree even if observations land in between — snapshot
        # sorted by key and let the count clamp (same contract as the
        # other live tables)
        return sorted(default_history().rows(),
                      key=lambda e: (e["node"], e["digest"]))

    def _worker_rows(self) -> List[dict]:
        if self.workers is None:
            return []
        try:
            return list(self.workers())
        except Exception:
            return []  # a wedged detector must not fail the table

    def _metrics_rows(self) -> List[Tuple[str, str, float]]:
        """(node, name, value) across the cluster: local registry rows,
        every polled worker's rows, and — when remote nodes exist — a
        'cluster' rollup summing each metric over all nodes.  The
        cluster poll is cached for ~1s so the bind-time row count and
        the executed page see ONE consistent snapshot (local-only
        snapshots are cheap and always fresh)."""
        import time

        from presto_tpu.obs.openmetrics import merge_rows

        if self.remote_metrics is not None and self._metrics_cache \
                and time.monotonic() - self._metrics_cache[0] < 1.0:
            return self._metrics_cache[1]
        per_node = {self.node_id: list(self.metrics.snapshot())}
        if self.remote_metrics is not None:
            try:
                for node, rows in self.remote_metrics().items():
                    per_node[node] = [(n, float(v)) for n, v in rows]
            except Exception:
                pass  # a dead worker must not fail the system table
        out = [(node, n, float(v))
               for node in sorted(per_node)
               for n, v in per_node[node]]
        if len(per_node) > 1:
            out += [("cluster", n, v) for n, v in merge_rows(per_node)]
        if self.remote_metrics is not None:
            self._metrics_cache = (time.monotonic(), out)
        return out

    def _history_rows(self) -> List[Tuple[str, float, str, float]]:
        """(node, ts_ms, name, value) from the local metrics-history
        ring plus every polled worker's ring.  Same ~1s cache contract
        as _metrics_rows: bind-time row count and the executed page
        must see ONE snapshot when a cluster poll is involved."""
        import time

        from presto_tpu.obs.timeseries import HISTORY

        if self.remote_history is not None and self._history_cache \
                and time.monotonic() - self._history_cache[0] < 1.0:
            return self._history_cache[1]
        out = [(self.node_id, float(ts), n, float(v))
               for ts, n, v in HISTORY.rows()]
        if self.remote_history is not None:
            try:
                for node, rows in self.remote_history().items():
                    out += [(str(node), float(ts), str(n), float(v))
                            for ts, n, v in rows]
            except Exception:
                pass  # a dead worker must not fail the system table
            self._history_cache = (time.monotonic(), out)
        return out

    def _pool_rows(self) -> List[dict]:
        if self.pools is not None:
            try:
                rows = list(self.pools())
                if rows:
                    return rows
            except Exception:
                pass  # fall through to the process pool
        from presto_tpu.memory import default_memory_pool

        return [pool_row(self.node_id, default_memory_pool())]

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        if table == "system_runtime_queries":
            evs = list(self.history.completed)
            cols: List[List] = [
                [e.query_id for e in evs],
                [e.state for e in evs],
                [e.user for e in evs],
                [e.rows for e in evs],
                [e.end_time - e.create_time for e in evs],
                [e.sql.strip()[:200] for e in evs],
                [e.dist_stages for e in evs],
                [e.dist_fallback for e in evs],
                [getattr(e, "planning_ms", None) for e in evs],
                [getattr(e, "compile_ms", None) for e in evs],
                [getattr(e, "execution_ms", None) for e in evs],
                [None if getattr(e, "cache_hit", None) is None
                 else int(e.cache_hit) for e in evs],
                [getattr(e, "queued_ms", None) for e in evs],
                [getattr(e, "memory_blocked_ms", None) for e in evs],
            ]
        elif table == "system_runtime_tasks":
            ts = self.tasks.entries()
            cols = [
                [t.task_id for t in ts],
                [t.source for t in ts],
                [t.state for t in ts],
                [t.trace_token for t in ts],
                [t.elapsed_ms for t in ts],
                [t.rows for t in ts],
                [t.splits for t in ts],
                [t.concurrency for t in ts],
                [t.stall_ms for t in ts],
                [t.prefetch_hits for t in ts],
            ]
        elif table == "system_metrics":
            snap = self._metrics_rows()
            cols = [[node for node, _, _ in snap],
                    [n for _, n, _ in snap],
                    [float(v) for _, _, v in snap]]
        elif table == "system_metrics_history":
            hist = self._history_rows()
            cols = [[node for node, _, _, _ in hist],
                    [float(ts) for _, ts, _, _ in hist],
                    [n for _, _, n, _ in hist],
                    [float(v) for _, _, _, v in hist]]
        elif table == "system_memory_pools":
            ps = self._pool_rows()
            cols = [
                [p["node"] for p in ps],
                [int(p["reserved"]) for p in ps],
                [int(p["peak"]) for p in ps],
                [int(p["limit"]) for p in ps],
                [int(p["queries"]) for p in ps],
            ]
        elif table == "system_plan_history":
            hs = self._plan_history_rows()
            cols = [
                [h["node"] for h in hs],
                [h["digest"] for h in hs],
                [int(h["n"]) for h in hs],
                [float(h["rows_mean"]) for h in hs],
                [int(h["rows_last"]) for h in hs],
                [None if h.get("est_last") is None
                 else float(h["est_last"]) for h in hs],
                [None if h.get("ratio_last") is None
                 else float(h["ratio_last"]) for h in hs],
                [int(h.get("peak_bytes_max", 0)) for h in hs],
            ]
        elif table == "system_runtime_workers":
            ws = self._worker_rows()
            cols = [
                [w.get("node_id") for w in ws],
                [w.get("uri") for w in ws],
                [w.get("state") for w in ws],
                [w.get("consecutive_failures") for w in ws],
                [w.get("last_heartbeat_ms") for w in ws],
                [w.get("last_error") for w in ws],
            ]
        else:
            ns = self.nodes()
            cols = [[n["node_id"] for n in ns], [n["state"] for n in ns]]
        schema = self.SCHEMAS[table]
        arrays, dicts, valids = [], [], []
        for vals, (_, t) in zip(cols, schema):
            valid = np.asarray([v is not None for v in vals], dtype=np.bool_)
            valids.append(valid)
            if t.is_string:
                # never an empty dictionary: an all-NULL column (every
                # query distributed fine) still needs a value for code 0
                d = Dictionary(sorted({v for v in vals if v is not None})
                               or [""])
                arrays.append(np.asarray(
                    [d.code_of(v) if v is not None else 0 for v in vals],
                    dtype=np.int32))
                dicts.append(d)
            else:
                arrays.append(np.asarray(
                    [v if v is not None else 0 for v in vals],
                    dtype=t.np_dtype))
                dicts.append(None)
        n = len(cols[0])
        # ladder capacity: the history length grows per query, and a
        # raw capacity here would bake one fresh XLA program per
        # history size (engine_lint raw-capacity rule)
        from presto_tpu.exec.local import bucket_capacity

        return Page.from_arrays(
            arrays, [t for _, t in schema], valids=valids,
            dictionaries=dicts, capacity=bucket_capacity(max(n, 1))
        )
