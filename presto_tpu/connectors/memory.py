"""In-memory (HBM-resident) table connector.

Reference analog: ``presto-memory`` (worker-RAM tables,
``presto-memory/src/main/java/com/facebook/presto/plugin/memory/``).
Tables are lists of device-resident Pages; loading from another
connector is the CTAS path.  Used by benchmarks to measure pure device
execution without per-run host data generation.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.page import Page
from presto_tpu.types import Type


class MemoryConnector:
    def __init__(self):
        self._tables: Dict[str, List[Page]] = {}
        self._schemas: Dict[str, List[Tuple[str, Type]]] = {}
        self._domains: Dict[str, Dict[str, Optional[Tuple[int, int]]]] = {}
        self._pks: Dict[str, Optional[List[str]]] = {}
        self._sort: Dict[str, Optional[List[str]]] = {}
        self._bucketing: Dict[str, Optional[tuple]] = {}
        self._dicts: Dict[str, Dict[str, object]] = {}
        # monotonically increasing per-table data version, bumped by
        # EVERY mutation (CTAS/INSERT/DELETE-rewrite/DDL) — the serving
        # tier's cache-invalidation token (serving/cache.py); one shared
        # counter so a drop+recreate can never repeat an old number,
        # paired with a per-INSTANCE token so two connectors holding
        # same-named, same-shaped tables with different data can never
        # alias each other's cache entries
        import uuid as _uuid

        self._instance_id = _uuid.uuid4().hex[:12]
        self._versions: Dict[str, int] = {}
        self._version_seq = 0

    def _bump_version(self, name: str) -> None:
        self._version_seq += 1
        self._versions[name] = self._version_seq

    def table_version(self, name: str):
        """Current data version: (instance token, counter); the counter
        is 0 until the first write through this connector instance."""
        return (self._instance_id, self._versions.get(name, 0))

    # -- loading ------------------------------------------------------------
    def create_table(
        self,
        name: str,
        schema: Sequence[Tuple[str, Type]],
        pages: Sequence[Page],
        domains: Optional[Dict[str, Tuple[int, int]]] = None,
        primary_key: Optional[List[str]] = None,
        sort_order: Optional[List[str]] = None,
        bucketing: Optional[tuple] = None,
    ) -> None:
        self._tables[name] = [_to_device(p) for p in pages]
        self._schemas[name] = list(schema)
        self._domains[name] = dict(domains or {})
        self._pks[name] = primary_key
        self._sort[name] = list(sort_order) if sort_order else None
        self._bucketing[name] = bucketing
        self._dicts[name] = {}
        for page in pages[:1]:
            for (col, t), b in zip(schema, page.blocks):
                if t.is_string:
                    self._dicts[name][col] = b.dictionary
        self._bump_version(name)

    def append_pages(self, name: str, pages: Sequence[Page]) -> None:
        self._tables[name].extend(_to_device(p) for p in pages)
        self._bump_version(name)

    def drop_table(self, name: str) -> None:
        for d in (self._tables, self._schemas, self._domains, self._pks,
                  self._sort, self._bucketing, self._dicts):
            d.pop(name, None)
        self._bump_version(name)

    def add_column(self, name: str, column: str, ctype: Type) -> None:
        """ALTER TABLE ADD COLUMN: existing rows read NULL in the new
        column (MemoryMetadata.addColumn analog — the reference's
        memory connector rejects this; hive-style NULL backfill here)."""
        import jax.numpy as jnp

        from presto_tpu.page import Block, Dictionary

        if any(c == column for c, _ in self._schemas[name]):
            raise ValueError(f"column {column} already exists in {name}")
        self._schemas[name] = list(self._schemas[name]) + [(column, ctype)]
        # dictionary-coded string columns get an empty dictionary so
        # downstream decode paths stay total (raw_varchar/varbinary are
        # value-carrying and take none)
        dic = (Dictionary([])
               if ctype.is_string and not ctype.is_raw_string else None)
        if dic is not None:
            self._dicts.setdefault(name, {})[column] = dic
        new_pages = []
        for p in self._tables[name]:
            data = jnp.zeros((p.capacity,) + ctype.value_shape,
                             dtype=ctype.np_dtype)
            blk = Block(data, jnp.zeros((p.capacity,), dtype=jnp.bool_),
                        ctype, dic)
            new_pages.append(Page(tuple(p.blocks) + (blk,), p.row_mask))
        self._tables[name] = new_pages
        self._bump_version(name)

    def drop_column(self, name: str, column: str) -> None:
        idxs = [i for i, (c, _) in enumerate(self._schemas[name])
                if c != column]
        if len(idxs) == len(self._schemas[name]):
            raise ValueError(f"column {column} not found in {name}")
        if not idxs:
            raise ValueError("cannot drop the only column")
        self._schemas[name] = [self._schemas[name][i] for i in idxs]
        self._tables[name] = [
            Page(tuple(p.blocks[i] for i in idxs), p.row_mask)
            for p in self._tables[name]
        ]
        self._domains.get(name, {}).pop(column, None)
        self._dicts.get(name, {}).pop(column, None)
        # planner metadata referencing the dropped column is void
        if self._pks.get(name) and column in self._pks[name]:
            self._pks[name] = None
        if self._sort.get(name) and column in self._sort[name]:
            self._sort[name] = None
        bk = self._bucketing.get(name)
        if bk is not None and column in bk[0]:
            self._bucketing[name] = None
        self._bump_version(name)

    def rename_table(self, name: str, new_name: str) -> None:
        if new_name in self._tables:
            raise ValueError(f"table {new_name} already exists")
        for d in (self._tables, self._schemas, self._domains, self._pks,
                  self._sort, self._bucketing, self._dicts):
            if name in d:
                d[new_name] = d.pop(name)
        self._bump_version(name)
        self._bump_version(new_name)

    def load_from(self, conn, table: str, name: Optional[str] = None,
                  columns: Optional[List[str]] = None) -> None:
        """Copy a table from another connector onto the device (CTAS).
        ``columns`` prunes to the listed columns."""
        name = name or table
        schema = conn.schema(table)
        keep = [i for i, (c, _) in enumerate(schema)
                if columns is None or c in columns]
        pages = []
        for s in range(conn.num_splits(table)):
            p = conn.page_for_split(table, s)
            pages.append(Page(tuple(p.blocks[i] for i in keep), p.row_mask))
        pruned_schema = [schema[i] for i in keep]
        domains = {}
        if hasattr(conn, "column_domain"):
            for c, _ in pruned_schema:
                domains[c] = conn.column_domain(table, c)
        pk = conn.primary_key(table) if hasattr(conn, "primary_key") else None
        if pk is not None and any(c not in [n for n, _ in pruned_schema] for c in pk):
            pk = None
        so = conn.sort_order(table) if hasattr(conn, "sort_order") else None
        if so is not None and any(c not in [n for n, _ in pruned_schema] for c in so):
            so = None
        bk = conn.bucketing(table) if hasattr(conn, "bucketing") else None
        if bk is not None and any(c not in [n for n, _ in pruned_schema] for c in bk[0]):
            bk = None
        self.create_table(name, pruned_schema, pages, domains, pk,
                          sort_order=so, bucketing=bk)

    # -- connector protocol -------------------------------------------------
    def table_names(self) -> List[str]:
        return list(self._tables.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self._schemas[table]

    def num_splits(self, table: str) -> int:
        return len(self._tables[table])

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        return self._tables[table][split]

    def row_count(self, table: str) -> int:
        import numpy as np

        return sum(int(np.asarray(p.num_rows())) for p in self._tables[table])

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        return self._domains.get(table, {}).get(column)

    def primary_key(self, table: str) -> Optional[List[str]]:
        return self._pks.get(table)

    def sort_order(self, table: str) -> Optional[List[str]]:
        """Declared physical ordering of the stored pages (feeds the
        streaming-aggregation path; ConnectorMetadata local-properties
        analog)."""
        return self._sort.get(table)

    def bucketing(self, table: str) -> Optional[tuple]:
        """(bucket_columns, alignment_token, bucket_count): split index
        is the bucket id (ConnectorNodePartitioningProvider analog)."""
        return self._bucketing.get(table)

    def dictionary_for(self, table: str, column: str):
        return self._dicts.get(table, {}).get(column)

    def max_split_rows(self, table: str) -> int:
        return max(p.capacity for p in self._tables[table])

    # -- transactions --------------------------------------------------------
    # Reference: ConnectorMetadata transaction hooks driven by
    # transaction/TransactionManager.java.  Writes stage on the handle
    # and publish atomically at commit (read-committed; no
    # read-your-writes inside an open transaction).

    def begin_transaction(self):
        return _MemoryTx()

    def commit_transaction(self, tx: "_MemoryTx") -> None:
        for op, args in tx.ops:
            getattr(self, op)(*args)

    def rollback_transaction(self, tx: "_MemoryTx") -> None:
        tx.ops.clear()

    def stage(self, tx: "_MemoryTx", op: str, *args) -> None:
        """Record a write to apply at commit (op = method name)."""
        tx.ops.append((op, args))


class _MemoryTx:
    """Staged write list (ConnectorTransactionHandle analog)."""

    def __init__(self):
        self.ops: List[tuple] = []


# PRESTO_TPU_PAD_LOAD=0 disables write-time ladder padding; resolved
# once per process (engine_lint env-read rule: _to_device runs per
# stored page), set_pad_load overrides for tests.
from presto_tpu.envflag import EnvFlag

_PAD_LOAD = EnvFlag("PRESTO_TPU_PAD_LOAD", default=True)
_pad_load_enabled = _PAD_LOAD


def set_pad_load(value) -> None:
    """Override hook (None re-resolves from the environment)."""
    _PAD_LOAD.set(value)


def _to_device(page: Page):
    """Pin a page's arrays in HBM once at write time — compacted result
    pages arrive numpy-backed (page.compact_host), and storing them
    as-is would re-pay the host->device transfer on every later scan.
    Pages also pad to pow2 capacity HERE, once: scan-time padding
    (exec/local.pad_page_pow2) costs a ~50ms device concat per ragged
    page per execution, so resident tables pre-pay it at load."""
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.page import Block

    from presto_tpu.exec.local import bucket_capacity

    cap = page.capacity
    tgt = bucket_capacity(cap)
    if tgt > cap and _pad_load_enabled():
        def padded(a):
            a = np.asarray(a)
            out = np.zeros((tgt,) + a.shape[1:], dtype=a.dtype)
            out[:cap] = a
            return out

        page = Page(
            tuple(Block(padded(b.data), padded(b.valid), b.type,
                        b.dictionary) for b in page.blocks),
            padded(page.row_mask))
    if not any(isinstance(b.data, np.ndarray) for b in page.blocks):
        return page
    return Page(
        tuple(
            Block(jnp.asarray(b.data), jnp.asarray(b.valid), b.type, b.dictionary)
            for b in page.blocks
        ),
        jnp.asarray(page.row_mask),
    )
