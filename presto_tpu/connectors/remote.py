"""Remote table service: any process can serve tables to the engine
over a small HTTP + binary-page protocol.

Reference analog: ``presto-thrift-connector`` (+ ``presto-thrift-api``,
``presto-thrift-testing-server``) — a connector whose backend is any
external service implementing ``PrestoThriftService`` (listTables /
getTableMetadata / getSplits / getRows), letting teams expose bespoke
storage to the engine without writing a connector.  Here the service
interface is HTTP endpoints speaking the engine's deduplicated binary
page frame (``server/serde.py``) instead of Thrift structs:

    GET  /v1/svc/tables                      table list (JSON)
    GET  /v1/svc/{table}/meta                schema / counts / dicts /
                                             index capability (JSON)
    GET  /v1/svc/{table}/stats/{split}       split min-max stats (JSON)
    GET  /v1/svc/{table}/page/{split}        one split (binary page)
    POST /v1/svc/{table}/index_lookup        point fetch (binary page)

``TableServiceServer`` turns ANY object satisfying the duck-typed
connector SPI into such a service (the testing-server analog);
``RemoteConnector`` is the engine-side client.  Dictionaries ride the
meta response once and are pinned client-side, so binary pages carry
only codes (the r3 deduplicated wire format).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.page import Dictionary, Page
from presto_tpu.server.serde import (deserialize_page, encode_page_batch,
                                     parse_page_batch, serialize_page,
                                     type_from_json, type_to_json)
from presto_tpu.types import Type


class TableServiceServer:
    """Serve a {name: connector} mapping as a remote table service."""

    def __init__(self, backings: Dict[str, object], host: str = "127.0.0.1",
                 port: int = 0):
        self._backings = dict(backings)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code: int = 200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _binary(self, body: bytes):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-presto-page")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _resolve(self, table: str):
                for conn in outer._backings.values():
                    if table in conn.table_names():
                        return conn
                return None

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                try:
                    if parts[:2] != ["v1", "svc"]:
                        return self._json({"error": "not found"}, 404)
                    if parts[2:] == ["tables"]:
                        names: List[str] = []
                        for conn in outer._backings.values():
                            names.extend(conn.table_names())
                        return self._json(sorted(set(names)))
                    table = urllib.parse.unquote(parts[2])
                    conn = self._resolve(table)
                    if conn is None:
                        return self._json({"error": "no such table"}, 404)
                    if parts[3:] == ["meta"]:
                        schema = conn.schema(table)
                        dicts = {}
                        if hasattr(conn, "dictionary_for"):
                            for c, t in schema:
                                if t.is_string and not t.is_raw_string:
                                    d = conn.dictionary_for(table, c)
                                    if d is not None:
                                        dicts[c] = list(d.values)
                        domains = {}
                        if hasattr(conn, "column_domain"):
                            for c, _ in schema:
                                dom = conn.column_domain(table, c)
                                if dom is not None:
                                    domains[c] = list(dom)
                        return self._json({
                            "schema": [[c, type_to_json(t)] for c, t in schema],
                            "num_splits": conn.num_splits(table),
                            "row_count": conn.row_count(table)
                            if hasattr(conn, "row_count") else None,
                            "dictionaries": dicts,
                            "domains": domains,
                            "has_stats": hasattr(conn, "split_stats"),
                            "has_index": hasattr(conn, "index_lookup"),
                        })
                    if len(parts) == 5 and parts[3] == "stats":
                        if not hasattr(conn, "split_stats"):
                            return self._json({})
                        st = conn.split_stats(table, int(parts[4]))
                        return self._json({c: list(v) for c, v in st.items()})
                    if len(parts) == 5 and parts[3] == "page":
                        page = conn.page_for_split(table, int(parts[4]))
                        return self._binary(serialize_page(page))
                    return self._json({"error": "not found"}, 404)
                except Exception as e:  # surface backend errors to client
                    return self._json({"error": repr(e)}, 500)

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                try:
                    if (len(parts) == 4 and parts[:2] == ["v1", "svc"]
                            and parts[3] == "index_lookup"):
                        table = urllib.parse.unquote(parts[2])
                        conn = self._resolve(table)
                        if conn is None or not hasattr(conn, "index_lookup"):
                            return self._json({"error": "no index"}, 404)
                        ln = int(self.headers.get("Content-Length", "0"))
                        req = json.loads(self.rfile.read(ln).decode())
                        keys = [tuple(k) if isinstance(k, list) else k
                                for k in req["keys"]]
                        pages = conn.index_lookup(table, req["columns"], keys)
                        if isinstance(pages, Page):
                            pages = [pages]
                        return self._binary(encode_page_batch(
                            [serialize_page(p) for p in pages]))
                    return self._json({"error": "not found"}, 404)
                except Exception as e:
                    return self._json({"error": repr(e)}, 500)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="table-service-http")

    def start(self) -> "TableServiceServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class RemoteConnector:
    """Engine-side client for a remote table service."""

    def __init__(self, uri: str, timeout: float = 30.0):
        self.uri = uri.rstrip("/")
        self.timeout = timeout
        self._meta: Dict[str, dict] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}

    # -- transport ----------------------------------------------------------
    def _get(self, path: str) -> bytes:
        with urllib.request.urlopen(
                f"{self.uri}{path}", timeout=self.timeout) as r:
            return r.read()

    def _get_json(self, path: str):
        return json.loads(self._get(path).decode())

    def meta(self, table: str) -> dict:
        m = self._meta.get(table)
        if m is None:
            m = self._meta[table] = self._get_json(
                f"/v1/svc/{urllib.parse.quote(table)}/meta")
            self._dicts[table] = {c: Dictionary(v)
                                  for c, v in m["dictionaries"].items()}
            if m.get("has_index"):
                # advertise the capability only when the service has it
                # (the binder's index-join rule gates on hasattr)
                self.index_lookup = self._index_lookup
        return m

    # -- connector SPI ------------------------------------------------------
    def table_names(self) -> List[str]:
        return self._get_json("/v1/svc/tables")

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return [(c, type_from_json(t)) for c, t in self.meta(table)["schema"]]

    def num_splits(self, table: str) -> int:
        return int(self.meta(table)["num_splits"])

    def row_count(self, table: str) -> int:
        rc = self.meta(table)["row_count"]
        if rc is not None:
            return int(rc)
        import numpy as np

        return sum(int(np.asarray(self.page_for_split(table, s).row_mask).sum())
                   for s in range(self.num_splits(table)))

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        self.meta(table)
        return self._dicts[table].get(column)

    def column_domain(self, table: str, column: str):
        dom = self.meta(table)["domains"].get(column)
        return tuple(dom) if dom else None

    def split_stats(self, table: str, split: int):
        if not self.meta(table)["has_stats"]:
            return {}
        st = self._get_json(
            f"/v1/svc/{urllib.parse.quote(table)}/stats/{split}")
        return {c: tuple(v) for c, v in st.items()}

    def _page_dicts(self, table: str) -> list:
        self.meta(table)
        return [self._dicts[table].get(c) for c, _ in self.meta(table)["schema"]]

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        raw = self._get(f"/v1/svc/{urllib.parse.quote(table)}/page/{split}")
        return deserialize_page(raw, dictionaries=self._page_dicts(table))

    def _index_lookup(self, table: str, columns: Sequence[str],
                      keys) -> List[Page]:
        body = json.dumps({"columns": list(columns),
                           "keys": [list(k) if isinstance(k, tuple) else k
                                    for k in keys]}).encode()
        req = urllib.request.Request(
            f"{self.uri}/v1/svc/{urllib.parse.quote(table)}/index_lookup",
            data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            raw = r.read()
        dicts = self._page_dicts(table)
        return [deserialize_page(r, dictionaries=dicts)
                for r in parse_page_batch(raw)]
