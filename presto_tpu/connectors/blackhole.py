"""Blackhole connector: synthetic no-op tables.

Reference analog: ``presto-blackhole`` — /dev/null-style tables with
configurable split/page/row counts and artificial latency, used as a
test fixture for scheduling/cancellation behavior.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.page import Page
from presto_tpu.types import BIGINT, Type


class BlackholeConnector:
    def __init__(self):
        self._tables: Dict[str, dict] = {}

    def create_table(
        self,
        name: str,
        schema: List[Tuple[str, Type]],
        splits: int = 1,
        rows_per_split: int = 0,
        page_latency_s: float = 0.0,
    ) -> None:
        self._tables[name] = {
            "schema": schema, "splits": splits,
            "rows": rows_per_split, "latency": page_latency_s,
        }

    # -- connector protocol -------------------------------------------------
    def table_names(self) -> List[str]:
        return list(self._tables.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self._tables[table]["schema"]

    def num_splits(self, table: str) -> int:
        return self._tables[table]["splits"]

    def row_count(self, table: str) -> int:
        t = self._tables[table]
        return t["splits"] * t["rows"]

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        t = self._tables[table]
        if t["latency"]:
            time.sleep(t["latency"])
        n = t["rows"]
        cols = [np.zeros(n, dtype=ty.np_dtype) for _, ty in t["schema"]]
        types = [ty for _, ty in t["schema"]]
        return Page.from_arrays(cols, types, capacity=capacity or max(n, 1))
