"""Connectors: data sources producing columnar Pages.

Reference analog: presto-tpch / presto-memory / presto-blackhole
connector modules plus the connector SPI (presto-spi/.../spi/connector/).
"""
