"""Local-file connector: directories of csv / json-lines as tables.

Reference analog: ``presto-local-file`` (reads server log files via a
declared schema) combined with the record-decoder layer the kafka/redis
connectors share (presto-record-decoder).  One table = one file or one
directory of same-format files; one file = one split.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.connectors.jdbc import _encode_column
from presto_tpu.page import Dictionary, Page
from presto_tpu.record_decoder import decoder_for
from presto_tpu.types import Type, parse_type


class LocalFileConnector:
    """Tables registered as (name, path, format, schema).

    ``schema`` entries use SQL type names ('bigint', 'double',
    'varchar', 'date', ...); dates/timestamps parse from ISO strings.
    """

    def __init__(self):
        self._tables: Dict[str, dict] = {}
        self._cache: Dict[str, List[Page]] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}

    def add_table(self, name: str, path: str, fmt: str,
                  schema: Sequence[Tuple[str, str]], **decoder_kw) -> None:
        typed = [(c, parse_type(t) if isinstance(t, str) else t)
                 for c, t in schema]
        self._tables[name] = {
            "path": path, "fmt": fmt, "schema": typed, "kw": decoder_kw,
        }

    # -- connector protocol -------------------------------------------------
    def table_names(self) -> List[str]:
        return list(self._tables)

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return self._tables[table]["schema"]

    def _files(self, table: str) -> List[str]:
        path = self._tables[table]["path"]
        if os.path.isdir(path):
            return [os.path.join(path, f) for f in sorted(os.listdir(path))
                    if not f.startswith(".")]
        return [path]

    def num_splits(self, table: str) -> int:
        return max(1, len(self._files(table)))

    def row_count(self, table: str) -> int:
        self._load(table)
        import numpy as np

        return sum(int(np.asarray(p.row_mask).sum()) for p in self._cache[table])

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None) -> Page:
        self._load(table)
        return self._cache[table][split]

    def dictionary_for(self, table: str, column: str):
        self._load(table)
        return self._dicts.get(table, {}).get(column)

    # -- loading ------------------------------------------------------------
    def _load(self, table: str) -> None:
        if table in self._cache:
            return
        meta = self._tables[table]
        schema = meta["schema"]
        dec = decoder_for(meta["fmt"], schema, **meta["kw"])
        dicts: Dict[str, Dictionary] = {}
        pages = []
        for path in self._files(table):
            with open(path) as f:
                cols_raw = dec.decode(f)
            cols, valids, page_dicts = [], [], []
            for (name, t), raw in zip(schema, cols_raw):
                converted = [_convert_temporal(v, t) for v in raw]
                data, valid, d = _encode_column(converted, t, dicts.get(name))
                if d is not None:
                    dicts[name] = d
                cols.append(data)
                valids.append(valid)
                page_dicts.append(d)
            pages.append(Page.from_arrays(cols, [t for _, t in schema],
                                          valids=valids, dictionaries=page_dicts))
        self._cache[table] = pages
        self._dicts[table] = dicts


def _convert_temporal(v, t: Type):
    if v is None:
        return None
    if t.name == "date":
        from presto_tpu.connectors.jdbc import _parse_date

        return _parse_date(v)
    if t.name == "timestamp":
        from presto_tpu.connectors.jdbc import _parse_ts

        return _parse_ts(v)
    return v
